"""Fleet aggregation: N replicas' ``/metrics`` + ``/slo`` merged
into one snapshot.

ROADMAP item 2 (multi-replica fleet behind a router, sustained-SLO
soak) needs a single pane over many replicas.  This module is that
substrate: :func:`scrape` pulls one replica's Prometheus text and SLO
document over plain HTTP, :func:`merge` folds any number of scrapes
into one fleet view —

- **counters sum** (they are monotone per-replica totals),
- **gauges** keep per-replica values plus min/max/sum (a mean of
  ``serve.queue_depth`` hides the hot replica; the spread is the
  signal),
- **latency histograms merge bucket-wise**: the ``/slo`` document
  carries raw geometric bucket tables in LogHistogram geometry, so
  fleet quantiles are recomputed from the summed buckets by the SAME
  estimator a single replica uses
  (:func:`pint_tpu.obs.slo.quantiles_from_buckets`) — not averaged
  p99s, which would be meaningless,
- the fleet **SLO verdict is worst-of** (one violating replica makes
  the fleet violated; a fleet is as healthy as its sickest member).

Exposed as ``pinttrace --fleet host:port,host:port,...``.
"""

from __future__ import annotations

import json
import re
import urllib.request

from pint_tpu.obs import slo as _slo

__all__ = ["scrape", "merge", "fleet_snapshot", "parse_prometheus",
           "format_fleet"]

_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$")

#: worst-of ordering for fleet verdicts (higher is worse).
_VERDICT_RANK = {"no_data": 0, "ok": 1, "violated": 2}


def parse_prometheus(text) -> dict:
    """Prometheus text exposition -> ``{"counters": {name: v},
    "gauges": {name: v}, "samples": {full_line_key: v}}``.  Counters
    are recognized by the ``_total`` suffix (how
    :func:`pint_tpu.metrics_http.render_prometheus` marks them);
    labeled samples (histogram quantiles) keep their label string in
    the key so merge can track them per-series."""
    out = {"counters": {}, "gauges": {}, "samples": {}}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels, raw = m.group(1), m.group(2) or "", m.group(3)
        try:
            value = float(raw)
        except ValueError:
            continue
        key = name + labels
        out["samples"][key] = value
        if labels:
            continue
        if name.endswith("_total"):
            out["counters"][name] = value
        else:
            out["gauges"][name] = value
    return out


def scrape(target, timeout=5.0) -> dict:
    """One replica's observability surface: ``{"target", "metrics",
    "slo", "error"}``.  A dead replica yields an ``error`` entry
    instead of raising — a fleet view with one replica down is still
    a fleet view (and the down replica is exactly what it should
    show)."""
    target = str(target).strip()
    base = f"http://{target}"
    doc = {"target": target, "metrics": None, "slo": None,
           "error": None}
    try:
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=timeout) as r:
            doc["metrics"] = parse_prometheus(
                r.read().decode("utf-8", "replace"))
        with urllib.request.urlopen(base + "/slo",
                                    timeout=timeout) as r:
            doc["slo"] = json.loads(r.read().decode("utf-8"))
    except Exception as e:  # noqa: BLE001 - any transport failure
        doc["error"] = f"{type(e).__name__}: {e}"
    return doc


def _merge_slo(slos) -> dict:
    """Bucket-wise merge of the replicas' /slo windows."""
    merged = {"windows": {}, "verdict": "no_data", "degraded": False,
              "objectives": None}
    worst = "no_data"
    for snap in slos:
        if not snap:
            continue
        if merged["objectives"] is None:
            merged["objectives"] = snap.get("objectives")
        merged["degraded"] = (merged["degraded"]
                              or bool(snap.get("degraded")))
        v = snap.get("verdict", "no_data")
        if _VERDICT_RANK.get(v, 0) > _VERDICT_RANK[worst]:
            worst = v
        for label, wdoc in (snap.get("windows") or {}).items():
            cell = merged["windows"].setdefault(
                label, {"n": 0, "errors": 0, "slow": 0,
                        "buckets": {}, "burn_rate": 0.0})
            cell["n"] += int(wdoc.get("n", 0))
            cell["errors"] += int(wdoc.get("errors", 0))
            cell["slow"] += int(wdoc.get("slow", 0))
            cell["burn_rate"] = max(cell["burn_rate"],
                                    float(wdoc.get("burn_rate", 0.0)))
            for idx, c in (wdoc.get("buckets") or {}).items():
                cell["buckets"][idx] = (cell["buckets"].get(idx, 0)
                                        + int(c))
    for cell in merged["windows"].values():
        qs = _slo.quantiles_from_buckets(cell["buckets"])
        cell["p50_ms"] = None if qs[50] is None else qs[50] * 1e3
        cell["p99_ms"] = None if qs[99] is None else qs[99] * 1e3
        n = cell["n"]
        cell["availability"] = (None if n == 0
                                else 1.0 - cell["errors"] / n)
    merged["verdict"] = worst
    return merged


def merge(snapshots) -> dict:
    """Fold replica scrapes into ONE fleet snapshot: summed counters,
    min/max/sum gauges, bucket-wise merged SLO windows, worst-of
    verdict."""
    live = [s for s in snapshots if s.get("error") is None]
    counters = {}
    gauges = {}
    for snap in live:
        metrics = snap.get("metrics") or {}
        for name, v in (metrics.get("counters") or {}).items():
            counters[name] = counters.get(name, 0.0) + v
        for name, v in (metrics.get("gauges") or {}).items():
            cell = gauges.setdefault(
                name, {"min": v, "max": v, "sum": 0.0, "n": 0})
            cell["min"] = min(cell["min"], v)
            cell["max"] = max(cell["max"], v)
            cell["sum"] += v
            cell["n"] += 1
    slo = _merge_slo([s.get("slo") for s in live])
    return {
        "replicas": len(snapshots),
        "replicas_up": len(live),
        "down": [{"target": s["target"], "error": s["error"]}
                 for s in snapshots if s.get("error") is not None],
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "slo": slo,
        "verdict": (slo["verdict"] if live else "no_data"),
    }


def fleet_snapshot(targets, timeout=5.0) -> dict:
    """Scrape every ``host:port`` in ``targets`` and merge: the
    ``pinttrace --fleet`` document (per-replica scrapes kept under
    ``"scrapes"`` for drill-down)."""
    scrapes = [scrape(t, timeout=timeout) for t in targets]
    doc = merge(scrapes)
    doc["targets"] = [s["target"] for s in scrapes]
    doc["scrapes"] = scrapes
    return doc


def format_fleet(doc) -> list:
    """Human-readable fleet summary lines."""
    lines = [
        f"fleet: {doc['replicas_up']}/{doc['replicas']} replicas up"
        f"  verdict={doc['verdict']}"
        + ("  DEGRADED" if doc["slo"].get("degraded") else "")]
    for d in doc.get("down", []):
        lines.append(f"  down {d['target']}: {d['error']}")
    for label in ("1m", "10m", "1h"):
        w = doc["slo"]["windows"].get(label)
        if not w or not w["n"]:
            continue
        p99 = (f"{w['p99_ms']:.2f}ms" if w.get("p99_ms") is not None
               else "-")
        avail = (f"{w['availability']:.4f}"
                 if w.get("availability") is not None else "-")
        lines.append(
            f"  {label:>3}: n={w['n']}  p99={p99}  avail={avail}  "
            f"burn={w['burn_rate']:.2f}")
    picks = [k for k in sorted(doc["counters"])
             if k.startswith("pint_tpu_serve_")
             or k.startswith("pint_tpu_slo_")
             or k.startswith("pint_tpu_obs_")]
    for name in picks[:16]:
        lines.append(f"  {name} = {doc['counters'][name]:g}")
    return lines
