"""Earth rotation: ITRF -> GCRS, owned natively (replaces erfa).

The reference delegates ITRF->GCRS to erfa's IAU2000B machinery
(reference: src/pint/erfautils.py:1-85 ``gcrs_posvel_from_itrf``).  Here the
equinox-based rotation is implemented directly:

    r_GCRS = P(t) . N(t) . R3(-GAST) . r_ITRF

- ERA/GMST: IAU 2000/2006 expressions (exact coefficients, public).
- Precession: IAU 2006 zeta_A/z_A/theta_A polynomials (Capitaine et al.).
- Nutation: leading IAU 2000 terms (9 largest; truncation ~ few mas,
  i.e. centimeters of site position — far below other builtin-path terms).
- Polar motion + UT1-UTC: read from standard IERS products installed in
  $PINT_TPU_IERS_DIR (pint_tpu/obs/iers.py): W(xp, yp) is applied ahead
  of R3(-GAST) and UT1 = UTC + dUT1 feeds the rotation angle.  With no
  data installed both are zero (~10 m of site position ~ 30 ns Roemer
  worst case from polar motion; |UT1-UTC| < 0.9 s -> up to ~420 m
  east-west ~ 1.4 us), documented in ACCURACY.md.  For simulate->fit
  self-consistency the zero-EOP path cancels exactly.

Host-side numpy (ingest path, runs once per dataset).
"""

from __future__ import annotations

import numpy as np

from pint_tpu import C_M_PER_S
from pint_tpu.time.scales import TT_MINUS_TAI, tai_minus_utc, tdb_minus_tt_seconds

_AS = np.pi / (180.0 * 3600.0)  # arcsec -> rad
_TURN = 2.0 * np.pi

#: Earth rotation rate factor (revolutions per UT1 day)
_ERA_RATE = 1.00273781191135448


def _julian_centuries_tt(tdb_sec):
    """TT julian centuries since J2000 from TDB seconds (TDB~TT to <2 ms,
    irrelevant for angles varying over centuries)."""
    return np.asarray(tdb_sec, np.float64) / (86400.0 * 36525.0)


def era_radians(ut1_jd_frac_days):
    """Earth rotation angle for UT1 days since J2000 (JD - 2451545.0)."""
    d = np.asarray(ut1_jd_frac_days, np.float64)
    f = d - np.floor(d)
    return _TURN * np.mod(0.7790572732640 + f + _ERA_RATE * np.floor(d)
                          + (_ERA_RATE - 1.0) * f, 1.0)


def _delaunay(T):
    """Fundamental lunisolar arguments [rad] (IERS 2003 linear terms)."""
    deg = np.pi / 180.0
    l = (134.96340251 + 477198.86756050 * T) * deg
    lp = (357.52910918 + 35999.05029094 * T) * deg
    F = (93.27209062 + 483202.01745772 * T) * deg
    D = (297.85019547 + 445267.11151675 * T) * deg
    Om = (125.04455501 - 1934.13626197 * T) * deg
    return l, lp, F, D, Om


# Leading IAU 2000 nutation terms: multipliers (l, l', F, D, Om) and
# in-phase amplitudes (dpsi_sin, deps_cos) in arcsec.
_NUT_TERMS = [
    ((0, 0, 0, 0, 1), -17.2064161, 9.2052331),
    ((0, 0, 2, -2, 2), -1.3170906, 0.5730336),
    ((0, 0, 2, 0, 2), -0.2276413, 0.0978459),
    ((0, 0, 0, 0, 2), 0.2074554, -0.0897492),
    ((0, 1, 0, 0, 0), 0.1475877, 0.0073871),
    ((0, 1, 2, -2, 2), -0.0516821, 0.0224386),
    ((1, 0, 0, 0, 0), 0.0711159, -0.0006750),
    ((0, 0, 2, 0, 1), -0.0387298, 0.0200728),
    ((1, 0, 2, 0, 2), -0.0301461, 0.0129025),
]


def nutation_angles(T):
    """(dpsi, deps) [rad] from the truncated IAU 2000 series."""
    args = _delaunay(T)
    dpsi = np.zeros_like(np.asarray(T, np.float64))
    deps = np.zeros_like(dpsi)
    for mults, a_psi, a_eps in _NUT_TERMS:
        arg = sum(m * a for m, a in zip(mults, args) if m != 0)
        dpsi = dpsi + a_psi * np.sin(arg)
        deps = deps + a_eps * np.cos(arg)
    return dpsi * _AS, deps * _AS


def mean_obliquity(T):
    """IAU 2006 mean obliquity [rad]."""
    return (84381.406 - 46.836769 * T - 0.0001831 * T * T) * _AS


def _R1(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack(
        [np.stack([o, z, z], -1), np.stack([z, c, s], -1), np.stack([z, -s, c], -1)],
        axis=-2,
    )


def _R3(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack(
        [np.stack([c, s, z], -1), np.stack([-s, c, z], -1), np.stack([z, z, o], -1)],
        axis=-2,
    )


def _R2(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack(
        [np.stack([c, z, -s], -1), np.stack([z, o, z], -1), np.stack([s, z, c], -1)],
        axis=-2,
    )


def precession_matrix(T):
    """IAU 2006 equatorial precession, mapping mean-of-date -> GCRS (J2000):
    P = R3(zetaA) R2(-thetaA) R3(zA)  (inverse of the Lieske date<-J2000
    composition R3(-zA) R2(thetaA) R3(-zetaA)).

    Orientation check (tested): the true pole of date mapped to J2000
    coordinates moves toward the vernal equinox, X ~ +2004.19" T.
    """
    zeta = (2.650545 + 2306.083227 * T + 0.2988499 * T**2 + 0.01801828 * T**3) * _AS
    z = (-2.650545 + 2306.077181 * T + 1.0927348 * T**2 + 0.01826837 * T**3) * _AS
    theta = (2004.191903 * T - 0.4294934 * T**2 - 0.04182264 * T**3) * _AS
    return _R3(zeta) @ _R2(-theta) @ _R3(z)


def nutation_matrix(T):
    """Nutation, mapping true-of-date -> mean-of-date:
    N = R1(-eps) R3(dpsi) R1(eps + deps)."""
    dpsi, deps = nutation_angles(T)
    eps = mean_obliquity(T)
    return _R1(-eps) @ _R3(dpsi) @ _R1(eps + deps)


def gast_radians(T, ut1_jd_frac_days):
    """Greenwich apparent sidereal time (equinox-based, IAU 2006)."""
    era = era_radians(ut1_jd_frac_days)
    # equation of the origins complement: GMST - ERA polynomial [arcsec]
    gmst_minus_era = (
        0.014506 + 4612.156534 * T + 1.3915817 * T**2 - 0.00000044 * T**3
    ) * _AS
    dpsi, _ = nutation_angles(T)
    eqeq = dpsi * np.cos(mean_obliquity(T))
    return era + gmst_minus_era + eqeq


def _utc_days_from_ticks(ticks):
    """UTC days since J2000 from TDB ticks."""
    tdb_sec = np.asarray(ticks, np.float64) / 2**32
    # invert TDB -> TT -> TAI -> UTC; iterate leap lookup once via day guess
    tt_sec = tdb_sec - tdb_minus_tt_seconds(tdb_sec)
    day_guess = np.floor(tt_sec / 86400.0 + 51544.5).astype(np.int64)
    utc_sec = tt_sec - TT_MINUS_TAI - tai_minus_utc(day_guess)
    # the TT-based day guess is ~69 s ahead of UTC: within the last
    # minute of a day preceding a leap-second insertion it lands on the
    # wrong day; one refinement with the UTC-based day settles it
    day = np.floor(utc_sec / 86400.0 + 51544.5).astype(np.int64)
    utc_sec = tt_sec - TT_MINUS_TAI - tai_minus_utc(day)
    return utc_sec / 86400.0


def polar_motion_matrix(xp_as, yp_as, T):
    """W = R3(-s') R2(xp) R1(yp): ITRF -> terrestrial intermediate frame
    (IERS 2010 conventions eq. 5.3).  Orientation check (tested): the
    ITRF pole (0,0,1) maps to ~(-xp, +yp, 1) in the intermediate frame,
    i.e. the CIP sits at (+xp, -yp) in ITRF coordinates."""
    sp = -0.000047 * T * _AS  # TIO locator s' (-47 uas/century)
    return _R3(-sp) @ _R2(np.asarray(xp_as, np.float64) * _AS) @ _R1(
        np.asarray(yp_as, np.float64) * _AS
    )


def gcrs_posvel_from_itrf(itrf_xyz_m, ticks):
    """Observatory GCRS posvel [light-seconds, ls/s] at TDB ticks.

    itrf_xyz_m: (3,) ITRF coordinates in meters; ticks: (...,) int64.
    """
    from pint_tpu.ephem import PosVel
    from pint_tpu.obs.iers import get_eop

    ticks = np.atleast_1d(np.asarray(ticks))
    T = _julian_centuries_tt(ticks.astype(np.float64) / 2**32)
    utc_d = _utc_days_from_ticks(ticks)

    r = np.asarray(itrf_xyz_m, np.float64) / C_M_PER_S  # light-seconds
    eop = get_eop()
    if eop is not None:
        xp, yp, dut1 = eop.at(utc_d + 51544.5)
        W = polar_motion_matrix(xp, yp, T)
        rw = np.einsum("...ij,j->...i", W, r)
        r0, r1, r2 = rw[..., 0], rw[..., 1], rw[..., 2]
        ut1_d = utc_d + dut1 / 86400.0
    else:
        r0, r1, r2 = r[0], r[1], r[2]
        ut1_d = utc_d

    gast = gast_radians(T, ut1_d)
    PN = precession_matrix(T) @ nutation_matrix(T)
    cg, sg = np.cos(gast), np.sin(gast)
    # R3(-GAST) r
    rot = np.stack(
        [cg * r0 - sg * r1, sg * r0 + cg * r1, np.broadcast_to(r2, cg.shape)],
        axis=-1,
    )
    omega = _TURN * _ERA_RATE / 86400.0  # rad/s
    vot = np.stack(
        [(-sg * r0 - cg * r1) * omega, (cg * r0 - sg * r1) * omega,
         np.zeros_like(cg)],
        axis=-1,
    )
    pos = np.einsum("...ij,...j->...i", PN, rot)
    vel = np.einsum("...ij,...j->...i", PN, vot)
    return PosVel(pos, vel)
