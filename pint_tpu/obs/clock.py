"""Clock correction files: tempo2 and TEMPO formats.

Behavioral counterpart of the reference's ClockFile (reference:
src/pint/observatory/clock_file.py:441,566): parses both community formats,
evaluates by linear interpolation with an out-of-range policy, and chains
files (site -> GPS -> UTC etc.).  No data ships with the framework (the
reference downloads from the IPTA clock-corrections repo at runtime; this
environment is zero-egress): files are discovered in $PINT_TPU_CLOCK_DIR
or ./clock by conventional names.

- tempo2 format (``*.clk``): ``# FROM TO`` header line, then
  ``mjd offset_seconds [...]`` rows.
- TEMPO format (``time*.dat``): fixed columns — MJD in [0:9],
  clkcorr1 (us) in [9:21], clkcorr2 (us) in [21:33], one-char site code at
  column 34; correction = clkcorr2 - clkcorr1; the historical
  ``clkcorr1 > 800 -> -818.8`` tempo adjustment is applied.
"""

from __future__ import annotations

import os
import warnings

import numpy as np


class ClockFile:
    """MJD-indexed clock offsets [s] with linear interpolation."""

    def __init__(self, mjds, offsets_sec, name="", limits="warn"):
        mjds = np.asarray(mjds, dtype=np.float64)
        offsets_sec = np.asarray(offsets_sec, dtype=np.float64)
        # a corrupted tabulation must fail loudly: 'nan'/'inf' parse as
        # valid floats, and np.interp would silently smear a single
        # NaN row across every TOA in its neighborhood
        bad = ~(np.isfinite(mjds) & np.isfinite(offsets_sec))
        if bad.any():
            raise ValueError(
                f"clock file {name or '<anonymous>'}: "
                f"{int(bad.sum())} non-finite MJD/offset row(s) "
                f"(first at index {int(np.flatnonzero(bad)[0])}) — a "
                "corrupted table must not silently interpolate")
        order = np.argsort(mjds, kind="stable")
        self.mjds = mjds[order]
        self.offsets = offsets_sec[order]
        self.name = name
        self.limits = limits
        self._warned = False

    def evaluate_sec(self, mjd):
        mjd = np.asarray(mjd, dtype=np.float64)
        if self.mjds.size == 0:
            return np.zeros_like(mjd)
        out_of_range = (mjd < self.mjds[0]) | (mjd > self.mjds[-1])
        if np.any(out_of_range):
            msg = (
                f"clock file {self.name}: {int(out_of_range.sum())} MJDs "
                f"outside coverage [{self.mjds[0]}, {self.mjds[-1]}]"
            )
            if self.limits == "error":
                raise ValueError(msg)
            if not self._warned:
                warnings.warn(msg + "; clamping to end values")
                self._warned = True
        return np.interp(mjd, self.mjds, self.offsets)

    # -- parsers -------------------------------------------------------------
    @classmethod
    def read_tempo2(cls, path, limits="warn"):
        mjds, offs = [], []
        with open(path) as f:
            for line in f:
                if line.startswith("#") or not line.strip():
                    continue
                parts = line.split()
                try:
                    mjd = float(parts[0])
                    off = float(parts[1])
                except (ValueError, IndexError):
                    continue
                mjds.append(mjd)
                offs.append(off)
        if not mjds:
            raise ValueError(
                f"clock file {path}: no parseable 'MJD offset' rows — "
                "a present-but-garbage file must not silently mean "
                "zero corrections")
        from pint_tpu import faults as _faults

        _faults.corrupt_clock_rows(mjds, offs)
        return cls(mjds, offs, name=os.path.basename(path), limits=limits)

    @classmethod
    def read_tempo(cls, path, site_code=None, limits="warn"):
        mjds, offs = [], []
        with open(path) as f:
            for line in f:
                if line.startswith("#") or not line.strip():
                    continue
                first = line.split()[0].upper() if line.split() else ""
                if first.startswith("MJD") or first.startswith("====="):
                    continue
                try:
                    mjd = float(line[:9])
                except (ValueError, IndexError):
                    continue
                if (mjd < 39000 and mjd != 0) or mjd > 100000:
                    continue

                def _field(a, b):
                    try:
                        return float(line[a:b])
                    except (ValueError, IndexError):
                        return None

                c1 = _field(9, 21)
                c2 = _field(21, 33)
                if c1 is None and c2 is None:
                    continue
                csite = line[34].lower() if len(line) > 34 else None
                if site_code is not None and csite != site_code.lower():
                    continue
                c1 = c1 or 0.0
                c2 = c2 or 0.0
                if c1 > 800.0:  # historical tempo convention
                    c1 -= 818.8
                mjds.append(mjd)
                offs.append((c2 - c1) * 1e-6)  # us -> s
        return cls(mjds, offs, name=os.path.basename(path), limits=limits)

    @classmethod
    def read(cls, path, fmt=None, **kw):
        if fmt is None:
            fmt = "tempo2" if str(path).endswith(".clk") else "tempo"
        if fmt == "tempo2":
            kw.pop("site_code", None)
            return cls.read_tempo2(path, **kw)
        return cls.read_tempo(path, **kw)

    # -- writers (reference: clock_file.py:295 write_tempo2_clock_file,
    # :355 write_tempo_clock_file) -------------------------------------------
    def write_tempo2(self, path, hdr_from="SITE", hdr_to="UTC(GPS)",
                     comments=""):
        with open(path, "w") as f:
            f.write(f"# {hdr_from} {hdr_to}\n")
            if comments:
                for ln in comments.splitlines():
                    f.write(f"# {ln}\n")
            for m, o in zip(self.mjds, self.offsets):
                f.write(f"{m:.6f} {o:.12e}\n")

    def write_tempo(self, path, site_code="1", comments=""):
        """TEMPO fixed-column time.dat format: the correction is stored
        in the clkcorr2 column (us), clkcorr1 = 0."""
        with open(path, "w") as f:
            f.write("# MJD       clkcorr1(us)  clkcorr2(us) s\n")
            if comments:
                for ln in comments.splitlines():
                    f.write(f"# {ln}\n")
            for m, o in zip(self.mjds, self.offsets):
                f.write(f"{m:9.2f}{0.0:12.3f}{o*1e6:12.4f} "
                        f"{site_code[:1]}\n")

    # -- combination (reference: clock_file.py merge) ------------------------
    @staticmethod
    def merge(clocks, trim=True):
        """One ClockFile whose corrections are the *sum* of the inputs
        (e.g. ao2gps + gps2utc -> ao2utc).  Discontinuities (repeated
        MJDs) in any input are propagated; with trim, coverage is the
        intersection of the inputs' ranges."""
        if not clocks:
            raise ValueError("nothing to merge")
        all_mjds = []
        discont = set()
        for c in clocks:
            all_mjds.append(c.mjds)
            dup = c.mjds[:-1][np.diff(c.mjds) == 0]
            discont.update(dup.tolist())
        mjds = np.unique(np.concatenate(all_mjds))
        rep = np.ones(len(mjds), dtype=int)
        for m in discont:
            rep[np.searchsorted(mjds, m)] = 2
        mjds = np.repeat(mjds, rep)
        total = np.zeros(len(mjds))
        for c in clocks:
            vals = np.interp(mjds, c.mjds, c.offsets)
            # at a discontinuity (repeated mjd), the left copy takes the
            # pre-jump value and the right copy the post-jump value
            dup_left = np.flatnonzero(np.diff(mjds) == 0)
            for i in dup_left:
                m = mjds[i]
                j = np.searchsorted(c.mjds, m)
                if j < len(c.mjds) - 1 and c.mjds[j] == c.mjds[j + 1]:
                    vals[i] = c.offsets[j]
                    vals[i + 1] = c.offsets[j + 1]
            total += vals
        lo = max(c.mjds[0] for c in clocks)
        hi = min(c.mjds[-1] for c in clocks)
        if trim:
            keep = (mjds >= lo) & (mjds <= hi)
            mjds, total = mjds[keep], total[keep]
        out = ClockFile.__new__(ClockFile)
        out.mjds = mjds
        out.offsets = total
        out.name = "+".join(c.name or "?" for c in clocks)
        out.limits = clocks[0].limits
        out._warned = False
        return out


class GlobalClockFile(ClockFile):
    """A registry-backed clock file that transparently refreshes when
    the underlying file changes on disk.

    The reference's GlobalClockFile (clock_file.py:781) re-downloads
    from the IPTA clock-corrections repository when TOAs fall past the
    end of the current version; this environment is zero-egress, so the
    refresh trigger is a file-mtime change in $PINT_TPU_CLOCK_DIR
    instead (drop in an updated file and running processes pick it up)."""

    def __init__(self, filename, fmt=None, site_code=None, limits="warn"):
        self.filename = filename
        self.fmt = fmt
        self.site_code = site_code
        self._mtime = None
        self._reload(limits)

    def _reload(self, limits="warn"):
        base = ClockFile.read(self.filename, fmt=self.fmt,
                              site_code=self.site_code, limits=limits)
        self.mjds = base.mjds
        self.offsets = base.offsets
        self.name = base.name
        self.limits = base.limits
        self._warned = False
        self._mtime = os.stat(self.filename).st_mtime_ns

    def evaluate_sec(self, mjd):
        try:
            if os.stat(self.filename).st_mtime_ns != self._mtime:
                self._reload(self.limits)
        except OSError:
            pass
        return super().evaluate_sec(mjd)


def _clock_dirs():
    from pint_tpu.obs.datadirs import search_dirs

    return search_dirs("PINT_TPU_CLOCK_DIR", "clock",
                       include_builtin=True)


def clock_data_identity():
    """Provenance string over every file in the clock search dirs
    (name, mtime, size) — part of the prepared-TOA cache hash so an
    installed or updated clock/BIPM file invalidates cached ticks."""
    from pint_tpu.obs.datadirs import data_identity

    return data_identity(_clock_dirs())


def find_clock_file(filename, fmt=None, site_code=None):
    """Locate one clock file by name in $PINT_TPU_CLOCK_DIR / ./clock
    (reference: observatory/__init__.py:867 find_clock_file, minus the
    network repository).  Returns a GlobalClockFile or None."""
    for d in _clock_dirs():
        path = os.path.join(d, filename)
        if os.path.exists(path):
            return GlobalClockFile(path, fmt=fmt, site_code=site_code)
    return None


def find_clock_chain(obs):
    """Locate the clock chain for a TopoObs.

    Per-site clock-file specs (obs.clock_files, mirroring the
    reference's observatories.json clock_file entries) are honored
    first; otherwise conventional names are tried: <name>2gps.clk +
    gps2utc.clk, or time_<name>.dat (tempo).  Returns a (possibly
    empty) list of ClockFile."""
    chain = []
    for spec in getattr(obs, "clock_files", ()) or ():
        if isinstance(spec, str):
            spec = {"name": spec}
        cf = find_clock_file(spec["name"], fmt=spec.get("format"),
                             site_code=spec.get("site",
                                                obs.tempo_code))
        if cf is not None:
            chain.append(cf)
    if chain:
        gps = find_clock_file("gps2utc.clk", fmt="tempo2")
        if gps is not None:
            chain.append(gps)
        return chain
    for d in _clock_dirs():
        site_files = [
            (os.path.join(d, f"{obs.name}2gps.clk"), "tempo2", None),
        ]
        if obs.tempo_code:
            # generic tempo files are keyed by site code: a site
            # without one (e.g. the IPTA-MDC fake 'axis') must not
            # absorb every site's entries via an unfiltered read
            site_files += [
                (os.path.join(d, f"time_{obs.name}.dat"), "tempo",
                 obs.tempo_code),
                (os.path.join(d, "time.dat"), "tempo", obs.tempo_code),
            ]
        for path, fmt, site in site_files:
            if os.path.exists(path):
                chain.append(GlobalClockFile(path, fmt=fmt,
                                             site_code=site))
                break
        if chain:
            break
    if chain:
        # GPS->UTC may live in a different search dir than the site
        # file (e.g. a user site file in ./clock over the bundled
        # gps2utc.clk): search all dirs
        gps = find_clock_file("gps2utc.clk", fmt="tempo2")
        if gps is not None:
            chain.append(gps)
    return chain


#: TT - TAI, seconds, exact by definition
_TT_MINUS_TAI = 32.184


def find_bipm_correction(version="BIPM2019"):
    """TT(BIPMxxxx) - TT(TAI) realization offsets as a ClockFile
    (reference: observatory/__init__.py:253 bipm_correction reading
    tai2tt_bipmXXXX.clk), or None when the data file is absent.  Falls
    back to the latest available earlier realization, like the
    reference's find_latest_bipm (:70).

    The published tai2tt_bipm*.clk files tabulate TT(BIPM) - TAI
    (~32.1843 s); the 32.184 s of TT(TAI) - TAI is subtracted here —
    exactly as the reference does — leaving the ~27 us realization
    offset."""
    version = version.upper().replace("TT(", "").replace(")", "")
    want = int(version.replace("BIPM", "") or 2019)
    best = None
    for d in _clock_dirs():
        for f in os.listdir(d):
            m = f.lower()
            if m.startswith("tai2tt_bipm") and m.endswith(".clk"):
                try:
                    yr = int(m[len("tai2tt_bipm"):-len(".clk")])
                except ValueError:
                    continue
                if yr <= want and (best is None or yr > best[0]):
                    best = (yr, os.path.join(d, f))
    if best is None:
        return None
    cf = GlobalClockFile(best[1], fmt="tempo2")
    cf.offsets = cf.offsets - _TT_MINUS_TAI
    # keep the subtraction across mtime refreshes
    orig_reload = cf._reload

    def _reload(limits="warn"):
        orig_reload(limits)
        cf.offsets = cf.offsets - _TT_MINUS_TAI

    cf._reload = _reload
    return cf
