"""Clock correction files: tempo2 and TEMPO formats.

Behavioral counterpart of the reference's ClockFile (reference:
src/pint/observatory/clock_file.py:441,566): parses both community formats,
evaluates by linear interpolation with an out-of-range policy, and chains
files (site -> GPS -> UTC etc.).  No data ships with the framework (the
reference downloads from the IPTA clock-corrections repo at runtime; this
environment is zero-egress): files are discovered in $PINT_TPU_CLOCK_DIR
or ./clock by conventional names.

- tempo2 format (``*.clk``): ``# FROM TO`` header line, then
  ``mjd offset_seconds [...]`` rows.
- TEMPO format (``time*.dat``): fixed columns — MJD in [0:9],
  clkcorr1 (us) in [9:21], clkcorr2 (us) in [21:33], one-char site code at
  column 34; correction = clkcorr2 - clkcorr1; the historical
  ``clkcorr1 > 800 -> -818.8`` tempo adjustment is applied.
"""

from __future__ import annotations

import os
import warnings

import numpy as np


class ClockFile:
    """MJD-indexed clock offsets [s] with linear interpolation."""

    def __init__(self, mjds, offsets_sec, name="", limits="warn"):
        mjds = np.asarray(mjds, dtype=np.float64)
        offsets_sec = np.asarray(offsets_sec, dtype=np.float64)
        order = np.argsort(mjds, kind="stable")
        self.mjds = mjds[order]
        self.offsets = offsets_sec[order]
        self.name = name
        self.limits = limits
        self._warned = False

    def evaluate_sec(self, mjd):
        mjd = np.asarray(mjd, dtype=np.float64)
        if self.mjds.size == 0:
            return np.zeros_like(mjd)
        out_of_range = (mjd < self.mjds[0]) | (mjd > self.mjds[-1])
        if np.any(out_of_range):
            msg = (
                f"clock file {self.name}: {int(out_of_range.sum())} MJDs "
                f"outside coverage [{self.mjds[0]}, {self.mjds[-1]}]"
            )
            if self.limits == "error":
                raise ValueError(msg)
            if not self._warned:
                warnings.warn(msg + "; clamping to end values")
                self._warned = True
        return np.interp(mjd, self.mjds, self.offsets)

    # -- parsers -------------------------------------------------------------
    @classmethod
    def read_tempo2(cls, path, limits="warn"):
        mjds, offs = [], []
        with open(path) as f:
            for line in f:
                if line.startswith("#") or not line.strip():
                    continue
                parts = line.split()
                try:
                    mjd = float(parts[0])
                    off = float(parts[1])
                except (ValueError, IndexError):
                    continue
                mjds.append(mjd)
                offs.append(off)
        return cls(mjds, offs, name=os.path.basename(path), limits=limits)

    @classmethod
    def read_tempo(cls, path, site_code=None, limits="warn"):
        mjds, offs = [], []
        with open(path) as f:
            for line in f:
                if line.startswith("#") or not line.strip():
                    continue
                first = line.split()[0].upper() if line.split() else ""
                if first.startswith("MJD") or first.startswith("====="):
                    continue
                try:
                    mjd = float(line[:9])
                except (ValueError, IndexError):
                    continue
                if (mjd < 39000 and mjd != 0) or mjd > 100000:
                    continue

                def _field(a, b):
                    try:
                        return float(line[a:b])
                    except (ValueError, IndexError):
                        return None

                c1 = _field(9, 21)
                c2 = _field(21, 33)
                if c1 is None and c2 is None:
                    continue
                csite = line[34].lower() if len(line) > 34 else None
                if site_code is not None and csite != site_code.lower():
                    continue
                c1 = c1 or 0.0
                c2 = c2 or 0.0
                if c1 > 800.0:  # historical tempo convention
                    c1 -= 818.8
                mjds.append(mjd)
                offs.append((c2 - c1) * 1e-6)  # us -> s
        return cls(mjds, offs, name=os.path.basename(path), limits=limits)

    @classmethod
    def read(cls, path, fmt=None, **kw):
        if fmt is None:
            fmt = "tempo2" if str(path).endswith(".clk") else "tempo"
        if fmt == "tempo2":
            kw.pop("site_code", None)
            return cls.read_tempo2(path, **kw)
        return cls.read_tempo(path, **kw)


def _clock_dirs():
    dirs = []
    env = os.environ.get("PINT_TPU_CLOCK_DIR")
    if env:
        dirs.append(env)
    dirs.append("clock")
    return [d for d in dirs if os.path.isdir(d)]


def find_clock_chain(obs):
    """Locate the clock chain for a TopoObs by conventional file names:
    <name>2gps.clk + gps2utc.clk, or time_<name>.dat (tempo).  Returns a
    (possibly empty) list of ClockFile."""
    chain = []
    for d in _clock_dirs():
        site_files = [
            (os.path.join(d, f"{obs.name}2gps.clk"), "tempo2", None),
            (os.path.join(d, f"time_{obs.name}.dat"), "tempo", obs.tempo_code),
            (os.path.join(d, f"time.dat"), "tempo", obs.tempo_code),
        ]
        for path, fmt, site in site_files:
            if os.path.exists(path):
                chain.append(ClockFile.read(path, fmt=fmt, site_code=site))
                break
        gps = os.path.join(d, "gps2utc.clk")
        if chain and os.path.exists(gps):
            chain.append(ClockFile.read_tempo2(gps))
        if chain:
            break
    return chain
