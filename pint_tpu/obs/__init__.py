"""Observatory registry and site geometry.

Counterpart of the reference's observatory layer (reference:
src/pint/observatory/__init__.py:149-560, topo_obs.py) redesigned for the
host-ingest role: observatories resolve names/aliases/tempo codes, supply
clock-correction chains, and produce SSB posvels for TOA epochs.

Site coordinates are embedded (public ITRF values, same data the reference
ships in observatories.json); `$PINT_TPU_OBS` may point at a JSON file of
extra/override sites with entries {"name": {"itrf_xyz": [x,y,z],
"aliases": [...], "tempo_code": "1", "itoa_code": "GB"}}.
"""

from __future__ import annotations

import json
import os
import warnings

import numpy as np

from pint_tpu import C_M_PER_S
from pint_tpu.ephem import PosVel, get_ephemeris
from pint_tpu.obs.erot import gcrs_posvel_from_itrf


class Observatory:
    """Base observatory: named, alias-resolvable, clock-correctable."""

    _registry: dict = {}

    def __init__(self, name, aliases=(), tempo_code=None, itoa_code=None):
        self.name = name.lower()
        self.aliases = tuple(a.lower() for a in aliases)
        self.tempo_code = tempo_code
        self.itoa_code = itoa_code
        # re-registration (e.g. $PINT_TPU_OBS override of a builtin site)
        # must also retarget aliases/codes, or tim-file site codes would
        # keep resolving to the stale object — so no setdefault for keys
        # we own; only refuse to steal keys that belong to a *different*
        # observatory's primary name
        prior = Observatory._registry.get(self.name)
        Observatory._registry[self.name] = self
        for key in self.aliases + tuple(
            c.lower() for c in (tempo_code, itoa_code) if c
        ):
            holder = Observatory._registry.get(key)
            if holder is None or holder is prior or holder.name != key:
                Observatory._registry[key] = self

    # -- geometry ------------------------------------------------------------
    def posvel_ssb(self, ticks, ephem="builtin") -> PosVel:
        """Observatory posvel wrt SSB [ls, ls/s] at TDB ticks."""
        raise NotImplementedError

    def earth_location_itrf(self):
        return None

    #: True if TOAs from this site are already barycentric TDB
    is_barycenter = False

    # -- clock ---------------------------------------------------------------
    def clock_corrections_sec(self, utc_mjd_float):
        """Observatory->UTC clock corrections [s] (host ingest).

        Default: no clock chain (warn once).  TopoObs looks for clock
        files; see pint_tpu.obs.clock.
        """
        return np.zeros_like(np.asarray(utc_mjd_float, dtype=np.float64))


class TopoObs(Observatory):
    """Ground observatory at fixed ITRF coordinates."""

    def __init__(self, name, itrf_xyz, clock_files=(), **kw):
        super().__init__(name, **kw)
        self.itrf_xyz = np.asarray(itrf_xyz, dtype=np.float64)
        self.clock_files = tuple(clock_files)
        self._clock_chain = None
        self._warned_noclock = False

    def posvel_gcrs(self, ticks) -> PosVel:
        return gcrs_posvel_from_itrf(self.itrf_xyz, ticks)

    def posvel_ssb(self, ticks, ephem="builtin") -> PosVel:
        from pint_tpu.ephem import body_posvel_ssb

        earth = body_posvel_ssb("earth", ticks, ephem)
        site = self.posvel_gcrs(ticks)
        return PosVel(earth.pos + site.pos, earth.vel + site.vel)

    def clock_corrections_sec(self, utc_mjd_float):
        from pint_tpu.obs.clock import find_clock_chain

        if self._clock_chain is None:
            self._clock_chain = find_clock_chain(self)
        mjd = np.asarray(utc_mjd_float, dtype=np.float64)
        if not self._clock_chain:
            if not self._warned_noclock:
                warnings.warn(
                    f"no clock files found for observatory '{self.name}' "
                    "(searched $PINT_TPU_CLOCK_DIR and ./clock); assuming "
                    "perfect site clock (corrections ~ 0.1-1 us are being "
                    "dropped)"
                )
                self._warned_noclock = True
            return np.zeros_like(mjd)
        out = np.zeros_like(mjd)
        for cf in self._clock_chain:
            out += cf.evaluate_sec(mjd)
        return out


class BarycenterObs(Observatory):
    """TOAs already at the SSB in TDB ('@' / 'bat'); geometry is a no-op.
    (reference: special_locations.py:71)"""

    is_barycenter = True

    def posvel_ssb(self, ticks, ephem="builtin") -> PosVel:
        ticks = np.atleast_1d(ticks)
        z = np.zeros(ticks.shape + (3,))
        return PosVel(z, z.copy())


class GeocenterObs(Observatory):
    """TOAs referenced to the geocenter (reference: special_locations.py:117)."""

    def posvel_ssb(self, ticks, ephem="builtin") -> PosVel:
        from pint_tpu.ephem import body_posvel_ssb

        return body_posvel_ssb("earth", ticks, ephem)


class T2SpacecraftObs(Observatory):
    """Spacecraft with per-TOA GCRS position given by tempo2-convention
    TOA flags: -telx/-tely/-telz [km], -vx/-vy/-vz [km/s] (reference:
    special_locations.py:161).  No GPS/site clock chain is assumed."""

    #: TOAs passes per-TOA flag dicts into posvel_ssb
    needs_flags = True

    def clock_corrections_sec(self, utc_mjd_float):
        return np.zeros_like(np.asarray(utc_mjd_float, np.float64))

    def posvel_gcrs(self, ticks, flags):
        def col(key, what):
            try:
                return np.array([float(f[key]) for f in flags])
            except KeyError:
                raise ValueError(
                    f"TOA lines for '{self.name}' need -telx/-tely/-telz "
                    f"(GCRS km) and -vx/-vy/-vz (km/s) flags; missing "
                    f"-{key} ({what})")

        km = 1000.0 / C_M_PER_S  # km -> light-seconds
        pos = np.stack([col(k, "position") for k in
                        ("telx", "tely", "telz")], axis=-1) * km
        vel = np.stack([col(k, "velocity") for k in
                        ("vx", "vy", "vz")], axis=-1) * km
        return PosVel(pos, vel)

    def posvel_ssb(self, ticks, ephem="builtin", flags=None) -> PosVel:
        from pint_tpu.ephem import body_posvel_ssb

        if flags is None:
            raise ValueError(
                "T2SpacecraftObs needs the per-TOA flags to resolve its "
                "position")
        earth = body_posvel_ssb("earth", ticks, ephem)
        return earth + self.posvel_gcrs(ticks, flags)


def get_observatory(name) -> Observatory:
    """Resolve an observatory by name / alias / tempo code / ITOA code."""
    _ensure_builtin()
    key = str(name).strip().lower()
    obs = Observatory._registry.get(key)
    if obs is None:
        raise KeyError(
            f"unknown observatory {name!r}; known: "
            + ", ".join(sorted(k for k, v in Observatory._registry.items()
                               if k == v.name))
        )
    return obs


# --- builtin site table -----------------------------------------------------
# ITRF XYZ in meters (public geodetic data; values as the pulsar-timing
# community uses them, cf. reference observatories.json) + tempo one-char
# codes and two-char ITOA codes.

_BUILTIN_SITES = {
    "gbt": ([882589.289, -4924872.368, 3943729.418], "1", "GB", ()),
    # fake telescope for the IPTA data challenge (reference
    # observatories.json "AXIS", imported from TEMPO2 observatories.dat)
    "axis": ([6378138.0, 0.0, 0.0], None, None, ("axi",)),
    "quabbin": ([1430913.350, -4495711.384, 4278113.975], "2", "QU", ()),
    "arecibo": ([2390487.080, -5564731.357, 1994720.633], "3", "AO", ("aoutc",)),
    "hobart": ([-3950077.96, 2522377.31, -4311667.52], "4", "HO", ()),
    "princeton": ([1288748.38, -4694221.77, 4107418.80], "5", "PR", ()),
    "vla": ([-1601192.0, -5041981.4, 3554871.4], "6", "VL", ("jvla",)),
    "parkes": ([-4554231.5, 2816759.1, -3454036.3], "7", "PK", ("pks",)),
    "jodrell": ([3822625.769, -154105.255, 5086486.256], "8", "JB", ()),
    "gb300": ([881856.58, -4925311.86, 3943459.70], "9", "G3", ()),
    "gb140": ([882872.57, -4924552.73, 3944154.92], "a", "G1", ()),
    "gb853": ([882315.33, -4925191.41, 3943414.05], "b", "G8", ()),
    "most": ([-4483311.64, 2648815.92, -3671909.31], "e", "MO", ()),
    "nancay": ([4324165.81, 165927.11, 4670132.83], "f", "NC", ("ncy",)),
    "effelsberg": ([4033947.146, 486990.898, 4900431.067], "g", "EF", ("eff",)),
    "jb_mkii": ([3822846.76, -153802.28, 5086285.90], "h", "J2", ("jbmk2",)),
    "wsrt": ([3828445.659, 445223.600, 5064921.568], "i", "WS", ("we",)),
    "fast": ([-1668557.0, 5506838.0, 2744934.0], "k", "FA", ()),
    "meerkat": ([5109360.133, 2006852.586, -3238948.127], "m", "MK", ()),
    "gmrt": ([1656342.30, 5797947.77, 2073243.16], "r", "GM", ()),
    "shao": ([-2826711.951, 4679231.627, 3274665.675], "s", "SH", ()),
    "lofar": ([3826577.462, 461022.624, 5064892.526], "t", "LF", ()),
    "mwa": ([-2559454.08, 5095372.14, -2849057.18], "u", "MW", ()),
    "pico_veleta": ([5088964.0, -301689.8, 3825017.0], "v", "PV", ("pv",)),
    "lwa1": ([-1602196.60, -5042313.47, 3553971.51], "x", "LW", ()),
    "chime": ([-2059166.313, -3621302.972, 4814304.113], "y", "CH", ()),
    "srt": ([4865182.766, 791922.689, 4035137.174], "z", "SR", ()),
}

_builtin_loaded = False


def export_all_clock_files(directory):
    """Write every registered observatory's resolved clock chain into
    ``directory`` as tempo2-format files (reference:
    topo_obs.py:425 export_all_clock_files) — a reproducibility
    snapshot of the clock data a run actually used.  Returns the list
    of written paths."""
    import os

    from pint_tpu.obs.clock import ClockFile, find_clock_chain

    _ensure_builtin()
    os.makedirs(directory, exist_ok=True)
    written = []
    seen = set()
    for obs in Observatory._registry.values():
        if id(obs) in seen or not isinstance(obs, TopoObs):
            continue
        seen.add(id(obs))
        chain = find_clock_chain(obs)
        if not chain:
            continue
        # one merged site->UTC file per observatory, always tempo2
        # format under a .clk name so the snapshot re-reads correctly
        merged = chain[0] if len(chain) == 1 else ClockFile.merge(chain)
        out = os.path.join(directory, f"{obs.name}2utc.clk")
        merged.write_tempo2(
            out, hdr_from=obs.name.upper(), hdr_to="UTC",
            comments="exported by pint_tpu (merged chain: "
                     + ", ".join(c.name or "?" for c in chain) + ")")
        written.append(out)
    return written


def _ensure_builtin():
    global _builtin_loaded
    if _builtin_loaded:
        return
    _builtin_loaded = True
    for name, (xyz, tcode, icode, aliases) in _BUILTIN_SITES.items():
        TopoObs(name, xyz, tempo_code=tcode, itoa_code=icode, aliases=aliases)
    BarycenterObs("barycenter", aliases=("@", "bat", "ssb"))
    GeocenterObs("geocenter", aliases=("coe", "0"), itoa_code="GC")
    T2SpacecraftObs("stl_geo", aliases=("spacecraft", "stl"))
    override = os.environ.get("PINT_TPU_OBS")
    if override:
        with open(override) as f:
            extra = json.load(f)
        for name, spec in extra.items():
            TopoObs(
                name,
                spec["itrf_xyz"],
                aliases=tuple(spec.get("aliases", ())),
                tempo_code=spec.get("tempo_code"),
                itoa_code=spec.get("itoa_code"),
            )
