"""Orbiting observatories: spacecraft position from orbit FITS files.

Counterpart of the reference's satellite_obs.py (SatelliteObs at :283,
load_FPorbit/load_FT2/load_nustar_orbit): the photon pipeline
(photonphase/fermiphase) needs the spacecraft's GCRS position at each
event time.  Supported products:

- FPorbit (RXTE/NICER/NuSTAR-style): binary table ``ORBIT``/``XTE_PE``
  with Time (MET s, TT) and X/Y/Z [m] (+ optional Vx/Vy/Vz [m/s]);
- Fermi FT2: binary table ``SC_DATA`` with START and SC_POSITION [m]
  (velocities derived by differentiation, like the reference).

Positions are spline-interpolated (cubic, scipy) at the TOA epochs;
requests farther than ``maxextrap_min`` from the nearest tabulated
point are an error (reference maxextrap semantics)."""

from __future__ import annotations

import numpy as np

from pint_tpu import C_M_PER_S
from pint_tpu.ephem import PosVel, body_posvel_ssb
from pint_tpu.fits import read_fits
from pint_tpu.obs import Observatory
from pint_tpu.time.scales import tdb_minus_tt_seconds

_MJD_J2000 = 51544.5


def _mjdref_days(header):
    if "MJDREFI" in header:
        return float(header["MJDREFI"]) + float(header.get("MJDREFF", 0.0))
    return float(header.get("MJDREF", 0.0))


def load_orbit(path):
    """(mjd_tt, pos_m (n,3), vel_mps (n,3)) from an FPorbit or FT2
    file (reference: load_FPorbit satellite_obs.py, load_FT2)."""
    hdus = read_fits(path)
    orbit = None
    for h in hdus[1:]:
        if h.name.upper() in ("ORBIT", "XTE_PE", "SC_DATA", "PREFILTER"):
            orbit = h
            break
    if orbit is None and len(hdus) > 1 and hdus[1].data:
        orbit = hdus[1]
    if orbit is None or not orbit.data:
        raise ValueError(f"{path}: no orbit table found")
    hdr = orbit.header
    ref = _mjdref_days(hdr)
    tz = float(hdr.get("TIMEZERO", 0.0))
    cols = {k.upper(): k for k in orbit.data}
    if "SC_POSITION" in cols:  # Fermi FT2
        t = np.asarray(orbit.data[cols["START"]], np.float64)
        pos = np.asarray(orbit.data[cols["SC_POSITION"]], np.float64)
        mjd_tt = ref + (t + tz) / 86400.0
        # FT2 has no velocity columns: differentiate (reference does
        # the same for FT2 products)
        tsec = (mjd_tt - mjd_tt[0]) * 86400.0
        vel = np.gradient(pos, tsec, axis=0)
    else:
        t = np.asarray(orbit.data[cols["TIME"]], np.float64)
        pos = np.stack([np.asarray(orbit.data[cols[c]], np.float64)
                        for c in ("X", "Y", "Z")], axis=1)
        mjd_tt = ref + (t + tz) / 86400.0
        if "VX" in cols:
            vel = np.stack([np.asarray(orbit.data[cols[c]], np.float64)
                            for c in ("VX", "VY", "VZ")], axis=1)
        else:
            tsec = (mjd_tt - mjd_tt[0]) * 86400.0
            vel = np.gradient(pos, tsec, axis=0)
    order = np.argsort(mjd_tt, kind="stable")
    return mjd_tt[order], pos[order], vel[order]


class SatelliteObs(Observatory):
    """An orbiting observatory (reference SatelliteObs,
    satellite_obs.py:283).  Event times are TT at the spacecraft."""

    is_barycenter = False

    def __init__(self, name, orbit_file, maxextrap_min=2.0, aliases=(),
                 **kw):
        super().__init__(name, aliases=aliases, **kw)
        self.orbit_file = orbit_file
        mjd_tt, pos, vel = load_orbit(orbit_file)
        self._mjd_tt = mjd_tt
        from scipy.interpolate import InterpolatedUnivariateSpline

        self._splines = [
            InterpolatedUnivariateSpline(mjd_tt, pos[:, i],
                                         ext="extrapolate")
            for i in range(3)
        ]
        self._vsplines = [
            InterpolatedUnivariateSpline(mjd_tt, vel[:, i],
                                         ext="extrapolate")
            for i in range(3)
        ]
        self.maxextrap_min = maxextrap_min

    def _check_bounds(self, mjd_tt):
        """Reject epochs farther than maxextrap from tabulated points
        (reference _check_bounds, satellite_obs.py:341)."""
        idx = np.clip(np.searchsorted(self._mjd_tt, mjd_tt), 1,
                      len(self._mjd_tt) - 1)
        near = np.minimum(np.abs(mjd_tt - self._mjd_tt[idx - 1]),
                          np.abs(self._mjd_tt[idx] - mjd_tt))
        worst = float(np.max(near)) * 1440.0
        if worst > self.maxextrap_min:
            raise ValueError(
                f"satellite {self.name}: epochs up to {worst:.2f} min "
                f"from the nearest orbit point (> maxextrap "
                f"{self.maxextrap_min} min) — supply a matching orbit "
                "file")

    def posvel_gcrs(self, ticks):
        tdb_sec = np.atleast_1d(np.asarray(ticks)).astype(np.float64) \
            / 2**32
        tt_sec = tdb_sec - tdb_minus_tt_seconds(tdb_sec)
        mjd_tt = _MJD_J2000 + tt_sec / 86400.0
        self._check_bounds(mjd_tt)
        pos = np.stack([s(mjd_tt) for s in self._splines], axis=-1)
        vel = np.stack([s(mjd_tt) for s in self._vsplines], axis=-1)
        return PosVel(pos / C_M_PER_S, vel / C_M_PER_S)

    def posvel_ssb(self, ticks, ephem="builtin") -> PosVel:
        earth = body_posvel_ssb("earth", ticks, ephem)
        return earth + self.posvel_gcrs(ticks)


def get_satellite_observatory(name, orbit_file, overwrite=True, **kw):
    """Create + register an orbiting observatory (reference:
    get_satellite_observatory, satellite_obs.py)."""
    from pint_tpu.obs import Observatory

    key = str(name).lower()
    if not overwrite and key in Observatory._registry:
        raise ValueError(f"observatory {name} already registered")
    return SatelliteObs(key, orbit_file, **kw)
