"""Request-scoped trace context for the serve plane.

The process-scoped telemetry of :mod:`pint_tpu.telemetry` answers
"what did this replica do"; this module answers "where did request X's
11 ms go" — which is non-trivial precisely because the serve plane
coalesces many requests into ONE batched device call.  The design:

- A W3C-style ``traceparent`` id (``00-<32 hex>-<16 hex>-01``) is
  **minted at admission** — or accepted from the client's
  ``traceparent`` header, so a caller that already lives inside a
  distributed trace keeps its id — and carried on the
  :class:`~pint_tpu.serve.state.Request` through batcher → flush →
  batched dispatch → response.
- The batched device call is recorded as ONE shared span
  (``serve.batch.device``) whose ``links`` list names every member
  request's ``(trace, span)``; each member emits its own request span
  linking back to the device span id.  A coalesced batch is therefore
  reconstructable as a tree: 1 device span fanning into N request
  spans (``pinttrace --chrome-trace`` draws the fan-out as flow
  arrows).
- Every 2xx response carries the ``traceparent`` plus a
  ``Server-Timing`` phase decomposition (queue wait, coalesce hold,
  stack/build, device, write-back) so the latency budget is
  client-visible without touching the sink.

Trace ids are **host-only** bookkeeping: they ride request objects
and response headers, never enter a traced program, and cannot change
any compiled shape — the zero-recompile contract is untouched.

Span records land in the JSONL sink via
:func:`pint_tpu.telemetry.emit_group` so one flush's device span and
its member request spans are written atomically: rotation can only
happen at a group boundary, never between a batch's begin and its
members (``--chrome-trace`` never sees a dangling track).
"""

from __future__ import annotations

import os
import re
import threading
import time

from pint_tpu import telemetry

__all__ = [
    "TraceContext", "from_headers", "mint", "new_span_id",
    "parse_traceparent",
    "server_timing", "response_headers", "device_span_record",
    "request_span_record", "collect_programs", "note_program",
]

#: ``version-traceid-spanid-flags``; only version 00 is emitted, any
#: parseable version is accepted (W3C forward-compat rule).
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

#: Server-Timing phase order (decomposition of a request's wall time).
PHASES = ("queue", "coalesce", "build", "device", "writeback")


def _hex(nbytes):
    return os.urandom(nbytes).hex()


class TraceContext:
    """One request's position in a trace: the 128-bit trace id shared
    by every span of the request's story, this hop's 64-bit span id,
    and the parent span id when the caller supplied one."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id, span_id=None, parent_id=None):
        self.trace_id = trace_id
        self.span_id = span_id or _hex(8)
        self.parent_id = parent_id

    def traceparent(self) -> str:
        """The W3C serialization carried on the response header."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_doc(self) -> dict:
        """The JSON-facing form riding result records."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "traceparent": self.traceparent()}

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"TraceContext({self.traceparent()!r})"


def new_span_id() -> str:
    """A fresh 64-bit span id (the shared device span of a batch)."""
    return _hex(8)


def mint() -> TraceContext:
    """A fresh root context (no client traceparent)."""
    telemetry.counter_add("obs.traces_minted")
    return TraceContext(_hex(16))


def parse_traceparent(value):
    """``(trace_id, span_id)`` from a traceparent header, or ``None``
    when malformed (malformed headers mint a fresh trace rather than
    poisoning the sink with unparseable ids)."""
    m = _TRACEPARENT_RE.match(str(value or "").strip().lower())
    if not m:
        return None
    trace_id, span_id = m.group(2), m.group(3)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # the all-zero ids are invalid per spec
    return trace_id, span_id


def from_headers(headers) -> TraceContext:
    """The admission-time context: continue the client's trace when a
    valid ``traceparent`` header is present (its span id becomes our
    parent), else mint a root.  ``headers`` is the lowercase-keyed
    dict the HTTP layer parsed."""
    parsed = parse_traceparent((headers or {}).get("traceparent"))
    if parsed is None:
        return mint()
    trace_id, parent_span = parsed
    telemetry.counter_add("obs.traces_continued")
    return TraceContext(trace_id, parent_id=parent_span)


# -- response decoration ----------------------------------------------------

def server_timing(phase_s) -> str:
    """The ``Server-Timing`` header value for one request's phase
    decomposition (durations in ms, W3C ``name;dur=`` syntax)."""
    parts = []
    for name in PHASES:
        if name in (phase_s or {}):
            parts.append(f"{name};dur={phase_s[name] * 1e3:.3f}")
    return ", ".join(parts)


def response_headers(doc):
    """Extra response headers for a result doc that carries trace
    and/or phase decoration; empty list otherwise."""
    extra = []
    trace = (doc or {}).get("trace")
    if isinstance(trace, dict) and trace.get("traceparent"):
        extra.append(("traceparent", trace["traceparent"]))
    timing = server_timing((doc or {}).get("phase_s"))
    if timing:
        extra.append(("Server-Timing", timing))
    return extra


# -- span records -----------------------------------------------------------

def device_span_record(span_id, ts, dur_s, links, **attrs) -> dict:
    """The ONE shared span of a batched device call.  ``links`` names
    every member request's ``{"trace", "span"}`` so the fan-out is
    reconstructable; the record carries no trace id of its own (it
    belongs to N traces at once)."""
    rec = {"type": "trace_span", "name": "serve.batch.device",
           "span": span_id, "ts": ts, "dur_s": dur_s,
           "links": list(links)}
    rec.update(attrs)
    return rec


def request_span_record(ctx, ts, dur_s, device_span, phase_s,
                        **attrs) -> dict:
    """One member request's span: its own (trace, span, parent) plus
    a link back to the shared device span it rode."""
    rec = {"type": "trace_span", "name": "serve.request",
           "trace": ctx.trace_id, "span": ctx.span_id,
           "ts": ts, "dur_s": dur_s,
           "links": [{"span": device_span}],
           "phase_s": dict(phase_s or {})}
    if ctx.parent_id:
        rec["parent"] = ctx.parent_id
    rec.update(attrs)
    return rec


# -- profiler join ----------------------------------------------------------
# dispatch_batch brackets its device phase in collect_programs(); the
# profiling proxy notes each program label it dispatches (hook
# registered below — profiling cannot import this module, the obs
# package initializer imports back from pint_tpu).  The device span
# then names the programs that actually ran for the batch.

_tls = threading.local()


def note_program(label):
    """Record one dispatched program label into the active collection
    scope (no-op outside one — a single thread-local read)."""
    sink = getattr(_tls, "programs", None)
    if sink is not None and label not in sink:
        sink.append(label)


class collect_programs:
    """Context manager collecting program labels dispatched on THIS
    thread; ``.labels`` holds them after exit."""

    def __init__(self):
        self.labels = []

    def __enter__(self):
        self._prev = getattr(_tls, "programs", None)
        _tls.programs = self.labels
        return self

    def __exit__(self, *exc):
        _tls.programs = self._prev
        return False


def _install_profiler_hook():
    try:
        from pint_tpu import profiling
        profiling.set_trace_hook(note_program)
    except Exception:  # pragma: no cover - profiling always importable
        pass


_install_profiler_hook()


def now_pair():
    """``(wall, perf)`` clock pair — span records carry wall-clock
    ``ts`` (joinable across replicas) while durations come from the
    monotonic clock."""
    return time.time(), time.perf_counter()
