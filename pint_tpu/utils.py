"""Statistical helpers: F-test, information criteria, weighted means,
Taylor-Horner evaluation.

Counterpart of the reference's utils grab-bag statistics (reference:
src/pint/utils.py:2123 ``FTest``, :2912 ``akaike_information_
criterion``, :2967 ``bayesian_information_criterion``, :2002
``weighted_mean``, :419 ``taylor_horner``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["FTest", "akaike_information_criterion",
           "bayesian_information_criterion", "weighted_mean",
           "taylor_horner", "taylor_horner_deriv"]


def FTest(chi2_simple, dof_simple, chi2_complex, dof_complex):
    """Probability that the chi^2 improvement of the more-complex model
    is by chance (reference utils.FTest): small values favor keeping
    the extra parameters.  Returns NaN if the complex model is not an
    improvement in reduced terms."""
    from scipy.stats import f as fdist

    delta_chi2 = chi2_simple - chi2_complex
    delta_dof = dof_simple - dof_complex
    if delta_dof <= 0 or dof_complex <= 0:
        raise ValueError("complex model must have fewer dof")
    if delta_chi2 <= 0:
        return 1.0
    F = (delta_chi2 / delta_dof) / (chi2_complex / dof_complex)
    return float(fdist.sf(F, delta_dof, dof_complex))


def akaike_information_criterion(lnlike, n_params):
    """AIC = 2k - 2 lnL (reference utils.py:2912)."""
    return 2.0 * n_params - 2.0 * lnlike


def bayesian_information_criterion(lnlike, n_params, n_data):
    """BIC = k ln N - 2 lnL (reference utils.py:2967)."""
    return n_params * np.log(n_data) - 2.0 * lnlike


def weighted_mean(data, errors=None, sdev=False):
    """(mean, error_on_mean[, weighted stdev]) with 1/sigma^2 weights
    (reference utils.weighted_mean)."""
    data = np.asarray(data, dtype=np.float64)
    if errors is None:
        w = np.ones_like(data)
    else:
        w = 1.0 / np.asarray(errors, dtype=np.float64) ** 2
    wsum = w.sum()
    mean = np.sum(data * w) / wsum
    err = np.sqrt(1.0 / wsum)
    if not sdev:
        return mean, err
    var = np.sum(w * (data - mean) ** 2) / wsum
    return mean, err, np.sqrt(var)


def taylor_horner(x, coeffs):
    """sum_k c_k x^k / k! by Horner's rule (reference
    utils.taylor_horner: taylor_horner(2.0, [10,3,4,12]) = 40.0)."""
    out = 0.0
    fact = float(len(coeffs))
    for c in coeffs[::-1]:
        out = out * x / fact + c
        fact -= 1.0
    return out


def taylor_horner_deriv(x, coeffs, deriv_order=1):
    """deriv_order-th derivative of taylor_horner (reference
    utils.taylor_horner_deriv)."""
    if deriv_order == 0:
        return taylor_horner(x, coeffs)
    return taylor_horner(x, list(coeffs[deriv_order:]))
