"""Statistical helpers: F-test, information criteria, weighted means,
Taylor-Horner evaluation.

Counterpart of the reference's utils grab-bag statistics (reference:
src/pint/utils.py:2123 ``FTest``, :2912 ``akaike_information_
criterion``, :2967 ``bayesian_information_criterion``, :2002
``weighted_mean``, :419 ``taylor_horner``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["FTest", "akaike_information_criterion",
           "bayesian_information_criterion", "weighted_mean",
           "taylor_horner", "taylor_horner_deriv", "info_string"]


def info_string(prefix_string="# ", comment=None, detailed=False):
    """Provenance string for output files: creation date, package
    version, user, host, OS (reference: utils.py:2314 info_string;
    gitpython/astropy extras replaced by the stdlib equivalents)."""
    import datetime
    import getpass
    import platform

    try:
        user = getpass.getuser()
    except (KeyError, OSError):  # no passwd entry (containers/CI)
        user = "unknown"
    lines = [
        f"Created: {datetime.datetime.now().isoformat()}",
        "pint_tpu: 0.1.0",
        f"User: {user}",
        f"Host: {platform.node()}",
        f"OS: {platform.platform()}",
    ]
    if detailed:
        import sys

        import jax

        lines += [f"Python: {sys.version.split()[0]}",
                  f"jax: {jax.__version__}",
                  f"numpy: {np.__version__}",
                  f"backend: {jax.default_backend()}"]
    if comment:
        lines += [f"Comment: {c}" for c in str(comment).splitlines()]
    return "\n".join(prefix_string + ln for ln in lines)


def FTest(chi2_simple, dof_simple, chi2_complex, dof_complex):
    """Probability that the chi^2 improvement of the more-complex model
    is by chance (reference utils.FTest): small values favor keeping
    the extra parameters.  Returns NaN if the complex model is not an
    improvement in reduced terms."""
    from scipy.stats import f as fdist

    delta_chi2 = chi2_simple - chi2_complex
    delta_dof = dof_simple - dof_complex
    if delta_dof <= 0 or dof_complex <= 0:
        raise ValueError("complex model must have fewer dof")
    if delta_chi2 <= 0:
        return 1.0
    F = (delta_chi2 / delta_dof) / (chi2_complex / dof_complex)
    return float(fdist.sf(F, delta_dof, dof_complex))


def akaike_information_criterion(lnlike, n_params):
    """AIC = 2k - 2 lnL (reference utils.py:2912)."""
    return 2.0 * n_params - 2.0 * lnlike


def bayesian_information_criterion(lnlike, n_params, n_data):
    """BIC = k ln N - 2 lnL (reference utils.py:2967)."""
    return n_params * np.log(n_data) - 2.0 * lnlike


def weighted_mean(data, errors=None, sdev=False):
    """(mean, error_on_mean[, weighted stdev]) with 1/sigma^2 weights
    (reference utils.weighted_mean)."""
    data = np.asarray(data, dtype=np.float64)
    if errors is None:
        w = np.ones_like(data)
    else:
        w = 1.0 / np.asarray(errors, dtype=np.float64) ** 2
    wsum = w.sum()
    mean = np.sum(data * w) / wsum
    err = np.sqrt(1.0 / wsum)
    if not sdev:
        return mean, err
    var = np.sum(w * (data - mean) ** 2) / wsum
    return mean, err, np.sqrt(var)


def taylor_horner(x, coeffs):
    """sum_k c_k x^k / k! by Horner's rule (reference
    utils.taylor_horner: taylor_horner(2.0, [10,3,4,12]) = 40.0)."""
    out = 0.0
    fact = float(len(coeffs))
    for c in coeffs[::-1]:
        out = out * x / fact + c
        fact -= 1.0
    return out


def taylor_horner_deriv(x, coeffs, deriv_order=1):
    """deriv_order-th derivative of taylor_horner (reference
    utils.taylor_horner_deriv)."""
    if deriv_order == 0:
        return taylor_horner(x, coeffs)
    return taylor_horner(x, list(coeffs[deriv_order:]))


# --- DMX helpers (reference: utils.py:786 dmx_ranges, :1083 dmxparse) ------

def dmx_ranges(toas, max_width_days=15.0, min_toas=1):
    """Construct DMX bin edges covering the TOAs (reference
    utils.py:786): greedy left-to-right windows of at most
    ``max_width_days`` containing at least ``min_toas`` TOAs.

    Returns a list of (mjd_lo, mjd_hi) pairs."""
    mjds = np.sort(np.asarray(toas.mjd_float, dtype=np.float64))
    ranges = []
    i = 0
    while i < len(mjds):
        lo = mjds[i]
        j = i
        while j + 1 < len(mjds) and mjds[j + 1] - lo <= max_width_days:
            j += 1
        if (j - i + 1) >= min_toas:
            ranges.append((lo - 1e-3, mjds[j] + 1e-3))
        i = j + 1
    return ranges


def add_dmx_ranges(model, ranges):
    """Attach a DispersionDMX component (or extend it) with the given
    (mjd_lo, mjd_hi) ranges; DMX_#### start at zero, free."""
    from pint_tpu.models.dispersion import DispersionDMX

    old_params = {}
    if model.has_component("DispersionDMX"):
        comp = model.component("DispersionDMX")
        old_params = {p.name: p for p in comp.params}
        start = max(comp.indices, default=0) + 1
        idx = list(comp.indices) + list(
            range(start, start + len(ranges)))
        model.remove_component("DispersionDMX")
    else:
        start = 1
        idx = list(range(1, 1 + len(ranges)))
    comp = DispersionDMX(indices=idx)
    # rebuilding must not silently freeze previously-free DMX bins or
    # drop their fitted uncertainties: carry the old Param state over
    for p in comp.params:
        old = old_params.get(p.name)
        if old is not None:
            p.frozen = old.frozen
            p.uncertainty = old.uncertainty
    model.add_component(comp)
    for k, (lo, hi) in enumerate(ranges, start=start):
        model.values[f"DMX_{k:04d}"] = 0.0
        model.values[f"DMXR1_{k:04d}"] = (lo - 51544.5) * 86400.0
        model.values[f"DMXR2_{k:04d}"] = (hi - 51544.5) * 86400.0
        model.params[f"DMX_{k:04d}"].frozen = False
    return model


def dmxparse(fitter):
    """Summarize fitted DMX values (reference: utils.py:1083 dmxparse):
    {dmxs, dmx_verrs, dmxeps (MJD mid), r1s, r2s, dmx_mean,
    dmx_mean_sub} with the weighted mean subtracted in dmx_mean_sub."""
    model = fitter.model
    comp = model.component("DispersionDMX")
    idx = sorted(comp.indices)
    vals = np.array([model.values[f"DMX_{i:04d}"] for i in idx])
    errs = np.array([
        model.params[f"DMX_{i:04d}"].uncertainty or np.nan for i in idx
    ])
    r1 = np.array([model.values[f"DMXR1_{i:04d}"] for i in idx])
    r2 = np.array([model.values[f"DMXR2_{i:04d}"] for i in idx])
    w = 1.0 / np.where(np.isfinite(errs) & (errs > 0), errs, np.inf)**2
    mean = (np.sum(vals * w) / np.sum(w)) if np.any(w > 0) else vals.mean()
    return {
        "dmxs": vals,
        "dmx_verrs": errs,
        "dmxeps": 51544.5 + (r1 + r2) / 2.0 / 86400.0,
        "r1s": 51544.5 + r1 / 86400.0,
        "r2s": 51544.5 + r2 / 86400.0,
        "dmx_mean": float(mean),
        "dmx_mean_sub": vals - mean,
    }


# --- WaveX setup/translation helpers (reference: utils.py:1457-2001) -------

def wavex_setup(model, t_span_days, n_freqs, family="WX"):
    """Attach a WaveX-family component with n_freqs harmonics of
    1/t_span (reference wavex_setup/dmwavex_setup): WXFREQ_000k set,
    WXSIN/WXCOS zeroed and free.  family: WX | DMWX | CMWX."""
    from pint_tpu.models.wavex import CMWaveX, DMWaveX, WaveX

    cls = {"WX": WaveX, "DMWX": DMWaveX, "CMWX": CMWaveX}[family]
    if model.has_component(cls.__name__):
        raise ValueError(f"{cls.__name__} already present")
    base_f = 1.0 / t_span_days  # WaveX freqs are 1/day
    comp = cls(indices=tuple(range(1, n_freqs + 1)))
    model.add_component(comp)
    for k in range(1, n_freqs + 1):
        model.values[f"{family}FREQ_{k:04d}"] = k * base_f
        model.values[f"{family}SIN_{k:04d}"] = 0.0
        model.values[f"{family}COS_{k:04d}"] = 0.0
        model.params[f"{family}SIN_{k:04d}"].frozen = False
        model.params[f"{family}COS_{k:04d}"].frozen = False
    return model


def translate_wave_to_wavex(model):
    """Convert a legacy Wave component to WaveX (reference:
    utils.py translate_wave_to_wavex): WAVEkA/WAVEkB sinusoids at
    k*WAVE_OM become WXSIN/WXCOS terms.

    Wave is a *phase* component (turns); WaveX is an achromatic delay
    [s]: delay = phase / F0, and the sine/cosine roles map directly."""
    from pint_tpu.models.wave import Wave

    wave = model.component("Wave")
    om = float(model.values["WAVE_OM"])  # rad/day
    n = wave.num_terms
    epoch = model.values.get("WAVEEPOCH", np.nan)
    if epoch != epoch:
        epoch = model.values.get("PEPOCH", 0.0)
    model.remove_component("Wave")
    from pint_tpu.models.wavex import WaveX

    comp = WaveX(indices=tuple(range(1, n + 1)))
    model.add_component(comp)
    # matching epochs makes the translation exact (both series use
    # tau = t - epoch): freq_k = k*WAVE_OM/(2 pi) [1/day]
    model.values["WXEPOCH"] = epoch
    for k in range(1, n + 1):
        a = float(model.values.get(f"WAVE{k}A", 0.0))
        b = float(model.values.get(f"WAVE{k}B", 0.0))
        model.values[f"WXFREQ_{k:04d}"] = k * om / (2.0 * np.pi)
        # wave PHASE = F0*(a sin + b cos); a WaveX DELAY d contributes
        # phase -F0*d, so the amplitudes flip sign
        model.values[f"WXSIN_{k:04d}"] = -a
        model.values[f"WXCOS_{k:04d}"] = -b
        model.values.pop(f"WAVE{k}A", None)
        model.values.pop(f"WAVE{k}B", None)
    model.values.pop("WAVE_OM", None)
    return model
