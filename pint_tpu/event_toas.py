"""Photon-event FITS files -> TOAs.

Counterpart of the reference event_toas module (reference:
src/pint/event_toas.py:1-721 ``get_NICER_TOAs`` etc., per-mission
default uncertainties; src/pint/fermi_toas.py:1-332 ``load_Fermi_TOAs``
with photon weights), on the pure-numpy FITS reader
(:mod:`pint_tpu.fits`).

Supported time systems: barycentered events (TIMESYS=TDB,
TIMEREF=SOLARSYSTEM -> observatory '@') and geocentric TT/UTC events
(-> 'geocenter' with a ``-timescale`` flag the TOA pipeline honors).
Spacecraft orbit-file interpolation is not implemented — barycenter
your events (e.g. with barycorr) first, as the reference's photonphase
also recommends for absolute timing.
"""

from __future__ import annotations

import warnings

import numpy as np

from pint_tpu.fits import read_events
from pint_tpu.toa import TOA, TOAs

__all__ = ["load_event_TOAs", "load_fits_TOAs", "get_NICER_TOAs",
           "get_RXTE_TOAs", "get_NuSTAR_TOAs", "get_XMM_TOAs",
           "get_Swift_TOAs", "get_IXPE_TOAs", "load_Fermi_TOAs"]

#: per-mission default TOA uncertainty [us] (reference event_toas.py
#: mission tables)
_MISSION_ERR_US = {
    "nicer": 0.1, "rxte": 2.5, "nustar": 65.0, "xmm": 30.0,
    "swift": 300.0, "ixpe": 100.0, "fermi": 1.0,
}


def _pi_to_kev(mission, pi):
    """Mission-specific PI-channel -> keV conversion (reference:
    event_toas.py per-mission tables)."""
    m = mission.lower()
    if m == "nicer":
        return pi * 0.010  # 10 eV channels
    if m == "xmm":
        return pi * 0.001  # 1 eV channels
    if m == "nustar":
        return pi * 0.040 + 1.6
    if m == "swift":
        return pi * 0.010
    raise ValueError(
        f"no PI->keV conversion known for mission {mission!r}; filter "
        "the events by energy before loading"
    )


def mjdref_from_header(header):
    """(integer MJD, fractional day) reference epoch from an event-FITS
    header (MJDREFI/MJDREFF or combined MJDREF)."""
    if "MJDREFI" in header:
        return int(header["MJDREFI"]), float(header.get("MJDREFF", 0.0))
    ref = float(header.get("MJDREF", 0.0))
    return int(ref), ref - int(ref)


_mjdref = mjdref_from_header  # internal callers


#: missions whose event extension is not named EVENTS
_MISSION_EXTNAME = {"rxte": "XTE_SE"}


def load_event_TOAs(path, mission, weights=None, extname=None,
                    energy_range_kev=None, errors_us=None,
                    ephem="builtin", planets=False, orbfile=None):
    """Read photon events into a TOAs object.

    weights: None | array | column name (e.g. Fermi 'WEIGHT'); stored as
    ``-weight`` flags for the photon-likelihood fitters.
    orbfile: FPorbit/FT2 spacecraft orbit file — registers an orbiting
    observatory (reference satellite_obs.py) so spacecraft-local event
    times use real orbital geometry instead of the geocenter.
    """
    if extname is None:
        extname = _MISSION_EXTNAME.get(mission.lower(), "EVENTS")
    header, data = read_events(path, extname=extname)
    time = np.asarray(data["TIME"], dtype=np.float64)
    timezero = float(header.get("TIMEZERO", 0.0))
    refi, reff = _mjdref(header)
    timesys = str(header.get("TIMESYS", "TT")).strip().upper()
    timeref = str(header.get("TIMEREF", "LOCAL")).strip().upper()
    if timeref in ("SOLARSYSTEM", "SSB"):
        obs = "@"
        scale = "tdb"
    elif timeref in ("GEOCENTRIC", "GEOCENTER"):
        obs = "geocenter"
        scale = timesys.lower()
    elif orbfile is not None:
        from pint_tpu.obs.satellite import get_satellite_observatory

        get_satellite_observatory(mission, orbfile)
        obs = mission.lower()
        scale = timesys.lower()
    else:
        warnings.warn(
            f"event file TIMEREF={timeref!r} (spacecraft-local times); "
            "treating as geocentric — pass an orbit file (orbfile=/"
            "--orbfile) or barycenter the events for absolute timing"
        )
        obs = "geocenter"
        scale = timesys.lower()

    if energy_range_kev is not None:
        if "PI" not in data:
            raise KeyError("energy_range_kev needs a PI column")
        kev = _pi_to_kev(mission, np.asarray(data["PI"], np.float64))
        lo, hi = energy_range_kev
        keep = (kev >= lo) & (kev <= hi)
    else:
        keep = np.ones(len(time), dtype=bool)

    if isinstance(weights, str):
        weights = np.asarray(data[weights], dtype=np.float64)
    err_us = errors_us if errors_us is not None else \
        _MISSION_ERR_US.get(mission.lower(), 1.0)

    toa_list = []
    widx = np.flatnonzero(keep)
    for j, t in enumerate(time[keep]):
        day_extra, ns = met_to_day_ns(reff, float(t), timezero)
        flags = {"timescale": scale, "mission": mission}
        if weights is not None:
            flags["weight"] = repr(float(weights[widx[j]]))
        toa_list.append(
            TOA(refi + int(day_extra), ns, 86400 * 10**9,
                err_us, 0.0, obs, flags, mission)
        )
    out = TOAs(toa_list, ephem=ephem, planets=planets,
               include_clock=False)
    # original FITS row index per kept TOA, so downstream writers
    # (photonphase/fermiphase --outfile) can index the raw event table
    # without assuming this loader kept every row in order
    out.fits_rows = widx
    return out


def met_to_day_ns(reff: float, t: float, timezero: float = 0.0):
    """(extra_days, ns_of_day) for MET second ``t`` past MJDREF
    fraction ``reff``, at sub-ns resolution.

    Never forms a ~1e18 ns value in float64 (2^53 quantizes that to
    ~128 ns): each addend is split into (integer, fractional) seconds
    with divmod so every float that gets scaled to ns stays well inside
    the exact-integer f64 range."""
    ref_ns = int(round(reff * 86400.0 * 1e9))
    tz_int, tz_frac = divmod(float(timezero), 1.0)
    t_int, t_frac = divmod(float(t), 1.0)
    total_ns = (
        ref_ns
        + (int(t_int) + int(tz_int)) * 10**9
        + int(round((t_frac + tz_frac) * 1e9))
    )
    return divmod(total_ns, 86400 * 10**9)


def load_fits_TOAs(path, mission="generic", **kw):
    return load_event_TOAs(path, mission, **kw)


def get_NICER_TOAs(path, **kw):
    return load_event_TOAs(path, "nicer", **kw)


def get_RXTE_TOAs(path, **kw):
    return load_event_TOAs(path, "rxte", **kw)


def get_NuSTAR_TOAs(path, **kw):
    return load_event_TOAs(path, "nustar", **kw)


def get_XMM_TOAs(path, **kw):
    return load_event_TOAs(path, "xmm", **kw)


def get_Swift_TOAs(path, **kw):
    return load_event_TOAs(path, "swift", **kw)


def get_IXPE_TOAs(path, **kw):
    return load_event_TOAs(path, "ixpe", **kw)


def load_Fermi_TOAs(path, weightcolumn="WEIGHT", **kw):
    """Fermi LAT photons with weights (reference fermi_toas.py).
    A missing weight column degrades to unweighted photons LOUDLY — a
    typo'd column name must not silently drop the weighting."""
    try:
        return load_event_TOAs(path, "fermi", weights=weightcolumn, **kw)
    except KeyError:
        warnings.warn(
            f"weight column {weightcolumn!r} not found in {path}; "
            "loading UNWEIGHTED photons")
        return load_event_TOAs(path, "fermi", **kw)
