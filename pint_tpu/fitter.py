"""Fitters: WLS (SVD) and GLS (Woodbury) Gauss-Newton on device.

Counterpart of the reference fitter layer (reference: src/pint/fitter.py:
185 base, :252 ``Fitter.auto``, :1940-2087 WLSFitter, :2090-2289
GLSFitter).  The reference's per-iteration recipe — design matrix,
whiten, column-normalize, solve, parameter step, covariance — becomes
one jitted function of the free-parameter vector; the design matrix is
``jax.jacfwd`` of the residual function (the reference's 124-s
hand-derivative hot spot, profiling/README.txt:58, disappears by
construction).

``Fitter.auto`` mirrors the reference's dispatch (fitter.py:252): GLS
when the model has correlated noise, WLS otherwise.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import flops as _flops
from pint_tpu import guard as _guard
from pint_tpu import telemetry
from pint_tpu.linalg import gls_normal_solve
from pint_tpu.residuals import Residuals, WidebandTOAResiduals
from pint_tpu.telemetry import span

__all__ = ["WLSFitter", "GLSFitter", "WidebandTOAFitter", "Fitter",
           "wls_gn_solve"]

# compile events fire during the first fit_toas; the jax.monitoring
# listener must exist before then for jit.compile_* counters to tick
telemetry._install_compile_listener()


def wls_gn_solve(resid_fn, vec, err, threshold=1e-14, rcond=None,
                 with_health=False):
    """One whitened, column-normalized SVD Gauss-Newton step.

    The shared numerical core of WLSFitter and the vmapped grid (one
    implementation, one threshold).  resid_fn(vec) -> residuals [s].
    Returns (new_vec, chi2_before, dpar, covariance).

    rcond: optional traced scalar raising the singular-value cutoff
    above ``threshold`` (the guard ladder's escalation — dynamic, so
    it costs zero new compiles).  with_health: additionally return a
    :class:`pint_tpu.guard.SolveDiag` from the SVD spectrum already in
    hand.
    """
    r = resid_fn(vec)
    J = jax.jacfwd(resid_fn)(vec)  # (N, P) d resid / d param
    w = 1.0 / err
    rw = r * w
    Jw = J * w[:, None]
    # column normalize (reference: utils.normalize_designmatrix)
    norms = jnp.sqrt(jnp.sum(Jw * Jw, axis=0))
    norms = jnp.where(norms == 0, 1.0, norms)
    Jn = Jw / norms[None, :]
    U, s, Vt = jnp.linalg.svd(Jn, full_matrices=False)
    smax = jnp.max(s)
    cut = threshold if rcond is None else jnp.maximum(threshold, rcond)
    s_inv = jnp.where(s > cut * smax, 1.0 / s, 0.0)
    dpar_n = -(Vt.T * s_inv[None, :]) @ (U.T @ rw)
    dpar = dpar_n / norms
    cov_n = (Vt.T * s_inv[None, :] ** 2) @ Vt
    cov = cov_n / jnp.outer(norms, norms)
    chi2 = jnp.sum(rw * rw)
    out = (vec + dpar, chi2, dpar, cov)
    if with_health:
        kept_min = jnp.min(jnp.where(s_inv > 0.0, s, smax))
        diag = _guard.SolveDiag(
            n_truncated=jnp.sum(s_inv == 0.0).astype(jnp.int32),
            cond_log10=jnp.log10(smax / jnp.maximum(kept_min, 1e-300)),
        )
        out = out + (diag,)
    return out


class Fitter:
    """Base fitter: holds (toas, model), exposes fit_toas().

    bucket: pad the TOAs to the next geometric size bucket
    (compile_cache.pad_toas) so nearby dataset sizes share one XLA
    executable.  None reads ``$PINT_TPU_BUCKET_TOAS`` (default off);
    explicit residuals suppress padding (their dataset is fixed).
    """

    def __init__(self, toas, model, residuals=None, bucket=None):
        if bucket is None:
            bucket = _cc.bucketing_default()
        if bucket and residuals is None:
            toas = _cc.pad_toas(toas)
        self.toas = toas
        self.model = model
        self.resids = residuals or Residuals(toas, model)
        self.prepared = self.resids.prepared

    @staticmethod
    def auto(toas, model, downhill=True, bucket=None):
        """Pick a fitter like the reference (fitter.py:252): wideband
        when the TOAs carry -pp_dm data (and the model says DMDATA), GLS
        when the model carries correlated noise, WLS otherwise; downhill
        variants when requested."""
        wideband = model.meta.get("DMDATA", "").split() and \
            model.meta["DMDATA"].split()[0].upper() in ("1", "Y", "YES",
                                                        "TRUE")
        if wideband:
            # DMDATA in the par is a request, not a guarantee — the TOAs
            # must actually carry -pp_dm measurements (reference
            # Fitter.auto checks toas.wideband)
            wideband = toas.wideband_dm_data()[2].any()
        if wideband:
            if downhill:
                from pint_tpu.downhill import WidebandDownhillFitter

                return WidebandDownhillFitter(toas, model, bucket=bucket)
            return WidebandTOAFitter(toas, model, bucket=bucket)
        if downhill:
            from pint_tpu.downhill import DownhillGLSFitter, DownhillWLSFitter

            if model.has_correlated_errors:
                return DownhillGLSFitter(toas, model, bucket=bucket)
            return DownhillWLSFitter(toas, model, bucket=bucket)
        if model.has_correlated_errors:
            return GLSFitter(toas, model, bucket=bucket)
        return WLSFitter(toas, model, bucket=bucket)

    # -- reporting -----------------------------------------------------------
    def get_summary(self) -> str:
        r = self.resids
        lines = [
            f"Fitted model {self.model.meta.get('PSR', self.model.name)} "
            f"with {len(self.toas)} TOAs, {len(self.model.free_params)} "
            "free parameters",
            f"chi2 = {r.chi2:.3f} / dof {r.dof} = {r.reduced_chi2:.4f}",
            f"weighted RMS = {r.rms_weighted() * 1e6:.4f} us",
            "",
            f"{'PARAM':<12s} {'VALUE':<24s} {'UNCERTAINTY':<12s}",
        ]
        params = self.model.params
        for name in self.model.free_params:
            p = params[name]
            unc = p.uncertainty
            lines.append(
                f"{name:<12s} {p.format(self.model.values[name]):<24s} "
                f"{unc if unc is not None else '':<12}"
            )
        return "\n".join(lines)

    def print_summary(self):
        print(self.get_summary())

    def ftest(self, unfreeze, maxiter=6):
        """F-test for adding parameters (reference: Fitter.ftest,
        fitter.py:619): refit a copy of the model with ``unfreeze``
        additionally free; returns {'p': chance probability,
        'chi2': new chi2, 'dof': new dof, 'fitter': the new fitter}.
        Small p favors keeping the extra parameters."""
        from pint_tpu.models import get_model
        from pint_tpu.utils import FTest

        chi2_1 = float(self.resids.chi2)
        dof_1 = self.resids.dof
        m2 = get_model(self.model.as_parfile())
        params = m2.params
        for name in unfreeze:
            if name not in params:
                raise KeyError(f"unknown parameter {name}")
            params[name].frozen = False
        f2 = type(self)(self.toas, m2)
        f2.fit_toas(maxiter=maxiter)
        chi2_2 = float(f2.resids.chi2)
        dof_2 = f2.resids.dof
        return {
            "p": FTest(chi2_1, dof_1, chi2_2, dof_2),
            "chi2": chi2_2,
            "dof": dof_2,
            "fitter": f2,
        }

    # -- shared machinery -----------------------------------------------------
    def _retrace(self):
        """(Re)key the jitted step for the current free-param set.
        The trace closes over the free-param *names*; a changed free set
        with the same count would otherwise hit the stale jit cache and
        silently write steps into the wrong parameters.

        The jitted callable comes from the process-level registry
        (compile_cache.shared_jit): the step takes the dataset as a
        DYNAMIC argument, so its key is purely structural and a second
        fitter on a same-shaped problem reuses this one's trace and
        executable — zero new XLA compiles."""
        telemetry.counter_add("fitter.retraces")
        self._traced_free = tuple(self.model.free_timing_params)
        # the guard's escalation scalar rides the data pytree as a
        # DYNAMIC leaf (precedent: n_real), so ladder rungs reuse the
        # same trace; the on/off flag changes the traced program and is
        # part of the key
        self._guard_on = _guard.enabled()
        self._fit_data = {**self.resids._data(),
                          "guard_eps": np.float64(0.0)}
        self._step_jit = _cc.shared_jit(
            self._step, key=self._step_key(),
            donate_argnums=_cc.donation_argnums((0,)))

    def _step_key(self):
        """Everything a trace of _step bakes in beyond the avals."""
        return ("fitter.step", type(self).__name__, self._traced_free,
                getattr(self, "threshold", None), self._guard_on,
                self.resids._structure_key())

    def warm_compile(self):
        """AOT-compile (lower().compile()) the fit step AND the
        residuals accessors the fit epilogue reports through (chi^2,
        weighted RMS) for this problem's shapes, without running a fit
        — with the persistent cache enabled this writes the
        executables to disk, so a future process's first fit is
        disk reads end to end.  Returns compile seconds."""
        vec = jnp.zeros(len(self._traced_free), dtype=jnp.float64)
        base = self.prepared._values_pytree()
        lowered = self._step_jit.lower(vec, base, self._fit_data)
        total = _cc.warm_timed(lowered.compile)
        warm_resids = getattr(self.resids, "warm_compile", None)
        if warm_resids is not None:
            total += warm_resids()
        return total

    def _resid_fn_of(self, base_values, data):
        free = self._traced_free

        def resid_fn(v):
            values = dict(base_values)
            for i, name in enumerate(free):
                values[name] = v[i]
            return self.resids.time_resids_at(values, data)

        return resid_fn

    def _merged(self, base_values, vec):
        values = dict(base_values)
        for i, name in enumerate(self._traced_free):
            values[name] = vec[i]
        return values

    # -- guard integration ----------------------------------------------------
    #: degradation-ladder escalation values (guard.JITTER_RUNGS)
    _guard_jitter_rungs = _guard.JITTER_RUNGS

    def _last_good_dict(self, vec_np):
        return {name: float(vec_np[i])
                for i, name in enumerate(self._traced_free)}

    def _check_step_health(self, health, last_good_np, n_iter):
        """THE per-iteration health check every fitter loop shares
        (plain/downhill/LM): one counter, one packed-``ok`` device
        read, StepDiverged with the last finite-chi^2 state on a bad
        verdict.  No-op with the guard off (empty health)."""
        if not health:
            return
        telemetry.counter_add("guard.checks")
        if _guard.verdict(health) != "ok":
            raise _guard.StepDiverged(
                health, last_good=self._last_good_dict(last_good_np),
                n_iter=n_iter)

    def _guard_data(self, guard_eps):
        if guard_eps == 0.0:
            return self._fit_data
        return {**self._fit_data, "guard_eps": np.float64(guard_eps)}

    def _guard_rungs(self, maxiter):
        """The degradation ladder for this fitter: baseline, then (when
        the guard is on) escalating jitter, then an optional downgrade
        (GLS fitters fall back to a WLS solve — `_downgrade_rung`)."""
        rungs = [("baseline", lambda: self._iterate(maxiter))]
        if self._guard_on:
            for name, eps in self._guard_jitter_rungs:
                rungs.append(
                    (name,
                     lambda e=eps: self._iterate(maxiter, guard_eps=e)))
            down = self._downgrade_rung(maxiter)
            if down is not None:
                rungs.append(down)
        return rungs

    def _downgrade_rung(self, maxiter):
        """Hook: the final ladder rung (GLS fitters downgrade to WLS)."""
        return None

    def _record_guard(self, rung, health, sp):
        """Publish the fit's guard outcome: ``fit_rung``/``fit_health``
        attributes always; fit meta + a warning when a degraded rung
        served (a degraded fit must be loud, never silent)."""
        self.fit_rung = rung
        self.fit_health = _guard.to_record(health)
        if rung != "baseline":
            self.model.meta["GUARD_RUNG"] = rung
            if sp is not None:
                sp.set(guard_rung=rung)
            warnings.warn(
                f"{type(self).__name__}: fit served by degradation "
                f"rung {rung!r} (see model.meta['GUARD_RUNG'] and "
                "fitter.fit_health)")
        else:
            # a later clean fit clears the flag — the meta lands in the
            # output par file and must describe THIS fit, not a
            # degraded one from before the data was fixed
            self.model.meta.pop("GUARD_RUNG", None)

    def _iterate(self, maxiter, guard_eps=0.0):
        """Run the Gauss-Newton loop once (one ladder rung).  Returns
        (vec, cov, extras, n_iter, health); raises guard.StepDiverged
        with the last finite-chi^2 parameter state on a bad verdict."""
        vec = jnp.array(
            [self.model.values[k] for k in self._traced_free],
            dtype=jnp.float64,
        )
        base = self.prepared._values_pytree()
        data = self._guard_data(guard_eps)
        chi2_prev = None
        cov = None
        n_iter = 0
        extras = ()
        health = ()
        last_good = np.array(
            [self.model.values[k] for k in self._traced_free])
        for _ in range(maxiter):
            # the step donates its input vector on TPU/GPU — snapshot
            # the candidate before the call so last_good stays readable
            vec_in = np.asarray(vec)
            vec, chi2, dpar, cov, *rest = self._step_jit(
                vec, base, data)
            extras, health = tuple(rest[:-1]), rest[-1]
            n_iter += 1
            chi2_f = float(chi2)
            if np.isfinite(chi2_f):
                # chi2 is evaluated at the INPUT vector — that vector
                # is the proven-good state
                last_good = vec_in
            self._check_step_health(health, last_good, n_iter)
            if chi2_prev is not None and \
                    abs(float(chi2_prev) - chi2_f) \
                    < 1e-8 * max(chi2_f, 1.0):
                break
            chi2_prev = chi2_f
        return vec, cov, extras, n_iter, health

    def fit_toas(self, maxiter=3):
        """Iterate Gauss-Newton steps; write back values + uncertainties.

        On divergence the guard's degradation ladder retries through
        escalating rungs; past the last rung a
        :class:`pint_tpu.guard.FitDivergedError` carries the last-good
        parameter vector and the health record — ``model.values`` is
        never written with non-finite results."""
        if not self.model.free_timing_params:
            raise ValueError(
                "no free timing parameters to fit (mark them with a '1' "
                "fit flag in the par file or clear Param.frozen)"
            )
        with span("fit_toas", fitter=type(self).__name__,
                  n_toa=len(self.toas),
                  n_free=len(self.model.free_timing_params),
                  maxiter=maxiter) as sp:
            if tuple(self.model.free_timing_params) != getattr(
                    self, "_traced_free", ()):
                self._retrace()
            else:
                telemetry.counter_add("fitter.jit_cache_hits")
            (vec, cov, extras, n_iter, health), rung = _guard.run_ladder(
                self._guard_rungs(maxiter), context=type(self).__name__)
            self._step_extras = extras
            # write back
            vec = np.asarray(vec)
            cov_np = np.asarray(cov)
            telemetry.record_transfer(vec)
            telemetry.record_transfer(cov_np)
            errs = np.sqrt(np.diag(cov_np))
            params = self.model.params
            for i, name in enumerate(self._traced_free):
                self.model.values[name] = float(vec[i])
                params[name].uncertainty = float(errs[i])
            self.covariance = cov_np
            flops_est = self._fit_flops_est(n_iter)
            telemetry.counter_add("fitter.iterations", n_iter)
            telemetry.counter_add("fit.flops_est", flops_est)
            sp.set(n_iter=n_iter, flops_est=flops_est)
            self._record_guard(rung, health, sp)
            self._update_fit_meta()
            self._post_fit()
            return float(self.resids.chi2)

    def _fit_flops_est(self, n_iter):
        """Modeled FLOPs of this fit (pint_tpu.flops cost model)."""
        n_basis = int(getattr(self.prepared, "noise_basis",
                              np.zeros((0, 0))).shape[1])
        return _flops.gls_fit_flops(
            len(self.toas), len(self._traced_free), n_basis, n_iter)

    def _update_fit_meta(self):
        """Record the fit summary into the model metadata so it lands in
        the output par file (reference: CHI2/TRES/NTOA params,
        timing_model.py:344-386)."""
        r = self.resids
        self.model.meta["NTOA"] = str(
            getattr(r, "n_real", None) or len(self.toas))
        self.model.meta["CHI2"] = f"{r.chi2:.6f}"
        self.model.meta["TRES"] = f"{r.rms_weighted() * 1e6:.6f}"

    def _post_fit(self):
        """Hook for subclasses (e.g. noise realizations)."""

    @property
    def parameter_correlation_matrix(self):
        d = np.sqrt(np.diag(self.covariance))
        return self.covariance / np.outer(d, d)


class WLSFitter(Fitter):
    """Weighted least squares via SVD of the whitened, column-normalized
    design matrix; Gauss-Newton iterations, all inside one jit.  Whitens
    by the noise-scaled uncertainties (EFAC/EQUAD), matching the
    reference WLS path (fitter.py:1990)."""

    def __init__(self, toas, model, residuals=None, threshold=1e-14,
                 bucket=None):
        super().__init__(toas, model, residuals, bucket=bucket)
        self.threshold = threshold
        self._retrace()

    def _fit_flops_est(self, n_iter):
        """The SVD step never touches the noise basis — cost it at
        basis width 0 even when the model carries noise components."""
        return _flops.wls_fit_flops(
            len(self.toas), len(self._traced_free), n_iter)

    def _step(self, vec, base_values, data):
        """One Gauss-Newton WLS step.  base_values (the full values
        dict, including frozen params) and data (the dataset pytree)
        are dynamic arguments, so edits to frozen parameters between
        fits take effect without retracing and same-shaped problems
        share the trace; changes to WHICH params are free go through
        _retrace().  Returns (new_vec, chi2, dpar, cov, health) —
        health rides the same compiled program (empty with the guard
        off)."""
        resid_fn = self._resid_fn_of(base_values, data)
        sigma = self.resids.sigma_at(self._merged(base_values, vec), data)
        if not self._guard_on:
            return wls_gn_solve(resid_fn, vec, sigma,
                                self.threshold) + ((),)
        new_vec, chi2, dpar, cov, diag = wls_gn_solve(
            resid_fn, vec, sigma, self.threshold,
            rcond=data["guard_eps"], with_health=True)
        health = _guard.step_health(
            resid_fn(vec), sigma, chi2, dpar, cov, diag,
            valid=data["valid"],
            inputs_ok=_guard.batch_input_finite(data["batch"],
                                                data["valid"]))
        return new_vec, chi2, dpar, cov, health


class WidebandTOAFitter(Fitter):
    """Wideband fit: stacked [time; DM] residual vector with a block
    design matrix, solved through the same noise-augmented normal
    equations (reference: WidebandTOAFitter, fitter.py:2292-2640 via
    combine_design_matrices_by_quantity).  The correlated-noise basis
    acts on the time block; DM rows see DMEFAC/DMEQUAD-scaled white
    noise."""

    def __init__(self, toas, model, residuals=None, bucket=None):
        if residuals is None:
            if bucket is None:
                bucket = _cc.bucketing_default()
            if bucket:
                toas = _cc.pad_toas(toas)
            residuals = WidebandTOAResiduals(toas, model)
        super().__init__(toas, model, residuals=residuals, bucket=False)
        self.noise_realizations = {}
        self._retrace()

    def _stacked_resid_fn(self, base_values, data):
        free = self._traced_free
        toa_r = self.resids.toa
        dm_r = self.resids.dm

        def resid_fn(v):
            values = dict(base_values)
            for i, name in enumerate(free):
                values[name] = v[i]
            return jnp.concatenate(
                [toa_r.time_resids_at(values, data["toa"]),
                 dm_r.dm_resids_at(values, data["dm"])]
            )

        return resid_fn

    def _step(self, vec, base_values, data):
        values = self._merged(base_values, vec)
        sigma_t = self.resids.toa.sigma_at(values, data["toa"])
        sigma_dm = self.resids.dm.sigma_at(values, data["dm"])
        sigma = jnp.concatenate([sigma_t, sigma_dm])
        resid_fn = self._stacked_resid_fn(base_values, data)
        r = resid_fn(vec)
        J = jax.jacfwd(resid_fn)(vec)
        U_t, phi = self.resids.toa._noise_basis_phi_at(values,
                                                       data["toa"])
        U = jnp.concatenate(
            [U_t, jnp.zeros((sigma_dm.shape[0], U_t.shape[1]))], axis=0
        )
        if not self._guard_on:
            dpar, cov, ncoef, chi2 = gls_normal_solve(r, J, sigma, U,
                                                      phi)
            return vec + dpar, chi2, dpar, cov, ncoef, ()
        dpar, cov, ncoef, chi2, diag = gls_normal_solve(
            r, J, sigma, U, phi, guard_eps=data["guard_eps"],
            with_health=True)
        # the stacked [time; DM] vector needs a stacked pad mask: the
        # DM block's rows are the valid-indexed subset of the TOA rows
        v_t = data["toa"]["valid"]
        valid = None
        if v_t is not None:
            valid = jnp.concatenate(
                [v_t, v_t[data["dm"]["valid_idx"]]])
        health = _guard.step_health(
            r, sigma, chi2, dpar, cov, diag, valid=valid,
            inputs_ok=_guard.batch_input_finite(data["toa"]["batch"],
                                                v_t))
        return vec + dpar, chi2, dpar, cov, ncoef, health


class GLSFitter(Fitter):
    """Generalized least squares over the low-rank noise basis: the
    noise-augmented normal equations solved by Cholesky (reference:
    GLSFitter.fit_toas, fitter.py:2090-2289), one jitted step.

    After fit_toas(), ``noise_realizations`` maps each correlated-noise
    component to its basis-amplitude realization U_c @ a_c [s]
    (reference :2269-2282).
    """

    def __init__(self, toas, model, residuals=None, bucket=None):
        super().__init__(toas, model, residuals, bucket=bucket)
        self.noise_realizations = {}
        self._retrace()

    def _step(self, vec, base_values, data):
        resid_fn = self._resid_fn_of(base_values, data)
        values = self._merged(base_values, vec)
        sigma = self.resids.sigma_at(values, data)
        U, phi = self.resids._noise_basis_phi_at(values, data)
        r = resid_fn(vec)
        J = jax.jacfwd(resid_fn)(vec)
        if not self._guard_on:
            dpar, cov, ncoef, chi2 = gls_normal_solve(r, J, sigma, U,
                                                      phi)
            return vec + dpar, chi2, dpar, cov, ncoef, ()
        dpar, cov, ncoef, chi2, diag = gls_normal_solve(
            r, J, sigma, U, phi, guard_eps=data["guard_eps"],
            with_health=True)
        health = _guard.step_health(
            r, sigma, chi2, dpar, cov, diag, valid=data["valid"],
            inputs_ok=_guard.batch_input_finite(data["batch"],
                                                data["valid"]))
        return vec + dpar, chi2, dpar, cov, ncoef, health

    def _downgrade_rung(self, maxiter):
        """The ladder's last resort: a correlated-noise fit whose solve
        stays non-finite through every jitter rung falls back to the
        plain WLS step (noise-scaled white errors, no basis
        augmentation) on the SAME residuals — degraded statistics, but
        finite timing parameters with the rung flagged in fit meta."""
        def downgrade():
            wls = WLSFitter(self.toas, self.model,
                            residuals=self.resids)
            return wls._iterate(maxiter)

        return ("wls", downgrade)

    def _set_noise_realizations(self, ncoef):
        """Per-component noise realizations U_c @ a_c [s] (reference
        fitter.py:2269)."""
        ncoef = np.asarray(ncoef)
        self.noise_realizations = {}
        for name, (start, nb) in self.prepared.noise_dimensions().items():
            basis = np.asarray(self.prepared.noise_basis[:, start:start + nb])
            self.noise_realizations[name] = basis @ ncoef[start:start + nb]

    def _post_fit(self):
        """Solve once more at the written-back optimum so the noise
        realizations correspond to the reported parameters (the loop's
        extras are one Gauss-Newton step stale)."""
        if getattr(self, "fit_rung", "baseline") == "wls":
            # the GLS solve is the thing that diverged — re-running it
            # here would hand back the same non-finite amplitudes
            self.noise_realizations = {}
            return
        vec = jnp.array(
            [self.model.values[k] for k in self._traced_free],
            dtype=jnp.float64,
        )
        base = self.prepared._values_pytree()
        *_, ncoef, _health = self._step_jit(vec, base, self._fit_data)
        self._set_noise_realizations(ncoef)
