"""Fitters: WLS (SVD) and GLS (Woodbury) Gauss-Newton on device.

Counterpart of the reference fitter layer (reference: src/pint/fitter.py:
185 base, :252 ``Fitter.auto``, :1940-2087 WLSFitter, :2090-2289
GLSFitter).  The reference's per-iteration recipe — design matrix,
whiten, column-normalize, solve, parameter step, covariance — becomes
one jitted function of the free-parameter vector; the design matrix is
``jax.jacfwd`` of the residual function (the reference's 124-s
hand-derivative hot spot, profiling/README.txt:58, disappears by
construction).

``Fitter.auto`` mirrors the reference's dispatch (fitter.py:252): GLS
when the model has correlated noise, WLS otherwise.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import flops as _flops
from pint_tpu import guard as _guard
from pint_tpu import telemetry
from pint_tpu.linalg import StructuredU, basis_ncols, gls_normal_solve, \
    su_pad_rows
from pint_tpu.models.timing_model import frozen_delay_default, \
    hybrid_design_default
from pint_tpu.residuals import Residuals, WidebandTOAResiduals
from pint_tpu.telemetry import span

__all__ = ["WLSFitter", "GLSFitter", "WidebandTOAFitter", "Fitter",
           "wls_gn_solve", "resid_and_design",
           "wideband_resid_and_design"]

# compile events fire during the first fit_toas; the jax.monitoring
# listener must exist before then for jit.compile_* counters to tick
telemetry._install_compile_listener()


def resid_and_design(free, vec, partition, resid_of, linear_of):
    """(r, J) for the free-parameter vector ``vec`` — the hybrid
    analytic/AD design matrix build shared by every fitter step (plain,
    downhill, LM, wideband, grid, batched PTA).

    ``partition`` is PreparedModel.design_partition's ``(linear,
    nonlinear)`` split of ``free``.  ``resid_of(sub)`` evaluates the
    residual vector with the {name: value} dict ``sub`` overriding the
    base values; ``linear_of(values_sub)`` returns the (N, L)
    closed-form columns for the linear names at those values
    (Residuals.linear_design_at — one delay fold plus one ``jvp``
    through the phase stage, shared by every column).  ``jax.jacfwd``
    runs only over the nonlinear remainder, so the tangent width
    through the full residual chain drops from P to P_nl.  With an
    empty linear set this degrades to exactly the classic full-jacfwd
    build."""
    lin, nl = partition
    free = tuple(free)
    full = {name: vec[i] for i, name in enumerate(free)}
    r = resid_of(full)
    if not lin:
        def resid_fn(v):
            return resid_of({name: v[i] for i, name in enumerate(free)})

        return r, jax.jacfwd(resid_fn)(vec)
    idx = {name: i for i, name in enumerate(free)}
    J_lin = linear_of(full)
    if nl:
        nl_idx = jnp.asarray([idx[p] for p in nl])

        def resid_nl(nv):
            sub = dict(full)
            for j, p in enumerate(nl):
                sub[p] = nv[j]
            return resid_of(sub)

        J_nl = jax.jacfwd(resid_nl)(vec[nl_idx])
        blocks = jnp.concatenate([J_nl, J_lin], axis=1)
    else:
        blocks = J_lin
    # one gather back to free order instead of P column slices+stack
    order = {p: j for j, p in enumerate(tuple(nl) + tuple(lin))}
    perm = [order[p] for p in free]
    if perm == list(range(len(free))):
        return r, blocks
    return r, blocks[:, jnp.asarray(perm)]


def wideband_resid_and_design(resids, base_values, data, free, vec,
                              partition):
    """Hybrid (r, J) for the stacked wideband [time; DM] system —
    shared by WidebandTOAFitter and WidebandLMFitter.  The linear
    columns stack the time block (Residuals.linear_design_at) over the
    DM block (WidebandDMResiduals.linear_dm_design_at); the partition
    already required every linear owner with a ``dm_value`` to provide
    ``d_dm_d_param`` (design_partition(wideband=True))."""
    toa_r, dm_r = resids.toa, resids.dm

    def resid_of(sub):
        values = dict(base_values)
        values.update(sub)
        return jnp.concatenate(
            [toa_r.time_resids_at(values, data["toa"]),
             dm_r.dm_resids_at(values, data["dm"])])

    def linear_of(sub):
        values = dict(base_values)
        values.update(sub)
        lin = partition[0]
        return jnp.concatenate(
            [toa_r.linear_design_at(values, data["toa"], lin),
             dm_r.linear_dm_design_at(values, data["dm"], lin)], axis=0)

    return resid_and_design(free, vec, partition, resid_of, linear_of)


def wls_gn_solve(resid_fn, vec, err, threshold=1e-14, rcond=None,
                 with_health=False, rj=None, toa=None):
    """One whitened, column-normalized SVD Gauss-Newton step.

    The shared numerical core of WLSFitter and the vmapped grid (one
    implementation, one threshold).  resid_fn(vec) -> residuals [s].
    Returns (new_vec, chi2_before, dpar, covariance).

    rcond: optional traced scalar raising the singular-value cutoff
    above ``threshold`` (the guard ladder's escalation — dynamic, so
    it costs zero new compiles).  with_health: additionally return a
    :class:`pint_tpu.guard.SolveDiag` from the SVD spectrum already in
    hand.  rj: optional precomputed ``(r, J)`` — the hybrid design
    path (:func:`resid_and_design`) supplies it so the solve never
    re-runs ``jacfwd`` over the full chain; resid_fn may then be None.
    toa: optional :class:`pint_tpu.parallel.mesh.RowShard` keeping the
    whitened (N, P) system sharded over the TOA axis (the SVD itself
    gathers — the win is the upstream residual/design build staying
    sharded; the normal-equation GLS path is where the reduction
    decomposes, see linalg.gls_normal_solve).
    """
    if rj is not None:
        r, J = rj
    else:
        r = resid_fn(vec)
        J = jax.jacfwd(resid_fn)(vec)  # (N, P) d resid / d param
    if toa is not None:
        r, J, err = toa.rows(r), toa.rows(J), toa.rows(err)
    w = 1.0 / err
    rw = r * w
    Jw = J * w[:, None]
    # column normalize (reference: utils.normalize_designmatrix)
    norms = jnp.sqrt(jnp.sum(Jw * Jw, axis=0))
    norms = jnp.where(norms == 0, 1.0, norms)
    Jn = Jw / norms[None, :]
    U, s, Vt = jnp.linalg.svd(Jn, full_matrices=False)
    smax = jnp.max(s)
    cut = threshold if rcond is None else jnp.maximum(threshold, rcond)
    s_inv = jnp.where(s > cut * smax, 1.0 / s, 0.0)
    dpar_n = -(Vt.T * s_inv[None, :]) @ (U.T @ rw)
    dpar = dpar_n / norms
    cov_n = (Vt.T * s_inv[None, :] ** 2) @ Vt
    cov = cov_n / jnp.outer(norms, norms)
    chi2 = jnp.sum(rw * rw)
    out = (vec + dpar, chi2, dpar, cov)
    if with_health:
        kept_min = jnp.min(jnp.where(s_inv > 0.0, s, smax))
        diag = _guard.SolveDiag(
            n_truncated=jnp.sum(s_inv == 0.0).astype(jnp.int32),
            cond_log10=jnp.log10(smax / jnp.maximum(kept_min, 1e-300)),
        )
        out = out + (diag,)
    return out


class Fitter:
    """Base fitter: holds (toas, model), exposes fit_toas().

    bucket: pad the TOAs to the next geometric size bucket
    (compile_cache.pad_toas) so nearby dataset sizes share one XLA
    executable.  None reads ``$PINT_TPU_BUCKET_TOAS`` (default off);
    explicit residuals suppress padding (their dataset is fixed).

    mesh: an optional device mesh with a ``toa`` axis
    (:func:`pint_tpu.parallel.mesh.make_mesh`) sharding the SEQUENCE
    dimension of this single pulsar's fit over devices: the dataset
    pytree is TOA-padded to a device multiple and placed with
    NamedShardings, and the Woodbury/normal-equation contractions of
    the step reduce shard-local with one small-(P+K) all-reduce
    (linalg ``toa=`` / :class:`~pint_tpu.parallel.mesh.RowShard`) —
    a 20-year dataset's O(N (P+K)^2) gram assembly parallelizes.
    Segment-sum ECORR epoch blocks are pad-aligned to shard
    boundaries (``mesh.toa_shard_plan`` → sentinel row insertion) or
    the basis falls back dense, brute-force-equal either way.  The
    mesh joins the step's jit key: a second same-shaped sharded
    fitter performs zero new XLA compiles, and ``mesh=None`` keys
    and behaves exactly as before.
    """

    #: which frozen-noise leaves this class's step consumes: every
    #: step whitens with ``noise_sigma``, but only the GLS normal
    #: equations also read ``(noise_phi, noise_gram)`` — building the
    #: ~N K^2 gram eagerly (then shipping and donating its leaves
    #: through every step call) for a WLS/LM/Powell step that never
    #: reads it is pure waste on correlated-noise models.
    _noise_gram_leaves = False

    def __init__(self, toas, model, residuals=None, bucket=None,
                 mesh=None):
        if bucket is None:
            bucket = _cc.bucketing_default()
        self._toa_mesh = mesh
        if mesh is not None:
            if residuals is not None:
                raise ValueError(
                    "mesh= needs to pad/align the TOA axis itself; "
                    "explicit residuals are unsupported on the "
                    "TOA-sharded path")
            from pint_tpu.parallel import mesh as _pm

            ndev = _pm.axis_size(mesh, "toa")
            n = len(toas)
            if getattr(toas, "n_real", None) is not None:
                # already padded (bucketed upstream): pad_toas would
                # reject a conflicting re-pad target, but appending
                # further sentinel rows through the row-plan path is
                # exact (the plan machinery carries the pad_valid
                # mask whether or not the pads are a suffix)
                target = _pm.pad_to_multiple(n, ndev)
                if target != n:
                    toas = _cc.apply_toa_row_plan(
                        toas, np.concatenate(
                            [np.arange(n),
                             np.full(target - n, -1)]))
                _pm.record_pad_waste("toa", toas.n_real, target)
            else:
                target = _cc.bucket_size(n) if bucket else n
                target = _pm.pad_to_multiple(max(target, n), ndev)
                toas = _cc.pad_toas(toas, n_target=target)
                _pm.record_pad_waste("toa", n, target)
        elif bucket and residuals is None:
            toas = _cc.pad_toas(toas)
        self.toas = toas
        self.model = model
        self.resids = residuals or Residuals(toas, model)
        self.prepared = self.resids.prepared
        if mesh is not None:
            self._align_toa_epochs()

    def _align_toa_epochs(self):
        """Segment-sum ECORR epoch blocks must not straddle TOA-shard
        boundaries (the segment reduction would scatter-add across
        devices): when the dataset's epoch layout straddles, re-lay
        the rows with sentinel pads pushing each epoch cluster inside
        one shard (``mesh.toa_shard_plan`` +
        ``compile_cache.apply_toa_row_plan``), rebuilding the
        residuals over the realigned dataset; when no plan exists
        (an epoch cluster larger than a shard), fall back to the
        dense basis — both brute-force-equal to the unsharded fit."""
        from pint_tpu.linalg import su_to_dense
        from pint_tpu.parallel import mesh as _pm

        ndev = _pm.axis_size(self._toa_mesh, "toa")
        if ndev <= 1:
            return
        for attempt in range(2):
            su = self.resids._U_ext
            if not isinstance(su, StructuredU):
                return
            seg = np.asarray(su.seg)
            k_e = int(su.eslot.shape[0])
            if _pm.toa_epochs_aligned(seg, k_e, ndev):
                return
            if attempt == 0:
                plan = _pm.toa_shard_plan(seg, k_e, ndev)
                if plan is not None:
                    telemetry.counter_add("mesh.toa_align_replans")
                    self.toas = _cc.apply_toa_row_plan(self.toas,
                                                       plan)
                    self.resids = Residuals(self.toas, self.model)
                    self.prepared = self.resids.prepared
                    continue
            telemetry.counter_add("mesh.ecorr_dense_fallbacks")
            warnings.warn(
                "ECORR epoch blocks straddle TOA-shard boundaries "
                "and cannot be pad-aligned; serving the dense basis "
                "for this sharded fit")
            self.resids._U_ext = su_to_dense(su)
            self.resids._data_cached = None
            self.resids._structure_key_cached = None
            return

    @staticmethod
    def auto(toas, model, downhill=True, bucket=None):
        """Pick a fitter like the reference (fitter.py:252): wideband
        when the TOAs carry -pp_dm data (and the model says DMDATA), GLS
        when the model carries correlated noise, WLS otherwise; downhill
        variants when requested."""
        wideband = model.meta.get("DMDATA", "").split() and \
            model.meta["DMDATA"].split()[0].upper() in ("1", "Y", "YES",
                                                        "TRUE")
        if wideband:
            # DMDATA in the par is a request, not a guarantee — the TOAs
            # must actually carry -pp_dm measurements (reference
            # Fitter.auto checks toas.wideband)
            wideband = toas.wideband_dm_data()[2].any()
        if wideband:
            if downhill:
                from pint_tpu.downhill import WidebandDownhillFitter

                return WidebandDownhillFitter(toas, model, bucket=bucket)
            return WidebandTOAFitter(toas, model, bucket=bucket)
        if downhill:
            from pint_tpu.downhill import DownhillGLSFitter, DownhillWLSFitter

            if model.has_correlated_errors:
                return DownhillGLSFitter(toas, model, bucket=bucket)
            return DownhillWLSFitter(toas, model, bucket=bucket)
        if model.has_correlated_errors:
            return GLSFitter(toas, model, bucket=bucket)
        return WLSFitter(toas, model, bucket=bucket)

    # -- reporting -----------------------------------------------------------
    def get_summary(self) -> str:
        r = self.resids
        lines = [
            f"Fitted model {self.model.meta.get('PSR', self.model.name)} "
            f"with {len(self.toas)} TOAs, {len(self.model.free_params)} "
            "free parameters",
            f"chi2 = {r.chi2:.3f} / dof {r.dof} = {r.reduced_chi2:.4f}",
            f"weighted RMS = {r.rms_weighted() * 1e6:.4f} us",
            "",
            f"{'PARAM':<12s} {'VALUE':<24s} {'UNCERTAINTY':<12s}",
        ]
        params = self.model.params
        for name in self.model.free_params:
            p = params[name]
            unc = p.uncertainty
            lines.append(
                f"{name:<12s} {p.format(self.model.values[name]):<24s} "
                f"{unc if unc is not None else '':<12}"
            )
        return "\n".join(lines)

    def print_summary(self):
        print(self.get_summary())

    def ftest(self, unfreeze, maxiter=6):
        """F-test for adding parameters (reference: Fitter.ftest,
        fitter.py:619): refit a copy of the model with ``unfreeze``
        additionally free; returns {'p': chance probability,
        'chi2': new chi2, 'dof': new dof, 'fitter': the new fitter}.
        Small p favors keeping the extra parameters."""
        from pint_tpu.models import get_model
        from pint_tpu.utils import FTest

        chi2_1 = float(self.resids.chi2)
        dof_1 = self.resids.dof
        m2 = get_model(self.model.as_parfile())
        params = m2.params
        for name in unfreeze:
            if name not in params:
                raise KeyError(f"unknown parameter {name}")
            params[name].frozen = False
        f2 = type(self)(self.toas, m2)
        f2.fit_toas(maxiter=maxiter)
        chi2_2 = float(f2.resids.chi2)
        dof_2 = f2.resids.dof
        return {
            "p": FTest(chi2_1, dof_1, chi2_2, dof_2),
            "chi2": chi2_2,
            "dof": dof_2,
            "fitter": f2,
        }

    # -- shared machinery -----------------------------------------------------
    def _partition_setup(self):
        """Compute the structure-aware split for the current free set:
        the frozen-delay component list (owns no free parameter — its
        delay enters the trace as precomputed DATA), the hybrid
        linear/nonlinear design partition, and the frozen-value
        fingerprint that detects stale precomputed leaves.  Returns the
        extra data leaves to merge into the fit-data pytree."""
        free = self._traced_free
        prep = self.prepared
        self._hybrid_on = hybrid_design_default()
        self._frozen_on = frozen_delay_default()
        self._frozen_names = (prep.frozen_delay_split(free)
                              if self._frozen_on else ())
        wideband = isinstance(self.resids, WidebandTOAResiduals)
        if self._hybrid_on:
            self._partition = prep.design_partition(
                free, frozen=self._frozen_names, wideband=wideband)
        else:
            self._partition = ((), tuple(free))
        self._frozen_fp = prep.frozen_param_values(self._frozen_names)
        telemetry.counter_add("fitter.linear_cols",
                              len(self._partition[0]))
        telemetry.counter_add("fitter.frozen_components",
                              len(self._frozen_names))
        frozen, tzr_frozen = prep.frozen_delay_leaves(self._frozen_names)
        leaves = {}
        if frozen is not None:
            leaves["frozen"] = frozen
            if tzr_frozen is not None:
                leaves["tzr_frozen"] = tzr_frozen
        # frozen-noise fast path: when no free parameter belongs to a
        # noise component, sigma / U / phi are constants of the fit —
        # they enter the traced step as precomputed DATA leaves (same
        # contract as the frozen delays: dynamic, so trace sharing and
        # zero-recompile survive), and the GLS normal matrix reuses the
        # precomputed (K, K) noise gram instead of rebuilding the
        # O(N (P+K)^2) weighted gram every iteration
        self._noise_owned = tuple(sorted(
            p.name for c in prep.model.noise_components for p in c.params))
        self._noise_frozen = (
            self._frozen_on
            and not wideband
            and set(self._noise_owned).isdisjoint(free))
        if self._noise_frozen:
            self._noise_fp = self._noise_param_values()
            leaves.update(self._noise_leaves())
            telemetry.counter_add("fitter.noise_frozen")
        return leaves

    def _noise_param_values(self):
        """{param: value} over the noise components — the fingerprint
        that detects stale frozen-noise leaves (an EFAC edited between
        fits must re-fold sigma, never serve the old one)."""
        return {name: float(self.model.values.get(name, np.nan))
                for name in self._noise_owned}

    def _noise_leaves(self):
        """Precompute the fit-constant noise arrays host-side: sigma
        always; (phi, gram) only for classes whose step consumes them
        (``_noise_gram_leaves`` — the GLS normal equations).  The
        guard ladder's dynamic capacity jitter keeps working: the
        gram-served chi^2 applies the same per-diagonal relative ridge
        in-trace (linalg.gls_normal_solve)."""
        from pint_tpu.linalg import noise_gram_precompute

        base = self.prepared._values_pytree()
        sigma = jnp.asarray(np.asarray(self.resids.sigma_fn(base)))
        leaves = {"noise_sigma": sigma}
        if not self._noise_gram_leaves:
            return leaves
        # U itself already rides the data pytree as "U_ext"; phi/gram
        # are built even for an uncorrelated model (whose basis is just
        # the mean-offset column) — the GLS step uses them regardless
        U, phi = self.resids._noise_basis_phi(base)
        leaves["noise_phi"] = jnp.asarray(np.asarray(phi))
        leaves["noise_gram"] = jnp.asarray(np.asarray(
            noise_gram_precompute(sigma, U, phi)))
        return leaves

    def _inject_frozen(self, data, leaves):
        """Merge the frozen-delay leaves into the fit-data pytree (the
        time-block sub-dict on the wideband layout)."""
        if not leaves:
            return data
        if "toa" in data:
            return {**data, "toa": {**data["toa"], **leaves}}
        return {**data, **leaves}

    @staticmethod
    def _fp_same(a, b):
        """NaN-tolerant {param: value} fingerprint equality."""
        return a.keys() == b.keys() and all(
            v == b[k] or (v != v and b[k] != b[k]) for k, v in a.items())

    def _refresh_frozen(self):
        """Re-fold the frozen-delay / frozen-noise leaves when a frozen
        parameter was edited between fits (fingerprint mismatch) — a
        cheap host recompute, never a retrace: the leaves are dynamic
        data."""
        if getattr(self, "_frozen_names", ()):
            fp = self.prepared.frozen_param_values(self._frozen_names)
            if not self._fp_same(fp, self._frozen_fp):
                telemetry.counter_add("fitter.frozen_refreshes")
                self._frozen_fp = fp
                frozen, tzr_frozen = self.prepared.frozen_delay_leaves(
                    self._frozen_names)
                leaves = {"frozen": frozen}
                if tzr_frozen is not None:
                    leaves["tzr_frozen"] = tzr_frozen
                self._fit_data = self._inject_frozen(
                    {k: v for k, v in self._fit_data.items()
                     if k not in ("frozen", "tzr_frozen")}, leaves)
        if getattr(self, "_noise_frozen", False):
            fp = self._noise_param_values()
            if not self._fp_same(fp, self._noise_fp):
                telemetry.counter_add("fitter.noise_refreshes")
                self._noise_fp = fp
                self._fit_data = {**self._fit_data,
                                  **self._noise_leaves()}
        # refreshed leaves are host arrays — re-commit them onto the
        # TOA mesh so the executable's input shardings stay stable
        # (no-op unsharded; a committed leaf re-placed is free)
        self._shard_fit_data()

    def _kepler_depth_guard(self):
        """Post-fit Kepler-depth verification.  The Newton unroll
        depth is a STATIC ctx int chosen from the PREPARE-time
        eccentricity class (binary/base.prepare); a fit that moves
        ECC/EDOT into a higher class would otherwise iterate a
        too-shallow solver silently (e = 0.9 at the 4-deep unroll
        leaves ~1e-5 rad in the eccentric anomaly).  Called after
        write-back: re-derives the reach at the FITTED values, deepens
        the unroll when the class rose, and re-keys the traces.
        Returns True when the caller must run the fit again — the
        previous solution came from the shallow solver.  Depth is
        monotone over four classes, so the refit loop is bounded."""
        reach = self.prepared.kepler_ecc_reach()
        if reach == float("-inf"):
            return False
        if not self.resids.ensure_kepler_depth(reach):
            return False
        telemetry.counter_add("fitter.kepler_depth_refits")
        warnings.warn(
            "fitted eccentricity reach %.3g exceeds the prepare-time "
            "Kepler depth class — deepening the Newton unroll and "
            "refitting" % reach)
        self._retrace()
        return True

    def _fit_with_depth_guard(self, rungs_fn):
        """The guard-laddered fit + write-back + post-fit Kepler depth
        verification shared by the plain, downhill and LM fit loops
        (Powell's scipy-shaped variant has its own).  Depth classes
        are monotone (4 -> 6 -> 8 -> full), so the guard can force at
        most three reruns — each after a ``_retrace``, which is why
        ``rungs_fn`` rebuilds its rung closures against the current
        traced state.  Returns (vec_np, cov_np, n_iter, health,
        rung)."""
        for _depth_try in range(4):
            (vec, cov, extras, n_iter, health), rung = \
                _guard.run_ladder(rungs_fn(),
                                  context=type(self).__name__)
            self._step_extras = extras
            # write back (cov diagonal clipped: a last-ulp negative
            # variance must not write a NaN uncertainty)
            vec_np = np.asarray(vec)
            cov_np = np.asarray(cov)
            telemetry.record_transfer(vec_np)
            telemetry.record_transfer(cov_np)
            errs = np.sqrt(np.clip(np.diag(cov_np), 0, None))
            params = self.model.params
            for i, name in enumerate(self._traced_free):
                self.model.values[name] = float(vec_np[i])
                params[name].uncertainty = float(errs[i])
            self.covariance = cov_np
            if not self._kepler_depth_guard():
                break
        return vec_np, cov_np, n_iter, health, rung

    def _retrace(self):
        """(Re)key the jitted step for the current free-param set.
        The trace closes over the free-param *names*; a changed free set
        with the same count would otherwise hit the stale jit cache and
        silently write steps into the wrong parameters.

        The jitted callable comes from the process-level registry
        (compile_cache.shared_jit): the step takes the dataset as a
        DYNAMIC argument, so its key is purely structural and a second
        fitter on a same-shaped problem reuses this one's trace and
        executable — zero new XLA compiles."""
        telemetry.counter_add("fitter.retraces")
        self._traced_free = tuple(self.model.free_timing_params)
        # the guard's escalation scalar rides the data pytree as a
        # DYNAMIC leaf (precedent: n_real), so ladder rungs reuse the
        # same trace; the on/off flag changes the traced program and is
        # part of the key
        self._guard_on = _guard.enabled()
        # flight-recorder gate: the single-fitter loop is host-driven
        # (one _step_jit call per iteration), so the per-iteration
        # record accumulates host-side and the step PROGRAM is
        # gate-invariant — but the gate still keys uniformly with the
        # grid/PTA programs it DOES re-trace, so the gate->key lint
        # (tools/check_jit_gates.py) stays one rule with no per-site
        # exemptions and a future in-trace fitter loop can't miss it
        self._iter_trace = _cc.iter_trace_default()
        # TOA-axis sharding: the RowShard is closed over by the step
        # trace (its constraints change the program — the mesh rides
        # the key below), and the dataset pytree is committed onto the
        # mesh so a second same-shaped sharded fitter reuses both the
        # placement and the executable
        self._toa_shard = None
        if self._toa_mesh is not None:
            from pint_tpu.parallel import mesh as _pm

            self._toa_shard = _pm.RowShard(self._toa_mesh)
        leaves = self._partition_setup()
        self._fit_data = self._inject_frozen(
            {**self.resids._data(), "guard_eps": np.float64(0.0)},
            leaves)
        self._shard_fit_data()
        self._step_jit = _cc.shared_jit(
            self._step, key=self._step_key(),
            donate_argnums=_cc.donation_argnums((0,)),
            label=f"fitter.step:{type(self).__name__}"
                  + (":sharded" if self._toa_mesh is not None else ""))
        if self._toa_mesh is not None:
            from pint_tpu.parallel import mesh as _pm

            self._step_jit.set_mesh(_pm.mesh_desc(self._toa_mesh))
        # flops.py's per-step estimate rides the program record so the
        # profiler can reconcile it against XLA's own cost_analysis
        # (>2x disagreement -> profile.flops_mismatch)
        self._step_jit.set_analytic_flops(self._fit_flops_est(1))

    def _shard_fit_data(self):
        """Commit the fit-data pytree onto the TOA mesh (no-op
        unsharded).  Re-run after any host-side leaf refresh — a
        freshly-built uncommitted leaf among committed ones would
        change the executable's input-sharding signature and force a
        recompile."""
        if self._toa_mesh is None:
            return
        from pint_tpu.parallel import mesh as _pm

        self._fit_data = _pm.shard_toa_data(
            self._toa_mesh, self._fit_data, len(self.toas))

    def _step_key(self):
        """Everything a trace of _step bakes in beyond the avals.
        The design partition and frozen-component list change the
        traced program (which columns are analytic, which chain
        members fold in data), so they are part of the key — as are
        the env gates through them, and the TOA mesh (the RowShard
        constraints change the traced program;
        mesh.mesh_jit_key also carries the process topology)."""
        from pint_tpu.parallel import mesh as _pm

        return ("fitter.step", type(self).__name__, self._traced_free,
                getattr(self, "threshold", None), self._guard_on,
                self._iter_trace,
                self._partition, self._frozen_names, self._noise_frozen,
                self.resids._structure_key()) \
            + _pm.mesh_jit_key(self._toa_mesh)

    def _rj(self, vec, base_values, data):
        """(r, J) over the traced free set — the hybrid analytic/AD
        design build (see resid_and_design)."""

        def resid_of(sub):
            values = dict(base_values)
            values.update(sub)
            return self.resids.time_resids_at(values, data)

        def linear_of(sub):
            values = dict(base_values)
            values.update(sub)
            return self.resids.linear_design_at(values, data,
                                                self._partition[0])

        return resid_and_design(self._traced_free, vec,
                                self._partition, resid_of, linear_of)

    def _warm_entry(self):
        """The registry program ``warm_compile`` AOT-compiles —
        subclass hook (the downhill family warms its halving step, the
        program its fit loop actually drives)."""
        return self._step_jit

    def warm_compile(self):
        """AOT-compile (lower().compile()) the fit step AND the
        residuals accessors the fit epilogue reports through (chi^2,
        weighted RMS) for this problem's shapes, without running a fit
        — with the persistent cache enabled this writes the
        executables to disk, so a future process's first fit is
        disk reads end to end.  Lowering through the registry proxy
        also records the argument spec AOT export serializes from
        (compile_cache.export_executables), so a warmed-but-never-run
        process can still export.  Returns compile seconds."""
        vec = jnp.zeros(len(self._traced_free), dtype=jnp.float64)
        base = self.prepared._values_pytree()
        lowered = self._warm_entry().lower(vec, base, self._fit_data)
        total = _cc.warm_timed(lowered.compile)
        warm_resids = getattr(self.resids, "warm_compile", None)
        if warm_resids is not None:
            total += warm_resids()
        return total

    def _resid_fn_of(self, base_values, data):
        free = self._traced_free

        def resid_fn(v):
            values = dict(base_values)
            for i, name in enumerate(free):
                values[name] = v[i]
            return self.resids.time_resids_at(values, data)

        return resid_fn

    def _merged(self, base_values, vec):
        values = dict(base_values)
        for i, name in enumerate(self._traced_free):
            values[name] = vec[i]
        return values

    # -- guard integration ----------------------------------------------------
    #: degradation-ladder escalation values (guard.JITTER_RUNGS)
    _guard_jitter_rungs = _guard.JITTER_RUNGS

    def _last_good_dict(self, vec_np):
        return {name: float(vec_np[i])
                for i, name in enumerate(self._traced_free)}

    def _check_step_health(self, health, last_good_np, n_iter):
        """THE per-iteration health check every fitter loop shares
        (plain/downhill/LM): one counter, one packed-``ok`` device
        read, StepDiverged with the last finite-chi^2 state on a bad
        verdict.  No-op with the guard off (empty health)."""
        if not health:
            return
        telemetry.counter_add("guard.checks")
        if _guard.verdict(health) != "ok":
            raise _guard.StepDiverged(
                health, last_good=self._last_good_dict(last_good_np),
                n_iter=n_iter)

    def _guard_data(self, guard_eps):
        if guard_eps == 0.0:
            return self._fit_data
        return {**self._fit_data, "guard_eps": np.float64(guard_eps)}

    def _guard_rungs(self, maxiter):
        """The degradation ladder for this fitter: baseline, then (when
        the guard is on) escalating jitter, then an optional downgrade
        (GLS fitters fall back to a WLS solve — `_downgrade_rung`).
        Each rung tells ``_iterate`` its own name, so the flight
        recorder's per-iteration entries carry the serving rung and
        guard_eps — an escalation is visible IN the iteration trace,
        not just as the final GUARD_RUNG verdict."""
        rungs = [("baseline", lambda: self._iterate(maxiter))]
        if self._guard_on:
            for name, eps in self._guard_jitter_rungs:
                rungs.append(
                    (name,
                     lambda e=eps, n=name: self._iterate(
                         maxiter, guard_eps=e, rung=n)))
            down = self._downgrade_rung(maxiter)
            if down is not None:
                rungs.append(down)
        return rungs

    # -- flight recorder ------------------------------------------------------
    def _note_iteration(self, chi2_f, vec_in, vec_new, health,
                        guard_eps, rung):
        """One per-iteration convergence entry
        (``$PINT_TPU_ITER_TRACE``): the single-fitter loop already
        syncs chi^2 per iteration, so the extra device read here is
        the step vector it is about to read back anyway.  ``ok``
        reads the guard's packed bit when the guard is on (already
        synced by `_check_step_health`), the finiteness of
        (chi^2, step) otherwise."""
        d = np.asarray(vec_new) - vec_in
        if health:
            ok = bool(np.asarray(health.ok))
        else:
            ok = bool(np.isfinite(chi2_f) and np.all(np.isfinite(d)))
        entries = getattr(self, "_iter_entries", None)
        if entries is None:
            entries = self._iter_entries = []
        entries.append({
            "i": len(entries), "chi2": chi2_f,
            "step_norm": float(np.sqrt(np.sum(d * d))),
            "max_dpar": float(np.max(np.abs(d))) if d.size else 0.0,
            "ok": ok, "guard_eps": float(guard_eps), "rung": rung,
        })

    def _emit_iter_trace(self, rung):
        """Publish the fit's accumulated iteration record: the
        ``iter_trace`` attribute always (gate on), one JSONL record
        when a sink is attached."""
        entries = getattr(self, "_iter_entries", None)
        if not entries:
            return
        self.iter_trace = list(entries)
        telemetry.emit(telemetry.iter_trace_record(
            f"fitter.step:{type(self).__name__}", self.iter_trace,
            kind="fit", rung=rung, n_toa=len(self.toas),
            n_free=len(self._traced_free)))

    def _inputs_fingerprint(self):
        """Cheap run-ledger identity of this fit's inputs: a hash of
        the residuals structure key, the TOA count, and the free set
        — NOT a content fingerprint (hashing the dataset per fit
        would cost more than the fit's host side), but enough to say
        "these two runs fit the same problem shape"."""
        import hashlib

        return hashlib.blake2b(
            repr((self.resids._structure_key(), len(self.toas),
                  tuple(self.model.free_timing_params))).encode(),
            digest_size=8).hexdigest()

    def _downgrade_rung(self, maxiter):
        """Hook: the final ladder rung (GLS fitters downgrade to WLS)."""
        return None

    def _record_guard(self, rung, health, sp):
        """Publish the fit's guard outcome: ``fit_rung``/``fit_health``
        attributes always; a ``{"type": "health"}`` ledger record
        (joined to the run by the emit-time tag); fit meta + a warning
        when a degraded rung served (a degraded fit must be loud,
        never silent)."""
        self.fit_rung = rung
        self.fit_health = _guard.to_record(health)
        telemetry.emit({"type": "health",
                        "context": type(self).__name__,
                        "rung": rung, **self.fit_health})
        if rung != "baseline":
            self.model.meta["GUARD_RUNG"] = rung
            if sp is not None:
                sp.set(guard_rung=rung)
            warnings.warn(
                f"{type(self).__name__}: fit served by degradation "
                f"rung {rung!r} (see model.meta['GUARD_RUNG'] and "
                "fitter.fit_health)")
        else:
            # a later clean fit clears the flag — the meta lands in the
            # output par file and must describe THIS fit, not a
            # degraded one from before the data was fixed
            self.model.meta.pop("GUARD_RUNG", None)

    def _iterate(self, maxiter, guard_eps=0.0, rung="baseline"):
        """Run the Gauss-Newton loop once (one ladder rung).  Returns
        (vec, cov, extras, n_iter, health); raises guard.StepDiverged
        with the last finite-chi^2 parameter state on a bad verdict.
        ``rung`` labels this attempt's flight-recorder entries."""
        vec = jnp.array(
            [self.model.values[k] for k in self._traced_free],
            dtype=jnp.float64,
        )
        base = self.prepared._values_pytree()
        data = self._guard_data(guard_eps)
        chi2_prev = None
        cov = None
        n_iter = 0
        extras = ()
        health = ()
        last_good = np.array(
            [self.model.values[k] for k in self._traced_free])
        for _ in range(maxiter):
            # the step donates its input vector on TPU/GPU — snapshot
            # the candidate before the call so last_good stays readable
            vec_in = np.asarray(vec)
            vec, chi2, dpar, cov, *rest = self._step_jit(
                vec, base, data)
            extras, health = tuple(rest[:-1]), rest[-1]
            n_iter += 1
            chi2_f = float(chi2)
            if np.isfinite(chi2_f):
                # chi2 is evaluated at the INPUT vector — that vector
                # is the proven-good state
                last_good = vec_in
            if self._iter_trace:
                self._note_iteration(chi2_f, vec_in, vec, health,
                                     guard_eps, rung)
            self._check_step_health(health, last_good, n_iter)
            if chi2_prev is not None and \
                    abs(float(chi2_prev) - chi2_f) \
                    < 1e-8 * max(chi2_f, 1.0):
                break
            chi2_prev = chi2_f
        return vec, cov, extras, n_iter, health

    def fit_toas(self, maxiter=3):
        """Iterate Gauss-Newton steps; write back values + uncertainties.

        On divergence the guard's degradation ladder retries through
        escalating rungs; past the last rung a
        :class:`pint_tpu.guard.FitDivergedError` carries the last-good
        parameter vector and the health record — ``model.values`` is
        never written with non-finite results."""
        if not self.model.free_timing_params:
            raise ValueError(
                "no free timing parameters to fit (mark them with a '1' "
                "fit flag in the par file or clear Param.frozen)"
            )
        with telemetry.run_scope(
                "fit", fitter=type(self).__name__,
                n_toa=len(self.toas),
                fingerprint=self._inputs_fingerprint()), \
            span("fit_toas", fitter=type(self).__name__,
                 n_toa=len(self.toas),
                 n_free=len(self.model.free_timing_params),
                 maxiter=maxiter) as sp:
            if tuple(self.model.free_timing_params) != getattr(
                    self, "_traced_free", ()):
                self._retrace()
            else:
                telemetry.counter_add("fitter.jit_cache_hits")
                # an edited frozen parameter must refresh the
                # precomputed delay leaves (data, not a retrace) — the
                # partition re-keys only when the free SET changes
                self._refresh_frozen()
            self._iter_entries = [] if self._iter_trace else None
            vec, cov_np, n_iter, health, rung = \
                self._fit_with_depth_guard(
                    lambda: self._guard_rungs(maxiter))
            flops_est = self._fit_flops_est(n_iter)
            telemetry.counter_add("fitter.iterations", n_iter)
            telemetry.counter_add("fit.flops_est", flops_est)
            sp.set(n_iter=n_iter, flops_est=flops_est)
            self._record_guard(rung, health, sp)
            self._emit_iter_trace(rung)
            self._update_fit_meta()
            self._post_fit()
            return float(self.resids.chi2)

    def _fit_flops_est(self, n_iter):
        """Modeled FLOPs of this fit (pint_tpu.flops cost model) —
        structure-aware: only the nonlinear remainder pays a tangent
        chain, and segment-carried ECORR columns cost O(N) instead of
        dense matmul terms."""
        n_basis = int(getattr(self.prepared, "noise_basis",
                              np.zeros((0, 0))).shape[1])
        return _flops.gls_fit_flops(
            len(self.toas), len(self._traced_free), n_basis, n_iter,
            n_lin=len(self._partition[0]),
            ecorr_seg=getattr(self.resids, "ecorr_segment_cols", 0))

    def _update_fit_meta(self):
        """Record the fit summary into the model metadata so it lands in
        the output par file (reference: CHI2/TRES/NTOA params,
        timing_model.py:344-386)."""
        r = self.resids
        self.model.meta["NTOA"] = str(
            getattr(r, "n_real", None) or len(self.toas))
        self.model.meta["CHI2"] = f"{r.chi2:.6f}"
        self.model.meta["TRES"] = f"{r.rms_weighted() * 1e6:.6f}"

    def _post_fit(self):
        """Hook for subclasses (e.g. noise realizations)."""

    @property
    def parameter_correlation_matrix(self):
        d = np.sqrt(np.diag(self.covariance))
        return self.covariance / np.outer(d, d)


class WLSFitter(Fitter):
    """Weighted least squares via SVD of the whitened, column-normalized
    design matrix; Gauss-Newton iterations, all inside one jit.  Whitens
    by the noise-scaled uncertainties (EFAC/EQUAD), matching the
    reference WLS path (fitter.py:1990)."""

    def __init__(self, toas, model, residuals=None, threshold=1e-14,
                 bucket=None, mesh=None):
        super().__init__(toas, model, residuals, bucket=bucket,
                         mesh=mesh)
        self.threshold = threshold
        self._retrace()

    def _fit_flops_est(self, n_iter):
        """The SVD step never touches the noise basis — cost it at
        basis width 0 even when the model carries noise components."""
        return _flops.wls_fit_flops(
            len(self.toas), len(self._traced_free), n_iter,
            n_lin=len(self._partition[0]))

    def _step(self, vec, base_values, data):
        """One Gauss-Newton WLS step.  base_values (the full values
        dict, including frozen params) and data (the dataset pytree)
        are dynamic arguments, so edits to frozen parameters between
        fits take effect without retracing and same-shaped problems
        share the trace; changes to WHICH params are free go through
        _retrace().  Returns (new_vec, chi2, dpar, cov, health) —
        health rides the same compiled program (empty with the guard
        off)."""
        if self._noise_frozen:
            sigma = data["noise_sigma"]
        else:
            sigma = self.resids.sigma_at(self._merged(base_values, vec),
                                         data)
        rj = self._rj(vec, base_values, data)
        if not self._guard_on:
            return wls_gn_solve(None, vec, sigma,
                                self.threshold, rj=rj,
                                toa=self._toa_shard) + ((),)
        new_vec, chi2, dpar, cov, diag = wls_gn_solve(
            None, vec, sigma, self.threshold,
            rcond=data["guard_eps"], with_health=True, rj=rj,
            toa=self._toa_shard)
        health = _guard.step_health(
            rj[0], sigma, chi2, dpar, cov, diag,
            valid=data["valid"],
            inputs_ok=_guard.batch_input_finite(data["batch"],
                                                data["valid"]))
        return new_vec, chi2, dpar, cov, health


class WidebandTOAFitter(Fitter):
    """Wideband fit: stacked [time; DM] residual vector with a block
    design matrix, solved through the same noise-augmented normal
    equations (reference: WidebandTOAFitter, fitter.py:2292-2640 via
    combine_design_matrices_by_quantity).  The correlated-noise basis
    acts on the time block; DM rows see DMEFAC/DMEQUAD-scaled white
    noise."""

    def __init__(self, toas, model, residuals=None, bucket=None):
        if residuals is None:
            if bucket is None:
                bucket = _cc.bucketing_default()
            if bucket:
                toas = _cc.pad_toas(toas)
            residuals = WidebandTOAResiduals(toas, model)
        super().__init__(toas, model, residuals=residuals, bucket=False)
        self.noise_realizations = {}
        self._retrace()

    def _rj(self, vec, base_values, data):
        return wideband_resid_and_design(
            self.resids, base_values, data, self._traced_free, vec,
            self._partition)

    def _step(self, vec, base_values, data):
        values = self._merged(base_values, vec)
        sigma_t = self.resids.toa.sigma_at(values, data["toa"])
        sigma_dm = self.resids.dm.sigma_at(values, data["dm"])
        sigma = jnp.concatenate([sigma_t, sigma_dm])
        r, J = self._rj(vec, base_values, data)
        U_t, phi = self.resids.toa._noise_basis_phi_at(values,
                                                       data["toa"])
        if isinstance(U_t, StructuredU):
            # the DM block sees no noise basis: zero rows, outside
            # every ECORR epoch (segment id K_e)
            U = su_pad_rows(U_t, sigma_dm.shape[0])
        else:
            U = jnp.concatenate(
                [U_t, jnp.zeros((sigma_dm.shape[0], U_t.shape[1]))],
                axis=0)
        if not self._guard_on:
            dpar, cov, ncoef, chi2 = gls_normal_solve(r, J, sigma, U,
                                                      phi)
            return vec + dpar, chi2, dpar, cov, ncoef, ()
        dpar, cov, ncoef, chi2, diag = gls_normal_solve(
            r, J, sigma, U, phi, guard_eps=data["guard_eps"],
            with_health=True)
        # the stacked [time; DM] vector needs a stacked pad mask: the
        # DM block's rows are the valid-indexed subset of the TOA rows
        v_t = data["toa"]["valid"]
        valid = None
        if v_t is not None:
            valid = jnp.concatenate(
                [v_t, v_t[data["dm"]["valid_idx"]]])
        health = _guard.step_health(
            r, sigma, chi2, dpar, cov, diag, valid=valid,
            inputs_ok=_guard.batch_input_finite(data["toa"]["batch"],
                                                v_t))
        return vec + dpar, chi2, dpar, cov, ncoef, health


class GLSFitter(Fitter):
    """Generalized least squares over the low-rank noise basis: the
    noise-augmented normal equations solved by Cholesky (reference:
    GLSFitter.fit_toas, fitter.py:2090-2289), one jitted step.

    After fit_toas(), ``noise_realizations`` maps each correlated-noise
    component to its basis-amplitude realization U_c @ a_c [s]
    (reference :2269-2282).
    """

    _noise_gram_leaves = True

    def __init__(self, toas, model, residuals=None, bucket=None,
                 mesh=None):
        super().__init__(toas, model, residuals, bucket=bucket,
                         mesh=mesh)
        self.noise_realizations = {}
        self._retrace()

    def _step(self, vec, base_values, data):
        values = self._merged(base_values, vec)
        if self._noise_frozen:
            # frozen-noise fast path: sigma/phi/gram arrive as
            # precomputed data leaves; the chi^2 is served from the
            # gram's Cholesky with the guard's capacity jitter applied
            # in-trace (gls_normal_solve)
            sigma = data["noise_sigma"]
            U, phi = data["U_ext"], data["noise_phi"]
            gram = data["noise_gram"]
        else:
            sigma = self.resids.sigma_at(values, data)
            U, phi = self.resids._noise_basis_phi_at(values, data)
            gram = None
        r, J = self._rj(vec, base_values, data)
        if not self._guard_on:
            dpar, cov, ncoef, chi2 = gls_normal_solve(
                r, J, sigma, U, phi, gram=gram, toa=self._toa_shard)
            return vec + dpar, chi2, dpar, cov, ncoef, ()
        dpar, cov, ncoef, chi2, diag = gls_normal_solve(
            r, J, sigma, U, phi, gram=gram,
            guard_eps=data["guard_eps"], with_health=True,
            toa=self._toa_shard)
        health = _guard.step_health(
            r, sigma, chi2, dpar, cov, diag, valid=data["valid"],
            inputs_ok=_guard.batch_input_finite(data["batch"],
                                                data["valid"]))
        return vec + dpar, chi2, dpar, cov, ncoef, health

    def _downgrade_rung(self, maxiter):
        """The ladder's last resort: a correlated-noise fit whose solve
        stays non-finite through every jitter rung falls back to the
        plain WLS step (noise-scaled white errors, no basis
        augmentation) on the SAME residuals — degraded statistics, but
        finite timing parameters with the rung flagged in fit meta."""
        def downgrade():
            wls = WLSFitter(self.toas, self.model,
                            residuals=self.resids)
            out = wls._iterate(maxiter, rung="wls")
            # the downgrade iterations run on a throwaway fitter —
            # the SERVED rung's entries must land in THIS fitter's
            # flight record, or the one case the recorder exists to
            # explain (every jitter rung failed) records nothing
            served = getattr(wls, "_iter_entries", None)
            if served:
                if getattr(self, "_iter_entries", None) is None:
                    self._iter_entries = []
                for e in served:
                    self._iter_entries.append(
                        {**e, "i": len(self._iter_entries)})
            return out

        return ("wls", downgrade)

    def _set_noise_realizations(self, ncoef):
        """Per-component noise realizations U_c @ a_c [s] (reference
        fitter.py:2269)."""
        ncoef = np.asarray(ncoef)
        self.noise_realizations = {}
        for name, (start, nb) in self.prepared.noise_dimensions().items():
            basis = np.asarray(self.prepared.noise_basis[:, start:start + nb])
            self.noise_realizations[name] = basis @ ncoef[start:start + nb]

    def _post_fit(self):
        """Solve once more at the written-back optimum so the noise
        realizations correspond to the reported parameters (the loop's
        extras are one Gauss-Newton step stale)."""
        if getattr(self, "fit_rung", "baseline") == "wls":
            # the GLS solve is the thing that diverged — re-running it
            # here would hand back the same non-finite amplitudes
            self.noise_realizations = {}
            return
        vec = jnp.array(
            [self.model.values[k] for k in self._traced_free],
            dtype=jnp.float64,
        )
        base = self.prepared._values_pytree()
        *_, ncoef, _health = self._step_jit(vec, base, self._fit_data)
        self._set_noise_realizations(ncoef)
