"""Fitters: WLS (SVD) and GLS (Woodbury) Gauss-Newton on device.

Counterpart of the reference fitter layer (reference: src/pint/fitter.py:
185 base, :252 ``Fitter.auto``, :1940-2087 WLSFitter, :2090-2289
GLSFitter).  The reference's per-iteration recipe — design matrix,
whiten, column-normalize, solve, parameter step, covariance — becomes
one jitted function of the free-parameter vector; the design matrix is
``jax.jacfwd`` of the residual function (the reference's 124-s
hand-derivative hot spot, profiling/README.txt:58, disappears by
construction).

``Fitter.auto`` mirrors the reference's dispatch (fitter.py:252): GLS
when the model has correlated noise, WLS otherwise.
"""

from __future__ import annotations

import copy
import os
import warnings
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import compile_cache as _cc
from pint_tpu import flops as _flops
from pint_tpu import guard as _guard
from pint_tpu import telemetry
from pint_tpu.linalg import NormalBlocks, StructuredU, _phi_terms, \
    _ut_dot, _weighted_gram, basis_ncols, gls_normal_solve, \
    normal_solve_from_blocks, su_dense_rows, su_pad_rows
from pint_tpu.models.timing_model import frozen_delay_default, \
    hybrid_design_default
from pint_tpu.residuals import Residuals, WidebandTOAResiduals
from pint_tpu.telemetry import span

__all__ = ["WLSFitter", "GLSFitter", "WidebandTOAFitter", "Fitter",
           "wls_gn_solve", "resid_and_design",
           "wideband_resid_and_design"]

# compile events fire during the first fit_toas; the jax.monitoring
# listener must exist before then for jit.compile_* counters to tick
telemetry._install_compile_listener()


def resid_and_design(free, vec, partition, resid_of, linear_of):
    """(r, J) for the free-parameter vector ``vec`` — the hybrid
    analytic/AD design matrix build shared by every fitter step (plain,
    downhill, LM, wideband, grid, batched PTA).

    ``partition`` is PreparedModel.design_partition's ``(linear,
    nonlinear)`` split of ``free``.  ``resid_of(sub)`` evaluates the
    residual vector with the {name: value} dict ``sub`` overriding the
    base values; ``linear_of(values_sub)`` returns the (N, L)
    closed-form columns for the linear names at those values
    (Residuals.linear_design_at — one delay fold plus one ``jvp``
    through the phase stage, shared by every column).  ``jax.jacfwd``
    runs only over the nonlinear remainder, so the tangent width
    through the full residual chain drops from P to P_nl.  With an
    empty linear set this degrades to exactly the classic full-jacfwd
    build."""
    lin, nl = partition
    free = tuple(free)
    full = {name: vec[i] for i, name in enumerate(free)}
    r = resid_of(full)
    if not lin:
        def resid_fn(v):
            return resid_of({name: v[i] for i, name in enumerate(free)})

        return r, jax.jacfwd(resid_fn)(vec)
    idx = {name: i for i, name in enumerate(free)}
    J_lin = linear_of(full)
    if nl:
        nl_idx = jnp.asarray([idx[p] for p in nl])

        def resid_nl(nv):
            sub = dict(full)
            for j, p in enumerate(nl):
                sub[p] = nv[j]
            return resid_of(sub)

        J_nl = jax.jacfwd(resid_nl)(vec[nl_idx])
        blocks = jnp.concatenate([J_nl, J_lin], axis=1)
    else:
        blocks = J_lin
    # one gather back to free order instead of P column slices+stack
    order = {p: j for j, p in enumerate(tuple(nl) + tuple(lin))}
    perm = [order[p] for p in free]
    if perm == list(range(len(free))):
        return r, blocks
    return r, blocks[:, jnp.asarray(perm)]


def wideband_resid_and_design(resids, base_values, data, free, vec,
                              partition):
    """Hybrid (r, J) for the stacked wideband [time; DM] system —
    shared by WidebandTOAFitter and WidebandLMFitter.  The linear
    columns stack the time block (Residuals.linear_design_at) over the
    DM block (WidebandDMResiduals.linear_dm_design_at); the partition
    already required every linear owner with a ``dm_value`` to provide
    ``d_dm_d_param`` (design_partition(wideband=True))."""
    toa_r, dm_r = resids.toa, resids.dm

    def resid_of(sub):
        values = dict(base_values)
        values.update(sub)
        return jnp.concatenate(
            [toa_r.time_resids_at(values, data["toa"]),
             dm_r.dm_resids_at(values, data["dm"])])

    def linear_of(sub):
        values = dict(base_values)
        values.update(sub)
        lin = partition[0]
        return jnp.concatenate(
            [toa_r.linear_design_at(values, data["toa"], lin),
             dm_r.linear_dm_design_at(values, data["dm"], lin)], axis=0)

    return resid_and_design(free, vec, partition, resid_of, linear_of)


def wls_gn_solve(resid_fn, vec, err, threshold=1e-14, rcond=None,
                 with_health=False, rj=None, toa=None):
    """One whitened, column-normalized SVD Gauss-Newton step.

    The shared numerical core of WLSFitter and the vmapped grid (one
    implementation, one threshold).  resid_fn(vec) -> residuals [s].
    Returns (new_vec, chi2_before, dpar, covariance).

    rcond: optional traced scalar raising the singular-value cutoff
    above ``threshold`` (the guard ladder's escalation — dynamic, so
    it costs zero new compiles).  with_health: additionally return a
    :class:`pint_tpu.guard.SolveDiag` from the SVD spectrum already in
    hand.  rj: optional precomputed ``(r, J)`` — the hybrid design
    path (:func:`resid_and_design`) supplies it so the solve never
    re-runs ``jacfwd`` over the full chain; resid_fn may then be None.
    toa: optional :class:`pint_tpu.parallel.mesh.RowShard` keeping the
    whitened (N, P) system sharded over the TOA axis (the SVD itself
    gathers — the win is the upstream residual/design build staying
    sharded; the normal-equation GLS path is where the reduction
    decomposes, see linalg.gls_normal_solve).
    """
    if rj is not None:
        r, J = rj
    else:
        r = resid_fn(vec)
        J = jax.jacfwd(resid_fn)(vec)  # (N, P) d resid / d param
    if toa is not None:
        r, J, err = toa.rows(r), toa.rows(J), toa.rows(err)
    w = 1.0 / err
    rw = r * w
    Jw = J * w[:, None]
    # column normalize (reference: utils.normalize_designmatrix)
    norms = jnp.sqrt(jnp.sum(Jw * Jw, axis=0))
    norms = jnp.where(norms == 0, 1.0, norms)
    Jn = Jw / norms[None, :]
    U, s, Vt = jnp.linalg.svd(Jn, full_matrices=False)
    smax = jnp.max(s)
    cut = threshold if rcond is None else jnp.maximum(threshold, rcond)
    s_inv = jnp.where(s > cut * smax, 1.0 / s, 0.0)
    dpar_n = -(Vt.T * s_inv[None, :]) @ (U.T @ rw)
    dpar = dpar_n / norms
    cov_n = (Vt.T * s_inv[None, :] ** 2) @ Vt
    cov = cov_n / jnp.outer(norms, norms)
    chi2 = jnp.sum(rw * rw)
    out = (vec + dpar, chi2, dpar, cov)
    if with_health:
        kept_min = jnp.min(jnp.where(s_inv > 0.0, s, smax))
        diag = _guard.SolveDiag(
            n_truncated=jnp.sum(s_inv == 0.0).astype(jnp.int32),
            cond_log10=jnp.log10(smax / jnp.maximum(kept_min, 1e-300)),
        )
        out = out + (diag,)
    return out


# -- streaming appends (module helpers) -------------------------------------
#
# The serve plane's incremental ingestion path (arXiv 1210.0584): an
# appended observing epoch touches the normal-equation system only
# through row sums, so DeltaN new TOAs are a rank-DeltaN update.  The
# fitter keeps RAW (uncentered) weighted moments of the current
# linearization as stream state and derives the mean-centered
# NormalBlocks at solve time — the global weighted-mean coupling of
# ``subtract_mean`` (appending rows moves the mean, which moves EVERY
# row's residual) collapses to a rank-one correction instead of an
# O(N) re-read.  See docs/streaming.md for the algebra.


def stream_block_default():
    """Padded block size for append deltas
    (``$PINT_TPU_STREAM_BLOCK``): every nightly delta pads to this many
    rows, so the per-append delta/refit programs compile ONCE and serve
    any DeltaN up to the block — zero recompiles on the steady-state
    append path."""
    raw = os.environ.get("PINT_TPU_STREAM_BLOCK", "")
    try:
        n = int(raw)
    except ValueError:
        n = 0
    return n if n > 0 else 32


def stream_triage_sigma_default():
    """Anomaly-triage threshold in whitened sigma units
    (``$PINT_TPU_STREAM_TRIAGE_SIGMA``)."""
    raw = os.environ.get("PINT_TPU_STREAM_TRIAGE_SIGMA", "")
    try:
        v = float(raw)
    except ValueError:
        v = 0.0
    return v if v > 0 else 7.0


def stream_recapture_default():
    """Incremental refits between full moment re-captures
    (``$PINT_TPU_STREAM_RECAPTURE``).  The refit linearizes at the
    capture point and first-order-shifts the moments after each step;
    periodic recapture re-anchors the Jacobian at the current optimum
    so the quadratic residue of the timing model cannot accumulate."""
    raw = os.environ.get("PINT_TPU_STREAM_RECAPTURE", "")
    try:
        n = int(raw)
    except ValueError:
        n = 0
    return n if n > 0 else 8


class _StreamMoments(NamedTuple):
    """Raw (uncentered) weighted moments of the current linearization.

    With q the residual and Jq its design evaluated WITHOUT mean
    subtraction, w the 1/sigma^2 weights, U the (raw) extended noise
    basis and phi its prior: every mean-centered normal-equation block
    is an exact function of these sums (:func:`_derive_blocks`), and
    DeltaN appended rows update each by a row sum."""

    a_qq: jnp.ndarray   # (P, P)  Jq^T W Jq
    a_qu: jnp.ndarray   # (P, K)  Jq^T W U
    g_uu: jnp.ndarray   # (K, K)  U^T W U + Phi^-1
    b_q: jnp.ndarray    # (P,)    Jq^T W q
    b_u: jnp.ndarray    # (K,)    U^T W q
    rr: jnp.ndarray     # ()      q^T W q
    s_j: jnp.ndarray    # (P,)    Jq^T W 1
    s_u: jnp.ndarray    # (K,)    U^T W 1
    s_q: jnp.ndarray    # ()      1^T W q
    s_w: jnp.ndarray    # ()      1^T W 1


def _derive_blocks(m: _StreamMoments, center) -> NormalBlocks:
    """Mean-centered :class:`~pint_tpu.linalg.NormalBlocks` from raw
    moments.  Subtracting the weighted mean mu_x = S_x / S_w from two
    row vectors turns their weighted product sum into
    S_xy - S_x S_y / S_w; only the residual/design side centers — U
    stays raw in the GLS system (the mean-offset column carries the
    mean there)."""
    if not center:
        return NormalBlocks(a_jj=m.a_qq, a_ju=m.a_qu, gram=m.g_uu,
                            y_j=m.b_q, y_u=m.b_u, rr=m.rr)
    c = 1.0 / m.s_w
    return NormalBlocks(
        a_jj=m.a_qq - c * jnp.outer(m.s_j, m.s_j),
        a_ju=m.a_qu - c * jnp.outer(m.s_j, m.s_u),
        gram=m.g_uu,
        y_j=m.b_q - c * m.s_j * m.s_q,
        y_u=m.b_u - c * m.s_u * m.s_q,
        rr=m.rr - c * m.s_q ** 2)


def _u_rows(U, rows):
    """Dense rows of an extended basis (handles StructuredU) — the
    append path's (DeltaN, K) slice."""
    if isinstance(U, StructuredU):
        return np.asarray(su_dense_rows(U, np.asarray(rows)))
    return np.asarray(U)[np.asarray(rows)]


def _u_rows_slice(U, row0, dn):
    """Contiguous ``[row0, row0+dn)`` dense rows of an extended basis.
    For a dense on-device basis this is a ``dynamic_slice`` — O(DeltaN
    K) device->host, instead of ``np.asarray(U)`` pulling the whole
    (N, K) matrix across per append."""
    if isinstance(U, StructuredU):
        return np.asarray(su_dense_rows(U, np.arange(row0, row0 + dn)))
    if isinstance(U, jnp.ndarray):
        return np.asarray(jax.lax.dynamic_slice(
            U, (row0, 0), (dn, U.shape[1])))
    return np.asarray(U)[row0:row0 + dn]


def _quarantine_rows(delta, rows):
    """Copy of an append delta with the given rows turned into
    zero-weight sentinels: quarantined TOAs keep their dataset slot
    (layout, flags, the ``-quarantine 1`` audit mark) but carry
    ``PAD_ERROR_US`` uncertainty, so no weighted reduction sees them —
    the triage's hold-out, not a deletion."""
    out = delta[np.arange(len(delta))]
    out.error_us = np.asarray(out.error_us, dtype=np.float64).copy()
    out.error_us[rows] = _cc.PAD_ERROR_US
    for i in rows:
        out.flags[int(i)]["quarantine"] = "1"
    return out


class _MiniAppend(NamedTuple):
    """One append delta prepared as a tiny padded dataset — the O(DeltaN)
    evaluation surface for the delta rows' residuals, design rows,
    sigma and frozen-delay leaf entries."""

    toas: object
    prep: object
    res: object
    data: dict
    n: int        # real delta rows
    block: int    # padded block length
    frozen: object = None  # frozen-delay leaves, computed once


class Fitter:
    """Base fitter: holds (toas, model), exposes fit_toas().

    bucket: pad the TOAs to the next geometric size bucket
    (compile_cache.pad_toas) so nearby dataset sizes share one XLA
    executable.  None reads ``$PINT_TPU_BUCKET_TOAS`` (default off);
    explicit residuals suppress padding (their dataset is fixed).

    mesh: an optional device mesh with a ``toa`` axis
    (:func:`pint_tpu.parallel.mesh.make_mesh`) sharding the SEQUENCE
    dimension of this single pulsar's fit over devices: the dataset
    pytree is TOA-padded to a device multiple and placed with
    NamedShardings, and the Woodbury/normal-equation contractions of
    the step reduce shard-local with one small-(P+K) all-reduce
    (linalg ``toa=`` / :class:`~pint_tpu.parallel.mesh.RowShard`) —
    a 20-year dataset's O(N (P+K)^2) gram assembly parallelizes.
    Segment-sum ECORR epoch blocks are pad-aligned to shard
    boundaries (``mesh.toa_shard_plan`` → sentinel row insertion) or
    the basis falls back dense, brute-force-equal either way.  The
    mesh joins the step's jit key: a second same-shaped sharded
    fitter performs zero new XLA compiles, and ``mesh=None`` keys
    and behaves exactly as before.
    """

    #: which frozen-noise leaves this class's step consumes: every
    #: step whitens with ``noise_sigma``, but only the GLS normal
    #: equations also read ``(noise_phi, noise_gram)`` — building the
    #: ~N K^2 gram eagerly (then shipping and donating its leaves
    #: through every step call) for a WLS/LM/Powell step that never
    #: reads it is pure waste on correlated-noise models.
    _noise_gram_leaves = False

    def __init__(self, toas, model, residuals=None, bucket=None,
                 mesh=None):
        if bucket is None:
            bucket = _cc.bucketing_default()
        self._toa_mesh = mesh
        if mesh is not None:
            if residuals is not None:
                raise ValueError(
                    "mesh= needs to pad/align the TOA axis itself; "
                    "explicit residuals are unsupported on the "
                    "TOA-sharded path")
            from pint_tpu.parallel import mesh as _pm

            ndev = _pm.axis_size(mesh, "toa")
            n = len(toas)
            if getattr(toas, "n_real", None) is not None:
                # already padded (bucketed upstream): pad_toas would
                # reject a conflicting re-pad target, but appending
                # further sentinel rows through the row-plan path is
                # exact (the plan machinery carries the pad_valid
                # mask whether or not the pads are a suffix)
                target = _pm.pad_to_multiple(n, ndev)
                if target != n:
                    toas = _cc.apply_toa_row_plan(
                        toas, np.concatenate(
                            [np.arange(n),
                             np.full(target - n, -1)]))
                _pm.record_pad_waste("toa", toas.n_real, target)
            else:
                target = _cc.bucket_size(n) if bucket else n
                target = _pm.pad_to_multiple(max(target, n), ndev)
                toas = _cc.pad_toas(toas, n_target=target)
                _pm.record_pad_waste("toa", n, target)
        elif bucket and residuals is None:
            toas = _cc.pad_toas(toas)
        self.toas = toas
        self.model = model
        self.resids = residuals or Residuals(toas, model)
        self.prepared = self.resids.prepared
        if mesh is not None:
            self._align_toa_epochs()

    def _align_toa_epochs(self):
        """Segment-sum ECORR epoch blocks must not straddle TOA-shard
        boundaries (the segment reduction would scatter-add across
        devices): when the dataset's epoch layout straddles, re-lay
        the rows with sentinel pads pushing each epoch cluster inside
        one shard (``mesh.toa_shard_plan`` +
        ``compile_cache.apply_toa_row_plan``), rebuilding the
        residuals over the realigned dataset; when no plan exists
        (an epoch cluster larger than a shard), fall back to the
        dense basis — both brute-force-equal to the unsharded fit."""
        from pint_tpu.linalg import su_to_dense
        from pint_tpu.parallel import mesh as _pm

        ndev = _pm.axis_size(self._toa_mesh, "toa")
        if ndev <= 1:
            return
        for attempt in range(2):
            su = self.resids._U_ext
            if not isinstance(su, StructuredU):
                return
            seg = np.asarray(su.seg)
            k_e = int(su.eslot.shape[0])
            if _pm.toa_epochs_aligned(seg, k_e, ndev):
                return
            if attempt == 0:
                plan = _pm.toa_shard_plan(seg, k_e, ndev)
                if plan is not None:
                    telemetry.counter_add("mesh.toa_align_replans")
                    self.toas = _cc.apply_toa_row_plan(self.toas,
                                                       plan)
                    self.resids = Residuals(self.toas, self.model)
                    self.prepared = self.resids.prepared
                    continue
            telemetry.counter_add("mesh.ecorr_dense_fallbacks")
            warnings.warn(
                "ECORR epoch blocks straddle TOA-shard boundaries "
                "and cannot be pad-aligned; serving the dense basis "
                "for this sharded fit")
            self.resids._U_ext = su_to_dense(su)
            self.resids._data_cached = None
            self.resids._structure_key_cached = None
            return

    @staticmethod
    def auto(toas, model, downhill=True, bucket=None):
        """Pick a fitter like the reference (fitter.py:252): wideband
        when the TOAs carry -pp_dm data (and the model says DMDATA), GLS
        when the model carries correlated noise, WLS otherwise; downhill
        variants when requested."""
        wideband = model.meta.get("DMDATA", "").split() and \
            model.meta["DMDATA"].split()[0].upper() in ("1", "Y", "YES",
                                                        "TRUE")
        if wideband:
            # DMDATA in the par is a request, not a guarantee — the TOAs
            # must actually carry -pp_dm measurements (reference
            # Fitter.auto checks toas.wideband)
            wideband = toas.wideband_dm_data()[2].any()
        if wideband:
            if downhill:
                from pint_tpu.downhill import WidebandDownhillFitter

                return WidebandDownhillFitter(toas, model, bucket=bucket)
            return WidebandTOAFitter(toas, model, bucket=bucket)
        if downhill:
            from pint_tpu.downhill import DownhillGLSFitter, DownhillWLSFitter

            if model.has_correlated_errors:
                return DownhillGLSFitter(toas, model, bucket=bucket)
            return DownhillWLSFitter(toas, model, bucket=bucket)
        if model.has_correlated_errors:
            return GLSFitter(toas, model, bucket=bucket)
        return WLSFitter(toas, model, bucket=bucket)

    # -- reporting -----------------------------------------------------------
    def get_summary(self) -> str:
        r = self.resids
        lines = [
            f"Fitted model {self.model.meta.get('PSR', self.model.name)} "
            f"with {len(self.toas)} TOAs, {len(self.model.free_params)} "
            "free parameters",
            f"chi2 = {r.chi2:.3f} / dof {r.dof} = {r.reduced_chi2:.4f}",
            f"weighted RMS = {r.rms_weighted() * 1e6:.4f} us",
            "",
            f"{'PARAM':<12s} {'VALUE':<24s} {'UNCERTAINTY':<12s}",
        ]
        params = self.model.params
        for name in self.model.free_params:
            p = params[name]
            unc = p.uncertainty
            lines.append(
                f"{name:<12s} {p.format(self.model.values[name]):<24s} "
                f"{unc if unc is not None else '':<12}"
            )
        return "\n".join(lines)

    def print_summary(self):
        print(self.get_summary())

    def ftest(self, unfreeze, maxiter=6):
        """F-test for adding parameters (reference: Fitter.ftest,
        fitter.py:619): refit a copy of the model with ``unfreeze``
        additionally free; returns {'p': chance probability,
        'chi2': new chi2, 'dof': new dof, 'fitter': the new fitter}.
        Small p favors keeping the extra parameters."""
        from pint_tpu.models import get_model
        from pint_tpu.utils import FTest

        chi2_1 = float(self.resids.chi2)
        dof_1 = self.resids.dof
        m2 = get_model(self.model.as_parfile())
        params = m2.params
        for name in unfreeze:
            if name not in params:
                raise KeyError(f"unknown parameter {name}")
            params[name].frozen = False
        f2 = type(self)(self.toas, m2)
        f2.fit_toas(maxiter=maxiter)
        chi2_2 = float(f2.resids.chi2)
        dof_2 = f2.resids.dof
        return {
            "p": FTest(chi2_1, dof_1, chi2_2, dof_2),
            "chi2": chi2_2,
            "dof": dof_2,
            "fitter": f2,
        }

    # -- shared machinery -----------------------------------------------------
    def _partition_setup(self):
        """Compute the structure-aware split for the current free set:
        the frozen-delay component list (owns no free parameter — its
        delay enters the trace as precomputed DATA), the hybrid
        linear/nonlinear design partition, and the frozen-value
        fingerprint that detects stale precomputed leaves.  Returns the
        extra data leaves to merge into the fit-data pytree."""
        free = self._traced_free
        prep = self.prepared
        self._hybrid_on = hybrid_design_default()
        self._frozen_on = frozen_delay_default()
        self._frozen_names = (prep.frozen_delay_split(free)
                              if self._frozen_on else ())
        wideband = isinstance(self.resids, WidebandTOAResiduals)
        if self._hybrid_on:
            self._partition = prep.design_partition(
                free, frozen=self._frozen_names, wideband=wideband)
        else:
            self._partition = ((), tuple(free))
        self._frozen_fp = prep.frozen_param_values(self._frozen_names)
        telemetry.counter_add("fitter.linear_cols",
                              len(self._partition[0]))
        telemetry.counter_add("fitter.frozen_components",
                              len(self._frozen_names))
        frozen, tzr_frozen = prep.frozen_delay_leaves(self._frozen_names)
        leaves = {}
        if frozen is not None:
            leaves["frozen"] = frozen
            if tzr_frozen is not None:
                leaves["tzr_frozen"] = tzr_frozen
        # frozen-noise fast path: when no free parameter belongs to a
        # noise component, sigma / U / phi are constants of the fit —
        # they enter the traced step as precomputed DATA leaves (same
        # contract as the frozen delays: dynamic, so trace sharing and
        # zero-recompile survive), and the GLS normal matrix reuses the
        # precomputed (K, K) noise gram instead of rebuilding the
        # O(N (P+K)^2) weighted gram every iteration
        self._noise_owned = tuple(sorted(
            p.name for c in prep.model.noise_components for p in c.params))
        self._noise_frozen = (
            self._frozen_on
            and not wideband
            and set(self._noise_owned).isdisjoint(free))
        if self._noise_frozen:
            self._noise_fp = self._noise_param_values()
            leaves.update(self._noise_leaves())
            telemetry.counter_add("fitter.noise_frozen")
        return leaves

    def _noise_param_values(self):
        """{param: value} over the noise components — the fingerprint
        that detects stale frozen-noise leaves (an EFAC edited between
        fits must re-fold sigma, never serve the old one)."""
        return {name: float(self.model.values.get(name, np.nan))
                for name in self._noise_owned}

    def _noise_leaves(self):
        """Precompute the fit-constant noise arrays host-side: sigma
        always; (phi, gram) only for classes whose step consumes them
        (``_noise_gram_leaves`` — the GLS normal equations).  The
        guard ladder's dynamic capacity jitter keeps working: the
        gram-served chi^2 applies the same per-diagonal relative ridge
        in-trace (linalg.gls_normal_solve)."""
        from pint_tpu.linalg import noise_gram_precompute

        base = self.prepared._values_pytree()
        sigma = jnp.asarray(np.asarray(self.resids.sigma_fn(base)))
        leaves = {"noise_sigma": sigma}
        if not self._noise_gram_leaves:
            return leaves
        # U itself already rides the data pytree as "U_ext"; phi/gram
        # are built even for an uncorrelated model (whose basis is just
        # the mean-offset column) — the GLS step uses them regardless
        U, phi = self.resids._noise_basis_phi(base)
        leaves["noise_phi"] = jnp.asarray(np.asarray(phi))
        leaves["noise_gram"] = jnp.asarray(np.asarray(
            noise_gram_precompute(sigma, U, phi)))
        return leaves

    def _inject_frozen(self, data, leaves):
        """Merge the frozen-delay leaves into the fit-data pytree (the
        time-block sub-dict on the wideband layout)."""
        if not leaves:
            return data
        if "toa" in data:
            return {**data, "toa": {**data["toa"], **leaves}}
        return {**data, **leaves}

    @staticmethod
    def _fp_same(a, b):
        """NaN-tolerant {param: value} fingerprint equality."""
        return a.keys() == b.keys() and all(
            v == b[k] or (v != v and b[k] != b[k]) for k, v in a.items())

    def _refresh_frozen(self):
        """Re-fold the frozen-delay / frozen-noise leaves when a frozen
        parameter was edited between fits (fingerprint mismatch) — a
        cheap host recompute, never a retrace: the leaves are dynamic
        data."""
        if getattr(self, "_frozen_names", ()):
            fp = self.prepared.frozen_param_values(self._frozen_names)
            if not self._fp_same(fp, self._frozen_fp):
                telemetry.counter_add("fitter.frozen_refreshes")
                self._frozen_fp = fp
                frozen, tzr_frozen = self.prepared.frozen_delay_leaves(
                    self._frozen_names)
                leaves = {"frozen": frozen}
                if tzr_frozen is not None:
                    leaves["tzr_frozen"] = tzr_frozen
                self._fit_data = self._inject_frozen(
                    {k: v for k, v in self._fit_data.items()
                     if k not in ("frozen", "tzr_frozen")}, leaves)
        if getattr(self, "_noise_frozen", False):
            fp = self._noise_param_values()
            if not self._fp_same(fp, self._noise_fp):
                telemetry.counter_add("fitter.noise_refreshes")
                self._noise_fp = fp
                self._fit_data = {**self._fit_data,
                                  **self._noise_leaves()}
        # refreshed leaves are host arrays — re-commit them onto the
        # TOA mesh so the executable's input shardings stay stable
        # (no-op unsharded; a committed leaf re-placed is free)
        self._shard_fit_data()

    def _kepler_depth_guard(self):
        """Post-fit Kepler-depth verification.  The Newton unroll
        depth is a STATIC ctx int chosen from the PREPARE-time
        eccentricity class (binary/base.prepare); a fit that moves
        ECC/EDOT into a higher class would otherwise iterate a
        too-shallow solver silently (e = 0.9 at the 4-deep unroll
        leaves ~1e-5 rad in the eccentric anomaly).  Called after
        write-back: re-derives the reach at the FITTED values, deepens
        the unroll when the class rose, and re-keys the traces.
        Returns True when the caller must run the fit again — the
        previous solution came from the shallow solver.  Depth is
        monotone over four classes, so the refit loop is bounded."""
        reach = self.prepared.kepler_ecc_reach()
        if reach == float("-inf"):
            return False
        if not self.resids.ensure_kepler_depth(reach):
            return False
        telemetry.counter_add("fitter.kepler_depth_refits")
        warnings.warn(
            "fitted eccentricity reach %.3g exceeds the prepare-time "
            "Kepler depth class — deepening the Newton unroll and "
            "refitting" % reach)
        self._retrace()
        return True

    def _fit_with_depth_guard(self, rungs_fn):
        """The guard-laddered fit + write-back + post-fit Kepler depth
        verification shared by the plain, downhill and LM fit loops
        (Powell's scipy-shaped variant has its own).  Depth classes
        are monotone (4 -> 6 -> 8 -> full), so the guard can force at
        most three reruns — each after a ``_retrace``, which is why
        ``rungs_fn`` rebuilds its rung closures against the current
        traced state.  Returns (vec_np, cov_np, n_iter, health,
        rung)."""
        for _depth_try in range(4):
            (vec, cov, extras, n_iter, health), rung = \
                _guard.run_ladder(rungs_fn(),
                                  context=type(self).__name__)
            self._step_extras = extras
            # write back (cov diagonal clipped: a last-ulp negative
            # variance must not write a NaN uncertainty)
            vec_np = np.asarray(vec)
            cov_np = np.asarray(cov)
            telemetry.record_transfer(vec_np)
            telemetry.record_transfer(cov_np)
            errs = np.sqrt(np.clip(np.diag(cov_np), 0, None))
            params = self.model.params
            for i, name in enumerate(self._traced_free):
                self.model.values[name] = float(vec_np[i])
                params[name].uncertainty = float(errs[i])
            self.covariance = cov_np
            if not self._kepler_depth_guard():
                break
        return vec_np, cov_np, n_iter, health, rung

    def _retrace(self):
        """(Re)key the jitted step for the current free-param set.
        The trace closes over the free-param *names*; a changed free set
        with the same count would otherwise hit the stale jit cache and
        silently write steps into the wrong parameters.

        The jitted callable comes from the process-level registry
        (compile_cache.shared_jit): the step takes the dataset as a
        DYNAMIC argument, so its key is purely structural and a second
        fitter on a same-shaped problem reuses this one's trace and
        executable — zero new XLA compiles."""
        telemetry.counter_add("fitter.retraces")
        # a retrace re-keys the step (free set / partition / structure
        # changed) — any captured stream moments describe the OLD
        # program's linearization; drop them (append_refit re-captures)
        self._stream = None
        self._traced_free = tuple(self.model.free_timing_params)
        # the guard's escalation scalar rides the data pytree as a
        # DYNAMIC leaf (precedent: n_real), so ladder rungs reuse the
        # same trace; the on/off flag changes the traced program and is
        # part of the key
        self._guard_on = _guard.enabled()
        # flight-recorder gate: the single-fitter loop is host-driven
        # (one _step_jit call per iteration), so the per-iteration
        # record accumulates host-side and the step PROGRAM is
        # gate-invariant — but the gate still keys uniformly with the
        # grid/PTA programs it DOES re-trace, so the gate->key lint
        # (tools/check_jit_gates.py) stays one rule with no per-site
        # exemptions and a future in-trace fitter loop can't miss it
        self._iter_trace = _cc.iter_trace_default()
        # TOA-axis sharding: the RowShard is closed over by the step
        # trace (its constraints change the program — the mesh rides
        # the key below), and the dataset pytree is committed onto the
        # mesh so a second same-shaped sharded fitter reuses both the
        # placement and the executable
        self._toa_shard = None
        if self._toa_mesh is not None:
            from pint_tpu.parallel import mesh as _pm

            self._toa_shard = _pm.RowShard(self._toa_mesh)
        leaves = self._partition_setup()
        self._fit_data = self._inject_frozen(
            {**self.resids._data(), "guard_eps": np.float64(0.0)},
            leaves)
        self._shard_fit_data()
        self._step_jit = _cc.shared_jit(
            self._step, key=self._step_key(),
            donate_argnums=_cc.donation_argnums((0,)),
            label=f"fitter.step:{type(self).__name__}"
                  + (":sharded" if self._toa_mesh is not None else ""))
        if self._toa_mesh is not None:
            from pint_tpu.parallel import mesh as _pm

            self._step_jit.set_mesh(_pm.mesh_desc(self._toa_mesh))
        # flops.py's per-step estimate rides the program record so the
        # profiler can reconcile it against XLA's own cost_analysis
        # (>2x disagreement -> profile.flops_mismatch)
        self._step_jit.set_analytic_flops(self._fit_flops_est(1))

    def _shard_fit_data(self):
        """Commit the fit-data pytree onto the TOA mesh (no-op
        unsharded).  Re-run after any host-side leaf refresh — a
        freshly-built uncommitted leaf among committed ones would
        change the executable's input-sharding signature and force a
        recompile."""
        if self._toa_mesh is None:
            return
        from pint_tpu.parallel import mesh as _pm

        self._fit_data = _pm.shard_toa_data(
            self._toa_mesh, self._fit_data, len(self.toas))

    def _step_key(self):
        """Everything a trace of _step bakes in beyond the avals.
        The design partition and frozen-component list change the
        traced program (which columns are analytic, which chain
        members fold in data), so they are part of the key — as are
        the env gates through them, and the TOA mesh (the RowShard
        constraints change the traced program;
        mesh.mesh_jit_key also carries the process topology)."""
        from pint_tpu.parallel import mesh as _pm

        return ("fitter.step", type(self).__name__, self._traced_free,
                getattr(self, "threshold", None), self._guard_on,
                self._iter_trace,
                self._partition, self._frozen_names, self._noise_frozen,
                self.resids._structure_key()) \
            + _pm.mesh_jit_key(self._toa_mesh)

    def _rj(self, vec, base_values, data):
        """(r, J) over the traced free set — the hybrid analytic/AD
        design build (see resid_and_design)."""

        def resid_of(sub):
            values = dict(base_values)
            values.update(sub)
            return self.resids.time_resids_at(values, data)

        def linear_of(sub):
            values = dict(base_values)
            values.update(sub)
            return self.resids.linear_design_at(values, data,
                                                self._partition[0])

        return resid_and_design(self._traced_free, vec,
                                self._partition, resid_of, linear_of)

    def _warm_entry(self):
        """The registry program ``warm_compile`` AOT-compiles —
        subclass hook (the downhill family warms its halving step, the
        program its fit loop actually drives)."""
        return self._step_jit

    def warm_compile(self):
        """AOT-compile (lower().compile()) the fit step AND the
        residuals accessors the fit epilogue reports through (chi^2,
        weighted RMS) for this problem's shapes, without running a fit
        — with the persistent cache enabled this writes the
        executables to disk, so a future process's first fit is
        disk reads end to end.  Lowering through the registry proxy
        also records the argument spec AOT export serializes from
        (compile_cache.export_executables), so a warmed-but-never-run
        process can still export.  Returns compile seconds."""
        vec = jnp.zeros(len(self._traced_free), dtype=jnp.float64)
        base = self.prepared._values_pytree()
        lowered = self._warm_entry().lower(vec, base, self._fit_data)
        total = _cc.warm_timed(lowered.compile)
        warm_resids = getattr(self.resids, "warm_compile", None)
        if warm_resids is not None:
            total += warm_resids()
        return total

    def _resid_fn_of(self, base_values, data):
        free = self._traced_free

        def resid_fn(v):
            values = dict(base_values)
            for i, name in enumerate(free):
                values[name] = v[i]
            return self.resids.time_resids_at(values, data)

        return resid_fn

    def _merged(self, base_values, vec):
        values = dict(base_values)
        for i, name in enumerate(self._traced_free):
            values[name] = vec[i]
        return values

    # -- guard integration ----------------------------------------------------
    #: degradation-ladder escalation values (guard.JITTER_RUNGS)
    _guard_jitter_rungs = _guard.JITTER_RUNGS

    def _last_good_dict(self, vec_np):
        return {name: float(vec_np[i])
                for i, name in enumerate(self._traced_free)}

    def _check_step_health(self, health, last_good_np, n_iter):
        """THE per-iteration health check every fitter loop shares
        (plain/downhill/LM): one counter, one packed-``ok`` device
        read, StepDiverged with the last finite-chi^2 state on a bad
        verdict.  No-op with the guard off (empty health)."""
        if not health:
            return
        telemetry.counter_add("guard.checks")
        if _guard.verdict(health) != "ok":
            raise _guard.StepDiverged(
                health, last_good=self._last_good_dict(last_good_np),
                n_iter=n_iter)

    def _guard_data(self, guard_eps):
        if guard_eps == 0.0:
            return self._fit_data
        return {**self._fit_data, "guard_eps": np.float64(guard_eps)}

    def _guard_rungs(self, maxiter):
        """The degradation ladder for this fitter: baseline, then (when
        the guard is on) escalating jitter, then an optional downgrade
        (GLS fitters fall back to a WLS solve — `_downgrade_rung`).
        Each rung tells ``_iterate`` its own name, so the flight
        recorder's per-iteration entries carry the serving rung and
        guard_eps — an escalation is visible IN the iteration trace,
        not just as the final GUARD_RUNG verdict."""
        rungs = [("baseline", lambda: self._iterate(maxiter))]
        if self._guard_on:
            for name, eps in self._guard_jitter_rungs:
                rungs.append(
                    (name,
                     lambda e=eps, n=name: self._iterate(
                         maxiter, guard_eps=e, rung=n)))
            down = self._downgrade_rung(maxiter)
            if down is not None:
                rungs.append(down)
        return rungs

    # -- flight recorder ------------------------------------------------------
    def _note_iteration(self, chi2_f, vec_in, vec_new, health,
                        guard_eps, rung):
        """One per-iteration convergence entry
        (``$PINT_TPU_ITER_TRACE``): the single-fitter loop already
        syncs chi^2 per iteration, so the extra device read here is
        the step vector it is about to read back anyway.  ``ok``
        reads the guard's packed bit when the guard is on (already
        synced by `_check_step_health`), the finiteness of
        (chi^2, step) otherwise."""
        d = np.asarray(vec_new) - vec_in
        if health:
            ok = bool(np.asarray(health.ok))
        else:
            ok = bool(np.isfinite(chi2_f) and np.all(np.isfinite(d)))
        entries = getattr(self, "_iter_entries", None)
        if entries is None:
            entries = self._iter_entries = []
        entries.append({
            "i": len(entries), "chi2": chi2_f,
            "step_norm": float(np.sqrt(np.sum(d * d))),
            "max_dpar": float(np.max(np.abs(d))) if d.size else 0.0,
            "ok": ok, "guard_eps": float(guard_eps), "rung": rung,
        })

    def _emit_iter_trace(self, rung):
        """Publish the fit's accumulated iteration record: the
        ``iter_trace`` attribute always (gate on), one JSONL record
        when a sink is attached."""
        entries = getattr(self, "_iter_entries", None)
        if not entries:
            return
        self.iter_trace = list(entries)
        telemetry.emit(telemetry.iter_trace_record(
            f"fitter.step:{type(self).__name__}", self.iter_trace,
            kind="fit", rung=rung, n_toa=len(self.toas),
            n_free=len(self._traced_free)))

    def _inputs_fingerprint(self):
        """Cheap run-ledger identity of this fit's inputs: a hash of
        the residuals structure key, the TOA count, and the free set
        — NOT a content fingerprint (hashing the dataset per fit
        would cost more than the fit's host side), but enough to say
        "these two runs fit the same problem shape"."""
        import hashlib

        return hashlib.blake2b(
            repr((self.resids._structure_key(), len(self.toas),
                  tuple(self.model.free_timing_params))).encode(),
            digest_size=8).hexdigest()

    def _downgrade_rung(self, maxiter):
        """Hook: the final ladder rung (GLS fitters downgrade to WLS)."""
        return None

    def _record_guard(self, rung, health, sp):
        """Publish the fit's guard outcome: ``fit_rung``/``fit_health``
        attributes always; a ``{"type": "health"}`` ledger record
        (joined to the run by the emit-time tag); fit meta + a warning
        when a degraded rung served (a degraded fit must be loud,
        never silent)."""
        self.fit_rung = rung
        self.fit_health = _guard.to_record(health)
        telemetry.emit({"type": "health",
                        "context": type(self).__name__,
                        "rung": rung, **self.fit_health})
        if rung != "baseline":
            self.model.meta["GUARD_RUNG"] = rung
            if sp is not None:
                sp.set(guard_rung=rung)
            warnings.warn(
                f"{type(self).__name__}: fit served by degradation "
                f"rung {rung!r} (see model.meta['GUARD_RUNG'] and "
                "fitter.fit_health)")
        else:
            # a later clean fit clears the flag — the meta lands in the
            # output par file and must describe THIS fit, not a
            # degraded one from before the data was fixed
            self.model.meta.pop("GUARD_RUNG", None)

    def _iterate(self, maxiter, guard_eps=0.0, rung="baseline"):
        """Run the Gauss-Newton loop once (one ladder rung).  Returns
        (vec, cov, extras, n_iter, health); raises guard.StepDiverged
        with the last finite-chi^2 parameter state on a bad verdict.
        ``rung`` labels this attempt's flight-recorder entries."""
        vec = jnp.array(
            [self.model.values[k] for k in self._traced_free],
            dtype=jnp.float64,
        )
        base = self.prepared._values_pytree()
        data = self._guard_data(guard_eps)
        chi2_prev = None
        cov = None
        n_iter = 0
        extras = ()
        health = ()
        last_good = np.array(
            [self.model.values[k] for k in self._traced_free])
        for _ in range(maxiter):
            # the step donates its input vector on TPU/GPU — snapshot
            # the candidate before the call so last_good stays readable
            vec_in = np.asarray(vec)
            vec, chi2, dpar, cov, *rest = self._step_jit(
                vec, base, data)
            extras, health = tuple(rest[:-1]), rest[-1]
            n_iter += 1
            chi2_f = float(chi2)
            if np.isfinite(chi2_f):
                # chi2 is evaluated at the INPUT vector — that vector
                # is the proven-good state
                last_good = vec_in
            if self._iter_trace:
                self._note_iteration(chi2_f, vec_in, vec, health,
                                     guard_eps, rung)
            self._check_step_health(health, last_good, n_iter)
            if chi2_prev is not None and \
                    abs(float(chi2_prev) - chi2_f) \
                    < 1e-8 * max(chi2_f, 1.0):
                break
            chi2_prev = chi2_f
        return vec, cov, extras, n_iter, health

    def fit_toas(self, maxiter=3):
        """Iterate Gauss-Newton steps; write back values + uncertainties.

        On divergence the guard's degradation ladder retries through
        escalating rungs; past the last rung a
        :class:`pint_tpu.guard.FitDivergedError` carries the last-good
        parameter vector and the health record — ``model.values`` is
        never written with non-finite results."""
        if not self.model.free_timing_params:
            raise ValueError(
                "no free timing parameters to fit (mark them with a '1' "
                "fit flag in the par file or clear Param.frozen)"
            )
        with telemetry.run_scope(
                "fit", fitter=type(self).__name__,
                n_toa=len(self.toas),
                fingerprint=self._inputs_fingerprint()), \
            span("fit_toas", fitter=type(self).__name__,
                 n_toa=len(self.toas),
                 n_free=len(self.model.free_timing_params),
                 maxiter=maxiter) as sp:
            if tuple(self.model.free_timing_params) != getattr(
                    self, "_traced_free", ()):
                self._retrace()
            else:
                telemetry.counter_add("fitter.jit_cache_hits")
                # an edited frozen parameter must refresh the
                # precomputed delay leaves (data, not a retrace) — the
                # partition re-keys only when the free SET changes
                self._refresh_frozen()
            self._iter_entries = [] if self._iter_trace else None
            vec, cov_np, n_iter, health, rung = \
                self._fit_with_depth_guard(
                    lambda: self._guard_rungs(maxiter))
            flops_est = self._fit_flops_est(n_iter)
            telemetry.counter_add("fitter.iterations", n_iter)
            telemetry.counter_add("fit.flops_est", flops_est)
            sp.set(n_iter=n_iter, flops_est=flops_est)
            self._record_guard(rung, health, sp)
            self._emit_iter_trace(rung)
            self._update_fit_meta()
            self._post_fit()
            return float(self.resids.chi2)

    def _fit_flops_est(self, n_iter):
        """Modeled FLOPs of this fit (pint_tpu.flops cost model) —
        structure-aware: only the nonlinear remainder pays a tangent
        chain, and segment-carried ECORR columns cost O(N) instead of
        dense matmul terms."""
        n_basis = int(getattr(self.prepared, "noise_basis",
                              np.zeros((0, 0))).shape[1])
        return _flops.gls_fit_flops(
            len(self.toas), len(self._traced_free), n_basis, n_iter,
            n_lin=len(self._partition[0]),
            ecorr_seg=getattr(self.resids, "ecorr_segment_cols", 0))

    def _update_fit_meta(self):
        """Record the fit summary into the model metadata so it lands in
        the output par file (reference: CHI2/TRES/NTOA params,
        timing_model.py:344-386)."""
        r = self.resids
        self.model.meta["NTOA"] = str(
            getattr(r, "n_real", None) or len(self.toas))
        self.model.meta["CHI2"] = f"{r.chi2:.6f}"
        self.model.meta["TRES"] = f"{r.rms_weighted() * 1e6:.6f}"

    def _post_fit(self):
        """Hook for subclasses (e.g. noise realizations)."""

    @property
    def parameter_correlation_matrix(self):
        d = np.sqrt(np.diag(self.covariance))
        return self.covariance / np.outer(d, d)

    # -- streaming appends ------------------------------------------------
    #: incremental-refit state: {"moments": _StreamMoments,
    #: "since_capture": int, "frozen_fp": ..., "noise_fp": ...} — or
    #: None when no capture is live (cold, or invalidated by a
    #: re-prepare / free-set change / parameter edit)
    _stream = None

    def _stream_check(self):
        if isinstance(self.resids, WidebandTOAResiduals):
            raise NotImplementedError(
                "streaming append: narrowband residuals only")
        if self.resids.subtract_mean and \
                not self.resids.use_weighted_mean:
            raise NotImplementedError(
                "streaming refit supports the weighted-mean convention "
                "only: an unweighted mean couples rows through sums the "
                "stream moments do not carry")

    def _stream_raw_view(self):
        """A shallow no-mean view of the residuals: the stream state
        tracks RAW moments, so the capture and delta programs evaluate
        residuals/design without the in-trace mean subtraction — the
        centering happens on the moments at solve time
        (:func:`_derive_blocks`)."""
        raw = copy.copy(self.resids)
        raw.subtract_mean = False
        raw._jit_cache = {}
        raw._data_cached = None
        raw._structure_key_cached = None
        return raw

    def _stream_mini_build(self, delta):
        """Prepare an append delta as a tiny padded dataset.  The block
        pads to ``$PINT_TPU_STREAM_BLOCK`` so every nightly delta shares
        one program shape; the TZR anchor is frozen to the BASE prepare
        (a mini-local TZR would re-derive the reference phase from the
        delta night), and correlated-noise ctx entries are replaced by a
        canonical empty basis — the mini programs never read one (the
        merged prepare owns the real epoch bookkeeping), and a
        data-dependent epoch count here would re-key the shared mini
        trace on every append."""
        block = stream_block_default()
        n_t = block if len(delta) <= block \
            else _cc.bucket_size(len(delta))
        mtoas = _cc.pad_toas(delta, n_target=n_t)
        # tzr=False: the mini never derives its own absolute-phase
        # anchor — the BASE prepare's is grafted in below, so the TZR
        # component sweep would be pure throwaway work
        mprep = self.model.prepare(mtoas, tzr=False)
        mprep.tzr_batch = self.prepared.tzr_batch
        mprep.tzr_ctx = self.prepared.tzr_ctx
        for c in self.model.noise_components:
            if getattr(c, "introduces_correlated_errors", False):
                mprep.ctx[type(c).__name__] = {
                    "basis": np.zeros((n_t, 0)), "counts": ()}
        mprep._noise_basis_comps = []
        mprep.noise_basis = jnp.asarray(np.zeros((n_t, 0)))
        mres = Residuals(mtoas, mprep, subtract_mean=False,
                         track_mode=self.resids.track_mode,
                         use_weighted_mean=self.resids.use_weighted_mean)
        leaves = {}
        mfrozen = None
        if getattr(self, "_frozen_names", ()):
            frozen, tzr_frozen = mprep.frozen_delay_leaves(
                self._frozen_names)
            if frozen is not None:
                mfrozen = frozen
                leaves["frozen"] = frozen
                if tzr_frozen is not None:
                    leaves["tzr_frozen"] = tzr_frozen
        mdata = self._inject_frozen(
            {**mres._data(), "guard_eps": np.float64(0.0)}, leaves)
        return _MiniAppend(toas=mtoas, prep=mprep, res=mres, data=mdata,
                           n=len(delta), block=n_t, frozen=mfrozen)

    def _stream_capture_jit(self):
        """The one O(N) pass of the streaming path: raw weighted
        moments of the current linearization, shared-jitted so a second
        same-shaped capture performs zero new compiles."""
        raw = self._stream_raw
        use_basis = self._noise_gram_leaves

        def capture_fn(vec, base_values, data):
            def resid_of(sub):
                values = dict(base_values)
                values.update(sub)
                return raw.time_resids_at(values, data)

            def linear_of(sub):
                values = dict(base_values)
                values.update(sub)
                return raw.linear_design_at(values, data,
                                            self._partition[0])

            q, jq = resid_and_design(self._traced_free, vec,
                                     self._partition, resid_of,
                                     linear_of)
            # the frozen-noise sigma leaf, NOT a mask: pad sentinels
            # carry their ~1e-32 weights exactly as in the batch step,
            # so streamed and batch solves see identical inputs
            sigma = data["noise_sigma"]
            w = 1.0 / sigma ** 2
            jw = jq * w[:, None]
            if use_basis:
                u = data["U_ext"]
                # the precomputed gram leaf IS U^T W U + Phi^-1 — reuse
                # it so capture and batch step agree bit-for-bit
                g_uu = data["noise_gram"]
                a_qu = _ut_dot(u, jw).T
                b_u = _ut_dot(u, w * q)
                s_u = _ut_dot(u, w)
            else:
                p = len(self._traced_free)
                a_qu = jnp.zeros((p, 0))
                g_uu = jnp.zeros((0, 0))
                b_u = jnp.zeros((0,))
                s_u = jnp.zeros((0,))
            return _StreamMoments(
                a_qq=jw.T @ jq, a_qu=a_qu, g_uu=g_uu,
                b_q=jw.T @ q, b_u=b_u, rr=jnp.sum(w * q * q),
                s_j=jnp.sum(jw, axis=0), s_u=s_u,
                s_q=jnp.sum(w * q), s_w=jnp.sum(w))

        # capture_fn is constructed fresh per call: fn_token makes the
        # registry identity the key alone (every closed-over static —
        # partition, free set, raw structure — is in the key)
        key = ("stream.capture", type(self).__name__, self._traced_free,
               self._partition, self._frozen_names, use_basis,
               raw._structure_key())
        return _cc.shared_jit(capture_fn, key=key,
                              fn_token="stream.capture",
                              label="stream.capture")

    def _stream_delta_jit(self, mres):
        """(q, Jq, sigma) of the delta block at the current parameters
        — evaluated on the mini dataset, raw (no mean subtraction).
        Keyed on the mini STRUCTURE: every same-shaped nightly delta
        reuses one trace."""
        def delta_fn(vec, base_values, data):
            def resid_of(sub):
                values = dict(base_values)
                values.update(sub)
                return mres.time_resids_at(values, data)

            def linear_of(sub):
                values = dict(base_values)
                values.update(sub)
                return mres.linear_design_at(values, data,
                                             self._partition[0])

            q, jq = resid_and_design(self._traced_free, vec,
                                     self._partition, resid_of,
                                     linear_of)
            sigma = mres.sigma_at(self._merged(base_values, vec), data)
            return q, jq, sigma

        key = ("stream.delta", type(self).__name__, self._traced_free,
               self._partition, self._frozen_names,
               mres._structure_key())
        return _cc.shared_jit(delta_fn, key=key,
                              fn_token="stream.delta",
                              label="stream.delta")

    def _stream_refit_jit(self):
        """Rank-DeltaN moment update + solve + first-order re-anchor,
        one O((P+K)^2 DeltaN + (P+K)^3) program with NO term
        proportional to N.  ``valid_d`` masks the block padding (and
        quarantined rows) to exactly zero weight, so one static block
        shape serves every DeltaN."""
        center = bool(self.resids.subtract_mean)

        def refit_fn(m, q_d, j_d, sigma_d, u_d, valid_d, guard_eps):
            w = jnp.where(valid_d, 1.0 / sigma_d ** 2, 0.0)
            jw = j_d * w[:, None]
            m = m._replace(
                a_qq=m.a_qq + jw.T @ j_d,
                a_qu=m.a_qu + jw.T @ u_d,
                g_uu=m.g_uu + u_d.T @ (u_d * w[:, None]),
                b_q=m.b_q + jw.T @ q_d,
                b_u=m.b_u + u_d.T @ (w * q_d),
                rr=m.rr + jnp.sum(w * q_d * q_d),
                s_j=m.s_j + jnp.sum(jw, axis=0),
                s_u=m.s_u + u_d.T @ w,
                s_q=m.s_q + jnp.sum(w * q_d),
                s_w=m.s_w + jnp.sum(w))
            dpar, cov, ncoef, chi2 = normal_solve_from_blocks(
                _derive_blocks(m, center), guard_eps=guard_eps)
            # first-order shift to the post-step linearization point:
            # q(theta + dpar) = q + Jq dpar in the linear model
            # (normal_blocks_shift, on the raw moments)
            m2 = m._replace(
                b_q=m.b_q + m.a_qq @ dpar,
                b_u=m.b_u + m.a_qu.T @ dpar,
                rr=(m.rr + 2.0 * jnp.dot(dpar, m.b_q)
                    + dpar @ m.a_qq @ dpar),
                s_q=m.s_q + jnp.dot(m.s_j, dpar))
            return m2, dpar, cov, chi2

        key = ("stream.refit", type(self).__name__, self._traced_free,
               center)
        return _cc.shared_jit(refit_fn, key=key,
                              fn_token="stream.refit",
                              label="stream.refit")

    def stream_prepare(self):
        """Capture the streaming-refit state at the current parameters
        (normally: right after a converged ``fit_toas``).  Requires the
        frozen-noise fast path — with free noise parameters an append
        changes sigma/phi on every row and nothing is incremental."""
        self._stream_check()
        if not getattr(self, "_noise_frozen", False):
            raise NotImplementedError(
                "streaming refit requires the frozen-noise fast path "
                "(no free noise parameters)")
        with span("fitter.stream_prepare", n_toa=len(self.toas)):
            self._stream_raw = self._stream_raw_view()
            cap = self._stream_capture_jit()
            vec = jnp.array(
                [self.model.values[k] for k in self._traced_free],
                dtype=jnp.float64)
            base = self.prepared._values_pytree()
            m = cap(vec, base, self._fit_data)
            self._stream = {
                "moments": m,
                "since_capture": 0,
                "frozen_fp": dict(self._frozen_fp),
                "noise_fp": dict(self._noise_fp),
            }
            telemetry.counter_add("stream.captures")
        return self._stream["moments"]

    def _stream_triage(self, q, sigma, t_s, threshold):
        """Anomaly triage of an arriving delta, whitened against the
        PRE-append fit (the residual signatures of arXiv 2010.10322):
        scattered outliers quarantine row-by-row; a coherent one-sided
        excursion across most of the night is a glitch- or
        acceleration-shaped event the timing solution must NOT absorb —
        the whole delta is quarantined into the guard record for
        intervention, and the warm fit keeps serving."""
        m = self._stream["moments"]
        s_w = float(m.s_w)
        mu = float(m.s_q) / s_w if s_w > 0 else 0.0
        z = (np.asarray(q) - mu) / np.asarray(sigma)
        out = np.abs(z) > threshold
        outliers = np.flatnonzero(out)
        verdict, quarantine = "clean", outliers
        if outliers.size:
            one_sided = bool(np.all(z[out] > 0) or np.all(z[out] < 0))
            if len(z) >= 3 and out.mean() >= 0.5 and one_sided:
                tc = t_s - t_s.mean()
                zc = z - z.mean()
                denom = np.sqrt((tc ** 2).sum() * (zc ** 2).sum())
                slope = abs(float((tc * zc).sum() / denom)) \
                    if denom > 0 else 0.0
                verdict = "acceleration" if slope > 0.8 else "glitch"
                quarantine = np.arange(len(z))
            else:
                verdict = "outlier"
            telemetry.counter_add("stream.triage_outliers",
                                  float(outliers.size))
            telemetry.counter_add("stream.quarantined",
                                  float(quarantine.size))
            warnings.warn(
                f"stream triage: {verdict} signature in appended TOAs "
                f"({outliers.size}/{len(z)} rows beyond "
                f"{threshold:.1f} sigma); {quarantine.size} rows "
                "quarantined")
        telemetry.counter_add(f"stream.triage_{verdict}")
        return {"verdict": verdict, "z": z, "outliers": outliers,
                "quarantine": np.asarray(quarantine, dtype=np.int64),
                "threshold": float(threshold)}

    def append(self, delta, quarantine=(), _mini=None):
        """Structural append: merge ``delta`` into the (padded) dataset
        and refresh residuals + fit-data leaves — incrementally when
        the delta fits the current bucket (flip pad sentinels to real
        rows: same shapes, same structure key, zero new executables;
        frozen-delay / sigma leaves patched from an O(DeltaN)
        mini-dataset evaluation and the noise gram by a rank-DeltaN row
        swap), otherwise a full re-prepare at the next bucket.  Returns
        True on the incremental path, False on the fallback.
        ``quarantine`` lists delta row indices held out of every solve
        (zero-weight sentinels flagged ``-quarantine 1``)."""
        if isinstance(self.resids, WidebandTOAResiduals):
            raise NotImplementedError(
                "streaming append: narrowband residuals only")
        if self._toa_mesh is not None:
            raise NotImplementedError(
                "streaming append: unsharded fitters only (the "
                "TOA-shard row plan interleaves sentinel rows)")
        dn = len(delta)
        with span("fitter.append", n_delta=dn) as sp:
            row0 = getattr(self.toas, "n_filled", None) \
                or getattr(self.toas, "n_real", None) or len(self.toas)
            quarantine = np.unique(np.asarray(
                quarantine, dtype=np.int64).ravel()) \
                if np.size(quarantine) else np.zeros(0, dtype=np.int64)
            if quarantine.size and (quarantine[0] < 0
                                    or quarantine[-1] >= dn):
                raise ValueError("quarantine indices outside the delta")
            if quarantine.size:
                delta = _quarantine_rows(delta, quarantine)
            merged, in_bucket = _cc.append_toas(self.toas, delta)
            if quarantine.size:
                pv = getattr(merged, "pad_valid", None)
                if pv is None:
                    nf = getattr(merged, "n_filled", len(merged))
                    pv = np.arange(len(merged)) < nf
                pv = np.asarray(pv, dtype=bool).copy()
                pv[row0 + quarantine] = False
                merged.pad_valid = pv
            traced = getattr(self, "_traced_free", None)
            old_key = self.resids._structure_key() \
                if traced is not None else None
            kwargs = dict(
                subtract_mean=self.resids.subtract_mean,
                track_mode=self.resids.track_mode,
                use_weighted_mean=self.resids.use_weighted_mean)
            prepared = None
            resids = None
            if in_bucket and old_key is not None:
                prepared = self.prepared.prepare_appended(
                    merged, n0=row0,
                    mini_ctx=(_mini.prep.ctx
                              if _mini is not None else None))
            if prepared is not None:
                resids = Residuals(merged, prepared, **kwargs)
                if resids._structure_key() != old_key:
                    # a static ctx class drifted under the new span
                    # (e.g. the Kepler unroll depth) — the streamed
                    # prepare cannot serve the existing executables
                    prepared = None
            if prepared is None:
                telemetry.counter_add("stream.reprepares")
                sp.set(mode="reprepare")
                self.toas = merged
                self.resids = Residuals(merged, self.model, **kwargs)
                self.prepared = self.resids.prepared
                self._stream = None
                if traced is not None:
                    self._retrace()
                return False
            telemetry.counter_add("stream.appends")
            telemetry.counter_add("stream.append_rows", float(dn))
            sp.set(mode="incremental")
            old_data = self._fit_data
            old_u = self.resids._U_ext
            self.toas = merged
            self.resids = resids
            self.prepared = prepared
            if tuple(self.model.free_timing_params) != traced:
                # the free set changed since the last trace — the leaf
                # patch would refresh data for a stale program
                self._stream = None
                self._retrace()
                return True
            mini = _mini if _mini is not None \
                else self._stream_mini_build(delta)
            self._append_fit_data(old_data, old_u, mini, row0, dn,
                                  quarantine)
            if self._stream is not None:
                # rebind the raw stream view onto the replaced
                # residuals (structure unchanged — the capture/delta
                # programs persist)
                self._stream_raw = self._stream_raw_view()
            return True

    def _append_fit_data(self, old, old_u, mini, row0, dn, quarantine):
        """O(DeltaN) refresh of the fit-data pytree after an in-bucket
        append: the delta rows' frozen-delay and sigma leaf entries
        come from the mini dataset, and the noise gram takes a
        rank-DeltaN row swap (sentinel rows out, real rows in —
        linalg.noise_gram_append) instead of the O(N K^2) rebuild.
        Rows past the delta keep their old pad-clone leaf values: they
        differ from a from-scratch prepare's clones of the NEW last
        row, but at 1/PAD_ERROR_US^2 ~ 1e-44 weight every assembled
        quantity agrees far below the documented 1e-10 budget."""
        from pint_tpu.linalg import noise_gram_append

        data = {**self.resids._data(),
                "guard_eps": old.get("guard_eps", np.float64(0.0))}
        leaves = {}
        if "frozen" in old:
            mfrozen = mini.frozen
            if mfrozen is None:
                mfrozen, _ = mini.prep.frozen_delay_leaves(
                    self._frozen_names)
            # device-side row patch: only the DeltaN new entries cross
            # the host boundary; the old rows stay resident
            frozen = {}
            for name, arr in old["frozen"].items():
                frozen[name] = jax.lax.dynamic_update_slice(
                    jnp.asarray(arr),
                    jnp.asarray(np.asarray(mfrozen[name])[:dn]),
                    (row0,))
            leaves["frozen"] = frozen
            if "tzr_frozen" in old:
                leaves["tzr_frozen"] = old["tzr_frozen"]
        if getattr(self, "_noise_frozen", False):
            base = self.prepared._values_pytree()
            sig_rows = np.asarray(
                mini.res.sigma_fn(base))[:dn].copy()
            if quarantine.size:
                # quarantined rows carry the sentinel uncertainty; the
                # exact EFAC/EQUAD fold of a 1e22 us error is
                # indistinguishable at w ~ 1e-44 — stamp the sentinel
                sig_rows[quarantine] = _cc.PAD_ERROR_US * 1e-6
            old_sigma = jnp.asarray(old["noise_sigma"])
            old_sig_rows = np.asarray(jax.lax.dynamic_slice(
                old_sigma, (row0,), (dn,)))
            leaves["noise_sigma"] = jax.lax.dynamic_update_slice(
                old_sigma, jnp.asarray(sig_rows), (row0,))
            if self._noise_gram_leaves:
                leaves["noise_phi"] = old["noise_phi"]
                leaves["noise_gram"] = noise_gram_append(
                    old["noise_gram"], row0,
                    jnp.asarray(sig_rows),
                    jnp.asarray(_u_rows_slice(
                        self.resids._U_ext, row0, dn)),
                    jnp.asarray(old_sig_rows),
                    jnp.asarray(_u_rows_slice(old_u, row0, dn)))
        self._fit_data = self._inject_frozen(data, leaves)
        self._shard_fit_data()
        telemetry.counter_add("stream.leaf_patches")

    def append_refit(self, delta, triage_sigma=None, maxiter=3):
        """The serve plane's streaming ingest: triage the arriving
        delta against the pre-append fit, append it (incremental leaf
        patch when it fits the bucket), and refit by a rank-DeltaN
        update to the captured moments — O((P+K)^2 DeltaN + (P+K)^3)
        per append, no O(N) pass.  Falls back to the full ladder fit at
        bucket boundaries and on a non-finite incremental solve.
        Returns a report dict: mode ("incremental" | "reprepare" |
        "refit_full" | "fallback"), triage, chi2 (evaluated at the
        pre-step vector on the incremental path, the gls convention),
        dpar, in_bucket."""
        self._stream_check()
        dn = len(delta)
        with span("fitter.append_refit", n_delta=dn) as sp:
            if self._stream is None:
                # cold start (or post-fallback): one O(N) capture at
                # the current fit before the first streamed append
                self.stream_prepare()
            elif not (self._fp_same(
                        self.prepared.frozen_param_values(
                            self._frozen_names),
                        self._stream["frozen_fp"])
                      and self._fp_same(self._noise_param_values(),
                                        self._stream["noise_fp"])):
                # a frozen/noise parameter was edited since capture —
                # the moments are stale; re-fold leaves and re-anchor
                self._refresh_frozen()
                self.stream_prepare()
            row0 = getattr(self.toas, "n_filled", None) \
                or getattr(self.toas, "n_real", None) or len(self.toas)
            mini = self._stream_mini_build(delta)
            djit = self._stream_delta_jit(mini.res)
            vec = jnp.array(
                [self.model.values[k] for k in self._traced_free],
                dtype=jnp.float64)
            base = self.prepared._values_pytree()
            q_b, j_b, sigma_b = djit(vec, base, mini.data)
            q_b = np.asarray(q_b)
            j_b = np.asarray(j_b)
            sigma_b = np.asarray(sigma_b)
            thresh = triage_sigma if triage_sigma is not None \
                else stream_triage_sigma_default()
            t_s = np.asarray(mini.toas.ticks[:dn],
                             dtype=np.float64) / 2.0 ** 32
            tri = self._stream_triage(q_b[:dn], sigma_b[:dn], t_s,
                                      thresh)
            in_bucket = self.append(delta,
                                    quarantine=tri["quarantine"],
                                    _mini=mini)
            if not in_bucket or self._stream is None:
                # bucket boundary (full re-prepare happened) or the
                # stream was invalidated: full laddered refit, fresh
                # capture
                mode = "reprepare" if not in_bucket else "refit_full"
                sp.set(mode=mode)
                chi2 = self.fit_toas(maxiter=maxiter)
                self.stream_prepare()
                return {"mode": mode, "triage": tri, "chi2": chi2,
                        "dpar": None, "in_bucket": in_bucket}
            k_cols = basis_ncols(self.resids._U_ext) \
                if self._noise_gram_leaves else 0
            u_b = np.zeros((mini.block, k_cols))
            if k_cols:
                u_b[:dn] = _u_rows_slice(self.resids._U_ext, row0, dn)
            valid_b = np.zeros(mini.block, dtype=bool)
            valid_b[:dn] = True
            valid_b[tri["quarantine"]] = False
            rjit = self._stream_refit_jit()
            m2, dpar, cov, chi2 = rjit(
                self._stream["moments"], jnp.asarray(q_b),
                jnp.asarray(j_b), jnp.asarray(sigma_b),
                jnp.asarray(u_b), jnp.asarray(valid_b),
                np.float64(0.0))
            dpar_np = np.asarray(dpar)
            cov_np = np.asarray(cov)
            chi2_f = float(chi2)
            if not (np.isfinite(chi2_f) and np.isfinite(dpar_np).all()
                    and np.isfinite(cov_np).all()):
                telemetry.counter_add("stream.solve_fallbacks")
                sp.set(mode="fallback")
                self._stream = None
                chi2 = self.fit_toas(maxiter=maxiter)
                self.stream_prepare()
                return {"mode": "fallback", "triage": tri,
                        "chi2": chi2, "dpar": None, "in_bucket": True}
            errs = np.sqrt(np.clip(np.diag(cov_np), 0.0, None))
            params = self.model.params
            for i, name in enumerate(self._traced_free):
                self.model.values[name] = float(
                    self.model.values[name] + dpar_np[i])
                params[name].uncertainty = float(errs[i])
            self.covariance = cov_np
            self._stream["moments"] = m2
            self._stream["since_capture"] += 1
            telemetry.counter_add("stream.refits")
            sp.set(mode="incremental", chi2=chi2_f)
            if self._stream["since_capture"] >= \
                    stream_recapture_default():
                self.stream_prepare()
            return {"mode": "incremental", "triage": tri,
                    "chi2": chi2_f, "dpar": dpar_np, "in_bucket": True}


class WLSFitter(Fitter):
    """Weighted least squares via SVD of the whitened, column-normalized
    design matrix; Gauss-Newton iterations, all inside one jit.  Whitens
    by the noise-scaled uncertainties (EFAC/EQUAD), matching the
    reference WLS path (fitter.py:1990)."""

    def __init__(self, toas, model, residuals=None, threshold=1e-14,
                 bucket=None, mesh=None):
        super().__init__(toas, model, residuals, bucket=bucket,
                         mesh=mesh)
        self.threshold = threshold
        self._retrace()

    def _fit_flops_est(self, n_iter):
        """The SVD step never touches the noise basis — cost it at
        basis width 0 even when the model carries noise components."""
        return _flops.wls_fit_flops(
            len(self.toas), len(self._traced_free), n_iter,
            n_lin=len(self._partition[0]))

    def _step(self, vec, base_values, data):
        """One Gauss-Newton WLS step.  base_values (the full values
        dict, including frozen params) and data (the dataset pytree)
        are dynamic arguments, so edits to frozen parameters between
        fits take effect without retracing and same-shaped problems
        share the trace; changes to WHICH params are free go through
        _retrace().  Returns (new_vec, chi2, dpar, cov, health) —
        health rides the same compiled program (empty with the guard
        off)."""
        if self._noise_frozen:
            sigma = data["noise_sigma"]
        else:
            sigma = self.resids.sigma_at(self._merged(base_values, vec),
                                         data)
        rj = self._rj(vec, base_values, data)
        if not self._guard_on:
            return wls_gn_solve(None, vec, sigma,
                                self.threshold, rj=rj,
                                toa=self._toa_shard) + ((),)
        new_vec, chi2, dpar, cov, diag = wls_gn_solve(
            None, vec, sigma, self.threshold,
            rcond=data["guard_eps"], with_health=True, rj=rj,
            toa=self._toa_shard)
        health = _guard.step_health(
            rj[0], sigma, chi2, dpar, cov, diag,
            valid=data["valid"],
            inputs_ok=_guard.batch_input_finite(data["batch"],
                                                data["valid"]))
        return new_vec, chi2, dpar, cov, health


class WidebandTOAFitter(Fitter):
    """Wideband fit: stacked [time; DM] residual vector with a block
    design matrix, solved through the same noise-augmented normal
    equations (reference: WidebandTOAFitter, fitter.py:2292-2640 via
    combine_design_matrices_by_quantity).  The correlated-noise basis
    acts on the time block; DM rows see DMEFAC/DMEQUAD-scaled white
    noise."""

    def __init__(self, toas, model, residuals=None, bucket=None):
        if residuals is None:
            if bucket is None:
                bucket = _cc.bucketing_default()
            if bucket:
                toas = _cc.pad_toas(toas)
            residuals = WidebandTOAResiduals(toas, model)
        super().__init__(toas, model, residuals=residuals, bucket=False)
        self.noise_realizations = {}
        self._retrace()

    def _rj(self, vec, base_values, data):
        return wideband_resid_and_design(
            self.resids, base_values, data, self._traced_free, vec,
            self._partition)

    def _step(self, vec, base_values, data):
        values = self._merged(base_values, vec)
        sigma_t = self.resids.toa.sigma_at(values, data["toa"])
        sigma_dm = self.resids.dm.sigma_at(values, data["dm"])
        sigma = jnp.concatenate([sigma_t, sigma_dm])
        r, J = self._rj(vec, base_values, data)
        U_t, phi = self.resids.toa._noise_basis_phi_at(values,
                                                       data["toa"])
        if isinstance(U_t, StructuredU):
            # the DM block sees no noise basis: zero rows, outside
            # every ECORR epoch (segment id K_e)
            U = su_pad_rows(U_t, sigma_dm.shape[0])
        else:
            U = jnp.concatenate(
                [U_t, jnp.zeros((sigma_dm.shape[0], U_t.shape[1]))],
                axis=0)
        if not self._guard_on:
            dpar, cov, ncoef, chi2 = gls_normal_solve(r, J, sigma, U,
                                                      phi)
            return vec + dpar, chi2, dpar, cov, ncoef, ()
        dpar, cov, ncoef, chi2, diag = gls_normal_solve(
            r, J, sigma, U, phi, guard_eps=data["guard_eps"],
            with_health=True)
        # the stacked [time; DM] vector needs a stacked pad mask: the
        # DM block's rows are the valid-indexed subset of the TOA rows
        v_t = data["toa"]["valid"]
        valid = None
        if v_t is not None:
            valid = jnp.concatenate(
                [v_t, v_t[data["dm"]["valid_idx"]]])
        health = _guard.step_health(
            r, sigma, chi2, dpar, cov, diag, valid=valid,
            inputs_ok=_guard.batch_input_finite(data["toa"]["batch"],
                                                v_t))
        return vec + dpar, chi2, dpar, cov, ncoef, health


class GLSFitter(Fitter):
    """Generalized least squares over the low-rank noise basis: the
    noise-augmented normal equations solved by Cholesky (reference:
    GLSFitter.fit_toas, fitter.py:2090-2289), one jitted step.

    After fit_toas(), ``noise_realizations`` maps each correlated-noise
    component to its basis-amplitude realization U_c @ a_c [s]
    (reference :2269-2282).
    """

    _noise_gram_leaves = True

    def __init__(self, toas, model, residuals=None, bucket=None,
                 mesh=None):
        super().__init__(toas, model, residuals, bucket=bucket,
                         mesh=mesh)
        self.noise_realizations = {}
        self._retrace()

    def _step(self, vec, base_values, data):
        values = self._merged(base_values, vec)
        if self._noise_frozen:
            # frozen-noise fast path: sigma/phi/gram arrive as
            # precomputed data leaves; the chi^2 is served from the
            # gram's Cholesky with the guard's capacity jitter applied
            # in-trace (gls_normal_solve)
            sigma = data["noise_sigma"]
            U, phi = data["U_ext"], data["noise_phi"]
            gram = data["noise_gram"]
        else:
            sigma = self.resids.sigma_at(values, data)
            U, phi = self.resids._noise_basis_phi_at(values, data)
            gram = None
        r, J = self._rj(vec, base_values, data)
        if not self._guard_on:
            dpar, cov, ncoef, chi2 = gls_normal_solve(
                r, J, sigma, U, phi, gram=gram, toa=self._toa_shard)
            return vec + dpar, chi2, dpar, cov, ncoef, ()
        dpar, cov, ncoef, chi2, diag = gls_normal_solve(
            r, J, sigma, U, phi, gram=gram,
            guard_eps=data["guard_eps"], with_health=True,
            toa=self._toa_shard)
        health = _guard.step_health(
            r, sigma, chi2, dpar, cov, diag, valid=data["valid"],
            inputs_ok=_guard.batch_input_finite(data["batch"],
                                                data["valid"]))
        return vec + dpar, chi2, dpar, cov, ncoef, health

    def _downgrade_rung(self, maxiter):
        """The ladder's last resort: a correlated-noise fit whose solve
        stays non-finite through every jitter rung falls back to the
        plain WLS step (noise-scaled white errors, no basis
        augmentation) on the SAME residuals — degraded statistics, but
        finite timing parameters with the rung flagged in fit meta."""
        def downgrade():
            wls = WLSFitter(self.toas, self.model,
                            residuals=self.resids)
            out = wls._iterate(maxiter, rung="wls")
            # the downgrade iterations run on a throwaway fitter —
            # the SERVED rung's entries must land in THIS fitter's
            # flight record, or the one case the recorder exists to
            # explain (every jitter rung failed) records nothing
            served = getattr(wls, "_iter_entries", None)
            if served:
                if getattr(self, "_iter_entries", None) is None:
                    self._iter_entries = []
                for e in served:
                    self._iter_entries.append(
                        {**e, "i": len(self._iter_entries)})
            return out

        return ("wls", downgrade)

    def _set_noise_realizations(self, ncoef):
        """Per-component noise realizations U_c @ a_c [s] (reference
        fitter.py:2269)."""
        ncoef = np.asarray(ncoef)
        self.noise_realizations = {}
        for name, (start, nb) in self.prepared.noise_dimensions().items():
            basis = np.asarray(self.prepared.noise_basis[:, start:start + nb])
            self.noise_realizations[name] = basis @ ncoef[start:start + nb]

    def _post_fit(self):
        """Solve once more at the written-back optimum so the noise
        realizations correspond to the reported parameters (the loop's
        extras are one Gauss-Newton step stale)."""
        if getattr(self, "fit_rung", "baseline") == "wls":
            # the GLS solve is the thing that diverged — re-running it
            # here would hand back the same non-finite amplitudes
            self.noise_realizations = {}
            return
        vec = jnp.array(
            [self.model.values[k] for k in self._traced_free],
            dtype=jnp.float64,
        )
        base = self.prepared._values_pytree()
        *_, ncoef, _health = self._step_jit(vec, base, self._fit_data)
        self._set_noise_realizations(ncoef)
