"""Fitters: WLS (SVD) Gauss-Newton on device.

Counterpart of the reference fitter layer (reference: src/pint/fitter.py:
185 base, :1940-2087 WLSFitter).  The reference's per-iteration recipe —
design matrix, whiten, column-normalize, SVD, parameter step, covariance —
becomes one jitted function of the free-parameter vector; the design
matrix is ``jax.jacfwd`` of the residual function (the reference's 124-s
hand-derivative hot spot, profiling/README.txt:58, disappears by
construction).

``Fitter.auto`` mirrors the reference's dispatch (fitter.py:252): GLS
when the model has correlated noise (later milestone), WLS otherwise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.residuals import Residuals

__all__ = ["WLSFitter", "Fitter", "wls_gn_solve"]


def wls_gn_solve(resid_fn, vec, err, threshold=1e-14):
    """One whitened, column-normalized SVD Gauss-Newton step.

    The shared numerical core of WLSFitter and the vmapped grid (one
    implementation, one threshold).  resid_fn(vec) -> residuals [s].
    Returns (new_vec, chi2_before, dpar, covariance).
    """
    r = resid_fn(vec)
    J = jax.jacfwd(resid_fn)(vec)  # (N, P) d resid / d param
    w = 1.0 / err
    rw = r * w
    Jw = J * w[:, None]
    # column normalize (reference: utils.normalize_designmatrix)
    norms = jnp.sqrt(jnp.sum(Jw * Jw, axis=0))
    norms = jnp.where(norms == 0, 1.0, norms)
    Jn = Jw / norms[None, :]
    U, s, Vt = jnp.linalg.svd(Jn, full_matrices=False)
    smax = jnp.max(s)
    s_inv = jnp.where(s > threshold * smax, 1.0 / s, 0.0)
    dpar_n = -(Vt.T * s_inv[None, :]) @ (U.T @ rw)
    dpar = dpar_n / norms
    cov_n = (Vt.T * s_inv[None, :] ** 2) @ Vt
    cov = cov_n / jnp.outer(norms, norms)
    chi2 = jnp.sum(rw * rw)
    return vec + dpar, chi2, dpar, cov


class Fitter:
    """Base fitter: holds (toas, model), exposes fit_toas()."""

    def __init__(self, toas, model, residuals=None):
        self.toas = toas
        self.model = model
        self.resids = residuals or Residuals(toas, model)
        self.prepared = self.resids.prepared

    @staticmethod
    def auto(toas, model, downhill=True):
        # correlated-noise dispatch lands with the GLS milestone
        return WLSFitter(toas, model)

    # -- reporting -----------------------------------------------------------
    def get_summary(self) -> str:
        r = self.resids
        lines = [
            f"Fitted model {self.model.meta.get('PSR', self.model.name)} "
            f"with {len(self.toas)} TOAs, {len(self.model.free_params)} "
            "free parameters",
            f"chi2 = {r.chi2:.3f} / dof {r.dof} = {r.reduced_chi2:.4f}",
            f"weighted RMS = {r.rms_weighted() * 1e6:.4f} us",
            "",
            f"{'PARAM':<12s} {'VALUE':<24s} {'UNCERTAINTY':<12s}",
        ]
        params = self.model.params
        for name in self.model.free_params:
            p = params[name]
            unc = p.uncertainty
            lines.append(
                f"{name:<12s} {p.format(self.model.values[name]):<24s} "
                f"{unc if unc is not None else '':<12}"
            )
        return "\n".join(lines)

    def print_summary(self):
        print(self.get_summary())


class WLSFitter(Fitter):
    """Weighted least squares via SVD of the whitened, column-normalized
    design matrix; Gauss-Newton iterations, all inside one jit."""

    def __init__(self, toas, model, residuals=None, threshold=1e-14):
        super().__init__(toas, model, residuals)
        self.threshold = threshold
        self._retrace()

    def _retrace(self):
        """(Re)build the jitted step for the current free-param set.
        The trace closes over the free-param *names*; a changed free set
        with the same count would otherwise hit the stale jit cache and
        silently write steps into the wrong parameters."""
        self._traced_free = tuple(self.model.free_params)
        self._step_jit = jax.jit(self._step)

    def _step(self, vec, base_values):
        """One Gauss-Newton WLS step.  base_values (the full values dict,
        including frozen params) is a dynamic argument so that edits to
        frozen parameters between fits take effect without retracing;
        changes to WHICH params are free go through _retrace()."""
        free = self._traced_free

        def resid_fn(v):
            values = dict(base_values)
            for i, name in enumerate(free):
                values[name] = v[i]
            return self.resids.time_resids_fn(values)

        return wls_gn_solve(
            resid_fn, vec, self.prepared.batch.error_s, self.threshold
        )

    def fit_toas(self, maxiter=3):
        """Iterate Gauss-Newton steps; write back values + uncertainties."""
        if not self.model.free_params:
            raise ValueError(
                "no free parameters to fit (mark them with a '1' fit flag "
                "in the par file or clear Param.frozen)"
            )
        if tuple(self.model.free_params) != self._traced_free:
            self._retrace()
        vec = self.prepared.values_to_vector()
        base = self.prepared._values_pytree()
        chi2_prev = None
        cov = None
        for _ in range(maxiter):
            vec, chi2, dpar, cov = self._step_jit(vec, base)
            if chi2_prev is not None and abs(float(chi2_prev) - float(chi2)) \
                    < 1e-8 * max(float(chi2), 1.0):
                break
            chi2_prev = chi2
        # write back
        values = self.prepared.vector_to_values(np.asarray(vec))
        for k, v in values.items():
            self.model.values[k] = float(v)
        errs = np.sqrt(np.diag(np.asarray(cov)))
        params = self.model.params
        for i, name in enumerate(self.model.free_params):
            params[name].uncertainty = float(errs[i])
        self.covariance = np.asarray(cov)
        # refresh residuals cache-free view
        return float(self.resids.chi2)

    @property
    def parameter_correlation_matrix(self):
        d = np.sqrt(np.diag(self.covariance))
        return self.covariance / np.outer(d, d)
