"""pint_tpu — a TPU-native pulsar-timing framework built on JAX/XLA.

A ground-up redesign of the capabilities of PINT (NANOGrav's pulsar timing
package, reference: /root/reference) for TPU hardware:

- The delay/phase component chain is a pure jit-compiled function
  ``phase(params, toa_batch)`` over struct-of-array TOA batches.
- ``numpy.longdouble`` (x87 80-bit) precision is replaced by double-double
  float64 arithmetic (:mod:`pint_tpu.dd`, ~32 significant digits) which runs
  on TPU, where no extended-precision type exists.
- Design matrices come from autodiff (``jax.jacfwd``) instead of a
  hand-written derivative registry (reference: ``timing_model.py:1910``),
  with hand-derivative escape hatches for precision-critical columns.
- Whole fits batch with ``vmap`` over chi^2-grid points and over pulsars and
  shard over device meshes with ``jax.sharding``.

The host-side ingest layer (``.par``/``.tim`` parsing, clock corrections,
time-scale transforms, solar-system ephemerides) is self-contained: unlike
the reference, this package does not depend on astropy / erfa / jplephem.
"""

import os

import jax

# Double-double arithmetic and microsecond-level time handling require real
# float64 semantics everywhere; enable before any tracing happens.
jax.config.update("jax_enable_x64", True)

# Honor JAX_PLATFORMS even when a site plugin (e.g. a preregistered TPU
# backend) would otherwise win platform selection — the env var alone is
# not enough once the plugin is registered, the config must be set too.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

__version__ = "0.1.0"

# --- Physical constants -----------------------------------------------------
# Values match the reference's unit system (src/pint/__init__.py:61-107) so
# par files round-trip identically; all are public IAU/CODATA values.

C_M_PER_S = 299792458.0  #: speed of light [m/s] (exact, SI)
SECS_PER_DAY = 86400.0
AU_M = 149597870700.0  #: astronomical unit [m] (IAU 2012, exact)
AU_LS = AU_M / C_M_PER_S  #: AU in light-seconds (~499.005)

#: Dispersion constant: delay[s] = DM / DMconst / freq[MHz]^2.
#: The pulsar community fixes K == 1/2.41e-4 s MHz^2 cm^3 / pc by convention
#: (reference src/pint/__init__.py:84-90) rather than the CODATA value.
DM_CONST = 1.0 / 2.41e-4

#: GM/c^3 for solar-system bodies in seconds ("mass in time units"), used by
#: the Shapiro delay (reference src/pint/__init__.py:91-107).
T_SUN_S = 4.925490947000452e-06
T_MERCURY_S = 8.176988758e-13
T_VENUS_S = 1.205680558e-11
T_EARTH_S = 1.497600750e-11
T_MARS_S = 1.589111861e-12
T_JUPITER_S = 4.702819050e-09
T_SATURN_S = 1.408128810e-09
T_URANUS_S = 2.149646268e-10
T_NEPTUNE_S = 2.536815068e-10

#: Obliquity of the ecliptic at J2000 (IERS 2010 / "IERS2010" in ecliptic.dat),
#: arcseconds; the default frame for ecliptic astrometry.
OBLIQUITY_J2000_ARCSEC = 84381.406

MJD_J2000 = 51544.5  #: J2000.0 epoch as an MJD (TT)
DAYS_PER_JULIAN_YEAR = 365.25
SECS_PER_JULIAN_YEAR = DAYS_PER_JULIAN_YEAR * SECS_PER_DAY

from pint_tpu import dd  # noqa: E402  (re-export precision core)

__all__ = [
    "dd",
    "C_M_PER_S",
    "SECS_PER_DAY",
    "AU_M",
    "AU_LS",
    "DM_CONST",
    "T_SUN_S",
    "MJD_J2000",
    "OBLIQUITY_J2000_ARCSEC",
]
