"""Bayesian timing: priors, lnlikelihood, lnposterior, prior transform.

Counterpart of the reference BayesianTiming (reference:
src/pint/bayesian.py:12-252): exposes ``lnprior``, ``prior_transform``
(for nested samplers), ``lnlikelihood`` and ``lnposterior`` over the
free parameters, choosing the WLS or GLS likelihood by the model's
noise content, with wideband support.  TPU redesign: all four functions
are pure jax closures over the prepared model — jit them, ``jax.grad``
them (for HMC/NUTS-style samplers the reference cannot support), or
vmap them over walkers (:mod:`pint_tpu.sampler`).

Priors: uniform or normal per parameter.  Defaults follow the
reference's demand that proper priors exist: a parameter with a par
uncertainty gets Uniform(value ± width_sigma * unc); one without gets
an error asking for an explicit prior (the reference similarly requires
_default_prior_info / user priors for nested sampling).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.models.timing_model import TimingModel
from pint_tpu.residuals import Residuals, WidebandTOAResiduals

__all__ = ["UniformPrior", "NormalPrior", "BayesianTiming"]


@dataclass
class UniformPrior:
    lo: float
    hi: float

    def lnpdf(self, x):
        inside = jnp.logical_and(x >= self.lo, x <= self.hi)
        return jnp.where(inside, -jnp.log(self.hi - self.lo), -jnp.inf)

    def transform(self, u):
        return self.lo + u * (self.hi - self.lo)


@dataclass
class NormalPrior:
    mu: float
    sigma: float

    def lnpdf(self, x):
        z = (x - self.mu) / self.sigma
        return -0.5 * z * z - jnp.log(self.sigma) \
            - 0.5 * jnp.log(2.0 * jnp.pi)

    def transform(self, u):
        from jax.scipy.special import ndtri

        return self.mu + self.sigma * ndtri(u)


class BayesianTiming:
    """lnprior / lnlikelihood / lnposterior / prior_transform over the
    free parameters (timing + any unfrozen noise params).

    priors: optional {param_name: UniformPrior|NormalPrior}; parameters
    not listed get Uniform(value +/- width_sigma * uncertainty).
    """

    def __init__(self, model, toas, priors=None, width_sigma=10.0,
                 wideband=False):
        if isinstance(model, TimingModel):
            prepared = model.prepare(toas)
        else:
            prepared = model
        self.prepared = prepared
        self.model = prepared.model
        self.toas = toas
        self.wideband = wideband
        if wideband:
            self.resids = WidebandTOAResiduals(toas, prepared)
            toa_r = self.resids.toa
            dm_r = self.resids.dm

            def lnlike_values(values):
                lnl_t = toa_r.lnlikelihood_fn(values)
                r = dm_r.dm_resids_fn(values)
                s = dm_r.sigma_fn(values)
                lnl_dm = -0.5 * jnp.sum((r / s) ** 2) \
                    - jnp.sum(jnp.log(s)) \
                    - 0.5 * r.shape[0] * jnp.log(2.0 * jnp.pi)
                return lnl_t + lnl_dm
        else:
            self.resids = Residuals(toas, prepared)
            lnlike_values = self.resids.lnlikelihood_fn
        self._lnlike_values = lnlike_values
        self.param_names = list(self.model.free_params)
        self.nparams = len(self.param_names)
        self.priors = {}
        priors = priors or {}
        params = self.model.params
        for name in self.param_names:
            if name in priors:
                self.priors[name] = priors[name]
                continue
            pprior = getattr(params[name], "prior", None)
            if pprior is not None:
                self.priors[name] = pprior
                continue
            unc = params[name].uncertainty
            val = float(self.model.values[name])
            if not unc:
                raise ValueError(
                    f"parameter {name} has no uncertainty to build a "
                    "default prior from; pass an explicit prior "
                    "(reference bayesian.py requires proper priors too)"
                )
            w = width_sigma * float(unc)
            self.priors[name] = UniformPrior(val - w, val + w)
        self._base = prepared._values_pytree()

    # -- pure functions of the free-parameter vector -------------------------
    def _values_of(self, vec):
        values = dict(self._base)
        for i, name in enumerate(self.param_names):
            values[name] = vec[i]
        return values

    def lnprior(self, vec):
        lnp = 0.0
        for i, name in enumerate(self.param_names):
            lnp = lnp + self.priors[name].lnpdf(vec[i])
        return lnp

    def prior_transform(self, cube):
        """Unit hypercube -> parameter vector (for nested samplers,
        reference bayesian.py prior_transform)."""
        return jnp.stack(
            [
                self.priors[name].transform(cube[i])
                for i, name in enumerate(self.param_names)
            ]
        )

    def lnlikelihood(self, vec):
        return self._lnlike_values(self._values_of(vec))

    def lnposterior(self, vec):
        lnp = self.lnprior(vec)
        # evaluate the likelihood regardless (jit-safe, no branch) —
        # -inf prior dominates the sum
        return lnp + self.lnlikelihood(vec)

    # -- convenience ---------------------------------------------------------
    def start_vector(self):
        return np.array(
            [self.model.values[n] for n in self.param_names],
            dtype=np.float64,
        )

    def scale_vector(self):
        """Per-parameter scale for walker initialization (uncertainty,
        or prior width / 100 when only a prior exists)."""
        out = []
        params = self.model.params
        for name in self.param_names:
            unc = params[name].uncertainty
            if unc:
                out.append(float(unc))
            else:
                p = self.priors[name]
                out.append((p.hi - p.lo) / 100.0
                           if isinstance(p, UniformPrior) else p.sigma)
        return np.array(out)

    def sample(self, nwalkers=32, nsteps=500, seed=0, burn_frac=0.25):
        """Run the JAX ensemble sampler on lnposterior; returns
        (flatchain, sampler).  Sets model values to the max-posterior
        sample (reference MCMCFitter.fit_toas 'maxpost' behavior)."""
        from pint_tpu.sampler import EnsembleSampler

        s = EnsembleSampler(self.lnposterior, nwalkers=nwalkers, seed=seed)
        x0 = s.initial_ball(self.start_vector(), self.scale_vector())
        s.run_mcmc(x0, nsteps)
        best, _ = s.max_posterior()
        for i, name in enumerate(self.param_names):
            self.model.values[name] = float(best[i])
        burn = int(burn_frac * nsteps)
        return s.flatchain(burn=burn), s
