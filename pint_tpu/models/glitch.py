"""Glitches and piecewise spindown solutions.

Counterparts of the reference components (reference:
src/pint/models/glitch.py:13 ``glitch_phase``, src/pint/models/
piecewise.py:11 ``piecewise_phase``).  Both add extra spin-phase terms on
TOA subsets selected by epoch:

- Glitch i (t > GLEP_i): GLPH + dt (GLF0 + dt GLF1 / 2 + dt^2 GLF2 / 6)
  + GLF0D GLTD (1 - exp(-dt / GLTD)),  dt = t - GLEP_i - delay [s]
- Piecewise i (PWSTART_i <= t < PWSTOP_i): PWPH + dt PWF0 + dt^2 PWF1/2
  + dt^3 PWF2/6,  dt = t - PWEP_i - delay [s]

TPU design: the per-glitch Heaviside gates become ``jnp.where`` masks, so
all glitches evaluate as one fused elementwise pass with no host branch.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import SECS_PER_DAY
from pint_tpu.models.component import PhaseComponent
from pint_tpu.models.parameter import Param, prefix_index


class Glitch(PhaseComponent):
    register = True
    category = "glitch"
    trigger_params = ("GLEP",)

    _FIELDS = ("GLEP_", "GLPH_", "GLF0_", "GLF1_", "GLF2_", "GLF0D_",
               "GLTD_")

    def __init__(self, indices=()):
        super().__init__()
        self.indices = tuple(indices)
        for i in self.indices:
            self.add_param(Param(f"GLEP_{i}", kind="mjd", fittable=False,
                                 description=f"Epoch of glitch {i}"))
            self.add_param(Param(f"GLPH_{i}", units="turns",
                                 description=f"Phase step of glitch {i}"))
            self.add_param(Param(f"GLF0_{i}", units="Hz",
                                 description=f"Permanent dF0, glitch {i}"))
            self.add_param(Param(f"GLF1_{i}", units="Hz/s",
                                 description=f"Permanent dF1, glitch {i}"))
            self.add_param(Param(f"GLF2_{i}", units="Hz/s^2",
                                 description=f"Permanent dF2, glitch {i}"))
            self.add_param(Param(f"GLF0D_{i}", units="Hz",
                                 description=f"Decaying dF0, glitch {i}"))
            self.add_param(Param(f"GLTD_{i}", units="d", scale=SECS_PER_DAY,
                                 description=f"Decay timescale, glitch {i}"))

    @classmethod
    def from_parfile(cls, pardict):
        idx = sorted(
            {
                prefix_index(k)[1]
                for k in pardict
                if k.startswith("GLEP_") and prefix_index(k)
            }
        )
        return cls(indices=idx)

    def defaults(self):
        d = {}
        for i in self.indices:
            for f in ("GLPH_", "GLF0_", "GLF1_", "GLF2_", "GLF0D_",
                      "GLTD_"):
                d[f + str(i)] = 0.0
        return d

    def prepare(self, toas, model):
        t = toas.ticks.astype(np.float64) / 2**32
        return {"t_sec": jnp.asarray(t)}

    def phase(self, values, batch, ctx, delay):
        t = ctx["t_sec"] - delay
        phs = jnp.zeros_like(t)
        for i in self.indices:
            dt = t - values[f"GLEP_{i}"]
            on = dt > 0.0
            dts = jnp.where(on, dt, 0.0)
            tau = values[f"GLTD_{i}"]
            # decay term with a safe divide at GLTD == 0
            tau_safe = jnp.where(tau > 0.0, tau, 1.0)
            decay = jnp.where(
                tau > 0.0,
                values[f"GLF0D_{i}"] * tau
                * (1.0 - jnp.exp(-dts / tau_safe)),
                0.0,
            )
            phs = phs + jnp.where(
                on,
                values[f"GLPH_{i}"]
                + dts
                * (
                    values[f"GLF0_{i}"]
                    + dts * (values[f"GLF1_{i}"] / 2.0
                             + dts * values[f"GLF2_{i}"] / 6.0)
                )
                + decay,
                0.0,
            )
        return phs


class PiecewiseSpindown(PhaseComponent):
    """Per-interval extra spindown solution (PWEP/PWSTART/PWSTOP/PWF0..)."""

    register = True
    category = "piecewise"
    trigger_params = ("PWEP",)

    def __init__(self, indices=()):
        super().__init__()
        self.indices = tuple(indices)
        for i in self.indices:
            self.add_param(Param(f"PWEP_{i}", kind="mjd", fittable=False,
                                 description=f"Epoch of segment {i}"))
            self.add_param(Param(f"PWSTART_{i}", kind="mjd", fittable=False,
                                 description=f"Start of segment {i}"))
            self.add_param(Param(f"PWSTOP_{i}", kind="mjd", fittable=False,
                                 description=f"End of segment {i}"))
            self.add_param(Param(f"PWPH_{i}", units="turns",
                                 description=f"Phase offset, segment {i}"))
            self.add_param(Param(f"PWF0_{i}", units="Hz",
                                 description=f"dF0 in segment {i}"))
            self.add_param(Param(f"PWF1_{i}", units="Hz/s",
                                 description=f"dF1 in segment {i}"))
            self.add_param(Param(f"PWF2_{i}", units="Hz/s^2",
                                 description=f"dF2 in segment {i}"))

    @classmethod
    def from_parfile(cls, pardict):
        idx = sorted(
            {
                prefix_index(k)[1]
                for k in pardict
                if k.startswith("PWEP_") and prefix_index(k)
            }
        )
        return cls(indices=idx)

    def defaults(self):
        d = {}
        for i in self.indices:
            for f in ("PWPH_", "PWF0_", "PWF1_", "PWF2_"):
                d[f + str(i)] = 0.0
        return d

    def prepare(self, toas, model):
        t = toas.ticks.astype(np.float64) / 2**32
        masks = []
        for i in self.indices:
            lo = model.values[f"PWSTART_{i}"]
            hi = model.values[f"PWSTOP_{i}"]
            masks.append((t >= lo) & (t < hi))
        m = (
            np.stack(masks, 0)
            if masks
            else np.zeros((0, len(toas)), dtype=bool)
        )
        return {"t_sec": jnp.asarray(t), "masks": jnp.asarray(m)}

    def phase(self, values, batch, ctx, delay):
        t = ctx["t_sec"] - delay
        phs = jnp.zeros_like(t)
        for j, i in enumerate(self.indices):
            dt = t - values[f"PWEP_{i}"]
            phs = phs + jnp.where(
                ctx["masks"][j],
                values[f"PWPH_{i}"]
                + dt
                * (
                    values[f"PWF0_{i}"]
                    + dt * (values[f"PWF1_{i}"] / 2.0
                            + dt * values[f"PWF2_{i}"] / 6.0)
                ),
                0.0,
            )
        return phs
