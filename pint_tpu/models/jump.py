"""JUMPs: per-subset phase/delay offsets (mask parameters).

Counterpart of the reference jump components (reference:
src/pint/models/jump.py:12 DelayJump, :79 PhaseJump).  A JUMP selects a
TOA subset (flag / MJD range / freq range / telescope) and applies a
constant offset: PhaseJump adds ``+JUMP * F0`` turns (the reference's
convention, jump.py:135 — equivalent to DelayJump's ``-JUMP`` seconds in
the delay chain, since phase gains ``-F0 * delay``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.component import (
    DelayComponent,
    PhaseComponent,
    mask_from_select,
)
from pint_tpu.models.parameter import Param


class _JumpBase:
    def __init__(self, selects=()):
        super().__init__()
        self.selects = tuple(selects)
        for i, sel in enumerate(self.selects, start=1):
            self.add_param(
                Param(f"JUMP{i}", units="s", select=sel,
                      description=f"Jump {i} on {sel}")
            )

    @classmethod
    def from_parfile(cls, pardict):
        masks = pardict.get("__MASKS__", {})
        return cls(selects=[s for s, _ in masks.get("JUMP", [])])

    def defaults(self):
        return {f"JUMP{i}": 0.0 for i in range(1, len(self.selects) + 1)}

    def prepare(self, toas, model):
        masks = [
            np.asarray(mask_from_select(sel, toas)) for sel in self.selects
        ]
        m = (
            np.stack(masks, 0)
            if masks
            else np.zeros((0, len(toas)), dtype=bool)
        )
        return {"masks": jnp.asarray(m)}

    def _total_jump_sec(self, values, ctx, n_toa):
        if not self.selects:
            return jnp.zeros(n_toa)
        j = jnp.stack(
            [values[f"JUMP{i}"] for i in range(1, len(self.selects) + 1)]
        )
        return jnp.sum(ctx["masks"] * j[:, None], axis=0)


class PhaseJump(_JumpBase, PhaseComponent):
    category = "phase_jump"
    trigger_params = ("JUMP",)
    #: phase() converts the jump seconds to turns through the spindown
    #: component's F0 (reads_params contract; F0 is already nonlinear
    #: in the hybrid partition, so this only documents the read today)
    reads_params = ("F0",)

    def phase(self, values, batch, ctx, delay):
        jump = self._total_jump_sec(values, ctx, batch.ticks.shape[0])
        return jump * values["F0"]

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return tuple(
            f"JUMP{i}" for i in range(1, len(self.selects) + 1))

    def d_phase_d_param(self, values, batch, ctx, delay, name):
        i = int(name[4:])
        return ctx["masks"][i - 1] * values["F0"]


class DelayJump(_JumpBase, DelayComponent):
    category = "jump_delay"
    register = True
    trigger_params = ()

    def delay(self, values, batch, ctx, delay_accum):
        return -self._total_jump_sec(values, ctx, batch.ticks.shape[0])

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return tuple(
            f"JUMP{i}" for i in range(1, len(self.selects) + 1))

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        i = int(name[4:])
        return -ctx["masks"][i - 1].astype(jnp.float64)
