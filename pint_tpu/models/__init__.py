"""Timing-model layer: components, builder, parameters.

``get_model`` / ``get_model_and_toas`` are the public entry points
(reference: src/pint/models/model_builder.py:777,859).
"""

from pint_tpu.models.builder import (  # noqa: F401
    get_model,
    get_model_and_toas,
    parse_parfile,
)
from pint_tpu.models.component import (  # noqa: F401
    Component,
    DelayComponent,
    PhaseComponent,
)
from pint_tpu.models.timing_model import TimingModel, PreparedModel  # noqa: F401
