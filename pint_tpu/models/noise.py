"""Noise components: white-noise sigma scaling and low-rank correlated
noise bases.

Counterpart of the reference noise layer (reference:
src/pint/models/noise_model.py:15 NoiseComponent base, :32 ScaleToaError,
:320 EcorrNoise, :443 PLDMNoise, :560 PLChromNoise, :679 PLRedNoise,
helpers :834-905).  The functional contract splits each noise process
into a *static basis* (quantization / Fourier design matrices — fixed
per dataset, captured as jit constants) and a *weights function* of the
dynamic parameter values (ECORR^2, power-law PSD) so that GLS fitting,
Woodbury chi^2 and gradient-based noise fitting all trace through one
pure function.

Conventions matched to the reference:
- sigma' = EFAC * sqrt(sigma^2 + EQUAD^2) per mask (noise_model.py:159)
- ECORR basis = per-epoch quantization matrix, epochs grouped at dt=1 s
  over each ECORR mask, epochs with <2 TOAs dropped (noise_model.py:834)
- power-law weights = A^2/(12 pi^2) fyr^(gamma-3) f^(-gamma) * df with
  f = k/T, k=1..nf, fyr = 1/3.16e7 (noise_model.py:883-905)
- PLDM basis scaled by (1400/freq_MHz)^2; PLChrom by
  (1400/freq_MHz)^TNCHROMIDX (noise_model.py:505,643)
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.component import Component, mask_from_select
from pint_tpu.models.parameter import Param

__all__ = [
    "NoiseComponent",
    "ScaleToaError",
    "ScaleDmError",
    "EcorrNoise",
    "PLRedNoise",
    "PLDMNoise",
    "PLChromNoise",
    "PLBandNoise",
    "PLSystemNoise",
    "create_quantization_matrix",
    "powerlaw",
    "fourier_basis",
    "fourier_basis_from_freqs",
    "toa_fourier_basis",
]

#: 1/yr in Hz, the reference's fyr constant (noise_model.py:905)
FYR = 1.0 / 3.16e7


def create_quantization_matrix(t_s, dt=1.0, nmin=2) -> np.ndarray:
    """Quantization matrix mapping TOAs to observing epochs.

    t_s: TOA times in seconds (any monotonic-compatible origin).
    Groups TOAs within ``dt`` seconds of a running epoch reference;
    epochs with fewer than ``nmin`` members are dropped (reference:
    noise_model.py:834-875).
    """
    t_s = np.asarray(t_s, dtype=np.float64)
    if t_s.size == 0:
        return np.zeros((0, 0))
    isort = np.argsort(t_s)
    bucket_ref = [t_s[isort[0]]]
    bucket_ind = [[isort[0]]]
    for i in isort[1:]:
        if t_s[i] - bucket_ref[-1] < dt:
            bucket_ind[-1].append(i)
        else:
            bucket_ref.append(t_s[i])
            bucket_ind.append([i])
    keep = [ind for ind in bucket_ind if len(ind) >= nmin]
    U = np.zeros((len(t_s), len(keep)))
    for j, ind in enumerate(keep):
        U[ind, j] = 1.0
    return U


def rednoise_freqs(tspan_s: float, nmodes: int) -> np.ndarray:
    """Interleaved sin/cos sampling frequencies k/T, k=1..nmodes
    (reference: get_rednoise_freqs, noise_model.py:847)."""
    f = np.linspace(1.0 / tspan_s, nmodes / tspan_s, nmodes)
    out = np.zeros(2 * nmodes)
    out[::2] = f
    out[1::2] = f
    return out


def fourier_basis(t_s, nmodes: int, tspan_s=None) -> Tuple[np.ndarray, np.ndarray]:
    """Fourier design matrix (N, 2*nmodes), interleaved sin/cos columns
    (reference: create_fourier_design_matrix, noise_model.py:861)."""
    t_s = np.asarray(t_s, dtype=np.float64)
    T = tspan_s if tspan_s is not None else t_s.max() - t_s.min()
    # degenerate span (single-epoch TOAs, or superset-padded inert
    # noise blocks): any finite span gives a finite basis, and the
    # inert/deeply-suppressed weights zero out the contribution
    if not np.isfinite(T) or T <= 0.0:
        T = 86400.0
    freqs = rednoise_freqs(T, nmodes)
    F = np.zeros((len(t_s), 2 * nmodes))
    F[:, ::2] = np.sin(2 * np.pi * t_s[:, None] * freqs[::2])
    F[:, 1::2] = np.cos(2 * np.pi * t_s[:, None] * freqs[1::2])
    return F, freqs


def fourier_basis_from_freqs(t_s, freqs) -> np.ndarray:
    """Fourier design matrix on a FROZEN frequency comb — the streaming
    append path's basis build.  ``fourier_basis`` derives the comb from
    the dataset span, so re-preparing after an append would move every
    frequency and silently re-weight the old rows' red-noise columns;
    an appended epoch instead keeps the prepare-time comb (the same
    contract as the cross-pulsar GWB comb, which fixes ``tspan_s``
    array-wide).  Built with the identical sin/cos expressions as
    ``fourier_basis`` so old rows reproduce bit-exactly."""
    t_s = np.asarray(t_s, dtype=np.float64)
    freqs = np.asarray(freqs, dtype=np.float64)
    F = np.zeros((len(t_s), len(freqs)))
    F[:, ::2] = np.sin(2 * np.pi * t_s[:, None] * freqs[::2])
    F[:, 1::2] = np.cos(2 * np.pi * t_s[:, None] * freqs[1::2])
    return F


def toa_fourier_basis(toas, nmodes: int, tspan_s=None):
    """Fourier design matrix of a TOAs object on the absolute TDB
    second axis — THE shared implementation behind every red-noise
    basis in the tree (per-pulsar power-law components here, and the
    cross-pulsar common process / GWB injection in
    :mod:`pint_tpu.gw`, which pass the array-wide ``tspan_s`` so all
    pulsars share one coherent frequency comb)."""
    t = toas.ticks.astype(np.float64) / 2**32
    return fourier_basis(t, nmodes, tspan_s=tspan_s)


def powerlaw(f, amp, gamma):
    """Power-law PSD in s^2/Hz-ish GW convention (noise_model.py:899)."""
    return amp**2 / 12.0 / jnp.pi**2 * FYR ** (gamma - 3) * f ** (-gamma)


class NoiseComponent(Component):
    """Base: sigma scaling and/or a (static basis, dynamic weights) pair."""

    introduces_correlated_errors = False
    is_time_correlated = False

    def scaled_sigma(self, values, batch, ctx, sigma):
        """Transform the per-TOA sigma [s]; default identity."""
        return sigma

    def scaled_dm_sigma(self, values, ctx, dm_sigma):
        """Transform the per-TOA wideband DM sigma [pc/cm3]; identity."""
        return dm_sigma

    def basis(self, ctx) -> Optional[np.ndarray]:
        """Static (N, nb) basis, or None."""
        return None

    def weights(self, values, ctx):
        """(nb,) weight vector as a jax function of values, or None."""
        return None


class ScaleToaError(NoiseComponent):
    """EFAC/EQUAD/TNEQ white-noise rescaling (reference:
    noise_model.py:32-216).  sigma' = EFAC * sqrt(sigma^2 + EQUAD^2),
    each factor applying on its mask; TNEQ is log10(seconds) and is
    superseded by an EQUAD sharing the same selector."""

    category = "scale_toa_error"
    trigger_params = ("EFAC", "EQUAD", "TNEQ")

    def __init__(self, efac_selects=(), equad_selects=(), tneq_selects=()):
        super().__init__()
        self.efac_selects = tuple(efac_selects)
        self.equad_selects = tuple(equad_selects)
        self.tneq_selects = tuple(tneq_selects)
        # a TNEQ whose selector is duplicated by an EQUAD is inert
        # (EQUAD wins; reference noise_model.py:112-116) — kept as a
        # parameter so file-order numbering stays aligned, skipped in
        # the sigma computation
        self.tneq_active = tuple(
            s not in self.equad_selects for s in self.tneq_selects
        )
        for i, sel in enumerate(self.efac_selects, start=1):
            self.add_param(Param(f"EFAC{i}", select=sel,
                                 description=f"EFAC on {sel}"))
        for i, sel in enumerate(self.equad_selects, start=1):
            self.add_param(Param(f"EQUAD{i}", units="us", scale=1e-6,
                                 select=sel,
                                 description=f"EQUAD on {sel}"))
        for i, sel in enumerate(self.tneq_selects, start=1):
            self.add_param(Param(f"TNEQ{i}", units="log10(s)", select=sel,
                                 description=f"TNEQ on {sel}"))

    @classmethod
    def from_parfile(cls, pardict):
        masks = pardict.get("__MASKS__", {})
        return cls(
            efac_selects=[s for s, _ in masks.get("EFAC", [])],
            equad_selects=[s for s, _ in masks.get("EQUAD", [])],
            tneq_selects=[s for s, _ in masks.get("TNEQ", [])],
        )

    def defaults(self):
        d = {f"EFAC{i}": 1.0 for i in range(1, len(self.efac_selects) + 1)}
        d.update(
            {f"EQUAD{i}": 0.0 for i in range(1, len(self.equad_selects) + 1)}
        )
        d.update(
            {f"TNEQ{i}": -np.inf
             for i in range(1, len(self.tneq_selects) + 1)}
        )
        return d

    def prepare(self, toas, model):
        def stack(sels):
            ms = [np.asarray(mask_from_select(s, toas)) for s in sels]
            return jnp.asarray(
                np.stack(ms, 0) if ms else np.zeros((0, len(toas)), bool)
            )

        return {
            "efac_masks": stack(self.efac_selects),
            "equad_masks": stack(self.equad_selects),
            "tneq_masks": stack(self.tneq_selects),
        }

    def scaled_sigma(self, values, batch, ctx, sigma):
        s2 = sigma**2
        for i in range(1, len(self.equad_selects) + 1):
            q = values[f"EQUAD{i}"]
            s2 = s2 + ctx["equad_masks"][i - 1] * q**2
        for i in range(1, len(self.tneq_selects) + 1):
            if not self.tneq_active[i - 1]:
                continue
            q = 10.0 ** values[f"TNEQ{i}"]
            s2 = s2 + ctx["tneq_masks"][i - 1] * q**2
        sigma = jnp.sqrt(s2)
        for i in range(1, len(self.efac_selects) + 1):
            f = values[f"EFAC{i}"]
            sigma = jnp.where(ctx["efac_masks"][i - 1], sigma * f, sigma)
        return sigma


class ScaleDmError(NoiseComponent):
    """DMEFAC/DMEQUAD scaling of wideband DM measurement uncertainties
    (reference: noise_model.py:217-319)."""

    category = "scale_dm_error"
    trigger_params = ("DMEFAC", "DMEQUAD")

    def __init__(self, dmefac_selects=(), dmequad_selects=()):
        super().__init__()
        self.dmefac_selects = tuple(dmefac_selects)
        self.dmequad_selects = tuple(dmequad_selects)
        for i, sel in enumerate(self.dmefac_selects, start=1):
            self.add_param(Param(f"DMEFAC{i}", select=sel,
                                 description=f"DMEFAC on {sel}"))
        for i, sel in enumerate(self.dmequad_selects, start=1):
            self.add_param(Param(f"DMEQUAD{i}", units="pc cm^-3", select=sel,
                                 description=f"DMEQUAD on {sel}"))

    @classmethod
    def from_parfile(cls, pardict):
        masks = pardict.get("__MASKS__", {})
        return cls(
            dmefac_selects=[s for s, _ in masks.get("DMEFAC", [])],
            dmequad_selects=[s for s, _ in masks.get("DMEQUAD", [])],
        )

    def defaults(self):
        d = {f"DMEFAC{i}": 1.0
             for i in range(1, len(self.dmefac_selects) + 1)}
        d.update({f"DMEQUAD{i}": 0.0
                  for i in range(1, len(self.dmequad_selects) + 1)})
        return d

    def prepare(self, toas, model):
        def stack(sels):
            ms = [np.asarray(mask_from_select(s, toas)) for s in sels]
            return jnp.asarray(
                np.stack(ms, 0) if ms else np.zeros((0, len(toas)), bool)
            )

        return {
            "dmefac_masks": stack(self.dmefac_selects),
            "dmequad_masks": stack(self.dmequad_selects),
        }

    def scaled_dm_sigma(self, values, ctx, dm_sigma):
        s2 = dm_sigma**2
        for i in range(1, len(self.dmequad_selects) + 1):
            q = values[f"DMEQUAD{i}"]
            s2 = s2 + ctx["dmequad_masks"][i - 1] * q**2
        s = jnp.sqrt(s2)
        for i in range(1, len(self.dmefac_selects) + 1):
            f = values[f"DMEFAC{i}"]
            s = jnp.where(ctx["dmefac_masks"][i - 1], s * f, s)
        return s


class EcorrNoise(NoiseComponent):
    """Epoch-correlated white noise: rank-|epochs| quantization basis
    with weights ECORR^2 (reference: noise_model.py:320-442)."""

    category = "ecorr_noise"
    trigger_params = ("ECORR",)
    introduces_correlated_errors = True
    is_time_correlated = False

    def __init__(self, selects=()):
        super().__init__()
        self.selects = tuple(selects)
        for i, sel in enumerate(self.selects, start=1):
            self.add_param(Param(f"ECORR{i}", units="us", scale=1e-6,
                                 select=sel,
                                 description=f"ECORR on {sel}"))

    @classmethod
    def from_parfile(cls, pardict):
        masks = pardict.get("__MASKS__", {})
        return cls(selects=[s for s, _ in masks.get("ECORR", [])])

    def defaults(self):
        return {f"ECORR{i}": 0.0 for i in range(1, len(self.selects) + 1)}

    def prepare(self, toas, model):
        t = toas.ticks.astype(np.float64) / 2**32  # TDB seconds
        n = len(toas)
        # pad sentinels (bucketing/shard alignment) and quarantined
        # rows carry ~zero weight but clone a REAL row's time, so
        # letting them seed or nmin-count epochs ties the epoch layout
        # to the pad placement: suffix pads clone the LAST real TOA,
        # which moves on every streaming append and would shuffle the
        # old rows' basis columns.  Epochs are determined by live rows
        # only; excluded rows get all-zero basis rows (their 1e-44
        # weight made the column entry numerically irrelevant anyway,
        # and a shrunken epoch span can never straddle a shard
        # boundary the full span did not).
        flags = getattr(toas, "flags", None)
        if flags is not None:
            live = np.array(
                [f.get("pad") != "1" and f.get("quarantine") != "1"
                 for f in flags], dtype=bool)
        else:
            live = np.ones(n, dtype=bool)
        umats = []
        counts = []
        for sel in self.selects:
            mask = np.asarray(mask_from_select(sel, toas)) & live
            u_local = create_quantization_matrix(t[mask])
            u_full = np.zeros((n, u_local.shape[1]))
            u_full[mask, :] = u_local
            umats.append(u_full)
            counts.append(u_local.shape[1])
        basis = (
            np.concatenate(umats, axis=1) if umats else np.zeros((n, 0))
        )
        return {"basis": basis, "counts": tuple(counts)}

    def prepare_streamed(self, toas, model, old_ctx, n0):
        """Streaming-append re-prepare: keep the quantization basis
        when the appended rows provably cannot disturb it, veto to the
        full re-prepare otherwise.  ``create_quantization_matrix``
        keys buckets on their FIRST time with a running 1-s window, so
        rows arriving strictly LATER than every old row by more than
        the window can neither re-bucket old rows nor resurrect a
        dropped singleton; they only matter if they form a >=nmin
        epoch among themselves, which would add a column.  Vetoes
        (return None -> full re-prepare, always sound): a new row
        within the window of the last old epoch, out-of-order
        arrivals, or a new >=nmin epoch.  On the fast path the old
        basis is returned as-is — appended singleton rows carry
        all-zero basis rows exactly as a from-scratch prepare would
        give them, and pad rows were already excluded (all-zero)."""
        t = toas.ticks.astype(np.float64) / 2**32
        n = len(toas)
        n1 = getattr(toas, "n_filled", None) \
            or getattr(toas, "n_real", None) or n
        flags = getattr(toas, "flags", None)
        if flags is not None:
            live = np.array(
                [f.get("pad") != "1" and f.get("quarantine") != "1"
                 for f in flags], dtype=bool)
        else:
            live = np.ones(n, dtype=bool)
        for sel in self.selects:
            mask = np.asarray(mask_from_select(sel, toas)) & live
            t_old = t[:n0][mask[:n0]]
            t_new = t[n0:n1][mask[n0:n1]]
            if t_new.size == 0:
                continue
            if np.any(np.diff(t_new) < 0.0):
                return None
            if t_old.size and \
                    float(t_new.min()) < float(t_old.max()) + 1.0:
                return None
            if create_quantization_matrix(t_new).shape[1] > 0:
                return None
        return {"basis": old_ctx["basis"],
                "counts": old_ctx["counts"]}

    def basis(self, ctx):
        return ctx["basis"]

    def weights(self, values, ctx):
        counts = ctx["counts"]
        if not counts:
            return jnp.zeros(0)
        parts = [
            jnp.full(c, values[f"ECORR{i}"] ** 2)
            for i, c in enumerate(counts, start=1)
        ]
        return jnp.concatenate(parts) if parts else jnp.zeros(0)


class _PLNoiseBase(NoiseComponent):
    """Shared machinery for power-law Fourier-basis noise."""

    introduces_correlated_errors = True
    is_time_correlated = True
    #: (amp_param, gam_param, nmodes_param, default_nmodes)
    pl_params: Tuple[str, str, str, int] = ("", "", "", 30)

    def _nmodes(self, model):
        nm_par = self.pl_params[2]
        v = model.values.get(nm_par, np.nan)
        return int(v) if np.isfinite(v) and v > 0 else self.pl_params[3]

    def _freq_scaling(self, model, freq_mhz):
        return np.ones_like(freq_mhz)

    def prepare(self, toas, model):
        nf = self._nmodes(model)
        F, freqs = toa_fourier_basis(toas, nf)
        F = F * self._freq_scaling(model, toas.freq_mhz)[:, None]
        return {"basis": F, "freqs": freqs, "df": freqs[0]}

    def prepare_streamed(self, toas, model, old_ctx, n0):
        """Streaming-append re-prepare: extend the basis on the FROZEN
        prepare-time comb (``old_ctx['freqs']``) instead of the new
        span.  Old rows are bit-exact by construction (same comb, same
        ticks), so only the appended rows [n0, n_filled) are computed —
        O(DeltaN K), not O(N K); pad rows past the delta keep the old
        prepare's clone values (weight ~1e-44, the documented
        pad-staleness class).  The spectral resolution of the original
        span is kept until the next full re-prepare (bucket boundary).
        None when the mode count changed under us."""
        freqs = np.asarray(old_ctx["freqs"])
        if freqs.shape[0] != 2 * self._nmodes(model):
            return None
        n1 = getattr(toas, "n_filled", None) \
            or getattr(toas, "n_real", None) or len(toas)
        t = toas.ticks[n0:n1].astype(np.float64) / 2**32
        rows = fourier_basis_from_freqs(t, freqs)
        rows = rows * self._freq_scaling(
            model, toas.freq_mhz[n0:n1])[:, None]
        F = np.array(old_ctx["basis"], copy=True)
        F[n0:n1] = rows
        return {"basis": F, "freqs": freqs, "df": old_ctx["df"]}

    def basis(self, ctx):
        return ctx["basis"]

    def _amp_gam(self, values):
        amp = 10.0 ** values[self.pl_params[0]]
        gam = values[self.pl_params[1]]
        return amp, gam

    def weights(self, values, ctx):
        amp, gam = self._amp_gam(values)
        return powerlaw(jnp.asarray(ctx["freqs"]), amp, gam) * ctx["df"]


class _MaskedPLNoise(NoiseComponent):
    """Selector-scoped power-law noise: one independent Fourier
    power-law process per mask selector, with basis columns zeroed off
    the selector's TOA subset (tempo2 band/system noise, the
    correlated-noise families of arxiv 1107.5366 that plain TNRed
    cannot express).

    Every selector shares the pulsar's full-span frequency comb (the
    same ``toa_fourier_basis`` convention as :class:`_PLNoiseBase`);
    restricting a process to a band/system is purely a column mask, so
    the stacked GLS basis stays static per dataset and only the
    (amp, gamma) weights are dynamic — the shared-trace contract.

    Amplitude and index come from *paired* mask families: an AMP line's
    selector must have a matching GAM line with the identical selector
    (e.g. ``TNBANDAMP FREQ 500 1000 -13.5`` with ``TNBANDGAM FREQ 500
    1000 3.1``).  File order within each family assigns the numbered
    parameter names, exactly like EFAC/EQUAD.
    """

    introduces_correlated_errors = True
    is_time_correlated = True
    #: (amp_key, gam_key, nmodes_key, default_nmodes)
    mask_pl_params: Tuple[str, str, str, int] = ("", "", "", 15)

    def __init__(self, amp_selects=(), gam_selects=()):
        super().__init__()
        ak, gk, ck, _ = self.mask_pl_params
        self.amp_selects = tuple(amp_selects)
        self.gam_selects = tuple(gam_selects)
        unmatched = [s for s in self.amp_selects
                     if s not in self.gam_selects]
        if unmatched:
            raise ValueError(
                f"{ak} selector(s) {unmatched} have no {gk} line with "
                "the same selector (amplitude and index pair by "
                "selector, like tempo2 band/system noise)")
        for i, sel in enumerate(self.amp_selects, start=1):
            self.add_param(Param(f"{ak}{i}", select=sel,
                                 description=f"log10 amp on {sel}"))
        for i, sel in enumerate(self.gam_selects, start=1):
            self.add_param(Param(f"{gk}{i}", select=sel,
                                 description=f"spectral index on {sel}"))
        self.add_param(Param(ck, fittable=False,
                             description="modes per selector"))
        # amp i's index parameter, paired by selector (file order of
        # the two families may differ)
        self._gam_of = tuple(
            f"{gk}{self.gam_selects.index(sel) + 1}"
            for sel in self.amp_selects
        )

    @classmethod
    def from_parfile(cls, pardict):
        masks = pardict.get("__MASKS__", {})
        ak, gk = cls.mask_pl_params[0], cls.mask_pl_params[1]
        return cls(
            amp_selects=[s for s, _ in masks.get(ak, [])],
            gam_selects=[s for s, _ in masks.get(gk, [])],
        )

    def defaults(self):
        ak, gk, ck, _ = self.mask_pl_params
        # a deeply-suppressed finite default (not NaN): an AMP line
        # whose value is missing must stay inert, never poison the
        # Woodbury weights with NaN
        d = {f"{ak}{i}": -20.0
             for i in range(1, len(self.amp_selects) + 1)}
        d.update({f"{gk}{i}": 0.0
                  for i in range(1, len(self.gam_selects) + 1)})
        d[ck] = np.nan
        return d

    def _nmodes(self, model):
        v = model.values.get(self.mask_pl_params[2], np.nan)
        return int(v) if np.isfinite(v) and v > 0 else \
            self.mask_pl_params[3]

    def prepare(self, toas, model):
        nf = self._nmodes(model)
        F, freqs = toa_fourier_basis(toas, nf)
        blocks = [
            F * np.asarray(mask_from_select(sel, toas),
                           dtype=np.float64)[:, None]
            for sel in self.amp_selects
        ]
        basis = (np.concatenate(blocks, axis=1) if blocks
                 else np.zeros((len(toas), 0)))
        return {"basis": basis, "freqs": freqs, "df": freqs[0]}

    def prepare_streamed(self, toas, model, old_ctx, n0):
        """Streaming-append re-prepare on the frozen comb (see
        :meth:`_PLNoiseBase.prepare_streamed`); the per-selector masks
        are row-local flag/frequency predicates, so old rows are
        bit-exact and only the appended rows [n0, n_filled) are
        computed and patched in — O(DeltaN K)."""
        freqs = np.asarray(old_ctx["freqs"])
        if freqs.shape[0] != 2 * self._nmodes(model):
            return None
        n1 = getattr(toas, "n_filled", None) \
            or getattr(toas, "n_real", None) or len(toas)
        t = toas.ticks[n0:n1].astype(np.float64) / 2**32
        F = fourier_basis_from_freqs(t, freqs)
        blocks = [
            F * np.asarray(mask_from_select(sel, toas),
                           dtype=np.float64)[n0:n1, None]
            for sel in self.amp_selects
        ]
        rows = (np.concatenate(blocks, axis=1) if blocks
                else np.zeros((n1 - n0, 0)))
        basis = np.array(old_ctx["basis"], copy=True)
        basis[n0:n1] = rows
        return {"basis": basis, "freqs": freqs, "df": old_ctx["df"]}

    def basis(self, ctx):
        return ctx["basis"]

    def weights(self, values, ctx):
        ak = self.mask_pl_params[0]
        if not self.amp_selects:
            return jnp.zeros(0)
        f = jnp.asarray(ctx["freqs"])
        parts = []
        for i in range(1, len(self.amp_selects) + 1):
            amp = 10.0 ** values[f"{ak}{i}"]
            gam = values[self._gam_of[i - 1]]
            parts.append(powerlaw(f, amp, gam) * ctx["df"])
        return jnp.concatenate(parts)


class PLBandNoise(_MaskedPLNoise):
    """Band noise: an independent achromatic power-law process per
    radio-frequency band (tempo2 TNBandNoise; arxiv 1107.5366 sec 4.2
    — unmodelled band-correlated signals absorbed per-band instead of
    biasing the achromatic red noise).

    Par grammar: ``TNBANDAMP FREQ <lo_MHz> <hi_MHz> <log10 amp>``
    paired with ``TNBANDGAM FREQ <lo> <hi> <index>``; modes per band
    via ``TNBANDC`` (default 15)."""

    category = "pl_band_noise"
    trigger_params = ("TNBANDAMP",)
    mask_pl_params = ("TNBANDAMP", "TNBANDGAM", "TNBANDC", 15)


class PLSystemNoise(_MaskedPLNoise):
    """System noise: an independent power-law process per observing
    system, selected by flag (tempo2 TNSysNoise / TNGroupNoise;
    arxiv 1107.5366 sec 4.3 — per-backend instrumental noise).

    Par grammar: ``TNSYSAMP -<flag> <value> <log10 amp>`` paired with
    ``TNSYSGAM -<flag> <value> <index>`` (e.g. ``-sys ao_430``); modes
    per system via ``TNSYSC`` (default 15)."""

    category = "pl_system_noise"
    trigger_params = ("TNSYSAMP",)
    mask_pl_params = ("TNSYSAMP", "TNSYSGAM", "TNSYSC", 15)


class PLRedNoise(_PLNoiseBase):
    """Achromatic power-law red noise (reference: noise_model.py:679).
    Accepts TNRED{AMP,GAM,C} (tempo2 convention, log10 amplitude) or
    RNAMP/RNIDX (tempo convention, converted at weight evaluation)."""

    category = "pl_red_noise"
    trigger_params = ("TNREDAMP", "RNAMP")
    pl_params = ("TNREDAMP", "TNREDGAM", "TNREDC", 30)

    def __init__(self):
        super().__init__()
        self.add_param(Param("TNREDAMP", description="log10 red-noise amp"))
        self.add_param(Param("TNREDGAM", description="red-noise index"))
        self.add_param(Param("TNREDC", fittable=False,
                             description="number of red-noise modes"))
        self.add_param(Param("RNAMP", description="tempo red-noise amp"))
        self.add_param(Param("RNIDX", description="tempo red-noise index"))

    def build_params(self, pardict):
        pass

    @classmethod
    def from_parfile(cls, pardict):
        inst = cls()
        inst._use_rn = "TNREDAMP" not in pardict and "RNAMP" in pardict
        return inst

    def defaults(self):
        return {
            "TNREDAMP": np.nan, "TNREDGAM": np.nan, "TNREDC": np.nan,
            "RNAMP": np.nan, "RNIDX": np.nan,
        }

    def _amp_gam(self, values):
        if getattr(self, "_use_rn", False):
            # RNAMP/RNIDX convention (reference noise_model.py:766):
            # amp = RNAMP / ((86400*365.24*1e6)/(2 pi sqrt(3)))
            fac = (86400.0 * 365.24 * 1e6) / (2.0 * np.pi * np.sqrt(3.0))
            return values["RNAMP"] / fac, -values["RNIDX"]
        return 10.0 ** values["TNREDAMP"], values["TNREDGAM"]


class PLDMNoise(_PLNoiseBase):
    """Power-law DM noise: Fourier basis scaled by (1400/f_MHz)^2
    (reference: noise_model.py:443)."""

    category = "pl_dm_noise"
    trigger_params = ("TNDMAMP",)
    pl_params = ("TNDMAMP", "TNDMGAM", "TNDMC", 30)

    def __init__(self):
        super().__init__()
        self.add_param(Param("TNDMAMP", description="log10 DM-noise amp"))
        self.add_param(Param("TNDMGAM", description="DM-noise index"))
        self.add_param(Param("TNDMC", fittable=False,
                             description="number of DM-noise modes"))

    def build_params(self, pardict):
        pass

    @classmethod
    def from_parfile(cls, pardict):
        return cls()

    def defaults(self):
        return {"TNDMAMP": np.nan, "TNDMGAM": np.nan, "TNDMC": np.nan}

    def _freq_scaling(self, model, freq_mhz):
        with np.errstate(divide="ignore"):
            return np.where(
                np.isfinite(freq_mhz) & (freq_mhz > 0),
                (1400.0 / freq_mhz) ** 2,
                0.0,
            )


class PLChromNoise(_PLNoiseBase):
    """Power-law chromatic noise: basis scaled by
    (1400/f_MHz)^TNCHROMIDX (reference: noise_model.py:560)."""

    category = "pl_chrom_noise"
    trigger_params = ("TNCHROMAMP",)
    pl_params = ("TNCHROMAMP", "TNCHROMGAM", "TNCHROMC", 30)

    def __init__(self):
        super().__init__()
        self.add_param(Param("TNCHROMAMP",
                             description="log10 chromatic-noise amp"))
        self.add_param(Param("TNCHROMGAM",
                             description="chromatic-noise index"))
        self.add_param(Param("TNCHROMC", fittable=False,
                             description="number of chromatic modes"))
        # chromatic index: canonically owned by the chromatic delay
        # component; declared here too so a noise-only model parses it
        self.add_param(Param("TNCHROMIDX", fittable=False,
                             description="chromatic index alpha"))

    def build_params(self, pardict):
        pass

    @classmethod
    def from_parfile(cls, pardict):
        return cls()

    def defaults(self):
        return {"TNCHROMAMP": np.nan, "TNCHROMGAM": np.nan,
                "TNCHROMC": np.nan, "TNCHROMIDX": np.nan}

    def _freq_scaling(self, model, freq_mhz):
        # chromatic index from the chromatic component (default 4.0,
        # reference chromatic_model.py TNCHROMIDX default)
        alpha = model.values.get("TNCHROMIDX", np.nan)
        if not np.isfinite(alpha):
            alpha = 4.0
        with np.errstate(divide="ignore"):
            return np.where(
                np.isfinite(freq_mhz) & (freq_mhz > 0),
                (1400.0 / freq_mhz) ** alpha,
                0.0,
            )
