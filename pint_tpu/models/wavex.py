"""Fourier-series delay/DM/chromatic variation: WaveX, DMWaveX, CMWaveX.

Counterparts of the reference components (reference:
src/pint/models/wavex.py:12 ``wavex_delay``, src/pint/models/dmwavex.py:14,
src/pint/models/cmwavex.py:14): each holds sin/cos amplitude pairs at
explicit frequencies (1/day) relative to an epoch,

    q(t) = sum_k  S_k sin(2 pi f_k tau) + C_k cos(2 pi f_k tau),
    tau  = t - EPOCH - accumulated_delay   [days]

where q is an achromatic delay in seconds (WaveX), a DM in pc cm^-3
(DMWaveX, delay = K q / nu^2), or a chromatic measure (CMWaveX, delay =
K q / nu^TNCHROMIDX).  TPU design note: the k-sum is a single matmul-free
``sum`` over a stacked (k, N) sinusoid tensor — XLA fuses the trig +
reduction into one pass over HBM.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import DM_CONST, SECS_PER_DAY
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import Param, prefix_index


class _FourierBase(DelayComponent):
    """Shared machinery: indexed (FREQ, SIN, COS) triplets + epoch."""

    register = False
    #: prefix for the parameter family, e.g. "WX" -> WXFREQ_/WXSIN_/WXCOS_
    px: str = ""
    epoch_name: str = ""
    amp_units: str = "s"
    #: tau = t - EPOCH - accumulated_delay: the series reads the chain
    reads_delay_accum = True

    def __init__(self, indices=()):
        super().__init__()
        self.indices = tuple(indices)
        self.add_param(Param(self.epoch_name, kind="mjd", fittable=False,
                             description="Fourier series reference epoch"))
        for i in self.indices:
            self.add_param(Param(f"{self.px}FREQ_{i:04d}", units="1/d",
                                 fittable=False,
                                 description=f"Frequency of term {i}"))
            self.add_param(Param(f"{self.px}SIN_{i:04d}",
                                 units=self.amp_units,
                                 description=f"Sine amplitude of term {i}"))
            self.add_param(Param(f"{self.px}COS_{i:04d}",
                                 units=self.amp_units,
                                 description=f"Cosine amplitude {i}"))

    @classmethod
    def from_parfile(cls, pardict):
        idx = sorted(
            {
                prefix_index(k)[1]
                for k in pardict
                if k.startswith(cls.px + "FREQ_") and prefix_index(k)
            }
        )
        return cls(indices=idx)

    def defaults(self):
        d = {}
        for i in self.indices:
            d[f"{self.px}SIN_{i:04d}"] = 0.0
            d[f"{self.px}COS_{i:04d}"] = 0.0
        d[self.epoch_name] = np.nan
        return d

    def prepare(self, toas, model):
        ep = model.values.get(self.epoch_name, np.nan)
        if np.isnan(ep):
            ep = model.values.get("PEPOCH", 0.0)
        t = toas.ticks.astype(np.float64) / 2**32
        return {"t_days": jnp.asarray((t - ep) / SECS_PER_DAY)}

    def series(self, values, ctx, delay_accum):
        """q(t) summed over terms; shape (N,)."""
        if not self.indices:
            return jnp.zeros_like(ctx["t_days"])
        tau = ctx["t_days"] - delay_accum / SECS_PER_DAY
        freqs = jnp.stack(
            [values[f"{self.px}FREQ_{i:04d}"] for i in self.indices]
        )
        sins = jnp.stack(
            [values[f"{self.px}SIN_{i:04d}"] for i in self.indices]
        )
        coss = jnp.stack(
            [values[f"{self.px}COS_{i:04d}"] for i in self.indices]
        )
        arg = 2.0 * jnp.pi * freqs[:, None] * tau[None, :]
        return jnp.sum(
            sins[:, None] * jnp.sin(arg) + coss[:, None] * jnp.cos(arg),
            axis=0,
        )

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        """Sin/cos amplitudes are linear; the (non-fittable) frequencies
        and epoch sit inside the trig argument."""
        out = []
        for i in self.indices:
            out += [f"{self.px}SIN_{i:04d}", f"{self.px}COS_{i:04d}"]
        return tuple(out)

    def _series_column(self, values, ctx, delay_accum, name):
        """d series / d amplitude: the sinusoid at this term's
        frequency, with tau exactly as ``series`` builds it."""
        tau = ctx["t_days"] - delay_accum / SECS_PER_DAY
        i = int(name[-4:])
        arg = 2.0 * jnp.pi * values[f"{self.px}FREQ_{i:04d}"] * tau
        kind = name[len(self.px):len(self.px) + 3]
        return jnp.sin(arg) if kind == "SIN" else jnp.cos(arg)

    def _amp_scale(self, values, ctx, col):
        """Map a series column to a delay column (identity: WaveX)."""
        return col

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        return self._amp_scale(
            values, ctx, self._series_column(values, ctx, delay_accum,
                                             name))


class WaveX(_FourierBase):
    """Achromatic Fourier delay — the unbiased alternative to the legacy
    Wave component (reference: wavex.py:12)."""

    register = True
    category = "wavex"
    px = "WX"
    epoch_name = "WXEPOCH"
    amp_units = "s"
    trigger_params = ("WXFREQ",)

    def delay(self, values, batch, ctx, delay_accum):
        return self.series(values, ctx, delay_accum)


class DMWaveX(_FourierBase):
    """Fourier DM(t) variation (reference: dmwavex.py:14); delay
    = K DM(t) / nu^2 at the barycentric radio frequency."""

    register = True
    category = "dmwavex"
    px = "DMWX"
    epoch_name = "DMWXEPOCH"
    amp_units = "pc cm^-3"
    trigger_params = ("DMWXFREQ",)

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import bary_freq_mhz

        ctx = super().prepare(toas, model)
        ctx["bfreq"] = jnp.asarray(bary_freq_mhz(toas, model))
        return ctx

    def dm_value(self, values, batch, ctx):
        return self.series(values, ctx, 0.0)

    def delay(self, values, batch, ctx, delay_accum):
        dm = self.series(values, ctx, delay_accum)
        return DM_CONST * dm / ctx["bfreq"] ** 2

    def _amp_scale(self, values, ctx, col):
        return DM_CONST * col / ctx["bfreq"] ** 2

    def d_dm_d_param(self, values, batch, ctx, name):
        # dm_value evaluates the series at zero accumulated delay
        return self._series_column(values, ctx, 0.0, name)


class CMWaveX(_FourierBase):
    """Fourier chromatic-measure variation (reference: cmwavex.py:14);
    delay = K CM(t) / nu^TNCHROMIDX."""

    register = True
    category = "cmwavex"
    px = "CMWX"
    epoch_name = "CMWXEPOCH"
    amp_units = "pc cm^-3 MHz^(alpha-2)"
    trigger_params = ("CMWXFREQ",)

    def __init__(self, indices=()):
        super().__init__(indices)
        self.add_param(Param("TNCHROMIDX", units="", fittable=False,
                             description="Chromatic index alpha"))

    def defaults(self):
        d = super().defaults()
        d["TNCHROMIDX"] = 4.0
        return d

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import bary_freq_mhz

        ctx = super().prepare(toas, model)
        ctx["bfreq"] = jnp.asarray(bary_freq_mhz(toas, model))
        return ctx

    def delay(self, values, batch, ctx, delay_accum):
        cm = self.series(values, ctx, delay_accum)
        return DM_CONST * cm * ctx["bfreq"] ** (-values["TNCHROMIDX"])

    def _amp_scale(self, values, ctx, col):
        return DM_CONST * col * ctx["bfreq"] ** (-values["TNCHROMIDX"])
