"""Dispersion: DM Taylor series, DMX piecewise, DMJUMP.

Counterpart of the reference dispersion components (reference:
src/pint/models/dispersion_model.py:132 DispersionDM ``dispersion_time_
delay`` at :42-52, :310 DispersionDMX, :724 DispersionJump).
delay[s] = K * DM(t) / freq[MHz]^2 with K = 1/2.41e-4 (the community
convention constant, pint_tpu.DM_CONST).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import DM_CONST
from pint_tpu.models.component import (
    DelayComponent,
    mask_from_select,
)
from pint_tpu.models.parameter import Param, prefix_index


class DispersionDM(DelayComponent):
    category = "dispersion_constant"
    trigger_params = ("DM",)

    def __init__(self, num_dm_derivs=0):
        super().__init__()
        self.num_dm_derivs = num_dm_derivs
        self.add_param(Param("DM", units="pc cm^-3", description="Dispersion measure"))
        for k in range(1, num_dm_derivs + 1):
            self.add_param(Param(f"DM{k}", units=f"pc cm^-3/yr^{k}",
                                 description=f"DM derivative {k}"))
        self.add_param(Param("DMEPOCH", kind="mjd", fittable=False,
                             description="Epoch of DM"))

    @classmethod
    def from_parfile(cls, pardict):
        n = 0
        for key in pardict:
            pi = prefix_index(key)
            if pi and pi[0] == "DM" and not key.startswith("DMX"):
                n = max(n, pi[1])
        return cls(num_dm_derivs=n)

    def defaults(self):
        d = {f"DM{k}": 0.0 for k in range(1, self.num_dm_derivs + 1)}
        d["DM"] = 0.0
        d["DMEPOCH"] = np.nan
        return d

    def prepare(self, toas, model):
        ep = model.values.get("DMEPOCH", np.nan)
        if np.isnan(ep):
            ep = model.values.get("PEPOCH", 0.0)
        t = toas.ticks.astype(np.float64) / 2**32
        from pint_tpu.models.astrometry import bary_freq_mhz

        # DM1.. are in pc cm^-3 per YEAR^k (par-file convention; the
        # reference evaluates dt.to(u.yr), dispersion_model.py:274)
        return {
            "dt_yr": jnp.asarray((t - ep) / (365.25 * 86400.0)),
            # the reference evaluates dispersion at the *barycentric*
            # radio frequency (dispersion_model.py uses
            # barycentric_radio_freq); ~1e-4 relative Doppler matters
            # at the 100-ns level for ms-pulsar DM delays
            "bfreq": jnp.asarray(bary_freq_mhz(toas, model)),
        }

    def dm_at(self, values, ctx):
        dm = values["DM"]
        if self.num_dm_derivs:
            dt = ctx["dt_yr"]
            fact = 1.0
            power = dt
            for k in range(1, self.num_dm_derivs + 1):
                fact *= k
                dm = dm + values[f"DM{k}"] * power / fact
                power = power * dt
        return dm

    def dm_value(self, values, batch, ctx):
        return self.dm_at(values, ctx)

    def delay(self, values, batch, ctx, delay_accum):
        dm = self.dm_at(values, ctx)
        return DM_CONST * dm / ctx["bfreq"] ** 2

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return ("DM",) + tuple(
            f"DM{k}" for k in range(1, self.num_dm_derivs + 1))

    def _d_dm(self, ctx, name):
        """d DM(t) / d name: the Taylor monomial dt^k/k! (1 for DM),
        built with the same chained multiplies as dm_at."""
        if name == "DM":
            return jnp.ones_like(ctx["dt_yr"])
        k = int(name[2:])
        dt = ctx["dt_yr"]
        fact = 1.0
        power = dt
        for j in range(2, k + 1):
            fact *= j
            power = power * dt
        return power / fact

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        return DM_CONST * self._d_dm(ctx, name) / ctx["bfreq"] ** 2

    def d_dm_d_param(self, values, batch, ctx, name):
        return self._d_dm(ctx, name)


class DispersionDMX(DelayComponent):
    """Piecewise DM offsets over MJD ranges (DMX_####/DMXR1/DMXR2)."""

    category = "dispersion_dmx"
    trigger_params = ("DMX",)

    def __init__(self, indices=()):
        super().__init__()
        self.indices = tuple(indices)
        for i in self.indices:
            self.add_param(Param(f"DMX_{i:04d}", units="pc cm^-3",
                                 description=f"DM offset in range {i}"))
            self.add_param(Param(f"DMXR1_{i:04d}", kind="mjd",
                                 fittable=False,
                                 description=f"DMX range {i} start"))
            self.add_param(Param(f"DMXR2_{i:04d}", kind="mjd",
                                 fittable=False,
                                 description=f"DMX range {i} end"))

    @classmethod
    def from_parfile(cls, pardict):
        idx = sorted(
            {
                prefix_index(k)[1]
                for k in pardict
                if k.startswith("DMX_") and prefix_index(k)
            }
        )
        return cls(indices=idx)

    def defaults(self):
        return {f"DMX_{i:04d}": 0.0 for i in self.indices}

    def prepare(self, toas, model):
        masks = []
        for i in self.indices:
            lo = model.values[f"DMXR1_{i:04d}"] / 86400.0 + 51544.5
            hi = model.values[f"DMXR2_{i:04d}"] / 86400.0 + 51544.5
            masks.append((toas.mjd_float >= lo) & (toas.mjd_float <= hi))
        m = (
            np.stack(masks, axis=0)
            if masks
            else np.zeros((0, len(toas)), dtype=bool)
        )
        from pint_tpu.models.astrometry import bary_freq_mhz

        return {
            "masks": jnp.asarray(m),
            "bfreq": jnp.asarray(bary_freq_mhz(toas, model)),
        }

    def dm_value(self, values, batch, ctx):
        if not self.indices:
            return jnp.zeros_like(batch.freq_mhz)
        dmx = jnp.stack([values[f"DMX_{i:04d}"] for i in self.indices])
        return jnp.sum(ctx["masks"] * dmx[:, None], axis=0)

    def delay(self, values, batch, ctx, delay_accum):
        return DM_CONST * self.dm_value(values, batch, ctx) \
            / ctx["bfreq"] ** 2

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return tuple(f"DMX_{i:04d}" for i in self.indices)

    def _d_dm(self, ctx, name):
        j = self.indices.index(int(name[4:]))
        return ctx["masks"][j].astype(jnp.float64)

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        return DM_CONST * self._d_dm(ctx, name) / ctx["bfreq"] ** 2

    def d_dm_d_param(self, values, batch, ctx, name):
        return self._d_dm(ctx, name)


class DispersionJump(DelayComponent):
    """Constant offsets to the *measured DM values* on TOA subsets
    (DMJUMP mask parameters).  Affects only the wideband DM residuals,
    NOT the time delay (reference: dispersion_model.py:724-735 "will not
    apply to the dispersion time delay")."""

    category = "dispersion_jump"
    trigger_params = ("DMJUMP",)

    def __init__(self, selects=()):
        super().__init__()
        self.selects = tuple(selects)
        for i, sel in enumerate(self.selects, start=1):
            self.add_param(Param(f"DMJUMP{i}", units="pc cm^-3",
                                 select=sel,
                                 description=f"DM jump {i} ({sel})"))

    @classmethod
    def from_parfile(cls, pardict):
        masks = pardict.get("__MASKS__", {})
        return cls(selects=[s for s, _ in masks.get("DMJUMP", [])])

    def defaults(self):
        return {f"DMJUMP{i}": 0.0 for i in range(1, len(self.selects) + 1)}

    def prepare(self, toas, model):
        masks = [
            np.asarray(mask_from_select(sel, toas)) for sel in self.selects
        ]
        m = (
            np.stack(masks, 0)
            if masks
            else np.zeros((0, len(toas)), dtype=bool)
        )
        from pint_tpu.models.astrometry import bary_freq_mhz

        return {
            "masks": jnp.asarray(m),
            "bfreq": jnp.asarray(bary_freq_mhz(toas, model)),
        }

    def delay(self, values, batch, ctx, delay_accum):
        # DMJUMP models the DM *measurement*, not the dispersion delay
        # (reference d_delay_d_dmjump is identically zero)
        return jnp.zeros_like(batch.freq_mhz)

    def dm_value(self, values, batch, ctx):
        if not self.selects:
            return jnp.zeros_like(batch.freq_mhz)
        dj = jnp.stack(
            [values[f"DMJUMP{i}"] for i in range(1, len(self.selects) + 1)]
        )
        # sign: DMJUMP is subtracted from the modeled DM (reference
        # jump_dm adds -value)
        return -jnp.sum(ctx["masks"] * dj[:, None], axis=0)

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return tuple(
            f"DMJUMP{i}" for i in range(1, len(self.selects) + 1))

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        return jnp.zeros_like(batch.freq_mhz)

    def d_dm_d_param(self, values, batch, ctx, name):
        i = int(name[6:])
        return -ctx["masks"][i - 1].astype(jnp.float64)
