"""TimingModel: ordered component container and the pure phase function.

Counterpart of the reference's TimingModel (reference:
src/pint/models/timing_model.py:169,1515,1548 ``delay``/``phase``), with
the evaluation made explicitly functional: a prepared model exposes

    phase(values)  = (n_turns int64, frac float64)    [jit-compiled]

computed as the sequential delay fold (each delay component sees the
accumulated delay, matching the reference's chain semantics) followed by
the phase components and the TZR-phase subtraction.  ``values`` is a
``{param_name: f64 scalar}`` dict — a JAX pytree — so the same compiled
function serves fitting, vmapped grids, and MCMC.

Design matrices come from ``jax.jacfwd`` of the fractional phase
(replacing the reference's hand-derivative registry and its 124-s
designmatrix hot spot, profiling/README.txt:58-62).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import fixedpoint as fp
from pint_tpu.models.component import Component, DelayComponent, PhaseComponent

def _env_on(var: str) -> bool:
    """Default-on env gate: anything but 0/off/no/false enables."""
    import os

    return os.environ.get(var, "1").strip().lower() not in (
        "0", "off", "no", "false")


def hybrid_design_default() -> bool:
    """Whether fitters/grids build hybrid analytic/AD design matrices
    (``$PINT_TPU_HYBRID_DESIGN``, default on).  The gate changes traced
    programs, so callers fold it into their jit keys."""
    return _env_on("PINT_TPU_HYBRID_DESIGN")


def frozen_delay_default() -> bool:
    """Whether fitters/grids precompute frozen-component delays as
    dynamic data leaves (``$PINT_TPU_FROZEN_DELAY``, default on)."""
    return _env_on("PINT_TPU_FROZEN_DELAY")


#: evaluation order by category (reference DEFAULT_ORDER,
#: timing_model.py:107-123)
DEFAULT_ORDER = [
    "astrometry",
    "jump_delay",
    "troposphere",
    "solar_system_shapiro",
    "solar_wind",
    "solar_windx",
    "dispersion_constant",
    "dispersion_dmx",
    "dispersion_jump",
    "fdjumpdm",
    "dmwavex",
    "chromatic",
    "chromatic_cmx",
    "cmwavex",
    "pulsar_system",
    "frequency_dependent",
    "fdjump",
    "absolute_phase",
    "spindown",
    "phase_jump",
    "glitch",
    "piecewise",
    "wave",
    "wavex",
    "ifunc",
    "phase_offset",
]


class TimingModel:
    """Host-side model object: components + parameter metadata + values."""

    def __init__(self, name="", components=()):
        self.name = name
        self.components: List[Component] = []
        self.values: Dict[str, float] = {}
        self.meta: Dict[str, str] = {}  # PSR, EPHEM, CLK, UNITS ...
        for c in components:
            self.add_component(c)

    # -- structure -----------------------------------------------------------
    def add_component(self, comp: Component):
        self.components.append(comp)
        order = {cat: i for i, cat in enumerate(DEFAULT_ORDER)}
        self.components.sort(key=lambda c: order.get(c.category, 99))
        for p in comp.params:
            self.values.setdefault(p.name, np.nan)
        for k, v in comp.defaults().items():
            if np.isnan(self.values.get(k, np.nan)):
                self.values[k] = v

    def remove_component(self, comp_or_name):
        name = (
            comp_or_name
            if isinstance(comp_or_name, str)
            else type(comp_or_name).__name__
        )
        self.components = [
            c for c in self.components if type(c).__name__ != name
        ]

    def component(self, name) -> Component:
        for c in self.components:
            if type(c).__name__ == name:
                return c
        raise KeyError(name)

    def has_component(self, name) -> bool:
        return any(type(c).__name__ == name for c in self.components)

    @property
    def params(self) -> Dict[str, "Param"]:
        out = {}
        comps = self.components
        inert = getattr(self, "_superset_inert", None)
        if inert:
            # superset-inert members carry frozen copies of params
            # whose names collide with a real component's (PB/A1/...
            # across binary families): the REAL component's Param must
            # win the dict slot or the pulsar silently loses that
            # parameter's fit freedom (parallel.pta superset)
            comps = sorted(
                comps,
                key=lambda c: 0 if type(c).__name__ in inert else 1)
        for c in comps:
            for p in c.params:
                out[p.name] = p
        return out

    @property
    def free_params(self) -> List[str]:
        return [name for name, p in self.params.items() if not p.frozen]

    @free_params.setter
    def free_params(self, names):
        names = set(names)
        for _, p in self.params.items():
            p.frozen = p.name not in names

    @property
    def delay_components(self):
        return [c for c in self.components if isinstance(c, DelayComponent)]

    @property
    def phase_components(self):
        return [c for c in self.components if isinstance(c, PhaseComponent)]

    @property
    def noise_components(self):
        from pint_tpu.models.noise import NoiseComponent

        return [c for c in self.components if isinstance(c, NoiseComponent)]

    @property
    def has_correlated_errors(self) -> bool:
        """Any noise component with a low-rank basis (reference:
        timing_model.has_correlated_errors, timing_model.py:1062)."""
        return any(
            c.introduces_correlated_errors for c in self.noise_components
        )

    @property
    def has_time_correlated_errors(self) -> bool:
        return any(c.is_time_correlated for c in self.noise_components)

    @property
    def free_noise_params(self) -> List[str]:
        """Free parameters owned by noise components — fit by
        lnlikelihood maximization, not least squares (reference:
        fitter._fit_noise, fitter.py:1230)."""
        out = []
        for c in self.noise_components:
            out.extend(p.name for p in c.params if not p.frozen)
        return out

    @property
    def free_timing_params(self) -> List[str]:
        """Free parameters that enter the design matrix."""
        noise = set(self.free_noise_params)
        return [p for p in self.free_params if p not in noise]

    def __getitem__(self, name):
        return self.values[name]

    def __setitem__(self, name, value):
        if name not in self.values:
            raise KeyError(name)
        self.values[name] = float(value)

    # -- derived (func) parameters (reference funcParameter) -----------------
    def add_func_param(self, func_param):
        """Register a read-only derived parameter (an instance of
        pint_tpu.models.parameter.funcParameter)."""
        if not hasattr(self, "_func_params"):
            self._func_params = {}
        self._func_params[func_param.name] = func_param

    def func_value(self, name):
        return self._func_params[name].value(self)

    @property
    def func_params(self):
        return dict(getattr(self, "_func_params", {}))

    # -- preparation ---------------------------------------------------------
    def prepare(self, toas, tzr=True) -> "PreparedModel":
        """Bind this model to ``toas``.  ``tzr=False`` skips the TZR
        reference prepare — for throwaway preps whose caller grafts in
        an existing TZR anchor (the streaming-append mini datasets)."""
        return PreparedModel(self, toas, tzr=tzr)

    # -- output --------------------------------------------------------------
    def get_derived_params(self, rms_us=None, ntoas=None,
                           returndict=False):
        """Text report of derived quantities from the fitted model
        (reference: timing_model.py:3055 get_derived_params): period
        and derivatives, characteristic age, surface/light-cylinder B,
        Edot, and for binaries the mass function, minimum/median
        companion mass, and the ELL1 applicability check
        (asini/c * ecc^2 vs the timing precision)."""
        import numpy as np

        from pint_tpu import derived_quantities as dq

        vals = self.values
        out = {}
        lines = ["Derived Parameters:"]
        f0 = float(vals.get("F0", 0.0))
        if f0 > 0:
            p = 1.0 / f0
            out["P (s)"] = p
            lines.append(f"Period = {p:.12g} s")
            f1 = float(vals.get("F1", 0.0))
            if f1:
                pdot = -f1 / f0**2
                out["Pdot (s/s)"] = pdot
                lines.append(f"Pdot = {pdot:.6g}")
                if f1 < 0:
                    age = dq.pulsar_age_yr(f0, f1)
                    bsurf = dq.pulsar_B_gauss(f0, f1)
                    blc = dq.pulsar_B_lightcyl_gauss(f0, f1)
                    edot = dq.pulsar_edot(f0, f1)
                    out.update({"tau_c (yr)": age, "B_surf (G)": bsurf,
                                "B_LC (G)": blc, "Edot (erg/s)": edot})
                    lines += [
                        f"Characteristic age = {age:.4g} yr (braking n=3)",
                        f"Surface B field = {bsurf:.4g} G",
                        f"Magnetic field at light cylinder = {blc:.4g} G",
                        f"Spindown Edot = {edot:.4g} erg/s (I=1e45)",
                    ]
        if "PB" in vals or "FB0" in vals:
            pb_s = (float(vals["PB"]) if "PB" in vals
                    else 1.0 / float(vals["FB0"]))
            a1 = float(vals.get("A1", 0.0))
            out["PB (d)"] = pb_s / 86400.0
            lines.append(f"Binary period PB = {pb_s / 86400.0:.10g} d")
            if a1 > 0:
                mf = dq.mass_funct(pb_s, a1)
                out["Mass function (Msun)"] = mf
                lines.append(f"Mass function = {mf:.6g} Msun")
                mcmin = dq.companion_mass(pb_s, a1, i_rad=np.pi / 2,
                                          mp=1.4)
                mcmed = dq.companion_mass(pb_s, a1,
                                          i_rad=np.radians(60.0), mp=1.4)
                out["Mc,min (Msun)"] = mcmin
                out["Mc,median (Msun)"] = mcmed
                lines.append(
                    f"Min / median companion mass (Mp=1.4) = "
                    f"{mcmin:.4g} / {mcmed:.4g} Msun")
            if "EPS1" in vals and rms_us is not None and ntoas:
                ecc = float(np.hypot(vals.get("EPS1", 0.0),
                                     vals.get("EPS2", 0.0)))
                limit = a1 * ecc**2 * 1e6  # us
                ok = limit < rms_us / np.sqrt(float(ntoas))
                out["ELL1 ok"] = ok
                lines.append(
                    "ELL1 applicability: asini/c * ecc^2 = "
                    f"{limit:.3g} us {'<' if ok else '>!'} "
                    f"rms/sqrt(N) = {rms_us / np.sqrt(float(ntoas)):.3g}"
                    " us")
        text = "\n".join(lines)
        return (text, out) if returndict else text

    def d_phase_d_toa(self, toas, dt_s=2.0):
        """Instantaneous topocentric spin frequency [Hz] at each TOA
        (reference: timing_model.py d_phase_d_toa — the numerical
        sample-and-difference method): central difference of the FULL
        model phase with the TOAs shifted by +/-dt_s, re-deriving the
        solar-system geometry at the shifted times so Doppler (Roemer
        rate) and binary-orbit terms are included.  The integer turn
        difference is taken in exact int64 before any float conversion,
        so ~4e11-turn counts cost no precision."""
        import numpy as np

        shift_ticks = int(round(dt_s * 2**32))
        ns = []
        fracs = []
        for sign in (+1, -1):
            shifted = toas[np.arange(len(toas))]  # deep-enough copy
            shifted.ticks = toas.ticks + sign * shift_ticks
            shifted._compute_posvels()
            n, frac = self.prepare(shifted).phase()
            ns.append(np.asarray(n))
            fracs.append(np.asarray(frac, np.float64))
        dn = (ns[0] - ns[1]).astype(np.float64)  # exact: |dn| ~ 1e3
        return (dn + (fracs[0] - fracs[1])) / (2.0 * dt_s)

    def jump_flags_to_params(self, toas):
        """Materialize JUMP parameters for ``-tim_jump``/``-gui_jump``
        flag values that no existing JUMP selects (reference:
        timing_model.py:1727 jump_flags_to_params — tim-file JUMP
        command pairs become flags at parse time, and the user expects
        them to act as fitted JUMPs even without par-file lines).

        Returns the list of JUMP parameter names added (empty when all
        flag values are already covered)."""
        from pint_tpu.models.jump import PhaseJump
        from pint_tpu.models.parameter import Param

        flag_vals = []
        for flag in ("tim_jump", "gui_jump"):
            for f in toas.flags:
                if flag in f and (flag, str(f[flag])) not in flag_vals:
                    flag_vals.append((flag, str(f[flag])))
        if not flag_vals:
            return []
        if not self.has_component("PhaseJump"):
            self.add_component(PhaseJump())
        comp = self.component("PhaseJump")
        covered = {(s[1], str(s[2])) for s in comp.selects
                   if s and s[0] == "flag"}
        added = []
        for flag, val in flag_vals:
            if (flag, val) in covered:
                continue
            n = len(comp.selects) + 1
            sel = ("flag", flag, val)
            comp.selects = comp.selects + (sel,)
            name = f"JUMP{n}"
            comp.add_param(Param(name, units="s", select=sel,
                                 frozen=False,
                                 description=f"Jump from -{flag} {val}"))
            self.values[name] = 0.0
            added.append(name)
        return added

    def delete_jump_and_flags(self, toas, jump_num):
        """Remove JUMP<jump_num> from the PhaseJump component and strip
        its selecting flag from the TOAs; remaining jumps are
        renumbered densely (reference: timing_model.py:1804
        delete_jump_and_flags, the pintk helper)."""
        comp = self.component("PhaseJump")
        idx = int(jump_num) - 1
        if not 0 <= idx < len(comp.selects):
            raise ValueError(f"no JUMP{jump_num} to delete")
        sel = comp.selects[idx]
        if toas is not None and sel[0] == "flag":
            for f in toas.flags:
                if str(f.get(sel[1], "")) == str(sel[2]):
                    del f[sel[1]]
        selects = list(comp.selects)
        del selects[idx]
        old_params = [p for p in comp.params
                      if not p.name.startswith("JUMP")]
        jump_params = [p for p in comp.params if p.name.startswith("JUMP")]
        del jump_params[idx]
        # renumber densely: JUMP params are positional in the fold
        vals = [self.values.pop(f"JUMP{i+1}", 0.0)
                for i in range(len(comp.selects))]
        del vals[idx]
        comp.selects = tuple(selects)
        comp.params = old_params
        for i, (p, v) in enumerate(zip(jump_params, vals), start=1):
            p.name = f"JUMP{i}"
            p.select = selects[i - 1]
            comp.params.append(p)
            self.values[f"JUMP{i}"] = v

    def as_ECL(self, ecl="IERS2010"):
        """Copy with astrometry in ecliptic coordinates (covariance-
        propagated; reference timing_model.py:2961)."""
        from pint_tpu.models.astrometry import model_as_ECL

        return model_as_ECL(self, ecl)

    def as_ICRS(self):
        """Copy with astrometry in equatorial coordinates (reference
        timing_model.py:3011)."""
        from pint_tpu.models.astrometry import model_as_ICRS

        return model_as_ICRS(self)

    def as_parfile(self) -> str:
        from pint_tpu.models.builder import model_to_parfile

        return model_to_parfile(self)

    def compare(self, other, threshold_sigma=3.0, verbosity="max"):
        """Human-readable parameter comparison with another model
        (reference: TimingModel.compare, timing_model.py:2177 — the
        five-column ``PARAMETER | Model1 | Model2 | Diff_Sigma1 |
        Diff_Sigma2`` table; '!' marks > threshold_sigma changes, '*'
        marks grown uncertainties).

        verbosity: 'max' all params | 'med' fit params | 'min' only
        significant changes."""
        rows = [f"{'PARAMETER':<14s} {'Self':>24s} {'Other':>24s} "
                f"{'Diff_Sigma1':>12s} {'Diff_Sigma2':>12s}"]
        names = list(self.params)
        for name in names:
            p1 = self.params[name]
            v1 = self.values.get(name, np.nan)
            in2 = name in other.params
            v2 = other.values.get(name, np.nan) if in2 else np.nan
            u1 = p1.uncertainty
            u2 = other.params[name].uncertainty if in2 else None
            if isinstance(v1, float) and np.isnan(v1) and (
                not in2 or (isinstance(v2, float) and np.isnan(v2))
            ):
                continue
            diff = (v1 - v2) if in2 else np.nan
            s1 = abs(diff) / u1 if u1 else np.nan
            s2 = abs(diff) / u2 if u2 else np.nan
            flag = ""
            if (np.isfinite(s1) and s1 > threshold_sigma) or (
                np.isfinite(s2) and s2 > threshold_sigma
            ):
                flag += " !"
            if u1 and u2 and u2 > 1.05 * u1:
                flag += " *"
            if verbosity == "min" and not flag:
                continue
            if verbosity == "med" and p1.frozen and not flag:
                continue
            fmt = lambda v, p: (p.format(v) if not (
                isinstance(v, float) and np.isnan(v)) else "--")
            rows.append(
                f"{name:<14s} {fmt(v1, p1):>24s} {fmt(v2, p1):>24s} "
                f"{s1 if np.isfinite(s1) else float('nan'):>12.3g} "
                f"{s2 if np.isfinite(s2) else float('nan'):>12.3g}{flag}"
            )
        only_other = [n for n in other.params if n not in self.params
                      and not (isinstance(other.values.get(n), float)
                               and np.isnan(other.values.get(n, np.nan)))]
        if only_other:
            rows.append(f"# only in other model: {' '.join(only_other)}")
        return "\n".join(rows)


def gated_dm_sum(model, values, batch, ctx_map):
    """Sum of every component's ``dm_value`` contribution [pc cm^-3],
    with superset-inert members zeroed via their prepare-time
    ``__gate__`` (one definition shared by PreparedModel.total_dm_fn
    and the batched PTA wideband path, so DM gating semantics cannot
    drift between them)."""
    dm = jnp.zeros(batch.ticks.shape, dtype=jnp.float64)
    for c in model.components:
        f = getattr(c, "dm_value", None)
        if f is not None:
            ctx = ctx_map[type(c).__name__]
            contrib = f(values, batch, ctx)
            if "__gate__" in ctx:
                contrib = contrib * ctx["__gate__"]
            dm = dm + contrib
    return dm


def _ctx_patch_rows(old_ctx, mini_ctx, n0, n1, n_rows):
    """Row-local ctx refresh for the streaming append: per-row array
    leaves (leading axis ``n_rows``) take rows ``[n0, n1)`` from the
    mini prepare's leading rows; everything else (scalars, static
    depths, per-select index arrays) must be EQUAL between the old and
    mini prepares — a mismatch means the ctx is not row-local after
    all, and the caller falls back to the component's plain prepare.
    On-device leaves are patched with ``dynamic_update_slice`` so no
    O(N) array crosses the host boundary; pad rows past the delta keep
    the old prepare's clone values (weight ~1e-44).  Returns the new
    ctx dict, or None on any structural disagreement."""
    dn = n1 - n0
    if dn <= 0 or set(k for k in old_ctx if k != "__gate__") != \
            set(k for k in mini_ctx if k != "__gate__"):
        return None
    out = {}
    for k, v_old in old_ctx.items():
        if k == "__gate__":
            continue
        v_mini = mini_ctx[k]
        is_arr = isinstance(v_old, (np.ndarray, jax.Array))
        if is_arr and v_old.ndim >= 1 and v_old.shape[0] == n_rows:
            rows = np.asarray(v_mini)
            if rows.ndim != v_old.ndim or rows.shape[0] < dn or \
                    rows.shape[1:] != v_old.shape[1:]:
                return None
            rows = rows[:dn]
            if isinstance(v_old, jax.Array):
                out[k] = jax.lax.dynamic_update_slice(
                    v_old, jnp.asarray(rows, dtype=v_old.dtype),
                    (n0,) + (0,) * (v_old.ndim - 1))
            else:
                a = np.array(v_old, copy=True)
                a[n0:n1] = rows
                out[k] = a
            continue
        if is_arr and v_old.ndim == 2 and v_old.shape[1] == n_rows \
                and v_old.shape[0] != n_rows:
            # row-stacked per-select layout (k, N) — the white-noise
            # mask stacks; rows live on axis 1
            rows = np.asarray(v_mini)
            if rows.ndim != 2 or rows.shape[0] != v_old.shape[0] or \
                    rows.shape[1] < dn:
                return None
            rows = rows[:, :dn]
            if isinstance(v_old, jax.Array):
                out[k] = jax.lax.dynamic_update_slice(
                    v_old, jnp.asarray(rows, dtype=v_old.dtype),
                    (0, n0))
            else:
                a = np.array(v_old, copy=True)
                a[:, n0:n1] = rows
                out[k] = a
            continue
        try:
            if is_arr or isinstance(v_mini, (np.ndarray, jax.Array)):
                same = np.array_equal(np.asarray(v_old),
                                      np.asarray(v_mini))
            else:
                same = bool(v_old == v_mini)
        except Exception:
            return None
        if not same:
            return None
        out[k] = v_old
    return out


class PreparedModel:
    """Model bound to a dataset: static ctx captured, pure fns jitted.

    The reference recomputes mask selections and TZR phase lazily per call
    (toa_select.py caching, absolute_phase.py:get_TZR_toa); here they are
    resolved once, into jit-closure constants.
    """

    def __init__(self, model: TimingModel, toas, tzr=True):
        self.model = model
        self.toas = toas
        self.batch = toas.to_batch()
        self.ctx = {
            type(c).__name__: c.prepare(toas, model) for c in model.components
        }
        # heterogeneous-PTA superset gating: components added only to
        # align structures across pulsars get a 0.0 gate (their shared
        # parameter names — PB/A1/T0... — would otherwise make them
        # active); every component carries the key so the batched ctx
        # structure is uniform (pint_tpu.parallel.pta superset)
        inert = getattr(model, "_superset_inert", None)
        if inert is not None:
            for name, c_ctx in self.ctx.items():
                c_ctx["__gate__"] = jnp.float64(
                    0.0 if name in inert else 1.0)
        # TZR reference: a single synthetic TOA evaluated through the SAME
        # chain — but with its OWN prepare-time ctx (masks, dt_ticks, ...);
        # reusing the data ctx would silently evaluate TZR with data-TOA
        # static arrays (caught by simulate->fit self-consistency).
        self.tzr_batch = None
        self.tzr_ctx = None
        if tzr:
            for c in model.components:
                if hasattr(c, "make_tzr_toas"):
                    tzr_toas = c.make_tzr_toas(model, toas)
                    if tzr_toas is not None:
                        self.tzr_batch = tzr_toas.to_batch()
                        self.tzr_ctx = {
                            type(cc).__name__: cc.prepare(tzr_toas, model)
                            for cc in model.components
                        }
                        if inert is not None:
                            for name, c_ctx in self.tzr_ctx.items():
                                c_ctx["__gate__"] = jnp.float64(
                                    0.0 if name in inert else 1.0)
        # correlated-noise bases are static per dataset; stack them once
        # (reference: noise_model_designmatrix, timing_model.py:1690)
        self._noise_basis_comps = []
        parts = []
        for c in model.noise_components:
            b = c.basis(self.ctx[type(c).__name__])
            if b is not None and b.shape[1] > 0:
                self._noise_basis_comps.append(c)
                parts.append(np.asarray(b))
        n = self.batch.ticks.shape[0]
        self.noise_basis = jnp.asarray(
            np.concatenate(parts, axis=1) if parts else np.zeros((n, 0))
        )
        # pintlint: allow=PTL101 -- legacy per-instance phase accessor
        # (pre-registry API surface, pintk/polycos); the fit hot path
        # never touches it — it routes through Residuals' shared
        # programs
        self._phase_jit = jax.jit(self._phase_raw)

    def prepare_appended(self, toas, n0=None, mini_ctx=None):
        """Streaming re-prepare: bind this prepared model to ``toas``
        (the same dataset with rows appended in place of pad
        sentinels) while keeping every prepare-time frozen quantity
        frozen — the bucket-interior append path.

        Components offering ``prepare_streamed(toas, model, old_ctx,
        n0)`` extend their ctx on their own frozen anchors (the
        Fourier comb, the ECORR epoch layout); a hook returning None
        vetoes the streamed prepare (the caller falls back to a full
        re-prepare).  Components without the hook re-run their plain
        ``prepare`` — sound only for row-local ctx (masks, dt ticks),
        so an unknown *correlated* component vetoes conservatively.
        When the caller already prepared the delta as a mini dataset
        (``mini_ctx``: the mini PreparedModel's per-component ctx),
        those row-local entries are row-patched onto the old ctx
        instead — O(DeltaN) host work and, for on-device leaves, a
        device-side update with no O(N) re-upload; any structural
        disagreement (keys, shapes, non-row scalars) falls back to the
        plain per-component prepare, never to a wrong answer.  The TZR
        reference batch/ctx are carried over verbatim: the
        absolute-phase anchor of a streamed dataset never moves.

        Returns the new PreparedModel, or None on any veto."""
        if n0 is None:
            n0 = getattr(self.toas, "n_filled", None) \
                or getattr(self.toas, "n_real", None) or len(self.toas)
        n1 = getattr(toas, "n_filled", None) \
            or getattr(toas, "n_real", None) or len(toas)
        n_rows = len(toas)
        model = self.model
        ctx = {}
        for c in model.components:
            name = type(c).__name__
            old_ctx = self.ctx[name]
            hook = getattr(c, "prepare_streamed", None)
            if hook is not None:
                got = hook(toas, model, old_ctx, n0)
                if got is None:
                    return None
            elif getattr(c, "introduces_correlated_errors", False):
                # a correlated component without a streaming story
                # (e.g. a cross-pulsar common process) would need its
                # frozen gram re-derived — full re-prepare instead
                return None
            else:
                got = None
                if mini_ctx is not None and name in mini_ctx:
                    got = _ctx_patch_rows(old_ctx, mini_ctx[name],
                                          n0, n1, n_rows)
                if got is None:
                    got = c.prepare(toas, model)
            if "__gate__" in old_ctx:
                got["__gate__"] = old_ctx["__gate__"]
            ctx[name] = got
        new = object.__new__(PreparedModel)
        new.model = model
        new.toas = toas
        new.batch = toas.to_batch()
        new.ctx = ctx
        new.tzr_batch = self.tzr_batch
        new.tzr_ctx = self.tzr_ctx
        new._noise_basis_comps = []
        parts = []
        rows_parts = []
        for c in model.noise_components:
            b = c.basis(ctx[type(c).__name__])
            if b is not None and b.shape[1] > 0:
                new._noise_basis_comps.append(c)
                parts.append(b)
                rows_parts.append(np.asarray(b)[n0:n1])
        n = new.batch.ticks.shape[0]
        widths = sum(int(b.shape[1]) for b in parts)
        if (new._noise_basis_comps == self._noise_basis_comps
                and widths == int(self.noise_basis.shape[1])
                and n == int(self.noise_basis.shape[0])
                and n1 > n0):
            # rank-DeltaN stacked-basis refresh: the hooks certified
            # every old row bit-exact, so only the appended rows need
            # transferring — a device-side row patch instead of the
            # O(N K) host concat + full re-upload.  Pad rows past the
            # delta keep the OLD prepare's clones (weight ~1e-44; the
            # same pad-staleness class _append_fit_data documents).
            if widths:
                rows = np.concatenate(rows_parts, axis=1)
                new.noise_basis = jax.lax.dynamic_update_slice(
                    self.noise_basis, jnp.asarray(rows), (n0, 0))
            else:
                new.noise_basis = self.noise_basis
        else:
            new.noise_basis = jnp.asarray(
                np.concatenate([np.asarray(b) for b in parts], axis=1)
                if parts else np.zeros((n, 0)))
        # pintlint: allow=PTL101 -- same legacy accessor as __init__
        new._phase_jit = jax.jit(new._phase_raw)
        return new

    # -- noise interface ------------------------------------------------------
    def scaled_sigma_fn(self, values, batch=None, ctx=None):
        """Per-TOA uncertainty [s] after white-noise scaling (reference:
        scaled_toa_uncertainty, timing_model.py:1644).  batch/ctx
        default to this dataset's; the fit hot path passes them as
        dynamic jit arguments (compile_cache shared-trace contract)."""
        batch = self.batch if batch is None else batch
        ctx = self.ctx if ctx is None else ctx
        sigma = batch.error_s
        for c in self.model.noise_components:
            sigma = c.scaled_sigma(
                values, batch, ctx[type(c).__name__], sigma
            )
        return sigma

    def noise_weights_fn(self, values, ctx=None):
        """Concatenated basis weights phi, aligned with noise_basis
        columns (reference: noise_model_basis_weight,
        timing_model.py:1696)."""
        ctx = self.ctx if ctx is None else ctx
        parts = [
            c.weights(values, ctx[type(c).__name__])
            for c in self._noise_basis_comps
        ]
        return jnp.concatenate(parts) if parts else jnp.zeros(0)

    def noise_dimensions(self):
        """{component_name: (start, length)} slices into the stacked
        basis (reference: noise_model_dimensions, timing_model.py:1702)."""
        out = {}
        start = 0
        for c in self._noise_basis_comps:
            nb = int(c.basis(self.ctx[type(c).__name__]).shape[1])
            out[type(c).__name__] = (start, nb)
            start += nb
        return out

    # -- wideband DM interface ------------------------------------------------
    def total_dm_fn(self, values, batch=None, ctx=None):
        """Modeled DM [pc cm^-3] at each TOA: the sum of every
        component's ``dm_value`` contribution (reference:
        TimingModel.total_dm via dm_value_funcs)."""
        return gated_dm_sum(self.model, values,
                            self.batch if batch is None else batch,
                            self.ctx if ctx is None else ctx)

    def scaled_dm_sigma_fn(self, values, dm_sigma, ctx=None):
        """Wideband DM uncertainties after DMEFAC/DMEQUAD scaling
        (reference: scaled_dm_uncertainty)."""
        ctx = self.ctx if ctx is None else ctx
        for c in self.model.noise_components:
            f = getattr(c, "scaled_dm_sigma", None)
            if f is not None:
                dm_sigma = f(values, ctx[type(c).__name__], dm_sigma)
        return dm_sigma

    # pure function of values (pytree dict of f64 scalars)
    def _delay_raw(self, values, batch, ctx_map, frozen=None):
        """Sequential delay fold.  frozen: optional ``{component_name:
        precomputed (N,) delay}`` — those components' contributions
        enter the fold as DATA at their chain position instead of being
        re-evaluated (the frozen-delay precompute; see
        :meth:`frozen_delay_split` for when substitution is sound)."""
        total = jnp.zeros(batch.ticks.shape, dtype=jnp.float64)
        for c in self.model.delay_components:
            name = type(c).__name__
            if frozen is not None and name in frozen:
                total = total + frozen[name]
                continue
            ctx = ctx_map[name]
            d = c.delay(values, batch, ctx, total)
            if "__gate__" in ctx:
                d = d * ctx["__gate__"]
            total = total + d
        return total

    def _phase_sum_given_delay(self, values, batch, ctx_map, delay):
        """The phase-component fold at an explicit total delay — split
        out of :meth:`_phase_sum` so the hybrid design matrix can take
        one ``jvp`` through the phase stage alone (the pointwise
        d phase/d delay multiplier every delay-linear column shares)."""
        n = jnp.zeros(batch.ticks.shape, dtype=jnp.int64)
        frac = jnp.zeros(batch.ticks.shape, dtype=jnp.float64)
        for c in self.model.phase_components:
            ctx = ctx_map[type(c).__name__]
            ph = c.phase(values, batch, ctx, delay)
            gate = ctx.get("__gate__")
            if isinstance(ph, tuple):
                if gate is not None:
                    # int part cannot be float-gated; superset-added
                    # phase components contribute (0, 0) when inert
                    n = n + jnp.where(gate > 0, ph[0], 0)
                    frac = frac + ph[1] * gate
                else:
                    n = n + ph[0]
                    frac = frac + ph[1]
            else:
                frac = frac + (ph if gate is None else ph * gate)
        return n, frac

    def _phase_sum(self, values, batch, ctx_map, frozen=None):
        delay = self._delay_raw(values, batch, ctx_map, frozen=frozen)
        return self._phase_sum_given_delay(values, batch, ctx_map,
                                           delay)

    def _phase_raw_at(self, values, batch, ctx, tzr_batch, tzr_ctx,
                      frozen=None, tzr_frozen=None):
        """TZR-referenced (n, frac) with the dataset passed explicitly —
        the pure-function form the compile-cache shared traces use
        (batch/ctx arrive as jit arguments, not closure constants).
        frozen/tzr_frozen: optional precomputed-delay dicts riding the
        fit-data pytree (see _delay_raw)."""
        n, frac = self._phase_sum(values, batch, ctx, frozen=frozen)
        if tzr_batch is not None:
            tn, tfrac = self._phase_sum(values, tzr_batch, tzr_ctx,
                                        frozen=tzr_frozen)
            n = n - tn[0]
            frac = frac - tfrac[0]
        return fp.renorm_phase(n, frac)

    def _phase_raw(self, values):
        return self._phase_raw_at(values, self.batch, self.ctx,
                                  self.tzr_batch, self.tzr_ctx)

    # -- hybrid design matrix / frozen-delay partition -------------------------
    def frozen_delay_split(self, free_names):
        """Names of delay components whose delay arrays are constants of
        the fit given this free set: the component owns no free
        parameter, READS no free foreign parameter
        (``Component.reads_params`` — SolarSystemShapiro recomputes the
        pulsar direction from RAJ/DECJ inside ``delay()``, so freezing
        it against free astrometry would serve a stale direction AND
        drop d(Shapiro)/d(position) from the Jacobian), and either
        ignores the accumulated delay or sits in the all-frozen chain
        prefix (an accum-reader behind an active component varies
        through the chain even with its own parameters frozen, and must
        stay in the trace)."""
        free = set(free_names)
        frozen = []
        seen_active = False
        for c in self.model.delay_components:
            active = any(p.name in free for p in c.params) or any(
                n in free for n in getattr(c, "reads_params", ()))
            reads = getattr(c, "reads_delay_accum", False)
            if not active and (not reads or not seen_active):
                frozen.append(type(c).__name__)
            else:
                seen_active = True
        return tuple(frozen)

    def frozen_delay_leaves(self, frozen_names, values=None):
        """Precompute the frozen components' delay arrays host-side
        (eagerly, OUTSIDE any trace).  Returns ``(data_dict,
        tzr_dict_or_None)`` of concrete (N,)/(1,) arrays — dynamic
        leaves of the fit-data pytree, so a same-structure fitter still
        shares the trace and editing a frozen parameter between fits
        costs a cheap host re-fold, never a recompile.

        The running accumulator covers frozen components only: a frozen
        accum-reader is, by :meth:`frozen_delay_split`, preceded
        exclusively by frozen components, so the partial sum it sees
        here equals the full chain accum; non-readers ignore it."""
        if not frozen_names:
            return None, None
        want = set(frozen_names)
        v = self._values_pytree(values)

        def fold(batch, ctx_map):
            out = {}
            total = jnp.zeros(batch.ticks.shape, dtype=jnp.float64)
            for c in self.model.delay_components:
                name = type(c).__name__
                if name not in want:
                    continue
                ctx = ctx_map[name]
                d = c.delay(v, batch, ctx, total)
                if "__gate__" in ctx:
                    d = d * ctx["__gate__"]
                out[name] = jnp.asarray(np.asarray(d))
                total = total + d
            return out

        data = fold(self.batch, self.ctx)
        tzr = (fold(self.tzr_batch, self.tzr_ctx)
               if self.tzr_batch is not None else None)
        return data, tzr

    def frozen_param_values(self, frozen_names):
        """{param: value} over the frozen components — the fingerprint
        fit_toas compares so an edit to a frozen parameter refreshes
        the precomputed leaves instead of serving stale delays.  Covers
        the components' OWN params and their declared foreign reads
        (``reads_params``): an edit to a fixed RAJ between fits must
        re-fold the frozen Shapiro delay too."""
        out = {}
        for c in self.model.delay_components:
            if type(c).__name__ in frozen_names:
                names = [p.name for p in c.params]
                names += [n for n in getattr(c, "reads_params", ())
                          if n in self.model.values]
                for name in names:
                    out[name] = float(self.model.values.get(name,
                                                            np.nan))
        return out

    def kepler_ecc_reach(self, values=None):
        """Largest |eccentricity| the binary delay chain can see at
        ``values``: max over Kepler-solving binaries of |ECC| + |EDOT|
        times the dataset half-span (the same reach binary/base.prepare
        classifies).  NaN when a binary's ECC is unset; -inf when no
        Kepler binary is present."""
        v = self.model.values if values is None else values
        reach = float("-inf")
        for c in self.model.delay_components:
            f = getattr(c, "ecc_reach", None)
            if f is not None:
                reach = max(reach, f(v, self.batch))
        return reach

    def ensure_kepler_depth(self, ecc_max):
        """Monotonically raise every binary ctx's static Kepler Newton
        depth to cover eccentricities up to ``ecc_max`` (NaN -> the
        full e < 0.97 unroll).  The depth is a STATIC ctx int chosen
        from the prepare-time eccentricity class (binary/base.prepare);
        a fit or grid that can move ECC/EDOT beyond that class must
        call this first or the fixed-iteration solver silently
        under-converges (e = 0.9 at the 4-deep unroll leaves ~1e-5 rad
        in the eccentric anomaly).  Returns True when any ctx changed —
        callers holding a split static ctx (Residuals) must re-split
        and re-key their traces."""
        from pint_tpu.models.binary.kepler import newton_iters_for

        need = newton_iters_for(ecc_max)
        changed = False
        for ctx_map in (self.ctx, self.tzr_ctx):
            if not ctx_map:
                continue
            for sub in ctx_map.values():
                if (isinstance(sub, dict)
                        and sub.get("kepler_iters", need) < need):
                    sub["kepler_iters"] = need
                    changed = True
        return changed

    def design_partition(self, free_names, frozen=(), wideband=False):
        """Split free timing parameters into ``(linear, nonlinear)``
        tuples (free order preserved) — PINT's ``d_phase_d_param``
        split.  ``linear`` columns are built analytically in the trace
        (:meth:`linear_phase_columns`); ``jacfwd`` runs only over the
        nonlinear remainder.

        A parameter is linear iff EVERY component owning it lists it in
        ``linear_params()`` and, for delay components, no accum-reading
        delay component that is still in the trace (not in ``frozen``)
        follows it in the chain — a later binary/WaveX would feed the
        column back through the chain at far above the 1e-12
        hybrid==jacfwd pin.  ``wideband`` additionally requires any
        owner exposing ``dm_value`` to implement ``d_dm_d_param`` (the
        stacked fitters differentiate the DM block too)."""
        from pint_tpu.models.component import DelayComponent

        frozen = set(frozen)
        delay_comps = self.model.delay_components
        # unsafe_after[i]: an in-trace accum-reader strictly after i
        unsafe_after = [False] * len(delay_comps)
        flag = False
        for i in range(len(delay_comps) - 1, -1, -1):
            unsafe_after[i] = flag
            c = delay_comps[i]
            if (getattr(c, "reads_delay_accum", False)
                    and type(c).__name__ not in frozen):
                flag = True
        delay_pos = {id(c): i for i, c in enumerate(delay_comps)}

        # a free parameter READ (not owned) by an in-trace component
        # (Component.reads_params) gets contributions the owners'
        # closed-form columns cannot see — leave it to jacfwd.  A
        # frozen reader cannot read a free parameter at all
        # (frozen_delay_split), so only in-trace readers block.
        read_elsewhere = set()
        for c in self.model.components:
            if type(c).__name__ not in frozen:
                read_elsewhere.update(getattr(c, "reads_params", ()))

        linear, nonlinear = [], []
        for name in free_names:
            owners = [c for c in self.model.components
                      if c.has_param(name)]
            ok = bool(owners) and name not in read_elsewhere
            for c in owners:
                if name not in set(c.linear_params()):
                    ok = False
                    break
                if isinstance(c, DelayComponent):
                    if unsafe_after[delay_pos[id(c)]]:
                        ok = False
                        break
                    if wideband and getattr(c, "dm_value", None) \
                            is not None and getattr(
                                c, "d_dm_d_param", None) is None:
                        ok = False
                        break
            (linear if ok else nonlinear).append(name)
        return tuple(linear), tuple(nonlinear)

    def linear_phase_columns(self, values, batch, ctx_map, names,
                             frozen=None):
        """(N, L) matrix of d phase / d name [turns per unit] for the
        phase-linear parameters ``names``, inside the trace but WITHOUT
        any tangent pass through the delay chain: one delay fold
        collects each delay-owner's closed-form d delay/d param at its
        chain position, one ``jvp`` through the phase stage alone gives
        the shared pointwise d phase/d delay multiplier, and
        phase-owners contribute their columns directly."""
        import jax

        n_toa = batch.ticks.shape[0]
        want = list(names)
        delay_cols = {}
        phase_cols = {}

        def add(store, nm, col):
            prev = store.get(nm)
            store[nm] = col if prev is None else prev + col

        delay = jnp.zeros(n_toa, dtype=jnp.float64)
        for c in self.model.delay_components:
            cname = type(c).__name__
            ctx = ctx_map[cname]
            gate = ctx.get("__gate__")
            for nm in want:
                if c.has_param(nm):
                    col = c.d_delay_d_param(values, batch, ctx, delay,
                                            nm)
                    if gate is not None:
                        col = col * gate
                    add(delay_cols, nm, col)
            if frozen is not None and cname in frozen:
                d = frozen[cname]
            else:
                d = c.delay(values, batch, ctx, delay)
                if gate is not None:
                    d = d * gate
            delay = delay + d

        if delay_cols:
            def frac_of(dly):
                _, frac = self._phase_sum_given_delay(
                    values, batch, ctx_map, dly)
                return frac

            _, dphase_ddelay = jax.jvp(
                frac_of, (delay,), (jnp.ones_like(delay),))

        for c in self.model.phase_components:
            ctx = ctx_map[type(c).__name__]
            gate = ctx.get("__gate__")
            for nm in want:
                if c.has_param(nm):
                    col = c.d_phase_d_param(values, batch, ctx, delay,
                                            nm)
                    if gate is not None:
                        col = col * gate
                    add(phase_cols, nm, col)

        cols = []
        for nm in want:
            col = phase_cols.get(nm)
            dcol = delay_cols.get(nm)
            if dcol is not None:
                dcol = dphase_ddelay * dcol
                col = dcol if col is None else col + dcol
            if col is None:
                col = jnp.zeros(n_toa, dtype=jnp.float64)
            cols.append(col)
        return jnp.stack(cols, axis=1)

    def linear_dm_columns(self, values, batch, ctx_map, names):
        """(N, L) matrix of d DM / d name [pc cm^-3 per unit] — the
        wideband DM-block counterpart of linear_phase_columns.
        Components without a dm_value contribute zero columns."""
        n_toa = batch.ticks.shape[0]
        cols = []
        for nm in names:
            col = None
            for c in self.model.components:
                if c.has_param(nm) and getattr(c, "dm_value", None) \
                        is not None:
                    ctx = ctx_map[type(c).__name__]
                    d = c.d_dm_d_param(values, batch, ctx, nm)
                    gate = ctx.get("__gate__")
                    if gate is not None:
                        d = d * gate
                    col = d if col is None else col + d
            if col is None:
                col = jnp.zeros(n_toa, dtype=jnp.float64)
            cols.append(col)
        return jnp.stack(cols, axis=1)

    # -- public API ----------------------------------------------------------
    def delay(self, values=None):
        """Total delay [s] at the model's TOAs."""
        v = self._values_pytree(values)
        return self._delay_raw(v, self.batch, self.ctx)

    def phase(self, values=None):
        """(int64 turns, f64 frac) at the model's TOAs, TZR-referenced."""
        return self._phase_jit(self._values_pytree(values))

    def _values_pytree(self, values=None):
        v = dict(self.model.values) if values is None else dict(values)
        return {k: jnp.float64(x) for k, x in v.items()}

    # free-parameter vector interface (for fitters/grids)
    def values_to_vector(self, values=None) -> jnp.ndarray:
        v = self.model.values if values is None else values
        return jnp.array(
            [v[name] for name in self.model.free_params], dtype=jnp.float64
        )

    def vector_to_values(self, vec, base=None):
        out = dict(self.model.values if base is None else base)
        for i, name in enumerate(self.model.free_params):
            out[name] = vec[i]
        return out

    def frac_phase_fn(self):
        """values_vector -> frac turns (f64), for jacfwd design matrices."""

        def fn(vec):
            values = self.vector_to_values_traced(vec)
            _, frac = self._phase_raw(values)
            return frac

        return fn

    def vector_to_values_traced(self, vec):
        out = {k: jnp.float64(v) for k, v in self.model.values.items()}
        for i, name in enumerate(self.model.free_params):
            out[name] = vec[i]
        return out
