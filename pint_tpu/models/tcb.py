"""TCB <-> TDB conversion of par files.

Counterpart of the reference tcb_conversion module (reference:
src/pint/models/tcb_conversion.py:29 ``scale_parameter``, :70
``transform_mjd_parameter``, :98 ``convert_tcb_tdb``; constants from
Irwin & Fukushima 1999, the same as tempo2's transform plugin):

    x_tdb = x_tcb * K**(-d)            d = effective dimensionality
    t_tdb = (t_tcb - MJD0) / K + MJD0  for epochs
    K     = 1 + 1.55051979176e-8

Unlike the reference (which converts a built TimingModel), conversion
here happens at the par-text level before model construction — the
functional core only ever sees TDB quantities, so there is no
allow_tcb half-state to thread through components.  The conversion is
approximate (same caveat as the reference: re-fit afterwards).
"""

from __future__ import annotations

import re
from decimal import Decimal, getcontext
from typing import Optional

__all__ = ["IFTE_K", "IFTE_MJD0", "convert_parfile_tcb_tdb"]

getcontext().prec = 40

IFTE_MJD0 = Decimal("43144.0003725")
IFTE_KM1 = Decimal("1.55051979176e-8")
IFTE_K = 1 + IFTE_KM1

#: effective dimensionality d of each parameter: x_tdb = x_tcb * K^-d
#: (reference: each Parameter's effective_dimensionality; examples in
#: tcb_conversion.py:33-45 — F0: 1, F1: 2, A1: -1, DM: 1, PBDOT: 0).
#: Indexed families use a callable of the index.
_DIMS = {
    # spindown
    "F": lambda k: k + 1,
    # astrometry: angles 0, proper motions 1/time, parallax 1/distance
    "RAJ": 0, "DECJ": 0, "ELONG": 0, "ELAT": 0,
    "PMRA": 1, "PMDEC": 1, "PMELONG": 1, "PMELAT": 1, "PX": 1,
    # dispersion / chromatic
    "DM": lambda k: k + 1,
    "DMX": 1, "DMX_": 1, "DMJUMP": 1, "FDJUMPDM": 1,
    "NE_SW": 1, "SWXDM_": 1,
    "CM": lambda k: k + 1,
    # binaries: times -1, dimensionless 0, rates +... PBDOT/XDOT are
    # dimensionless; OMDOT deg/yr is 1; masses (time units via Tsun) -1
    "PB": -1, "A1": -1, "T0": "mjd", "TASC": "mjd",
    "ECC": 0, "OM": 0, "OMDOT": 1, "PBDOT": 0, "XDOT": 0, "EDOT": 1,
    "EPS1": 0, "EPS2": 0, "EPS1DOT": 1, "EPS2DOT": 1,
    "M2": -1, "MTOT": -1, "SINI": 0, "SHAPMAX": 0,
    "H3": -1, "H4": -1, "STIGMA": 0, "KIN": 0, "KOM": 0,
    "GAMMA": -1, "DR": 0, "DTH": 0, "A0": -1, "B0": -1,
    "FB": lambda k: k + 1,
    # glitches
    "GLF0_": 1, "GLF1_": 2, "GLF2_": 3, "GLF0D_": 1, "GLTD_": -1,
    "GLPH_": 0,
    # jumps & misc (seconds)
    "JUMP": -1, "WAVE_OM": 1,
}

#: parameters that are epochs (MJD transform); kind detection also
#: catches *_EPOCH-style names
_MJD_PARAMS = {
    "PEPOCH", "POSEPOCH", "DMEPOCH", "CMEPOCH", "T0", "TASC", "TZRMJD",
    "WAVEEPOCH", "START", "FINISH", "WXEPOCH", "DMWXEPOCH", "CMWXEPOCH",
    "SWXR1_", "SWXR2_", "DMXR1_", "DMXR2_", "GLEP_", "PWEP_", "PWSTART_",
    "PWSTOP_",
}

_NUM_RE = re.compile(r"^[+-]?(\d+\.?\d*|\.\d+)([eEdD][+-]?\d+)?$")


def _dim_of(key: str) -> Optional[object]:
    from pint_tpu.models.parameter import prefix_index

    if key in _DIMS:
        d = _DIMS[key]
        return d(0) if callable(d) else d
    pi = prefix_index(key)
    if pi and pi[0] in _DIMS:
        d = _DIMS[pi[0]]
        return d(pi[1]) if callable(d) else d
    return None


def _is_mjd(key: str) -> bool:
    if key in _MJD_PARAMS:
        return True
    m = re.match(r"^([A-Z0-9]+_)\d+$", key)
    return bool(m and m.group(1) in _MJD_PARAMS)


def _scale_str(tok: str, factor: Decimal) -> str:
    v = Decimal(tok.upper().replace("D", "E"))
    out = v * factor
    return f"{out:.20E}"


def _mjd_str(tok: str, backwards: bool) -> str:
    t = Decimal(tok.upper().replace("D", "E"))
    if backwards:
        out = (t - IFTE_MJD0) * IFTE_K + IFTE_MJD0
    else:
        out = (t - IFTE_MJD0) / IFTE_K + IFTE_MJD0
    return f"{out:.25f}".rstrip("0").rstrip(".")


def convert_parfile_tcb_tdb(text: str, backwards: bool = False) -> str:
    """Convert par-file text between TCB and TDB units.

    Mirrors the reference's parameter coverage (tcb_conversion.py:105:
    TZRMJD/TZRFRQ, EQUADs/ECORRs, red-noise amplitudes, Wave/IFunc pairs
    and FD parameters are NOT converted — same as the reference — except
    TZRMJD which we do transform since it is a plain epoch).
    """
    out_lines = []
    units_seen = False
    for raw in text.splitlines():
        stripped = raw.split("#")[0].rstrip()
        if not stripped.strip():
            out_lines.append(raw)
            continue
        toks = stripped.split()
        key = toks[0].upper()
        if key == "UNITS":
            out_lines.append(f"UNITS {'TCB' if backwards else 'TDB'}")
            units_seen = True
            continue
        d = _dim_of(key)
        try:
            if _is_mjd(key) and len(toks) > 1 and _NUM_RE.match(toks[1]):
                toks[1] = _mjd_str(toks[1], backwards)
                out_lines.append(" ".join(toks))
                continue
            if d not in (None, "mjd") and d != 0:
                p = 1 if backwards else -1
                factor = IFTE_K ** (p * int(d))
                # mask params: value sits after the selector tokens
                vi = 1
                if key in ("JUMP", "DMJUMP", "FDJUMPDM"):
                    if toks[1].startswith("-"):
                        vi = 3
                    elif toks[1].upper() in ("MJD", "FREQ"):
                        vi = 4
                    elif toks[1].upper() in ("TEL", "T"):
                        vi = 3
                if len(toks) > vi and _NUM_RE.match(toks[vi]):
                    toks[vi] = _scale_str(toks[vi], factor)
                    # uncertainty column scales identically
                    if len(toks) > vi + 2 and _NUM_RE.match(toks[vi + 2]):
                        toks[vi + 2] = _scale_str(toks[vi + 2], factor)
                out_lines.append(" ".join(toks))
                continue
        except Exception:
            pass
        out_lines.append(raw)
    if not units_seen:
        out_lines.append(f"UNITS {'TCB' if backwards else 'TDB'}")
    return "\n".join(out_lines) + "\n"
