"""Damour-Deruelle binary family: DD, DDS, DDH, DDGR, DDK.

Physics: Damour & Deruelle (1986) timing formula — Roemer + Einstein
delays through the second-order inverse timing expansion (their Eq.
46-52), Shapiro delay (Eq. 26), aberration (Eq. 27); GR-constrained
variant per Taylor & Weisberg (1989) Eq. 15-25; Kopeikin (1995, 1996)
annual-orbital-parallax and proper-motion corrections for DDK.
Reference counterparts: stand_alone_psr_binaries/DD_model.py,
DDS_model.py, DDH_model.py, DDGR_model.py, DDK_model.py wrapped by
binary_dd.py / binary_ddk.py.

The family shares one jax delay kernel; subclasses override
``dd_quantities`` (a1, omega, sini, tm2, gamma, dr, dth) — the analogue
of the reference's property overrides, resolved statically at trace
time so the jitted program contains only the selected variant.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import T_SUN_S
from pint_tpu.models.binary.base import BinaryComponent
from pint_tpu.models.binary.bt import KeplerianMixin
from pint_tpu.models.binary.kepler import true_anomaly
from pint_tpu.models.parameter import Param

_KPC_LS = 3.0856775814913673e19 / 299792458.0  #: kiloparsec in light-s
_MAS = np.deg2rad(1.0 / 3.6e6)  #: milliarcsecond in radians


class BinaryDD(KeplerianMixin, BinaryComponent):
    binary_name = "DD"
    epoch_param = "T0"

    def build_params(self, pardict):
        self.add_keplerian_params(pardict)
        self.add_shapiro_params()
        self.add_param(Param("DR", description="Relativistic e_r deformation"))
        self.add_param(Param("DTH", aliases=("DTHETA",),
                             description="Relativistic e_theta deformation"))
        self.add_param(Param("A0", units="s",
                             description="Aberration parameter A0"))
        self.add_param(Param("B0", units="s",
                             description="Aberration parameter B0"))

    def defaults(self):
        d = self.keplerian_defaults()
        d.update(M2=0.0, SINI=0.0, DR=0.0, DTH=0.0, A0=0.0, B0=0.0)
        return d

    # -- overridable PK quantity block ---------------------------------------
    def dd_quantities(self, values, dt, ctx, nu, forb):
        """(a1, omega, sini, tm2, gamma, dr, dth) for the delay kernel."""
        k = values["OMDOT"] / (2.0 * jnp.pi * forb)
        return dict(
            a1=values["A1"] + dt * values["XDOT"],
            omega=values["OM"] + k * nu,
            sini=values["SINI"],
            tm2=T_SUN_S * values["M2"],
            gamma=values["GAMMA"],
            dr=values["DR"],
            dth=values["DTH"],
        )

    def binary_delay(self, values, dt, ctx):
        E, ecc, forb = self.eccentric_anomaly(values, dt, ctx)
        sE, cE = jnp.sin(E), jnp.cos(E)
        nu = true_anomaly(E, ecc)
        q = self.dd_quantities(values, dt, ctx, nu, forb)
        a1, omega, gamma = q["a1"], q["omega"], q["gamma"]
        er = ecc * (1.0 + q["dr"])
        eth = ecc * (1.0 + q["dth"])
        sw, cw = jnp.sin(omega), jnp.cos(omega)
        alpha = a1 * sw
        beta = a1 * jnp.sqrt(1.0 - eth * eth) * cw
        # Dre = Roemer (Eq. 48) + Einstein (Eq. 25); phase derivatives
        # wrt eccentric anomaly for the inverse formula (Eq. 49-50)
        dre = alpha * (cE - er) + (beta + gamma) * sE
        drep = -alpha * sE + (beta + gamma) * cE
        drepp = -alpha * cE - (beta + gamma) * sE
        one_m_ecosE = 1.0 - ecc * cE
        nhat = 2.0 * jnp.pi * forb / one_m_ecosE
        nd = nhat * drep
        # inverse timing formula, Eq. 46-52 second order
        inv = dre * (
            1.0 - nd + nd * nd
            + 0.5 * nhat * nhat * dre * drepp
            - 0.5 * ecc * sE / one_m_ecosE * nhat * nhat * dre * drep
        )
        # Shapiro (Eq. 26)
        root = jnp.sqrt(1.0 - ecc * ecc)
        bracket = one_m_ecosE - q["sini"] * (sw * (cE - ecc) + root * cw * sE)
        shap = -2.0 * q["tm2"] * jnp.log(bracket)
        # aberration (Eq. 27)
        ab = values["A0"] * (jnp.sin(omega + nu) + ecc * sw) \
            + values["B0"] * (jnp.cos(omega + nu) + ecc * cw)
        return inv + shap + ab


class BinaryDDS(BinaryDD):
    """DD with SHAPMAX = -ln(1 - sin i) inclination parameterization
    (Kramer et al. 2006; reference: DDS_model.py)."""

    binary_name = "DDS"

    def build_params(self, pardict):
        super().build_params(pardict)
        self.params = [p for p in self.params if p.name != "SINI"]
        self.add_param(Param("SHAPMAX", description="-ln(1 - sin i)"))

    def defaults(self):
        d = super().defaults()
        d.pop("SINI", None)
        d["SHAPMAX"] = 0.0
        return d

    def dd_quantities(self, values, dt, ctx, nu, forb):
        q = BinaryDD.dd_quantities(
            self, dict(values, SINI=0.0), dt, ctx, nu, forb)
        q["sini"] = 1.0 - jnp.exp(-values["SHAPMAX"])
        return q


class BinaryDDH(BinaryDD):
    """DD with orthometric Shapiro parameters H3/STIGMA (Freire & Wex
    2010; reference: DDH_model.py): sini = 2 stigma/(1+stigma^2),
    T_Sun M2 = H3 / stigma^3."""

    binary_name = "DDH"

    def build_params(self, pardict):
        super().build_params(pardict)
        self.params = [p for p in self.params
                       if p.name not in ("SINI", "M2")]
        self.add_param(Param("H3", units="s",
                             description="Orthometric Shapiro amplitude"))
        self.add_param(Param("STIGMA", aliases=("VARSIGMA",),
                             description="Orthometric ratio"))

    def defaults(self):
        d = super().defaults()
        d.pop("SINI", None)
        d.pop("M2", None)
        d.update(H3=0.0, STIGMA=0.0)
        return d

    def dd_quantities(self, values, dt, ctx, nu, forb):
        q = BinaryDD.dd_quantities(
            self, dict(values, SINI=0.0, M2=0.0), dt, ctx, nu, forb)
        sig = values["STIGMA"]
        safe = jnp.where(sig == 0.0, 1.0, sig)
        q["sini"] = 2.0 * sig / (1.0 + sig * sig)
        q["tm2"] = jnp.where(sig == 0.0, 0.0, values["H3"] / safe**3)
        return q


class BinaryDDGR(BinaryDD):
    """GR-constrained DD: all post-Keplerian quantities derived from
    (MTOT, M2) per Taylor & Weisberg (1989) Eq. 15-25 (reference:
    DDGR_model.py _updatePK).  Masses in geometrized seconds via T_sun;
    the relativistic Kepler law is a fixed-point iteration."""

    binary_name = "DDGR"

    def build_params(self, pardict):
        super().build_params(pardict)
        if self.fb_terms is not None:
            raise NotImplementedError(
                "DDGR requires the PB parameterization (the relativistic "
                "Kepler law TW89 Eq. 15 is defined through PB); FB0... "
                "given")
        drop = ("SINI", "M2", "GAMMA", "OMDOT", "DR", "DTH")
        self.params = [p for p in self.params if p.name not in drop]
        self.add_param(Param("MTOT", units="Msun", description="Total mass"))
        self.add_param(Param("M2", units="Msun", description="Companion mass"))
        from pint_tpu.models.binary.base import DEG_PER_YEAR

        self.add_param(Param("XOMDOT", units="rad/s", scale=DEG_PER_YEAR,
                             description="Excess OMDOT vs GR (deg/yr)"))
        # XPBDOT already present via orbit params when PB-parameterized

    def defaults(self):
        d = super().defaults()
        for k in ("SINI", "GAMMA", "OMDOT", "DR", "DTH"):
            d.pop(k, None)
        d.update(MTOT=0.0, M2=0.0, XOMDOT=0.0)
        return d

    def _pk(self, values, dt):
        """GR PK quantities from (MTOT, M2, PB, ECC, A1)."""
        mt = T_SUN_S * values["MTOT"]
        m2 = T_SUN_S * values["M2"]
        m1 = mt - m2
        n = 2.0 * jnp.pi / values["PB"]
        ecc = values["ECC"] + dt * values["EDOT"]
        # relativistic Kepler (TW89 Eq. 15), fixed-point iterations
        arr0 = (mt / n**2) ** (1.0 / 3.0)
        arr = arr0
        for _ in range(8):
            arr = arr0 * (
                1.0 + (m1 * m2 / mt**2 - 9.0) * (mt / (2.0 * arr))
            ) ** (2.0 / 3.0)
        ar = arr * (m2 / mt)
        a1 = values["A1"] + dt * values["XDOT"]
        fe = (1.0 + (73.0 / 24.0) * ecc**2 + (37.0 / 96.0) * ecc**4) \
            * (1.0 - ecc**2) ** (-3.5)
        return dict(
            sini=a1 / ar,  # TW89 Eq. 20
            gamma=ecc * m2 * (m1 + 2.0 * m2) / (n * arr0 * mt),  # Eq. 17
            pbdot=(-192.0 * jnp.pi / 5.0) * n ** (5.0 / 3.0)
            * m1 * m2 * mt ** (-1.0 / 3.0) * fe,  # Eq. 18
            k=3.0 * mt / (arr0 * (1.0 - ecc**2)),  # Eq. 16
            dr=(3.0 * m1**2 + 6.0 * m1 * m2 + 2.0 * m2**2) / (mt * arr),
            dth=(3.5 * m1**2 + 6.0 * m1 * m2 + 2.0 * m2**2) / (mt * arr),
            n=n,
        )

    def orbits_and_freq(self, values, dt):
        if self.fb_terms is None:
            pk = self._pk(values, dt)
            values = dict(values, PBDOT=values["XPBDOT"] + pk["pbdot"],
                          XPBDOT=0.0)
        return BinaryComponent.orbits_and_freq(self, values, dt)

    def dd_quantities(self, values, dt, ctx, nu, forb):
        pk = self._pk(values, dt)
        return dict(
            a1=values["A1"] + dt * values["XDOT"],
            omega=values["OM"] + nu * (pk["k"] + values["XOMDOT"] / pk["n"]),
            sini=pk["sini"],
            tm2=T_SUN_S * values["M2"],
            gamma=pk["gamma"],
            dr=pk["dr"],
            dth=pk["dth"],
        )


class BinaryDDK(BinaryDD):
    """DD with Kopeikin (1995, 1996) corrections: secular (proper
    motion) and annual (parallax) variation of the apparent inclination,
    projected semi-major axis and periastron longitude.  KIN/KOM in the
    DT92 convention; KOM measured in the frame of the astrometry
    component (reference: DDK_model.py, binary_ddk.py:44)."""

    binary_name = "DDK"

    #: dd_quantities reads the astrometry component's parallax and
    #: proper motion in-trace (Kopeikin secular/annual terms) — free
    #: astrometry must keep this component out of the frozen set and
    #: its analytic columns honest (reads_params contract)
    reads_params = ("PX", "PMRA", "PMDEC", "PMELONG", "PMELAT")

    #: values forced when this component is added as an INERT member of
    #: a heterogeneous-PTA superset (parallel.pta): the gate zeroes its
    #: delay, but KIN=0 would put NaN (0/tan(0), 1/sin(0)) into the
    #: traced graph, and gate * NaN = NaN
    neutral_overrides = {"KIN": 1.0}

    def build_params(self, pardict):
        super().build_params(pardict)
        self.params = [p for p in self.params if p.name != "SINI"]
        self.add_param(Param("KIN", kind="angle",
                             description="Inclination angle (DT92)"))
        self.add_param(Param("KOM", kind="angle",
                             description="Long. of ascending node (DT92)"))
        self.add_param(Param("K96", kind="bool", fittable=False,
                             description="Apply proper-motion (K96) terms"))
        self.k96 = parse_k96(pardict)
        self.ecliptic = "ELONG" in pardict

    def defaults(self):
        d = super().defaults()
        d.pop("SINI", None)
        d.update(KIN=0.0, KOM=0.0, K96=1.0)
        return d

    def prepare(self, toas, model):
        ctx = super().prepare(toas, model)
        # observatory SSB position [ls] and pulsar unit vector, in the
        # astrometry frame (Kopeikin 1995 Eq. 15-16 geometry)
        obs = np.asarray(toas.ssb_obs_pos, dtype=np.float64)
        # the astrometry frame is the HOST model's ACTIVE astrometry
        # component — not the par this instance was built from (as a
        # superset donor this component is copied onto pulsars in
        # either frame, and a superset can hold both astrometry
        # classes, one inert; parallel.pta)
        inert = getattr(model, "_superset_inert", ()) or ()
        astrom = None
        for c in model.components:
            if c.category == "astrometry" and (
                    astrom is None or type(c).__name__ not in inert):
                astrom = c
        if astrom is None:
            raise ValueError("DDK requires an astrometry component")
        self.ecliptic = "Ecliptic" in type(astrom).__name__
        if self.ecliptic:
            # ICRS -> ecliptic with the model's ECL obliquity selection
            obs = obs @ np.asarray(astrom.eq_from_ecl)
            lon = model.values["ELONG"]
            lat = model.values["ELAT"]
            self._pm_names = ("PMELONG", "PMELAT")
        else:
            lon = model.values["RAJ"]
            lat = model.values["DECJ"]
            self._pm_names = ("PMRA", "PMDEC")
        # Kopeikin 1995 Eq. 15-16
        sl, cl = np.sin(lon), np.cos(lon)
        sb, cb = np.sin(lat), np.cos(lat)
        ctx["delta_I0"] = jnp.asarray(-obs[:, 0] * sl + obs[:, 1] * cl)
        ctx["delta_J0"] = jnp.asarray(
            -obs[:, 0] * sb * cl - obs[:, 1] * sb * sl + obs[:, 2] * cb
        )
        return ctx

    def dd_quantities(self, values, dt, ctx, nu, forb):
        from pint_tpu import SECS_PER_JULIAN_YEAR

        q = BinaryDD.dd_quantities(
            self, dict(values, SINI=0.0), dt, ctx, nu, forb)
        sin_kom, cos_kom = jnp.sin(values["KOM"]), jnp.cos(values["KOM"])
        masyr = _MAS / SECS_PER_JULIAN_YEAR
        pm_long = values[self._pm_names[0]] * masyr
        pm_lat = values[self._pm_names[1]] * masyr
        a1 = q["a1"]
        omega = q["omega"]
        kin = values["KIN"]
        if self.k96:
            # Kopeikin 1996 Eq. 10, 8, 9
            d_kin = (-pm_long * sin_kom + pm_lat * cos_kom) * dt
            kin = kin + d_kin
            a1 = a1 + a1 * d_kin / jnp.tan(kin)
            omega = omega + (pm_long * cos_kom + pm_lat * sin_kom) * dt \
                / jnp.sin(kin)
        # Kopeikin 1995 Eq. 18, 19 (annual orbital parallax); PX in mas
        # => 1/d [1/ls] = PX / _KPC_LS, vanishing smoothly as PX -> 0
        inv_d_ls = values["PX"] / _KPC_LS
        geo_x = ctx["delta_I0"] * sin_kom - ctx["delta_J0"] * cos_kom
        geo_w = ctx["delta_I0"] * cos_kom + ctx["delta_J0"] * sin_kom
        a1 = a1 + a1 / jnp.tan(kin) * inv_d_ls * geo_x
        omega = omega - inv_d_ls / jnp.sin(kin) * geo_w
        q.update(a1=a1, omega=omega, sini=jnp.sin(kin))
        return q


def parse_k96(pardict) -> bool:
    tok = pardict.get("K96", [["1"]])[0]
    return str(tok[0] if tok else "1").upper() in ("1", "Y", "T", "TRUE")
