"""Binary (pulsar_system) delay components.

Counterpart of the reference's two-layer binary design (PINT-facing
``PulsarBinary`` wrapper, reference: src/pint/models/pulsar_binary.py:40,
over unitless ``stand_alone_psr_binaries`` engines).  TPU redesign: one
layer — each binary family is a :class:`BinaryComponent` whose
``delay(values, batch, ctx, accum)`` is a pure jax function; all
parameter derivatives come from autodiff of that function (the
reference's ``d_binarydelay_d_xxxx`` chain-rule registry has no
equivalent here by construction).

Families land in submodules: ``ell1`` (ELL1/ELL1H/ELL1k), ``bt`` (BT),
``dd`` (DD/DDS/DDH/DDK/DDGR).
"""

from pint_tpu.models.binary.base import BinaryComponent, get_binary_class
from pint_tpu.models.binary.ell1 import BinaryELL1, BinaryELL1H, BinaryELL1k  # noqa: F401
from pint_tpu.models.binary.bt import BinaryBT, BinaryBTPiecewise  # noqa: F401
from pint_tpu.models.binary.dd import (  # noqa: F401
    BinaryDD,
    BinaryDDGR,
    BinaryDDH,
    BinaryDDK,
    BinaryDDS,
)

__all__ = ["BinaryComponent", "get_binary_class"]
