"""Kepler-equation solver: fixed-iteration Newton with implicit autodiff.

Counterpart of the reference's scipy-based ``compute_eccentric_anomaly``
(reference: stand_alone_psr_binaries/binary_generic.py:337).  TPU
redesign: a fixed Newton iteration count (no data-dependent control
flow, so it jits and vmaps), with the derivative supplied by the
implicit function theorem via ``jax.custom_jvp`` — dE/dM = 1/(1-e cosE),
dE/de = sinE/(1-e cosE) — so autodiff never differentiates through the
iteration loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: Newton iterations.  From E0 = M + e sinM convergence is quadratic;
#: 10 iterations reach float64 roundoff for e <~ 0.97.
_NEWTON_ITERS = 10


@jax.custom_jvp
def kepler_eccentric_anomaly(mean_anom, ecc):
    """Solve E - e sinE = M elementwise.  M may be any real (use the
    reduced branch for best trig accuracy); returns E near M."""
    E = mean_anom + ecc * jnp.sin(mean_anom)
    for _ in range(_NEWTON_ITERS):
        f = E - ecc * jnp.sin(E) - mean_anom
        fp = 1.0 - ecc * jnp.cos(E)
        E = E - f / fp
    return E


@kepler_eccentric_anomaly.defjvp
def _kepler_jvp(primals, tangents):
    mean_anom, ecc = primals
    dm, de = tangents
    E = kepler_eccentric_anomaly(mean_anom, ecc)
    denom = 1.0 - ecc * jnp.cos(E)
    dE = (dm + jnp.sin(E) * de) / denom
    return E, dE


def true_anomaly(E, ecc):
    """True anomaly nu from eccentric anomaly, continuous with E (the
    atan2 half-angle form keeps nu on the same branch as E)."""
    half = 0.5 * E
    nu_half = jnp.arctan2(
        jnp.sqrt(1.0 + ecc) * jnp.sin(half),
        jnp.sqrt(1.0 - ecc) * jnp.cos(half),
    )
    return 2.0 * nu_half
