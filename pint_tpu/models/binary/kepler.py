"""Kepler-equation solver: fixed-iteration Newton with implicit autodiff.

Counterpart of the reference's scipy-based ``compute_eccentric_anomaly``
(reference: stand_alone_psr_binaries/binary_generic.py:337).  TPU
redesign: a fixed Newton iteration count (no data-dependent control
flow, so it jits and vmaps), with the derivative supplied by the
implicit function theorem via ``jax.custom_jvp`` — dE/dM = 1/(1-e cosE),
dE/de = sinE/(1-e cosE) — so autodiff never differentiates through the
iteration loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

#: Newton iterations.  From E0 = M + e sinM convergence is quadratic;
#: 10 iterations reach float64 roundoff for e <~ 0.97.
_NEWTON_ITERS = 10


def newton_iters_for(ecc_max: float) -> int:
    """Newton depth sufficient for eccentricities up to ``ecc_max``,
    with at least two spare quadratic iterations beyond the proven
    bound — the solver's primal is trig-bound (two evals per
    iteration), so a nearly-circular MSP should not pay the e ~ 0.97
    unroll.  Error analysis: from E0 = M + e sinM, err0 <= e^2 and
    err_{k+1} <= err_k^2 * e / (2 (1 - e)); at the class bound each
    depth below lands under 1e-16 two iterations early.  Callers pick
    the class HOST-SIDE from the prepare-time eccentricity (plus EDOT
    drift over the dataset span) and carry it as static ctx, so it
    keys every shared trace; a fit moving ECC within its class keeps
    full f64 convergence by construction."""
    e = float(ecc_max)
    if not (e == e) or e < 0:  # NaN (unset ECC) -> full depth
        return _NEWTON_ITERS
    if e < 0.05:
        return 4
    if e < 0.25:
        return 6
    if e < 0.6:
        return 8
    return _NEWTON_ITERS


@partial(jax.custom_jvp, nondiff_argnums=(2,))
def kepler_eccentric_anomaly(mean_anom, ecc, iters=_NEWTON_ITERS):
    """Solve E - e sinE = M elementwise.  M may be any real (use the
    reduced branch for best trig accuracy); returns E near M.  iters
    is a static unroll depth (see :func:`newton_iters_for`)."""
    E = mean_anom + ecc * jnp.sin(mean_anom)
    for _ in range(iters):
        f = E - ecc * jnp.sin(E) - mean_anom
        fp = 1.0 - ecc * jnp.cos(E)
        E = E - f / fp
    return E


@kepler_eccentric_anomaly.defjvp
def _kepler_jvp(iters, primals, tangents):
    mean_anom, ecc = primals
    dm, de = tangents
    E = kepler_eccentric_anomaly(mean_anom, ecc, iters)
    denom = 1.0 - ecc * jnp.cos(E)
    dE = (dm + jnp.sin(E) * de) / denom
    return E, dE


def true_anomaly(E, ecc):
    """True anomaly nu from eccentric anomaly, continuous with E (the
    atan2 half-angle form keeps nu on the same branch as E)."""
    half = 0.5 * E
    nu_half = jnp.arctan2(
        jnp.sqrt(1.0 + ecc) * jnp.sin(half),
        jnp.sqrt(1.0 - ecc) * jnp.cos(half),
    )
    return 2.0 * nu_half
