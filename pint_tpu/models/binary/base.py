"""Shared binary-component machinery: orbit phase, epochs, Shapiro.

Reference counterparts: PulsarBinary parameter set (pulsar_binary.py:
88-205), OrbitPB/OrbitFBX (stand_alone_psr_binaries/binary_orbits.py),
PSR_BINARY base (binary_generic.py:17).  Here the orbit abstraction is a
pair of closed-form jax expressions (orbit count and orbital frequency)
selected *statically* at model build from the par file's
parameterization (PB vs FB0...), so the jitted delay has no branches.

Internal units: PB seconds; PBDOT/XPBDOT s/s (tempo 1e-12 rule applied
at parse); A1 light-seconds == seconds; XDOT s/s; FBk Hz s^{1-k};
epochs TDB seconds since J2000 (exact ticks kept for the base offset);
M2 solar masses; angles radians; OMDOT rad/s.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu import SECS_PER_DAY, SECS_PER_JULIAN_YEAR, T_SUN_S
from pint_tpu import fixedpoint as fp
from pint_tpu.models.component import BINARY_MODELS, DelayComponent
from pint_tpu.models.parameter import Param, prefix_index

#: deg/yr -> rad/s (OMDOT par units)
DEG_PER_YEAR = jnp.pi / 180.0 / SECS_PER_JULIAN_YEAR


def get_binary_class(name: str) -> type:
    try:
        return BINARY_MODELS[name.upper()]
    except KeyError:
        raise NotImplementedError(
            f"BINARY {name} not implemented (available: "
            f"{sorted(BINARY_MODELS)})"
        ) from None


class BinaryComponent(DelayComponent):
    """Base for binary families.  Subclasses set ``binary_name`` and
    ``epoch_param`` ('T0' or 'TASC') and implement ``binary_delay``."""

    category = "pulsar_system"
    binary_name: str = ""
    epoch_param: str = "T0"
    #: dt_epoch subtracts the accumulated delay chain: a perturbation of
    #: any EARLIER delay component feeds back through the orbital phase,
    #: so parameters upstream of a binary are never exactly phase-linear
    #: (see Component.reads_delay_accum / design_partition)
    reads_delay_accum = True

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.__dict__.get("binary_name"):
            BINARY_MODELS[cls.binary_name.upper()] = cls

    def __init__(self, fb_terms=None):
        super().__init__()
        #: None => PB parameterization; int n => FB0..FBn
        self.fb_terms = fb_terms

    # -- common parameter groups --------------------------------------------
    def add_orbit_params(self, pardict):
        nfb = None
        for key in pardict:
            pi = prefix_index(key)
            if pi and pi[0] == "FB":
                nfb = max(nfb if nfb is not None else 0, pi[1])
        self.fb_terms = nfb
        if nfb is not None:
            for k in range(nfb + 1):
                self.add_param(Param(
                    f"FB{k}", units=f"1/s^{k+1}",
                    description=f"Orbital frequency derivative {k}"))
        else:
            self.add_param(Param("PB", units="s", scale=SECS_PER_DAY,
                                 description="Orbital period (par: days)"))
            self.add_param(Param("PBDOT", unit_scale=True,
                                 description="Orbital period derivative"))
            self.add_param(Param("XPBDOT", unit_scale=True,
                                 description="Excess PBDOT vs GR"))
        self.add_param(Param(self.epoch_param, kind="mjd",
                             description="Orbit reference epoch"))

    def add_a1_params(self):
        self.add_param(Param("A1", units="ls",
                             description="Projected semi-major axis"))
        self.add_param(Param("XDOT", unit_scale=True, aliases=("A1DOT",),
                             description="Rate of change of A1"))

    def add_shapiro_params(self):
        self.add_param(Param("M2", units="Msun",
                             description="Companion mass"))
        self.add_param(Param("SINI", description="Sine of inclination"))

    def orbit_defaults(self):
        d = {self.epoch_param: 0.0}
        if self.fb_terms is not None:
            d.update({f"FB{k}": 0.0 for k in range(self.fb_terms + 1)})
        else:
            d.update({"PB": jnp.nan, "PBDOT": 0.0, "XPBDOT": 0.0})
        return d

    # -- evaluation helpers --------------------------------------------------
    def prepare(self, toas, model):
        ticks = getattr(model, "epoch_ticks", {}).get(
            self.epoch_param,
            int(round(model.values[self.epoch_param] * 2**32)),
        )
        dt0 = fp.ticks_to_seconds(jnp.asarray(toas.ticks)
                                  - jnp.int64(ticks))
        # static Kepler depth from the prepare-time eccentricity class
        # (incl. EDOT drift over the span): a python int, so it lands
        # in the STATIC ctx part and keys every shared trace — two
        # same-structure models in different eccentricity classes
        # never share an unroll
        from pint_tpu.models.binary.kepler import newton_iters_for

        ecc = abs(float(model.values.get("ECC", float("nan"))))
        edot = abs(float(model.values.get("EDOT", 0.0) or 0.0))
        span = float(jnp.max(jnp.abs(dt0))) if dt0.size else 0.0
        return {
            "dt0": dt0,
            "epoch_ref": jnp.float64(ticks / 2**32),
            "kepler_iters": newton_iters_for(ecc + edot * span),
        }

    def dt_epoch(self, values, ctx, accum):
        """Barycentric time since the orbit epoch [s]: exact tick base,
        differentiable epoch shift, minus the accumulated delay chain
        (reference: pulsar_binary.py:396 barycentric_time = tdbld - acc)."""
        return ctx["dt0"] - (values[self.epoch_param] - ctx["epoch_ref"]) \
            - accum

    def orbits_and_freq(self, values, dt):
        """(orbit count since epoch, orbital frequency [1/s]) at dt."""
        if self.fb_terms is not None:
            # orbits = sum_k FBk dt^(k+1)/(k+1)!,  freq = d orbits / d dt
            orbits = jnp.zeros_like(dt)
            freq = jnp.zeros_like(dt)
            k_fact = 1.0  # k!
            power = jnp.ones_like(dt)  # dt^k
            for k in range(self.fb_terms + 1):
                if k > 0:
                    k_fact *= k
                    power = power * dt
                fbk = values[f"FB{k}"]
                freq = freq + fbk * power / k_fact
                orbits = orbits + fbk * power * dt / (k_fact * (k + 1))
            return orbits, freq
        pb = values["PB"]
        pbd = values["PBDOT"] + values["XPBDOT"]
        u_ = dt / pb
        return u_ - 0.5 * pbd * u_ * u_, (1.0 - pbd * u_) / pb

    def orbit_phase(self, orbits):
        """Orbit phase angle in (-pi, pi]: reduce the orbit count before
        scaling by 2*pi so trig sees a small argument."""
        return 2.0 * jnp.pi * (orbits - jnp.round(orbits))

    def shapiro_m2sini(self, values, sin_phi_term):
        """-2 T_sun M2 ln(1 - SINI * s) with s the orbital-geometry
        factor (sin Phi for ELL1; DD passes its full bracket)."""
        return -2.0 * T_SUN_S * values["M2"] * jnp.log(sin_phi_term)

    def delay(self, values, batch, ctx, delay_accum):
        dt = self.dt_epoch(values, ctx, delay_accum)
        return self.binary_delay(values, dt, ctx)

    def binary_delay(self, values, dt, ctx):
        raise NotImplementedError
