"""BT binary model (Blandford & Teukolsky 1976).

Reference counterpart: stand_alone_psr_binaries/BT_model.py (delayL1,
delayL2, delayR composition) wrapped by binary_bt.py:21.  Delay =
(L1 + L2) * R with

    L1 = x sin(omega) (cosE - e)
    L2 = (x cos(omega) sqrt(1-e^2) + GAMMA) sinE
    R  = 1 - (2 pi / PB) (x cos(omega) sqrt(1-e^2) cosE
                          - x sin(omega) sinE) / (1 - e cosE)

where E solves Kepler's equation for the orbit phase and x, e, omega
drift linearly (XDOT, EDOT, OMDOT).
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.models.binary.base import DEG_PER_YEAR, BinaryComponent
from pint_tpu.models.binary.kepler import kepler_eccentric_anomaly
from pint_tpu.models.parameter import Param


class KeplerianMixin:
    """Shared Keplerian parameter group for BT/DD families (T0 epoch,
    ECC/EDOT, OM/OMDOT, GAMMA)."""

    def add_keplerian_params(self, pardict):
        self.add_orbit_params(pardict)
        self.add_a1_params()
        self.add_param(Param("ECC", aliases=("E",),
                             description="Eccentricity"))
        self.add_param(Param("EDOT", unit_scale=True, units="1/s",
                             description="Eccentricity derivative"))
        self.add_param(Param("OM", units="rad", scale=jnp.pi / 180.0,
                             description="Longitude of periastron (deg)"))
        self.add_param(Param("OMDOT", units="rad/s", scale=DEG_PER_YEAR,
                             description="Periastron advance (deg/yr)"))
        self.add_param(Param("GAMMA", units="s",
                             description="Einstein delay amplitude"))

    def keplerian_defaults(self):
        d = self.orbit_defaults()
        d.update(A1=0.0, XDOT=0.0, ECC=0.0, EDOT=0.0, OM=0.0, OMDOT=0.0,
                 GAMMA=0.0)
        return d

    def eccentric_anomaly(self, values, dt):
        """(E, ecc, orbital freq) at dt = t - T0."""
        orbits, forb = self.orbits_and_freq(values, dt)
        mean_anom = self.orbit_phase(orbits)
        ecc = values["ECC"] + dt * values["EDOT"]
        return kepler_eccentric_anomaly(mean_anom, ecc), ecc, forb


class BinaryBT(KeplerianMixin, BinaryComponent):
    binary_name = "BT"
    epoch_param = "T0"

    def build_params(self, pardict):
        self.add_keplerian_params(pardict)

    def defaults(self):
        return self.keplerian_defaults()

    def binary_delay(self, values, dt, ctx):
        E, ecc, forb = self.eccentric_anomaly(values, dt)
        a1 = values["A1"] + dt * values["XDOT"]
        omega = values["OM"] + dt * values["OMDOT"]
        sw, cw = jnp.sin(omega), jnp.cos(omega)
        sE, cE = jnp.sin(E), jnp.cos(E)
        root = jnp.sqrt(1.0 - ecc * ecc)
        l1 = a1 * sw * (cE - ecc)
        l2 = (a1 * cw * root + values["GAMMA"]) * sE
        # first-order emission-time correction (BT76 Eq. 2.33 third term)
        r = 1.0 - 2.0 * jnp.pi * forb * (a1 * cw * root * cE - a1 * sw * sE) \
            / (1.0 - ecc * cE)
        return (l1 + l2) * r
