"""BT binary model (Blandford & Teukolsky 1976).

Reference counterpart: stand_alone_psr_binaries/BT_model.py (delayL1,
delayL2, delayR composition) wrapped by binary_bt.py:21.  Delay =
(L1 + L2) * R with

    L1 = x sin(omega) (cosE - e)
    L2 = (x cos(omega) sqrt(1-e^2) + GAMMA) sinE
    R  = 1 - (2 pi / PB) (x cos(omega) sqrt(1-e^2) cosE
                          - x sin(omega) sinE) / (1 - e cosE)

where E solves Kepler's equation for the orbit phase and x, e, omega
drift linearly (XDOT, EDOT, OMDOT).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.binary.base import DEG_PER_YEAR, BinaryComponent
from pint_tpu.models.binary.kepler import kepler_eccentric_anomaly
from pint_tpu.models.parameter import Param, prefix_index


class KeplerianMixin:
    """Shared Keplerian parameter group for BT/DD families (T0 epoch,
    ECC/EDOT, OM/OMDOT, GAMMA)."""

    def add_keplerian_params(self, pardict):
        self.add_orbit_params(pardict)
        self.add_a1_params()
        self.add_param(Param("ECC", aliases=("E",),
                             description="Eccentricity"))
        self.add_param(Param("EDOT", unit_scale=True, units="1/s",
                             description="Eccentricity derivative"))
        self.add_param(Param("OM", units="rad", scale=jnp.pi / 180.0,
                             description="Longitude of periastron (deg)"))
        self.add_param(Param("OMDOT", units="rad/s", scale=DEG_PER_YEAR,
                             description="Periastron advance (deg/yr)"))
        self.add_param(Param("GAMMA", units="s",
                             description="Einstein delay amplitude"))

    def keplerian_defaults(self):
        d = self.orbit_defaults()
        d.update(A1=0.0, XDOT=0.0, ECC=0.0, EDOT=0.0, OM=0.0, OMDOT=0.0,
                 GAMMA=0.0)
        return d

    def ecc_reach(self, values, batch):
        """Largest |eccentricity| this binary's Kepler solve can see at
        ``values`` over the dataset: |ECC| + |EDOT| * max|t - T0| — the
        host-side reach PreparedModel.kepler_ecc_reach aggregates to
        validate the static Newton depth against fitted/gridded
        eccentricities."""
        from pint_tpu import fixedpoint as fp

        ecc = abs(float(values.get("ECC", float("nan"))))
        edot = abs(float(values.get("EDOT", 0.0) or 0.0))
        span = 0.0
        if edot and getattr(batch, "ticks", None) is not None:
            ticks = np.int64(int(round(
                float(values[self.epoch_param]) * 2**32)))
            dt0 = fp.ticks_to_seconds(np.asarray(batch.ticks) - ticks)
            span = float(np.max(np.abs(dt0))) if dt0.size else 0.0
        return ecc + edot * span

    def eccentric_anomaly(self, values, dt, ctx=None):
        """(E, ecc, orbital freq) at dt = t - T0.  ctx supplies the
        static Newton depth chosen at prepare time (kepler_iters)."""
        orbits, forb = self.orbits_and_freq(values, dt)
        mean_anom = self.orbit_phase(orbits)
        ecc = values["ECC"] + dt * values["EDOT"]
        iters = (ctx or {}).get("kepler_iters", 10)
        return kepler_eccentric_anomaly(mean_anom, ecc, iters), ecc, \
            forb


class BinaryBT(KeplerianMixin, BinaryComponent):
    binary_name = "BT"
    epoch_param = "T0"

    def build_params(self, pardict):
        self.add_keplerian_params(pardict)

    def defaults(self):
        return self.keplerian_defaults()

    def binary_delay(self, values, dt, ctx):
        return self._bt_delay_core(values, dt, values["A1"], ctx)

    def _bt_delay_core(self, values, dt, a1_base, ctx=None):
        E, ecc, forb = self.eccentric_anomaly(values, dt, ctx)
        a1 = a1_base + dt * values["XDOT"]
        omega = values["OM"] + dt * values["OMDOT"]
        sw, cw = jnp.sin(omega), jnp.cos(omega)
        sE, cE = jnp.sin(E), jnp.cos(E)
        root = jnp.sqrt(1.0 - ecc * ecc)
        l1 = a1 * sw * (cE - ecc)
        l2 = (a1 * cw * root + values["GAMMA"]) * sE
        # first-order emission-time correction (BT76 Eq. 2.33 third term)
        r = 1.0 - 2.0 * jnp.pi * forb * (a1 * cw * root * cE - a1 * sw * sE) \
            / (1.0 - ecc * cE)
        return (l1 + l2) * r


class BinaryBTPiecewise(BinaryBT):
    """BT with piecewise-constant T0/A1 over MJD ranges (reference:
    stand_alone_psr_binaries/BT_piecewise.py, 497 LoC; par params
    T0X_0000/A1X_0000 valid over [XR1_0000, XR2_0000]).

    TPU design: the per-piece TOA membership is a static 0/1 matrix
    built at prepare time, so the per-TOA effective (T0, A1) is a
    mask-weighted sum — fully vmappable, no data-dependent control
    flow."""

    binary_name = "BT_PIECEWISE"
    epoch_param = "T0"

    def __init__(self, piece_indices=(), fb_terms=None):
        self.piece_indices = tuple(piece_indices)
        super().__init__(fb_terms=fb_terms)

    @classmethod
    def from_parfile(cls, pardict):
        idx = set()
        for key in pardict:
            pi = prefix_index(key)
            if pi and pi[0] in ("T0X_", "A1X_"):
                idx.add(pi[1])
        inst = cls(piece_indices=sorted(idx))
        inst.build_params(pardict)
        return inst

    def build_params(self, pardict):
        super().build_params(pardict)
        for i in self.piece_indices:
            tag = f"{i:04d}"
            self.add_param(Param(f"T0X_{tag}", kind="mjd",
                                 description=f"Piece {i} T0"))
            self.add_param(Param(f"A1X_{tag}", units="ls",
                                 description=f"Piece {i} A1"))
            self.add_param(Param(f"XR1_{tag}", kind="mjd",
                                 fittable=False,
                                 description=f"Piece {i} start"))
            self.add_param(Param(f"XR2_{tag}", kind="mjd",
                                 fittable=False,
                                 description=f"Piece {i} end"))

    def defaults(self):
        d = super().defaults()
        for i in self.piece_indices:
            tag = f"{i:04d}"
            d[f"T0X_{tag}"] = np.nan
            d[f"A1X_{tag}"] = np.nan
            d[f"XR1_{tag}"] = 0.0
            d[f"XR2_{tag}"] = 0.0
        return d

    def prepare(self, toas, model):
        ctx = super().prepare(toas, model)
        t_sec = toas.ticks.astype(np.float64) / 2**32
        masks = []
        for i in self.piece_indices:
            tag = f"{i:04d}"
            lo = float(model.values[f"XR1_{tag}"])
            hi = float(model.values[f"XR2_{tag}"])
            masks.append(((t_sec >= lo) & (t_sec < hi))
                         .astype(np.float64))
        ctx["piece_masks"] = (np.stack(masks) if masks
                              else np.zeros((0, len(toas))))
        return ctx

    def binary_delay(self, values, dt, ctx):
        masks = ctx["piece_masks"]
        t0_off = jnp.zeros_like(dt)
        a1 = jnp.broadcast_to(values["A1"], dt.shape)
        for j, i in enumerate(self.piece_indices):
            tag = f"{i:04d}"
            m = masks[j]
            t0x = values[f"T0X_{tag}"]
            a1x = values[f"A1X_{tag}"]
            use_t0 = jnp.where(jnp.isnan(t0x), values["T0"], t0x)
            use_a1 = jnp.where(jnp.isnan(a1x), values["A1"], a1x)
            t0_off = t0_off + m * (use_t0 - values["T0"])
            a1 = a1 + m * (use_a1 - values["A1"])
        return self._bt_delay_core(values, dt - t0_off, a1, ctx)
