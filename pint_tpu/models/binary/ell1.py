"""ELL1 binary family: ELL1, ELL1H, ELL1k.

Physics: Lange et al. (2001) small-eccentricity expansion with the
third-order-in-eccentricity Roemer terms of Zhu et al. (2019) / Fiore et
al. (2023) (reference: stand_alone_psr_binaries/ELL1_model.py:delayR,
delayI; ELL1H harmonics per Freire & Wex (2010), ELL1H_model.py; ELL1k
exact omega-precession variant, ELL1k_model.py).

TPU redesign: the Roemer shape is represented as a 4-term harmonic
series with coefficients polynomial in (eps1, eps2), so its first and
second orbital-phase derivatives (needed by the Damour-Deruelle inverse
timing formula) are exact analytic sums — no hand-maintained expanded
derivative expressions, and every *parameter* derivative is autodiff.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu import T_SUN_S
from pint_tpu.models.binary.base import DEG_PER_YEAR, BinaryComponent
from pint_tpu.models.parameter import Param, prefix_index


def roemer_harmonic_coeffs(e1, e2):
    """Harmonic coefficients (a_k sin k*phi + b_k cos k*phi, k=1..4) of
    the ELL1 Roemer delay shape, complete to third order in eccentricity
    (Zhu et al. 2019 Eq. 1 / Fiore et al. 2023 Eq. 4 regrouped by
    harmonic)."""
    a = (
        1.0 - (5.0 * e2 * e2 + 3.0 * e1 * e1) / 8.0,
        e2 / 2.0 - (5.0 * e2 * e2 + 3.0 * e1 * e1) * e2 / 12.0,
        0.375 * (e2 * e2 - e1 * e1),
        e2 * (e2 * e2 - 3.0 * e1 * e1) / 3.0,
    )
    b = (
        e1 * e2 / 4.0,
        -e1 / 2.0 + e1 * (6.0 * e2 * e2 + 4.0 * e1 * e1) / 12.0,
        -0.75 * e1 * e2,
        e1 * (e1 * e1 - 3.0 * e2 * e2) / 3.0,
    )
    return a, b


def roemer_and_derivs(a1, phi, e1, e2):
    """(Dre, dDre/dphi, d2Dre/dphi2): Roemer delay and its orbital-phase
    derivatives from the harmonic representation."""
    dre = jnp.zeros_like(phi)
    drep = jnp.zeros_like(phi)
    drepp = jnp.zeros_like(phi)
    ak, bk = roemer_harmonic_coeffs(e1, e2)
    for k in range(1, 5):
        s, c = jnp.sin(k * phi), jnp.cos(k * phi)
        a, b = ak[k - 1], bk[k - 1]
        dre = dre + a * s + b * c
        drep = drep + k * (a * c - b * s)
        drepp = drepp - k * k * (a * s + b * c)
    return a1 * dre, a1 * drep, a1 * drepp


def inverse_timing_delay(dre, drep, drepp, nhat):
    """Damour & Deruelle (1986) Eq. 46-52 inverse timing formula carried
    to second order: the delay evaluated at the pulsar's emission time
    expressed through quantities at the arrival time."""
    nd = nhat * drep
    return dre * (1.0 - nd + nd * nd + 0.5 * nhat * nhat * dre * drepp)


class ELL1Base(BinaryComponent):
    """Shared ELL1 structure: TASC epoch, eps1/eps2, inverse Roemer."""

    register = False
    epoch_param = "TASC"

    def build_params(self, pardict):
        self.add_orbit_params(pardict)
        self.add_a1_params()
        self.add_param(Param("EPS1", description="e sin(omega) at TASC"))
        self.add_param(Param("EPS2", description="e cos(omega) at TASC"))
        self.add_param(Param("EPS1DOT", unit_scale=True, units="1/s",
                             description="Rate of EPS1"))
        self.add_param(Param("EPS2DOT", unit_scale=True, units="1/s",
                             description="Rate of EPS2"))

    def defaults(self):
        d = self.orbit_defaults()
        d.update(A1=0.0, XDOT=0.0, EPS1=0.0, EPS2=0.0, EPS1DOT=0.0,
                 EPS2DOT=0.0)
        return d

    def eps(self, values, dt):
        """(eps1, eps2) at dt = t - TASC (linear-drift model)."""
        return (values["EPS1"] + dt * values["EPS1DOT"],
                values["EPS2"] + dt * values["EPS2DOT"])

    def binary_delay(self, values, dt, ctx):
        orbits, forb = self.orbits_and_freq(values, dt)
        phi = self.orbit_phase(orbits)
        e1, e2 = self.eps(values, dt)
        a1 = values["A1"] + dt * values["XDOT"]
        dre, drep, drepp = roemer_and_derivs(a1, phi, e1, e2)
        nhat = 2.0 * jnp.pi * forb
        return inverse_timing_delay(dre, drep, drepp, nhat) \
            + self.shapiro_delay(values, phi)

    def shapiro_delay(self, values, phi):
        raise NotImplementedError


class BinaryELL1(ELL1Base):
    """ELL1 with M2/SINI Shapiro delay (Lange et al. 2001 Eq. A16;
    reference: ELL1_model.py ELL1model.delayS)."""

    binary_name = "ELL1"

    def build_params(self, pardict):
        super().build_params(pardict)
        self.add_shapiro_params()

    def defaults(self):
        d = super().defaults()
        d.update(M2=0.0, SINI=0.0)
        return d

    def shapiro_delay(self, values, phi):
        return -2.0 * T_SUN_S * values["M2"] * jnp.log1p(
            -values["SINI"] * jnp.sin(phi)
        )


class BinaryELL1k(BinaryELL1):
    """ELL1k (Susobhanan et al. 2018): exact periastron advance OMDOT
    and eccentricity-scale rate LNEDOT instead of the EPS1DOT/EPS2DOT
    linearization (reference: ELL1k_model.py eps1/eps2)."""

    binary_name = "ELL1K"

    def build_params(self, pardict):
        BinaryELL1.build_params(self, pardict)
        self.params = [p for p in self.params
                       if p.name not in ("EPS1DOT", "EPS2DOT")]
        self.add_param(Param("OMDOT", units="rad/s", scale=DEG_PER_YEAR,
                             description="Periastron advance (par: deg/yr)"))
        from pint_tpu import SECS_PER_JULIAN_YEAR

        self.add_param(Param("LNEDOT", units="1/s",
                             scale=1.0 / SECS_PER_JULIAN_YEAR,
                             description="d ln(ecc) / dt (par: 1/yr)"))

    def defaults(self):
        d = BinaryELL1.defaults(self)
        d.pop("EPS1DOT", None)
        d.pop("EPS2DOT", None)
        d.update(OMDOT=0.0, LNEDOT=0.0)
        return d

    def eps(self, values, dt):
        # rotate (EPS1, EPS2) by the accumulated periastron advance and
        # scale by the exponential-linearized eccentricity drift
        w = values["OMDOT"] * dt
        grow = 1.0 + values["LNEDOT"] * dt
        cw, sw = jnp.cos(w), jnp.sin(w)
        e1 = grow * (values["EPS1"] * cw + values["EPS2"] * sw)
        e2 = grow * (values["EPS2"] * cw - values["EPS1"] * sw)
        return e1, e2


class BinaryELL1H(ELL1Base):
    """ELL1 with orthometric Shapiro parameterization (Freire & Wex
    2010): H3 alone (3rd-harmonic), H3+H4 (harmonic sum, Eq. 19), or
    H3+STIGMA (exact log form, Eq. 29).  The parameterization choice is
    static at build time (reference: binary_ell1.py:389-415 dispatch)."""

    binary_name = "ELL1H"

    def build_params(self, pardict):
        super().build_params(pardict)
        self.add_param(Param("H3", units="s",
                             description="Orthometric Shapiro amplitude"))
        self.mode = "H3"
        self.nharms = int(float(pardict.get("NHARMS", [["3"]])[0][0]))
        # declared so the builder consumes it and parfile round-trips
        # preserve it (the value used is the static self.nharms)
        self.add_param(Param("NHARMS", fittable=False,
                             description="Shapiro harmonics summed"))
        if "STIGMA" in pardict or "VARSIGMA" in pardict:
            self.add_param(Param("STIGMA", aliases=("VARSIGMA",),
                                 description="Orthometric ratio"))
            self.mode = "STIGMA"
        elif "H4" in pardict:
            self.add_param(Param("H4", units="s",
                                 description="4th Shapiro harmonic"))
            self.mode = "H4"
            self.nharms = max(self.nharms, 7)

    def defaults(self):
        d = super().defaults()
        d["H3"] = 0.0
        d["NHARMS"] = float(self.nharms)
        if self.mode == "STIGMA":
            d["STIGMA"] = 0.0
        elif self.mode == "H4":
            d["H4"] = 0.0
        return d

    @staticmethod
    def _harmonic_sum(phi, stigma, nharms, factor_out=3):
        """sum_{k=3}^{nharms} c_k(stigma) * basis(k phi) with
        c_k = (-1)^pwr (2/k) stigma^(k-factor_out); basis sin for odd k
        (pwr=(k+1)/2), cos for even k (pwr=(k+2)/2).  Freire & Wex
        (2010) Eq. 10/13/19."""
        total = jnp.zeros_like(phi)
        for k in range(3, nharms + 1):
            if k % 2:
                pwr, basis = (k + 1) // 2, jnp.sin(k * phi)
            else:
                pwr, basis = (k + 2) // 2, jnp.cos(k * phi)
            coeff = (-1.0) ** pwr * 2.0 / k
            total = total + coeff * stigma ** (k - factor_out) * basis
        return total

    def shapiro_delay(self, values, phi):
        h3 = values["H3"]
        if self.mode == "STIGMA":
            # exact all-harmonic form for high inclination (Eq. 29)
            sig = values["STIGMA"]
            lognum = 1.0 + sig * sig - 2.0 * sig * jnp.sin(phi)
            return -2.0 * h3 / sig**3 * jnp.log(lognum)
        if self.mode == "H4":
            stigma = values["H4"] / jnp.where(h3 == 0.0, 1.0, h3)
        else:
            stigma = jnp.float64(0.0)
        return -2.0 * h3 * self._harmonic_sum(phi, stigma, self.nharms)
