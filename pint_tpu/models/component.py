"""Component base classes and registry.

Counterpart of the reference's ModelMeta/Component machinery (reference:
src/pint/models/timing_model.py:3264-3666) with the same extension
contract — subclassing auto-registers, so user components plug in exactly
like builtin ones — but a functional evaluation contract:

- a Component *instance* holds only static structure: parameter metadata
  (built from the par file, so prefix/mask families are concrete), epochs
  as exact ticks, category and ordering;
- ``prepare(toas)`` returns a ctx dict of static per-dataset arrays
  (boolean masks for mask params, cached geometry) that the jit closure
  captures as constants;
- ``delay(values, batch, ctx, delay_accum)`` / ``phase(values, batch,
  ctx, delay)`` are pure jax functions of the dynamic parameter dict.

Delay components return float64 seconds; phase components return float64
turns (small terms) or an (int64 turns, float64 frac) pair (spindown's
exact path).
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp

from pint_tpu.models.parameter import Param


#: BINARY par value -> binary component class (filled by
#: pint_tpu.models.binary subclasses; reference:
#: model_builder.choose_binary_model, model_builder.py:576)
BINARY_MODELS: Dict[str, type] = {}


class Component:
    """Base component.  Subclasses auto-register by class name."""

    registry: Dict[str, type] = {}
    category: str = ""
    register: bool = True
    #: par-file keys whose presence selects this component (builder hint)
    trigger_params: tuple = ()
    #: True when ``delay()`` reads its ``delay_accum`` argument — the
    #: accumulated delay of earlier chain members.  The hybrid design
    #: matrix (PreparedModel.design_partition) must know: a parameter of
    #: an EARLIER component perturbs every later accum-reader (binary
    #: orbital phase at t - accum shifts a DM column at the ~1e-4
    #: relative level), so its structured column carries the chain's
    #: suffix-response factor (one shared ``jvp`` per position) to stay
    #: exact against the 1e-12 hybrid==jacfwd pin.
    reads_delay_accum: bool = False
    #: names of OTHER components' parameters this component reads from
    #: ``values`` inside ``delay()``/``phase()`` (e.g. SolarSystemShapiro
    #: recomputes the pulsar direction from RAJ/DECJ; DDK reads PX and
    #: the proper motion).  The structured design build must evaluate
    #: this component's local partial too — an undeclared cross-read
    #: would silently drop that term from the analytic column.  Own
    #: (``has_param``) parameters need not be listed.
    reads_params: tuple = ()

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.__dict__.get("register", True) and cls.category:
            Component.registry[cls.__name__] = cls

    def __init__(self):
        self.params: List[Param] = []

    # -- structure -----------------------------------------------------------
    def add_param(self, p: Param):
        self.params.append(p)
        return p

    def param(self, name) -> Param:
        for p in self.params:
            if p.name == name:
                return p
        raise KeyError(name)

    def has_param(self, name) -> bool:
        return any(p.name == name for p in self.params)

    @classmethod
    def from_parfile(cls, pardict: dict):
        """Instantiate with concrete prefix/mask families for this par.
        Default: fixed parameter set from params_spec()."""
        inst = cls()
        inst.build_params(pardict)
        return inst

    def build_params(self, pardict: dict):
        raise NotImplementedError

    def defaults(self) -> dict:
        """Default values for this component's params (internal units)."""
        return {}

    # -- evaluation ----------------------------------------------------------
    def prepare(self, toas, model) -> dict:
        """Static per-dataset arrays; captured as jit constants."""
        return {}

    # -- hybrid design matrix (PINT's d_phase_d_param split) ------------------
    def linear_params(self) -> tuple:
        """Names of this component's parameters whose phase contribution
        is linear with a closed-form design column (the analytic half of
        the hybrid design matrix).  A name listed here promises the
        matching ``d_delay_d_param`` / ``d_phase_d_param`` hook returns
        the EXACT derivative of ``delay()`` / ``phase()`` — the hybrid
        column is regression-pinned against full ``jacfwd`` at 1e-12
        relative.  Default: nothing is analytic."""
        return ()


class DelayComponent(Component):
    def delay(self, values, batch, ctx, delay_accum):
        """Return delay in seconds (float64, shape of batch)."""
        raise NotImplementedError

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        """d delay / d ``name`` [s per internal unit], for names listed
        in :meth:`Component.linear_params`.  ``delay_accum`` is the
        accumulated delay of earlier chain members, exactly as
        ``delay()`` receives it."""
        raise NotImplementedError(
            f"{type(self).__name__} declares {name} linear but defines "
            "no d_delay_d_param")

    # optional extra hook ``d_dm_d_param(values, batch, ctx, name)``:
    # components exposing a ``dm_value`` must provide it for their
    # linear params or those params stay nonlinear on the wideband
    # (stacked [time; DM]) fitters, whose DM block differentiates the
    # modeled DM as well as the delay.


class PhaseComponent(Component):
    def phase(self, values, batch, ctx, delay):
        """Return phase turns: float64 array, or (int64, float64) pair."""
        raise NotImplementedError

    def d_phase_d_param(self, values, batch, ctx, delay, name):
        """d phase / d ``name`` [turns per internal unit], for names
        listed in :meth:`Component.linear_params`.  ``delay`` is the
        full accumulated delay, exactly as ``phase()`` receives it."""
        raise NotImplementedError(
            f"{type(self).__name__} declares {name} linear but defines "
            "no d_phase_d_param")


def mask_from_select(select: tuple, toas) -> "jnp.ndarray":
    """Resolve a mask-parameter selector to a boolean array over TOAs.

    Selector forms (reference maskParameter semantics, parameter.py:1782):
    ("flag", key, value) | ("mjd", lo, hi) | ("freq", lo, hi) |
    ("tel", obsname) | ("all",)
    """
    import numpy as np

    n = len(toas)
    kind = select[0]
    if kind == "all" or kind == "":
        m = np.ones(n, dtype=bool)
    elif kind == "flag":
        key, val = select[1], select[2]
        m = np.array(
            [f.get(key) == val for f in toas.flags], dtype=bool
        )
    elif kind == "mjd":
        lo, hi = float(select[1]), float(select[2])
        m = (toas.mjd_float >= lo) & (toas.mjd_float <= hi)
    elif kind == "freq":
        lo, hi = float(select[1]), float(select[2])
        m = (toas.freq_mhz >= lo) & (toas.freq_mhz <= hi)
    elif kind == "tel":
        from pint_tpu.obs import get_observatory

        target = get_observatory(select[1]).name
        m = np.array([o == target for o in toas.obs_names], dtype=bool)
    else:
        raise ValueError(f"unknown mask selector {select!r}")
    return jnp.asarray(m)


def parse_mask_select(tokens) -> tuple:
    """Parse par-file mask tokens after the value, e.g.
    ``JUMP -fe L-wide 0.001 1`` -> select ("flag","fe","L-wide").
    ``JUMP MJD 50000 51000 ...`` -> ("mjd", 50000.0, 51000.0).
    Returns (select, remaining_tokens)."""
    if not tokens:
        return ("all",), []
    t0 = tokens[0]
    if t0.startswith("-"):
        return ("flag", t0.lstrip("-"), tokens[1]), tokens[2:]
    u = t0.upper()
    if u == "MJD":
        return ("mjd", float(tokens[1]), float(tokens[2])), tokens[3:]
    if u == "FREQ":
        return ("freq", float(tokens[1]), float(tokens[2])), tokens[3:]
    if u in ("TEL", "T"):
        return ("tel", tokens[1]), tokens[2:]
    return ("all",), tokens
