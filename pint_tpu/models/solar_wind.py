"""Solar-wind dispersion: NE_SW spherical model and SWX piecewise model.

Counterparts of the reference components (reference:
src/pint/models/solar_wind_dispersion.py:290 SolarWindDispersion — SWM 0
implements Edwards+ 2006 Eq. 29-30: DM_sw = NE_SW au^2 rho / (r sin rho)
with rho = pi - elongation; SWM 1 implements Hazboun+ 2022 Eq. 11-12 (a
power-law radial density n ~ r^-SWP, hypergeometric path integral
``_dm_p_int`` at :19); :525 SolarWindDispersionX — per-interval SWXDM_
amplitudes with power-law index SWXP_, normalized by (conjunction -
opposition) geometry so SWXDM is the *excess* DM at conjunction).

TPU design: the geometry factor depends only on the (static) TOA-Sun
vectors and frozen power-law indices, so it is computed host-side once
in ``prepare`` (with scipy's hyp2f1 for SWM 1) and enters the jit
closure as a constant vector; the fittable amplitude NE_SW / SWXDM_k
then scales it linearly on device.  SWP/SWM are not fittable here
(the reference fits SWP numerically; rarely used).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import AU_LS, DM_CONST
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import Param, prefix_index

#: 1 pc in AU (IAU): 648000/pi
_AU_PER_PC = 648000.0 / np.pi


def _geometry_swm0(r_au: np.ndarray, elong: np.ndarray) -> np.ndarray:
    """Edwards+ 2006 geometry factor in pc (DM = NE_SW[cm^-3] * this)."""
    rho = np.pi - elong
    return rho / (r_au * np.sin(rho)) / _AU_PER_PC


def _geometry_swm1(r_au: np.ndarray, elong: np.ndarray,
                   p: float) -> np.ndarray:
    """Hazboun+ 2022 Eq. 11 path integral in pc for density ~ r^-p."""
    from scipy.special import hyp2f1

    if p <= 1:
        raise ValueError("solar-wind power-law index must be > 1")
    b = r_au * np.sin(elong)  # impact parameter [AU]
    z_sun = r_au * np.cos(elong)  # distance to closest approach [AU]
    # upper integration limit ~ "infinity": 1e14 light-seconds in AU
    # (the reference uses (1e14 s * c); the integral has converged many
    # orders of magnitude before this for any p > 1)
    z_p = 1e14 / AU_LS

    def dm_p_int(z):
        return (z / b) * hyp2f1(0.5, p / 2.0, 1.5, -(z**2) / b**2)

    return (
        (1.0 / b) ** p * b * (dm_p_int(z_p) - dm_p_int(-z_sun))
    ) / _AU_PER_PC


def _sun_geometry(toas, model):
    """Per-TOA (r_AU, elongation_rad) of the Sun seen from the obs."""
    from pint_tpu.models.astrometry import psr_dir_static

    n = psr_dir_static(model)
    s = np.asarray(toas.obs_sun_pos)  # obs->sun, light-seconds
    r_ls = np.linalg.norm(s, axis=-1)
    cos_e = np.clip((s @ n) / r_ls, -1.0, 1.0)
    return r_ls / AU_LS, np.arccos(cos_e)


class SolarWindDispersion(DelayComponent):
    register = True
    category = "solar_wind"
    trigger_params = ("NE_SW", "NE1AU", "SOLARN0")

    def __init__(self):
        super().__init__()
        self.add_param(Param("NE_SW", units="cm^-3",
                             aliases=("NE1AU", "SOLARN0"),
                             description="Solar wind density at 1 AU"))
        self.add_param(Param("SWM", units="", fittable=False,
                             description="Solar wind model (0|1)"))
        self.add_param(Param("SWP", units="", fittable=False,
                             description="Radial power-law index (SWM 1)"))

    def build_params(self, pardict):
        pass

    def defaults(self):
        return {"NE_SW": 0.0, "SWM": 0.0, "SWP": 2.0}

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import bary_freq_mhz

        r_au, elong = _sun_geometry(toas, model)
        swm = int(round(model.values.get("SWM", 0.0)))
        p = float(model.values.get("SWP", 2.0))
        if swm == 0:
            geom = _geometry_swm0(r_au, elong)
        elif swm == 1:
            geom = _geometry_swm1(r_au, elong, p)
        else:
            raise ValueError(f"SWM {swm} not supported (0|1)")
        return {
            "geometry_pc": jnp.asarray(geom),
            "bfreq": jnp.asarray(bary_freq_mhz(toas, model)),
        }

    def dm_at(self, values, ctx):
        return values["NE_SW"] * ctx["geometry_pc"]

    def dm_value(self, values, batch, ctx):
        return self.dm_at(values, ctx)

    def delay(self, values, batch, ctx, delay_accum):
        return DM_CONST * self.dm_at(values, ctx) / ctx["bfreq"] ** 2


class SolarWindDispersionX(DelayComponent):
    """Piecewise solar wind: SWXDM_i is the conjunction-excess DM in
    [SWXR1_i, SWXR2_i] with per-interval index SWXP_i (reference:
    solar_wind_dispersion.py:525 ``swx_dm``)."""

    register = True
    category = "solar_windx"
    trigger_params = ("SWXDM",)

    def __init__(self, indices=()):
        super().__init__()
        self.indices = tuple(indices)
        for i in self.indices:
            self.add_param(Param(f"SWXDM_{i:04d}", units="pc cm^-3",
                                 description=f"SW DM amplitude, range {i}"))
            self.add_param(Param(f"SWXP_{i:04d}", units="", fittable=False,
                                 description=f"SW power-law index {i}"))
            self.add_param(Param(f"SWXR1_{i:04d}", kind="mjd",
                                 fittable=False,
                                 description=f"SWX range {i} start"))
            self.add_param(Param(f"SWXR2_{i:04d}", kind="mjd",
                                 fittable=False,
                                 description=f"SWX range {i} end"))

    @classmethod
    def from_parfile(cls, pardict):
        idx = sorted(
            {
                prefix_index(k)[1]
                for k in pardict
                if k.startswith("SWXDM_") and prefix_index(k)
            }
        )
        return cls(indices=idx)

    def defaults(self):
        d = {f"SWXDM_{i:04d}": 0.0 for i in self.indices}
        d.update({f"SWXP_{i:04d}": 2.0 for i in self.indices})
        return d

    def _conj_opp_elongation(self, toas, model):
        """(min, max) Sun-pulsar elongation over a year, sampled from the
        geocenter (reference uses ``pint.utils.get_conjunction``)."""
        from pint_tpu.ephem import body_posvel_ssb
        from pint_tpu.models.astrometry import psr_dir_static

        n = psr_dir_static(model)
        t0 = float(np.median(toas.ticks)) / 2**32
        grid = np.linspace(t0 - 0.5 * 365.25 * 86400.0,
                           t0 + 0.5 * 365.25 * 86400.0, 4001)
        ticks = (grid * 2**32).astype(np.int64)
        sun = body_posvel_ssb("sun", ticks, toas.ephem).pos
        earth = body_posvel_ssb("earth", ticks, toas.ephem).pos
        s = sun - earth
        cos_e = np.clip(
            (s @ n) / np.linalg.norm(s, axis=-1), -1.0, 1.0
        )
        e = np.arccos(cos_e)
        return float(e.min()), float(e.max())

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import bary_freq_mhz

        r_au, elong = _sun_geometry(toas, model)
        e_conj, e_opp = self._conj_opp_elongation(toas, model)
        t = toas.ticks.astype(np.float64) / 2**32
        scaled = []
        masks = []
        for i in self.indices:
            p = float(model.values.get(f"SWXP_{i:04d}", 2.0))
            geom = (_geometry_swm1(r_au, elong, p)
                    if p != 2.0 else _geometry_swm0(r_au, elong))
            # normalization: conjunction/opposition geometry at r = 1 AU
            if p != 2.0:
                g_conj = _geometry_swm1(
                    np.array([1.0]), np.array([e_conj]), p)[0]
                g_opp = _geometry_swm1(
                    np.array([1.0]), np.array([e_opp]), p)[0]
            else:
                g_conj = _geometry_swm0(
                    np.array([1.0]), np.array([e_conj]))[0]
                g_opp = _geometry_swm0(
                    np.array([1.0]), np.array([e_opp]))[0]
            scaled.append((geom - g_opp) / (g_conj - g_opp))
            lo = model.values[f"SWXR1_{i:04d}"]
            hi = model.values[f"SWXR2_{i:04d}"]
            masks.append((t >= lo) & (t <= hi))
        ns = len(self.indices)
        return {
            "scaled_geom": jnp.asarray(
                np.stack(scaled, 0) if ns else np.zeros((0, len(toas)))
            ),
            "masks": jnp.asarray(
                np.stack(masks, 0) if ns
                else np.zeros((0, len(toas)), dtype=bool)
            ),
            "bfreq": jnp.asarray(bary_freq_mhz(toas, model)),
        }

    def dm_at(self, values, ctx):
        if not self.indices:
            return jnp.zeros(ctx["bfreq"].shape)
        amps = jnp.stack(
            [values[f"SWXDM_{i:04d}"] for i in self.indices]
        )
        return jnp.sum(
            ctx["masks"] * ctx["scaled_geom"] * amps[:, None], axis=0
        )

    def dm_value(self, values, batch, ctx):
        return self.dm_at(values, ctx)

    def delay(self, values, batch, ctx, delay_accum):
        return DM_CONST * self.dm_at(values, ctx) / ctx["bfreq"] ** 2
