"""Parameter metadata and par-file value codecs.

Counterpart of the reference's 2600-line parameter hierarchy (reference:
src/pint/models/parameter.py:109-2616), redesigned for the functional
core: a :class:`Param` is *metadata only* (name, kind, units, frozen,
aliases, parfile formatting); parameter *values* live in a flat
``{name: float64}`` dict that is a JAX pytree.  Canonical internal units
make every value a bare float64:

- angles -> radians          - times/epochs -> TDB seconds since J2000
- frequencies -> Hz          - DM -> pc cm^-3
- masses -> solar masses     - dimensionless as-is

Kinds:
- ``float``  : plain number (optionally with a par-file unit scale)
- ``angle``  : RA "17:48:52.75" (hourangle) or DEC "-20:21:29.0" (deg)
- ``mjd``    : epoch, parsed exactly then stored as ticks AND f64 seconds
- ``bool``   : Y/N/1/0/T/F
- ``str``    : passthrough (not fittable)
- ``prefix`` : indexed family template (F0,F1,... / GLF0_1 / DMX_0001)
- ``mask``   : value + TOA-subset selector (JUMP/EFAC/EQUAD/ECORR...)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from pint_tpu.time.mjd import mjd_string_to_day_frac, mjd_to_ticks_tdb

__all__ = ["Param", "parse_angle", "format_angle", "parse_bool",
           "mjd_value_to_ticks", "prefix_index"]


def parse_angle(s: str, hourangle: bool) -> float:
    """Par-file angle string -> radians.  Accepts sexagesimal
    (``17:48:52.75``) or plain degrees/hours as a bare float."""
    s = s.strip()
    if ":" in s:
        sign = -1.0 if s.lstrip().startswith("-") else 1.0
        parts = s.lstrip("+-").split(":")
        val = 0.0
        for i, p in enumerate(parts):
            val += abs(float(p)) / 60.0**i
        val *= sign
    else:
        val = float(s)
    scale = 15.0 if hourangle else 1.0
    return np.deg2rad(val * scale)


def format_angle(rad: float, hourangle: bool, ndigits=8) -> str:
    scale = 15.0 if hourangle else 1.0
    val = np.rad2deg(rad) / scale
    sign = "-" if val < 0 else ""
    val = abs(val)
    d = int(val)
    m = int((val - d) * 60)
    s = (val - d - m / 60.0) * 3600
    if round(s, ndigits) >= 60.0:
        s = 0.0
        m += 1
    if m >= 60:
        m = 0
        d += 1
    return f"{sign}{d:02d}:{m:02d}:{s:0{3+ndigits}.{ndigits}f}"


def parse_bool(s: str) -> bool:
    return str(s).strip().upper() in ("Y", "YES", "T", "TRUE", "1")


def mjd_value_to_ticks(s: str) -> int:
    """Par-file MJD string -> exact TDB ticks (par epochs are TDB when
    UNITS TDB, the only supported units for now)."""
    d, n, den = mjd_string_to_day_frac(str(s))
    return mjd_to_ticks_tdb(d, n, den)


_PREFIX_RE = re.compile(r"^([A-Za-z][A-Za-z0-9_]*?)_?(\d+)$")


def prefix_index(name: str):
    """Split an indexed name: 'F0' -> ('F', 0); 'DMX_0001' -> ('DMX_', 1);
    returns None if not indexed."""
    m = _PREFIX_RE.match(name)
    if not m:
        return None
    return m.group(1) + ("_" if name[len(m.group(1))] == "_" else ""), int(
        m.group(2)
    )


@dataclass
class Param:
    """Parameter metadata (values live in the model's params dict)."""

    name: str
    kind: str = "float"  # float|angle|mjd|bool|str|prefix|mask
    description: str = ""
    units: str = ""
    #: multiply par-file value by this to get internal units
    scale: float = 1.0
    #: tempo convention: a par value with |v| > scale_threshold is taken
    #: to be in units of scale_factor (e.g. "PBDOT 7.2" means 7.2e-12;
    #: reference: parameter.py:791-793)
    unit_scale: bool = False
    scale_factor: float = 1e-12
    scale_threshold: float = 1e-7
    frozen: bool = True
    fittable: bool = True
    hourangle: bool = False  # for kind=angle
    aliases: tuple = ()
    #: for mask params: selector spec, e.g. ("-fe", "L-wide") or
    #: ("mjd", 50000.0, 51000.0) or ("tel", "gbt")
    select: tuple = ()
    uncertainty: Optional[float] = None
    #: raw par-file string (kept for exact round-trip of unfit params)
    raw: Optional[str] = None
    #: optional per-parameter prior (an object with lnpdf(x), e.g.
    #: bayesian.UniformPrior/NormalPrior; reference: each Parameter
    #: carries a Prior used by BayesianTiming and MCMC walker init)
    prior: Optional[object] = None

    def parse(self, s: str) -> float:
        if self.kind == "angle":
            return parse_angle(s, self.hourangle)
        if self.kind == "mjd":
            return float(mjd_value_to_ticks(s)) / 2**32  # f64 seconds
        if self.kind == "bool":
            return float(parse_bool(s))
        s2 = s.upper().replace("D", "E") if re.search(r"\dD[+-]?\d", s.upper()) else s
        v = float(s2)
        if self.unit_scale and abs(v) > self.scale_threshold:
            v *= self.scale_factor
        return v * self.scale

    def parse_uncertainty(self, s: str) -> float:
        """Par-file uncertainty token -> internal units.  Float kinds get
        the full value treatment (D exponents, tempo unit_scale keyed on
        the uncertainty's own magnitude — matching the reference, where
        floatParameter shares one codec for value and uncertainty); other
        kinds scale linearly."""
        if self.kind == "float":
            return self.parse(s)
        return float(s.upper().replace("D", "E")) * self.scale

    def format(self, value: float, ndigits=15) -> str:
        if self.kind == "angle":
            return format_angle(value, self.hourangle)
        if self.kind == "mjd":
            from pint_tpu.time.mjd import ticks_to_mjd_string_tdb

            return ticks_to_mjd_string_tdb(int(round(value * 2**32)), 12)
        if self.kind == "bool":
            return "Y" if value else "N"
        if self.scale != 1.0:
            # float() first: repr of a numpy-2 scalar is
            # 'np.float64(...)', which no par parser reads back
            return repr(float(value) / self.scale)
        if ndigits >= 15:
            # shortest round-trip repr: %.15g drops the last 1-2
            # significant bits (an F0 would come back changed after
            # as_parfile -> get_model; caught by the fuzz harness)
            return repr(float(value))
        return f"{value:.{ndigits}g}"



class funcParameter:
    """Read-only derived parameter (reference: parameter.py:2373
    funcParameter): computed on demand from other model values.

    func(*vals) -> float, with ``depends`` naming the source params.
    Attach with ``model.add_func_param(...)`` and read through
    ``model.func_value(name)`` (or the attribute-style accessor the
    model exposes)."""

    def __init__(self, name, func, depends, description="", units=""):
        self.name = name
        self.func = func
        self.depends = tuple(depends)
        self.description = description
        self.units = units
        self.frozen = True
        self.fittable = False

    def value(self, model):
        return self.func(*(model.values[d] for d in self.depends))


class pairParameter:
    """A two-component parameter (reference: parameter.py:2196
    pairParameter, e.g. WAVEn sine/cosine pairs): parsed/written as two
    tokens, stored as component values ``NAME_A``/``NAME_B`` in the
    model values dict."""

    def __init__(self, name, description="", units=""):
        self.name = name
        self.description = description
        self.units = units
        self.frozen = True
        self.fittable = False

    @property
    def component_names(self):
        return (f"{self.name}_A", f"{self.name}_B")

    def parse_pair(self, tokens):
        a = float(str(tokens[0]).upper().replace("D", "E"))
        b = float(str(tokens[1]).upper().replace("D", "E")) \
            if len(tokens) > 1 else 0.0
        return a, b

    def format_pair(self, a, b):
        return f"{a!r} {b!r}"
