"""Legacy Wave sinusoid series and IFunc tabulated phase offsets.

Counterparts of the reference components (reference:
src/pint/models/wave.py:10 ``wave_phase``, src/pint/models/ifunc.py:10
``ifunc_phase``).  Both are phase components adding ``F0 * offset_sec``
turns, where offset_sec is a sinusoid series (Wave) or an interpolation
of tabulated (MJD, sec) points (IFunc).

Par-file forms are *pair-valued* lines (``WAVE1 a b``, ``IFUNC1 mjd
val [err]``), consumed via the component ``consume_parfile`` hook.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import SECS_PER_DAY
from pint_tpu.models.component import PhaseComponent
from pint_tpu.models.parameter import Param, mjd_value_to_ticks, prefix_index


class Wave(PhaseComponent):
    """Sinusoid-series timing-noise decomposition:
    phase = F0 * sum_k [a_k sin(k w tau) + b_k cos(k w tau)],
    w = WAVE_OM rad/day, tau = t - WAVEEPOCH - delay in days."""

    register = True
    category = "wave"
    trigger_params = ("WAVE_OM",)

    def __init__(self, num_terms=0):
        super().__init__()
        self.num_terms = num_terms
        self.add_param(Param("WAVE_OM", units="rad/d",
                             description="Base frequency of wave solution"))
        self.add_param(Param("WAVEEPOCH", kind="mjd", fittable=False,
                             description="Reference epoch of wave solution"))
        for k in range(1, num_terms + 1):
            self.add_param(Param(f"WAVE{k}A", units="s",
                                 description=f"Wave {k} sine amp"))
            self.add_param(Param(f"WAVE{k}B", units="s",
                                 description=f"Wave {k} cosine amp"))

    @classmethod
    def from_parfile(cls, pardict):
        n = 0
        for key in pardict:
            pi = prefix_index(key)
            if pi and pi[0] == "WAVE" and not key.startswith("WAVE_"):
                n = max(n, pi[1])
        return cls(num_terms=n)

    def defaults(self):
        d = {}
        for k in range(1, self.num_terms + 1):
            d[f"WAVE{k}A"] = 0.0
            d[f"WAVE{k}B"] = 0.0
        d["WAVEEPOCH"] = np.nan
        return d

    def consume_parfile(self, pardict, model):
        consumed = set()
        for k in range(1, self.num_terms + 1):
            key = f"WAVE{k}"
            if key in pardict and pardict[key][0]:
                toks = pardict[key][0]
                model.values[f"WAVE{k}A"] = float(toks[0].replace("D", "E"))
                if len(toks) > 1:
                    model.values[f"WAVE{k}B"] = float(
                        toks[1].replace("D", "E")
                    )
                consumed.add(key)
        return consumed

    def parfile_lines(self, model):
        lines = []
        handled = set()
        for k in range(1, self.num_terms + 1):
            a = float(model.values.get(f"WAVE{k}A", 0.0))
            b = float(model.values.get(f"WAVE{k}B", 0.0))
            lines.append(f"WAVE{k}         {a!r} {b!r}")
            handled |= {f"WAVE{k}A", f"WAVE{k}B"}
        return lines, handled

    def prepare(self, toas, model):
        ep = model.values.get("WAVEEPOCH", np.nan)
        if np.isnan(ep):
            ep = model.values.get("PEPOCH", 0.0)
        t = toas.ticks.astype(np.float64) / 2**32
        return {"t_days": jnp.asarray((t - ep) / SECS_PER_DAY)}

    def phase(self, values, batch, ctx, delay):
        if not self.num_terms:
            return jnp.zeros_like(ctx["t_days"])
        tau = ctx["t_days"] - delay / SECS_PER_DAY
        base = values["WAVE_OM"] * tau
        sec = jnp.zeros_like(tau)
        for k in range(1, self.num_terms + 1):
            arg = k * base
            sec = sec + values[f"WAVE{k}A"] * jnp.sin(arg)
            sec = sec + values[f"WAVE{k}B"] * jnp.cos(arg)
        return sec * values["F0"]

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        """Sine/cosine amplitudes are linear; WAVE_OM (inside the trig
        argument) stays nonlinear."""
        out = []
        for k in range(1, self.num_terms + 1):
            out += [f"WAVE{k}A", f"WAVE{k}B"]
        return tuple(out)

    def d_phase_d_param(self, values, batch, ctx, delay, name):
        tau = ctx["t_days"] - delay / SECS_PER_DAY
        k = int(name[4:-1])
        arg = k * (values["WAVE_OM"] * tau)
        trig = jnp.sin(arg) if name.endswith("A") else jnp.cos(arg)
        return trig * values["F0"]


class IFunc(PhaseComponent):
    """Tabulated phase offsets: phase = F0 * interp(t) with SIFUNC type
    0 (preceding-point/piecewise-constant) or 2 (linear); the reference's
    type-0 tempo2 convention (ifunc.py:10-148).  Points are static data
    (not fittable), matching the reference's pairParameters."""

    register = True
    category = "ifunc"
    trigger_params = ("SIFUNC",)

    def __init__(self, num_terms=0):
        super().__init__()
        self.num_terms = num_terms
        self.add_param(Param("SIFUNC", units="", fittable=False,
                             description="IFunc interpolation type (0|2)"))
        #: (mjd_tdb_float, offset_sec) points, set by consume_parfile
        self.points = np.zeros((0, 2))

    @classmethod
    def from_parfile(cls, pardict):
        n = 0
        for key in pardict:
            pi = prefix_index(key)
            if pi and pi[0] == "IFUNC":
                n = max(n, pi[1])
        return cls(num_terms=n)

    def defaults(self):
        return {"SIFUNC": 2.0}

    def consume_parfile(self, pardict, model):
        consumed = set()
        pts = []
        for k in range(1, self.num_terms + 1):
            key = f"IFUNC{k}"
            if key in pardict and len(pardict[key][0]) >= 2:
                toks = pardict[key][0]
                mjd_sec = mjd_value_to_ticks(toks[0]) / 2**32
                pts.append((mjd_sec / SECS_PER_DAY + 51544.5,
                            float(toks[1])))
                consumed.add(key)
        self.points = np.array(sorted(pts)) if pts else np.zeros((0, 2))
        return consumed

    def parfile_lines(self, model):
        itype = int(round(model.values.get("SIFUNC", 2.0)))
        lines = [f"SIFUNC          {itype} {self.points.shape[0]}"]
        for k, (mjd, sec) in enumerate(self.points, start=1):
            lines.append(
                f"IFUNC{k}         {float(mjd)!r} {float(sec)!r} 0"
            )
        return lines, {"SIFUNC"}

    def prepare(self, toas, model):
        t = toas.ticks.astype(np.float64) / 2**32
        return {
            "t_mjd": jnp.asarray(t / SECS_PER_DAY + 51544.5),
            "x": jnp.asarray(self.points[:, 0]),
            "y": jnp.asarray(self.points[:, 1]),
            # static: the interpolation type selects python control flow
            "itype": int(round(model.values.get("SIFUNC", 2.0))),
        }

    def phase(self, values, batch, ctx, delay):
        if self.points.shape[0] == 0:
            return jnp.zeros_like(ctx["t_mjd"])
        ts = ctx["t_mjd"] - delay / SECS_PER_DAY
        itype = ctx["itype"]
        x, y = ctx["x"], ctx["y"]
        if itype == 0:
            # nearest *preceding* tabulated point (tempo2 convention);
            # TOAs before the first point take the first value
            idx = jnp.clip(
                jnp.searchsorted(x, ts, side="right") - 1, 0, x.shape[0] - 1
            )
            sec = y[idx]
        elif itype == 2:
            sec = jnp.interp(ts, x, y)
        else:
            raise ValueError(f"SIFUNC type {itype} not supported (0|2)")
        return sec * values["F0"]
