"""Troposphere delay: Davis zenith hydrostatic delay x Niell mapping.

Counterpart of the reference TroposphereDelay (reference:
src/pint/models/troposphere_delay.py:16-369): zenith hydrostatic delay
from surface pressure (Davis et al. 1985 App. A; pressure from the US
Standard Atmosphere altitude law), scaled to the line of sight by the
Niell (1996, Eq. 4) continued-fraction mapping function with latitude
interpolation and annual variation; wet zenith delay is zero (the
reference's and tempo2's default) but the wet Niell map is implemented.

TPU design: the component has no fittable parameters, and the delay
depends only on static geometry (site location, source altitude, day of
year), so the whole delay vector is computed host-side in ``prepare``
with numpy and enters the jit closure as a constant — zero device cost.
Altitude comes from the site's geodetic zenith rotated ITRF->GCRS by our
own earth-rotation chain (pint_tpu.obs.erot) dotted with the pulsar
direction, replacing the reference's astropy AltAz transform.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import SECS_PER_DAY
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import Param

# Niell (1996) hydrostatic coefficients at LAT = 0,15,30,45,60,75,90 deg
# (values duplicated at the poles/equator for constant extrapolation
# within 15 degrees, as the reference does in __init__)
_LAT_DEG = np.array([0.0, 15.0, 30.0, 45.0, 60.0, 75.0, 90.0])
_A_AVG = np.array([1.2769934, 1.2769934, 1.2683230, 1.2465397, 1.2196049,
                   1.2045996, 1.2045996]) * 1e-3
_B_AVG = np.array([2.9153695, 2.9153695, 2.9152299, 2.9288445, 2.9022565,
                   2.9024912, 2.9024912]) * 1e-3
_C_AVG = np.array([62.610505, 62.610505, 62.837393, 63.721774, 63.824265,
                   64.258455, 64.258455]) * 1e-3
_A_AMP = np.array([0.0, 0.0, 1.2709626, 2.6523662, 3.4000452, 4.1202191,
                   4.1202191]) * 1e-5
_B_AMP = np.array([0.0, 0.0, 2.1414979, 3.0160779, 7.2562722, 11.723375,
                   11.723375]) * 1e-5
_C_AMP = np.array([0.0, 0.0, 9.0128400, 4.3497037, 84.795348, 170.37206,
                   170.37206]) * 1e-5
_A_HT, _B_HT, _C_HT = 2.53e-5, 5.49e-3, 1.14e-3
# wet-map coefficients
_AW = np.array([5.8021897, 5.8021897, 5.6794847, 5.8118019, 5.9727542,
                6.1641693, 6.1641693]) * 1e-4
_BW = np.array([1.4275268, 1.4275268, 1.5138625, 1.4572752, 1.5007428,
                1.7599082, 1.7599082]) * 1e-3
_CW = np.array([4.3472961, 4.3472961, 4.6729510, 4.3908931, 4.4626982,
                5.4736038, 5.4736038]) * 1e-2

_DOY_OFFSET = -28.0  # phase of the annual term
_EARTH_R_M = 6356766.0  # earth radius at 45 deg latitude
_C_M_S = 299792458.0

# WGS84 ellipsoid
_WGS84_A = 6378137.0
_WGS84_F = 1.0 / 298.257223563


def itrf_to_geodetic(xyz_m):
    """ITRF xyz [m] -> (lat_rad, lon_rad, height_m), WGS84 (Bowring)."""
    x, y, z = xyz_m
    lon = np.arctan2(y, x)
    e2 = _WGS84_F * (2.0 - _WGS84_F)
    b = _WGS84_A * (1.0 - _WGS84_F)
    ep2 = e2 / (1.0 - e2)
    p = np.hypot(x, y)
    theta = np.arctan2(z * _WGS84_A, p * b)
    lat = np.arctan2(
        z + ep2 * b * np.sin(theta) ** 3,
        p - e2 * _WGS84_A * np.cos(theta) ** 3,
    )
    n = _WGS84_A / np.sqrt(1.0 - e2 * np.sin(lat) ** 2)
    h = p / np.cos(lat) - n
    return lat, lon, h


def _herring_map(sin_alt, a, b, c):
    """Niell 1996 Eq. 4 continued fraction, normalized to 1 at zenith."""
    top = 1.0 + a / (1.0 + b / (1.0 + c))
    bottom = sin_alt + a / (sin_alt + b / (sin_alt + c))
    return top / bottom


def _interp_lat(lat_rad, table, year_frac, amp_table=None):
    """Coefficient at |lat| with annual variation, linear in latitude."""
    absl = np.rad2deg(abs(lat_rad))
    avg = np.interp(absl, _LAT_DEG, table)
    if amp_table is None:
        return avg
    amp = np.interp(absl, _LAT_DEG, amp_table)
    return avg + amp * np.cos(2.0 * np.pi * year_frac)


def zenith_hydrostatic_delay_s(lat_rad, height_m):
    """Davis et al. 1985 zenith delay [s] from standard-atmosphere
    pressure at the site altitude (reference: troposphere_delay.py
    ``zenith_delay`` + ``pressure_from_altitude``)."""
    gph = _EARTH_R_M * height_m / (_EARTH_R_M + height_m)
    if gph > 11000.0:
        raise ValueError("pressure model invalid above 11 km")
    temp = 288.15 - 0.0065 * height_m
    p_kpa = 101.325 * (288.15 / temp) ** -5.25575
    return (p_kpa / 43.921) / (
        _C_M_S
        * (1.0 - 0.00266 * np.cos(2.0 * lat_rad)
           - 0.00028 * height_m / 1000.0)
    )


def niell_hydrostatic_map(sin_alt, lat_rad, height_m, year_frac):
    a = _interp_lat(lat_rad, _A_AVG, year_frac, _A_AMP)
    b = _interp_lat(lat_rad, _B_AVG, year_frac, _B_AMP)
    c = _interp_lat(lat_rad, _C_AVG, year_frac, _C_AMP)
    base = _herring_map(sin_alt, a, b, c)
    fcorr = _herring_map(sin_alt, _A_HT, _B_HT, _C_HT)
    return base + (1.0 / sin_alt - fcorr) * height_m / 1000.0


def niell_wet_map(sin_alt, lat_rad):
    a = _interp_lat(lat_rad, _AW, None)
    b = _interp_lat(lat_rad, _BW, None)
    c = _interp_lat(lat_rad, _CW, None)
    return _herring_map(sin_alt, a, b, c)


class TroposphereDelay(DelayComponent):
    register = True
    category = "troposphere"
    trigger_params = ("CORRECT_TROPOSPHERE",)

    def __init__(self):
        super().__init__()
        self.add_param(Param("CORRECT_TROPOSPHERE", kind="bool",
                             fittable=False,
                             description="Enable troposphere delay"))

    def build_params(self, pardict):
        pass

    def defaults(self):
        return {"CORRECT_TROPOSPHERE": 1.0}

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import psr_dir_static
        from pint_tpu.obs import TopoObs, get_observatory
        from pint_tpu.obs.erot import gcrs_posvel_from_itrf

        delay = np.zeros(len(toas))
        if not model.values.get("CORRECT_TROPOSPHERE", 1.0):
            return {"delay": jnp.asarray(delay)}
        n_psr = psr_dir_static(model)
        t_mjd_tdb = (
            toas.ticks.astype(np.float64) / 2**32 / SECS_PER_DAY + 51544.5
        )
        for oname in set(toas.obs_names):
            obs = get_observatory(oname)
            if not isinstance(obs, TopoObs):
                continue  # troposphere only for ground sites
            m = np.array([o == oname for o in toas.obs_names])
            lat, lon, height = itrf_to_geodetic(obs.itrf_xyz)
            # geodetic zenith in ITRF, rotated to GCRS at each TOA (the
            # rotation is linear, so feed the unit vector through the
            # same ITRF->GCRS chain used for positions)
            zen_itrf = np.array(
                [np.cos(lat) * np.cos(lon), np.cos(lat) * np.sin(lon),
                 np.sin(lat)]
            )
            zen_gcrs = gcrs_posvel_from_itrf(
                zen_itrf, toas.ticks[m]
            ).pos
            zen_gcrs /= np.linalg.norm(zen_gcrs, axis=-1, keepdims=True)
            sin_alt = zen_gcrs @ n_psr
            # below-horizon TOAs (bad coordinates): delay -> 0, like the
            # reference's _validate_altitudes
            valid = sin_alt > 0.0
            sa = np.where(valid, sin_alt, 1.0)
            season = 0.5 if lat < 0 else 0.0
            yf = np.mod(
                2000.0 + (t_mjd_tdb[m] - 51544.5 + _DOY_OFFSET) / 365.25
                + season,
                1.0,
            )
            d = zenith_hydrostatic_delay_s(lat, height) * \
                niell_hydrostatic_map(sa, lat, height, yf)
            # wet zenith delay is 0 (tempo2 default) => no wet term
            delay[m] = np.where(valid, d, 0.0)
        return {"delay": jnp.asarray(delay)}

    def delay(self, values, batch, ctx, delay_accum):
        return ctx["delay"]
