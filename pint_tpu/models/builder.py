"""Par-file parsing and model construction.

Counterpart of the reference ModelBuilder (reference:
src/pint/models/model_builder.py:59 ``parse_parfile``, :435
``choose_model``, :777 ``get_model``, :859 ``get_model_and_toas``):
tokenize the par file, select components by their trigger parameters
(component classes self-register, so user components participate
automatically), instantiate concrete prefix/mask families, set values,
and record exact epoch ticks for precision-critical epochs.
"""

from __future__ import annotations

import os
import re
import warnings
from typing import Dict, List, Tuple

import numpy as np

from pint_tpu.models import component as _component  # noqa: F401
from pint_tpu.models.component import Component, parse_mask_select
from pint_tpu.models.parameter import Param, mjd_value_to_ticks
from pint_tpu.models.timing_model import TimingModel

# import builtin components so they register
from pint_tpu.models.absolute_phase import AbsPhase, PhaseOffset  # noqa: F401
from pint_tpu.models.astrometry import (  # noqa: F401
    AstrometryEcliptic,
    AstrometryEquatorial,
)
from pint_tpu.models.dispersion import (  # noqa: F401
    DispersionDM,
    DispersionDMX,
    DispersionJump,
)
from pint_tpu.models.jump import PhaseJump  # noqa: F401
from pint_tpu.models.noise import (  # noqa: F401
    EcorrNoise,
    PLBandNoise,
    PLChromNoise,
    PLDMNoise,
    PLRedNoise,
    PLSystemNoise,
    ScaleDmError,
    ScaleToaError,
)
from pint_tpu.models.solar_system_shapiro import SolarSystemShapiro  # noqa: F401
from pint_tpu.models.spindown import Spindown  # noqa: F401
from pint_tpu.models.wavex import CMWaveX, DMWaveX, WaveX  # noqa: F401
from pint_tpu.models.wave import IFunc, Wave  # noqa: F401
from pint_tpu.models.glitch import Glitch, PiecewiseSpindown  # noqa: F401
from pint_tpu.models.chromatic import ChromaticCM, ChromaticCMX  # noqa: F401
from pint_tpu.models.fd import FD, FDJump, FDJumpDM  # noqa: F401
from pint_tpu.models.solar_wind import (  # noqa: F401
    SolarWindDispersion,
    SolarWindDispersionX,
)
from pint_tpu.models.troposphere import TroposphereDelay  # noqa: F401
import pint_tpu.models.binary  # noqa: F401  (registers binary families)

__all__ = ["parse_parfile", "get_model", "get_model_and_toas",
           "model_to_parfile"]

#: par keys that are model metadata, not fit parameters
_META_KEYS = {
    "PSR", "PSRJ", "PSRB", "EPHEM", "CLK", "CLOCK", "UNITS", "TIMEEPH",
    "T2CMETHOD", "DILATEFREQ", "NTOA", "TRES",
    "CHI2", "CHI2R", "TZRSITE", "INFO", "BINARY", "START", "FINISH",
    "DMDATA", "MODE", "EPHVER", "NITS",
    "IBOOT", "DMX", "TRACK",
}

#: parameter-name aliases -> canonical (reference: each Param's aliases +
#: model_builder._pintify_parfile)
_ALIASES = {
    "E": "ECC",
    "PSRJ": "PSR",
    "PSRB": "PSR",
    "LAMBDA": "ELONG",
    "BETA": "ELAT",
    "PMLAMBDA": "PMELONG",
    "PMBETA": "PMELAT",
    "A1DOT": "XDOT",
    # noise mask-parameter aliases (reference noise_model.py:60-79,355)
    "T2EFAC": "EFAC",
    "TNEF": "EFAC",
    "T2EQUAD": "EQUAD",
    "TNECORR": "ECORR",
    "NE1AU": "NE_SW",
    "SOLARN0": "NE_SW",
}

#: tempo2 writes "FDJUMPp"; internally the mask family key is "FDpJUMP"
_FDJUMP_RE = re.compile(r"^FD(\d+)JUMP$")
_FDJUMP_ALT_RE = re.compile(r"^FDJUMP(\d+)$")

#: mask-parameter families: "KEY selector value [fit [unc]]" par lines
#: (reference maskParameter, parameter.py:1782)
_MASK_KEYS = (
    "JUMP", "DMJUMP", "EFAC", "EQUAD", "TNEQ", "ECORR",
    "DMEFAC", "DMEQUAD", "FDJUMPDM",
    "TNBANDAMP", "TNBANDGAM", "TNSYSAMP", "TNSYSGAM",
)


def parse_parfile(path_or_text: str) -> Dict[str, List[List[str]]]:
    """Tokenize a par file: {KEY: [tokens-after-key, ...]} (repeats kept,
    e.g. multiple JUMP lines; reference model_builder.py:59)."""
    if "\n" in path_or_text:
        text = path_or_text
    elif os.path.exists(path_or_text):
        with open(path_or_text) as f:
            text = f.read()
    else:
        # a single line without newline is a path, not par text — a typo'd
        # filename must not be silently tokenized as parameters
        raise FileNotFoundError(f"par file not found: {path_or_text!r}")
    out: Dict[str, List[List[str]]] = {}
    for raw in text.splitlines():
        line = raw.split("#")[0].rstrip()
        if not line.strip() or line.startswith(("C ", "c ")):
            continue
        tokens = line.split()
        key = tokens[0].upper()
        out.setdefault(key, []).append(tokens[1:])
    return out


def _canonical(key: str) -> str:
    return _ALIASES.get(key, key)


def choose_components(pardict) -> List[type]:
    """Select component classes whose trigger params appear."""
    keys = set(pardict)
    chosen = []
    for name, cls in Component.registry.items():
        trig = cls.trigger_params
        hit = False
        for t in trig:
            if t in keys:
                hit = True
            # prefix triggers: DMX matches DMX_0001 etc.
            elif any(k.startswith(t + "_") or
                     (k.startswith(t) and k[len(t):].isdigit())
                     for k in keys):
                hit = True
        if hit:
            chosen.append(cls)
    # StandardTimingModel always includes solar-system Shapiro
    if SolarSystemShapiro not in chosen:
        chosen.append(SolarSystemShapiro)
    return chosen


def get_model(parfile, allow_tcb=False, allow_T2=False) -> TimingModel:
    """Build a TimingModel from a par file (path or text).

    ``allow_tcb=True`` converts a ``UNITS TCB`` par to TDB on the fly
    (approximate — re-fit afterwards; reference: model_builder allow_tcb
    + tcb_conversion.convert_tcb_tdb).  ``allow_T2=True`` maps a Tempo2
    ``BINARY T2`` par onto the best-covering concrete binary model
    (reference allow_T2 / guess_binary_model)."""
    if allow_tcb:
        if os.path.exists(str(parfile)) and "\n" not in str(parfile):
            with open(parfile) as f:
                text = f.read()
        else:
            text = str(parfile)
        toks = parse_parfile(text).get("UNITS", [[""]])
        if toks and toks[0] and toks[0][0].upper() == "TCB":
            from pint_tpu.models.tcb import convert_parfile_tcb_tdb

            warnings.warn(
                "converting TCB par file to TDB; the conversion is "
                "approximate — re-fit the resulting model"
            )
            parfile = convert_parfile_tcb_tdb(text)
    pardict_raw = parse_parfile(parfile)
    # canonicalize keys
    pardict: Dict[str, List[List[str]]] = {}
    for k, v in pardict_raw.items():
        m = _FDJUMP_ALT_RE.match(k)
        if m:  # tempo2 "FDJUMPp" spelling -> internal "FDpJUMP"
            k = f"FD{m.group(1)}JUMP"
        pardict.setdefault(_canonical(k), []).extend(v)

    units = (pardict.get("UNITS", [["TDB"]])[0] or ["TDB"])[0].upper()
    if units not in ("TDB", ""):
        raise NotImplementedError(
            f"UNITS {units} not supported directly; pass allow_tcb=True "
            "to convert a TCB par file on the fly"
        )
    if "BINARY" in pardict:
        from pint_tpu.models.binary import get_binary_class

        bname = pardict["BINARY"][0][0]
        if bname.upper() == "T2":
            if not allow_T2:
                raise NotImplementedError(
                    "BINARY T2 is a Tempo2 meta-model; pass allow_T2="
                    "True (or run t2binary2pint) to map it onto the "
                    "best concrete model")
            pardict, chosen_name = convert_t2_binary(pardict)
            warnings.warn(
                f"BINARY T2 mapped onto {chosen_name} "
                "(reference guess_binary_model semantics)")
        else:
            get_binary_class(bname)  # raises if unknown

    # mask-parameter selectors must exist before component instantiation
    mask_keys = list(_MASK_KEYS) + [
        k for k in pardict if _FDJUMP_RE.match(k)
    ]
    masks: Dict[str, list] = {}
    for key in mask_keys:
        for tokens in pardict.get(key, []):
            sel, rest = parse_mask_select(tokens)
            masks.setdefault(key, []).append((sel, rest))
    if masks:
        pardict["__MASKS__"] = masks  # type: ignore

    model = TimingModel(name=str(parfile)[:120])
    chosen = choose_components(pardict)
    if any(_FDJUMP_RE.match(k) for k in masks):
        chosen.append(FDJump)
    if "BINARY" in pardict:
        from pint_tpu.models.binary import get_binary_class

        chosen.append(get_binary_class(pardict["BINARY"][0][0]))
    for cls in chosen:
        comp = cls.from_parfile(pardict)
        model.add_component(comp)

    model.epoch_ticks = {}
    params = model.params
    # component-declared aliases (VARSIGMA->STIGMA, DTHETA->DTH, ...)
    # resolved after instantiation, since only concrete components know
    # their parameter families
    alias_map = {}
    for p in params.values():
        for a in p.aliases:
            alias_map.setdefault(a, p.name)
    consumed = set()
    for key, occurrences in pardict.items():
        if key.startswith("__"):
            consumed.add(key)
            continue
        if key in _META_KEYS:
            model.meta[key] = " ".join(occurrences[0])
            consumed.add(key)
            continue
        if key in mask_keys:
            consumed.add(key)
            continue
        pname = key if key in params else alias_map.get(key)
        p = params.get(pname) if pname else None
        if p is None:
            continue
        tokens = occurrences[0]
        if not tokens:
            continue
        p.raw = tokens[0]
        model.values[pname] = p.parse(tokens[0])
        if p.kind == "mjd":
            model.epoch_ticks[pname] = mjd_value_to_ticks(tokens[0])
        if len(tokens) > 1 and p.fittable:
            if tokens[1] in ("1", "2"):
                p.frozen = False
            if len(tokens) > 2:
                try:
                    p.uncertainty = p.parse_uncertainty(tokens[2])
                except ValueError:
                    pass
        consumed.add(key)

    # mask-parameter values: KEYn in file order (JUMP1, EFAC2, ...)
    for key, entries in masks.items():
        for i, (_sel, rest) in enumerate(entries, start=1):
            name = f"{key}{i}"
            if name in params and rest:
                model.values[name] = params[name].parse(rest[0])
                if len(rest) > 1 and rest[1] in ("1", "2"):
                    params[name].frozen = False
                if len(rest) > 2:
                    try:
                        params[name].uncertainty = (
                            params[name].parse_uncertainty(rest[2])
                        )
                    except ValueError:
                        pass

    # pair-valued and other component-specific par lines (WAVEn, IFUNCn)
    for comp in model.components:
        hook = getattr(comp, "consume_parfile", None)
        if hook is not None:
            consumed |= set(hook(pardict, model))

    unknown = [
        k for k in pardict
        if k not in consumed and not k.startswith("__")
    ]
    # informational per-window companions of DMX ranges: tempo writes
    # them, nothing fits them; the reference drops them *silently*
    # (reference timing_model.py:105 ignore_prefix), so a NANOGrav par
    # must not print a 200-name warning here.  Still carried as
    # metadata for round-tripping.
    _SILENT_PREFIXES = ("DMXEP_", "DMXF1_", "DMXF2_")
    noisy = [k for k in unknown if not k.startswith(_SILENT_PREFIXES)]
    if noisy:
        warnings.warn(
            f"par parameters not (yet) supported, carried as metadata: "
            f"{sorted(noisy)}"
        )
    for k in unknown:
        model.meta.setdefault("__unknown__", {})[k] = pardict[k]

    # sanity: a timing model needs a spin frequency
    if not model.has_component("Spindown") or np.isnan(
        model.values.get("F0", np.nan)
    ):
        raise ValueError("par file lacks F0 (no spindown model)")
    # sanity: astrometry needs a complete position — a par carrying
    # ELONG without ELAT (or RAJ without DECJ) would otherwise produce
    # silently-NaN residuals (reference: MissingParameter from
    # Astrometry.validate)
    for a, b in (("RAJ", "DECJ"), ("ELONG", "ELAT")):
        have_a = not np.isnan(model.values.get(a, np.nan))
        have_b = not np.isnan(model.values.get(b, np.nan))
        if have_a != have_b:
            missing = b if have_a else a
            raise ValueError(
                f"par file sets {a if have_a else b} but not {missing}: "
                "incomplete sky position")
    return model


#: priority order for T2 binary-model guessing (reference
#: model_builder.py:40 _binary_model_priority)
_BINARY_PRIORITY = ["BT", "ELL1", "ELL1H", "ELL1K", "DD", "DDK",
                    "DDGR", "DDS", "DDH"]


def guess_binary_model(pardict):
    """Priority-ordered candidate binary models for a Tempo2 ``BINARY
    T2`` par (reference: guess_binary_model, model_builder.py:970):
    every model whose parameter set covers the par's binary-looking
    parameters, best guess first."""
    from pint_tpu.models.binary import get_binary_class
    from pint_tpu.models.component import BINARY_MODELS

    model_params = {}
    all_binary_params = set()
    for name in _BINARY_PRIORITY:
        if name not in BINARY_MODELS:
            continue
        comp = get_binary_class(name)()
        comp.build_params(pardict)  # params materialize lazily
        names = set()
        for p in comp.params:
            names.add(p.name)
            names.update(a.upper() for a in p.aliases)
        # FBn / orbital-frequency family and common tempo2 extras
        names.update(f"FB{i}" for i in range(10))
        if "KIN" in names:
            names.add("SINI")  # tempo2 T2+KIN convention
        model_params[name] = names
        all_binary_params |= names
    in_par = {k for k in pardict if k in all_binary_params}
    ranked = [name for name in _BINARY_PRIORITY
              if name in model_params
              and not (in_par - model_params[name])]
    if not ranked:
        raise ValueError(
            "no implemented binary model covers the par's binary "
            f"parameters {sorted(in_par)}")
    return ranked


def convert_t2_binary(pardict):
    """Rewrite a ``BINARY T2`` par dict to the best concrete model
    (reference: the allow_T2 path of ModelBuilder.choose_binary_model).
    Returns (new_pardict, chosen_model_name)."""
    chosen = guess_binary_model(pardict)[0]
    out = dict(pardict)
    out["BINARY"] = [[chosen]]
    return out, chosen


def planets_requested(model) -> bool:
    """Whether the par requests planet Shapiro delays.  PLANET_SHAPIRO
    may land in meta (bare par keyword spelling) OR as the registered
    bool parameter in model.values — the one definition every TOA
    loader must use (reference: model.PLANET_SHAPIRO.value)."""
    return bool(
        model.meta.get("PLANET_SHAPIRO", "N").upper() in ("Y", "1", "TRUE")
    ) or bool(model.values.get("PLANET_SHAPIRO", 0.0))


def get_model_and_toas(parfile, timfile, **kw):
    from pint_tpu.toa import get_TOAs

    model = get_model(parfile)
    planets = planets_requested(model)
    ephem = model.meta.get("EPHEM", "builtin")
    # honor the par CLK realization: TT(BIPMxxxx) requests the BIPM
    # offsets (applied when tai2tt data is available; see
    # obs.clock.find_bipm_correction), TT(TAI)/UNCORR do not
    clk = (model.meta.get("CLK") or model.meta.get("CLOCK") or "").upper()
    if "BIPM" in clk and "include_bipm" not in kw:
        kw["include_bipm"] = True
        kw.setdefault("bipm_version",
                      clk.replace("TT(", "").replace(")", ""))
    toas = get_TOAs(timfile, ephem=ephem, planets=planets,
                    **kw)
    # tim-file JUMP command pairs became -tim_jump flags at parse time;
    # materialize JUMP parameters for them (reference get_model_and_toas
    # behavior via jump_flags_to_params)
    model.jump_flags_to_params(toas)
    return model, toas


def model_to_parfile(model: TimingModel) -> str:
    """Round-trip a model to par format."""
    lines = []
    lead = ("PSR", "EPHEM", "CLK", "UNITS", "TZRSITE")
    for k in lead:
        if k in model.meta:
            lines.append(f"{k:<15s} {model.meta[k]}")
    # remaining metadata (START/FINISH/NTOA/CHI2/TRES/DMDATA/...,
    # reference as_parfile includes the fit summary params,
    # timing_model.py:344-386)
    for k, v in model.meta.items():
        if k in lead or k.startswith("__"):
            continue
        lines.append(f"{k:<15s} {v}")
    # components with non-par-shaped params (pair lines WAVEn a b,
    # IFUNCn mjd val) serialize themselves and mark params handled
    handled = set()
    for comp in model.components:
        hook = getattr(comp, "parfile_lines", None)
        if hook is not None:
            extra, done = hook(model)
            lines.extend(extra)
            handled |= set(done)
    params = model.params
    for name, p in params.items():
        if name in handled:
            continue
        v = model.values.get(name, np.nan)
        if isinstance(v, float) and np.isnan(v):
            continue
        fit = "1" if not p.frozen else "0"
        unc = (
            f" {p.uncertainty / p.scale:.6g}"
            if p.uncertainty is not None
            else ""
        )
        if p.select:
            kind = p.select[0]
            if kind == "flag":
                sel = f"-{p.select[1]} {p.select[2]} "
            elif kind in ("mjd", "freq"):
                sel = f"{kind.upper()} {p.select[1]} {p.select[2]} "
            elif kind == "tel":
                sel = f"TEL {p.select[1]} "
            else:
                sel = ""
            base = re.sub(r"\d+$", "", name)
            lines.append(f"{base:<8s} {sel}{p.format(v)} {fit}{unc}")
        else:
            lines.append(f"{name:<15s} {p.format(v)} {fit}{unc}")
    return "\n".join(lines) + "\n"
