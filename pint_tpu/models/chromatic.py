"""Generic chromatic (nu^-alpha) delay: ChromaticCM Taylor series.

Counterpart of the reference ChromaticCM (reference:
src/pint/models/chromatic_model.py:113 ``chromatic_time_delay``:
delay = K * CM(t) * (nu/MHz)^-TNCHROMIDX with CM(t) a Taylor series
about CMEPOCH in pc cm^-3 MHz^(alpha-2) / yr^k).  The Fourier variant
CMWaveX lives in :mod:`pint_tpu.models.wavex`.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import DM_CONST
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import Param, prefix_index


class ChromaticCM(DelayComponent):
    register = True
    category = "chromatic"
    trigger_params = ("CM",)

    def __init__(self, num_cm_derivs=0):
        super().__init__()
        self.num_cm_derivs = num_cm_derivs
        self.add_param(Param("CM", units="pc cm^-3 MHz^(alpha-2)",
                             description="Chromatic measure"))
        for k in range(1, num_cm_derivs + 1):
            self.add_param(Param(f"CM{k}",
                                 units=f"pc cm^-3 MHz^(alpha-2)/yr^{k}",
                                 description=f"CM derivative {k}"))
        self.add_param(Param("CMEPOCH", kind="mjd", fittable=False,
                             description="Epoch of CM"))
        self.add_param(Param("TNCHROMIDX", units="", fittable=False,
                             description="Chromatic index alpha"))

    @classmethod
    def from_parfile(cls, pardict):
        n = 0
        for key in pardict:
            pi = prefix_index(key)
            if pi and pi[0] == "CM" and not key.startswith(
                ("CMWX", "CMEPOCH")
            ):
                n = max(n, pi[1])
        return cls(num_cm_derivs=n)

    def defaults(self):
        d = {f"CM{k}": 0.0 for k in range(1, self.num_cm_derivs + 1)}
        d["CM"] = 0.0
        d["CMEPOCH"] = np.nan
        d["TNCHROMIDX"] = 4.0
        return d

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import bary_freq_mhz

        ep = model.values.get("CMEPOCH", np.nan)
        if np.isnan(ep):
            ep = model.values.get("PEPOCH", 0.0)
        t = toas.ticks.astype(np.float64) / 2**32
        return {
            "dt_yr": jnp.asarray((t - ep) / (365.25 * 86400.0)),
            "bfreq": jnp.asarray(bary_freq_mhz(toas, model)),
        }

    def cm_at(self, values, ctx):
        cm = values["CM"]
        if self.num_cm_derivs:
            dt = ctx["dt_yr"]
            fact = 1.0
            power = dt
            for k in range(1, self.num_cm_derivs + 1):
                fact *= k
                cm = cm + values[f"CM{k}"] * power / fact
                power = power * dt
        return cm

    def delay(self, values, batch, ctx, delay_accum):
        cm = self.cm_at(values, ctx)
        return DM_CONST * cm * ctx["bfreq"] ** (-values["TNCHROMIDX"])


class ChromaticCMX(DelayComponent):
    """Piecewise chromatic-measure offsets over MJD ranges
    (CMX_####/CMXR1/CMXR2) — the nu^-alpha analogue of DispersionDMX
    (reference: chromatic_model.py ChromaticCMX), for scattering-delay
    epochs a Taylor CM series cannot track.

    delay = K * CMX(t) * bfreq^-TNCHROMIDX with CMX(t) the sum of the
    window amplitudes covering t.  alpha defaults to 4 (thin-screen
    scattering) and is shared with ChromaticCM when both are present.
    Each CMX amplitude is exactly linear in the delay, so every window
    gets an analytic hybrid design-matrix column."""

    category = "chromatic_cmx"
    trigger_params = ("CMX",)

    def __init__(self, indices=()):
        super().__init__()
        self.indices = tuple(indices)
        for i in self.indices:
            self.add_param(Param(f"CMX_{i:04d}",
                                 units="pc cm^-3 MHz^(alpha-2)",
                                 description=f"CM offset in range {i}"))
            self.add_param(Param(f"CMXR1_{i:04d}", kind="mjd",
                                 fittable=False,
                                 description=f"CMX range {i} start"))
            self.add_param(Param(f"CMXR2_{i:04d}", kind="mjd",
                                 fittable=False,
                                 description=f"CMX range {i} end"))
        self.add_param(Param("TNCHROMIDX", units="", fittable=False,
                             description="Chromatic index alpha"))

    @classmethod
    def from_parfile(cls, pardict):
        idx = sorted(
            {
                prefix_index(k)[1]
                for k in pardict
                if k.startswith("CMX_") and prefix_index(k)
            }
        )
        return cls(indices=idx)

    def defaults(self):
        d = {f"CMX_{i:04d}": 0.0 for i in self.indices}
        d["TNCHROMIDX"] = 4.0
        return d

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import bary_freq_mhz

        masks = []
        for i in self.indices:
            lo = model.values[f"CMXR1_{i:04d}"] / 86400.0 + 51544.5
            hi = model.values[f"CMXR2_{i:04d}"] / 86400.0 + 51544.5
            masks.append((toas.mjd_float >= lo) & (toas.mjd_float <= hi))
        m = (
            np.stack(masks, axis=0)
            if masks
            else np.zeros((0, len(toas)), dtype=bool)
        )
        return {
            "masks": jnp.asarray(m),
            "bfreq": jnp.asarray(bary_freq_mhz(toas, model)),
        }

    def cmx_at(self, values, ctx):
        if not self.indices:
            return jnp.zeros(ctx["bfreq"].shape)
        cmx = jnp.stack([values[f"CMX_{i:04d}"] for i in self.indices])
        return jnp.sum(ctx["masks"] * cmx[:, None], axis=0)

    def delay(self, values, batch, ctx, delay_accum):
        return DM_CONST * self.cmx_at(values, ctx) \
            * ctx["bfreq"] ** (-values["TNCHROMIDX"])

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return tuple(f"CMX_{i:04d}" for i in self.indices)

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        j = self.indices.index(int(name[4:]))
        return DM_CONST * ctx["masks"][j].astype(jnp.float64) \
            * ctx["bfreq"] ** (-values["TNCHROMIDX"])
