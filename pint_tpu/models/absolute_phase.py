"""Absolute phase reference (TZR) and explicit phase offset.

Counterpart of the reference AbsPhase (reference:
src/pint/models/absolute_phase.py:11-140 ``make_TZR_toa``) and PhaseOffset
(reference: src/pint/models/phase_offset.py:9-53).  The TZR TOA is built
once at prepare time as a single-element TOABatch and evaluated through
the *same* jitted chain (SURVEY hard part (a)).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.component import PhaseComponent
from pint_tpu.models.parameter import Param


class AbsPhase(PhaseComponent):
    category = "absolute_phase"
    trigger_params = ("TZRMJD",)

    def __init__(self):
        super().__init__()
        self.add_param(Param("TZRMJD", kind="mjd", fittable=False,
                             description="TZR reference epoch"))
        self.add_param(Param("TZRFRQ", units="MHz", fittable=False,
                             description="TZR reference frequency"))
        # TZRSITE is a string; kept in model.meta by the builder

    def build_params(self, pardict):
        pass

    def defaults(self):
        return {"TZRMJD": np.nan, "TZRFRQ": np.inf}

    def phase(self, values, batch, ctx, delay):
        # contributes nothing directly; the TZR batch subtraction happens
        # in PreparedModel._phase_raw via make_tzr_batch below
        return jnp.zeros_like(delay)

    def make_tzr_toas(self, model, toas):
        """Single-TOA TOAs at TZRMJD/TZRSITE/TZRFRQ through full ingest.
        Returned as a TOAs object so the PreparedModel can build a
        TZR-specific prepare ctx for every component."""
        from pint_tpu.time.mjd import ticks_to_mjd_string_tdb
        from pint_tpu.toa import TOA, TOAs

        tzr_sec = model.values.get("TZRMJD", np.nan)
        if np.isnan(tzr_sec):
            return None
        site = model.meta.get("TZRSITE", "@")
        freq = model.values.get("TZRFRQ", np.inf)
        if not np.isfinite(freq) or freq == 0.0:
            freq = 0.0  # ingest maps 0 -> inf
        # TZRMJD is in the TOA convention for its site (UTC at a topo
        # site, TDB at '@'), so feed the raw par string through the same
        # ingest path a .tim line takes
        raw = self.param("TZRMJD").raw
        if raw is None:
            raw = ticks_to_mjd_string_tdb(int(round(tzr_sec * 2**32)), 16)
        from pint_tpu.time.mjd import mjd_string_to_day_frac

        day, num, den = mjd_string_to_day_frac(raw)
        tzr = TOA(day, num, den, 0.0, freq, site, {}, name="TZR")
        out = TOAs([tzr], ephem=toas.ephem, planets=toas.planets)
        out.is_tzr = True  # lets components opt out at the TZR TOA
        return out


class PhaseOffset(PhaseComponent):
    """Explicit overall phase offset PHOFF (replaces implicit mean
    subtraction when present; reference phase_offset.py)."""

    category = "phase_offset"
    trigger_params = ("PHOFF",)

    def __init__(self):
        super().__init__()
        self.add_param(Param("PHOFF", units="turns",
                             description="Overall phase offset"))

    def build_params(self, pardict):
        pass

    def defaults(self):
        return {"PHOFF": 0.0}

    def prepare(self, toas, model):
        # PHOFF must NOT apply at the TZR TOA or it cancels out of the
        # TZR-referenced residuals entirely (reference phase_offset.py
        # returns 0 for the TZR TOA for exactly this reason)
        return {"apply": not getattr(toas, "is_tzr", False)}

    def phase(self, values, batch, ctx, delay):
        if not ctx["apply"]:
            return jnp.zeros_like(delay)
        return -values["PHOFF"] * jnp.ones_like(delay)

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return ("PHOFF",)

    def d_phase_d_param(self, values, batch, ctx, delay, name):
        if not ctx["apply"]:  # the TZR TOA opts out (prepare above)
            return jnp.zeros_like(delay)
        return -jnp.ones_like(delay)
