"""Astrometry: Roemer delay, parallax, proper motion (equatorial & ecliptic).

Counterpart of the reference Astrometry components (reference:
src/pint/models/astrometry.py:41,272,753 — ``solar_system_geometric_delay``
at :155-184, PM propagation ``ssb_to_psb_xyz_ICRS`` at :469-529).
All geometry is float64 on-device: the Roemer delay is ~500 s needing
~ns => 2e-12 relative, comfortably inside even TPU's sloppy f64.

Equatorial (RAJ/DECJ/PMRA/PMDEC/PX) and ecliptic (ELONG/ELAT/PMELONG/
PMELAT) variants share the delay; the ecliptic one rotates to ICRS by the
fixed J2000 obliquity (reference: pulsar_ecliptic.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import AU_LS, OBLIQUITY_J2000_ARCSEC, SECS_PER_JULIAN_YEAR
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import Param

#: mas/yr -> rad/s
_MASYR = np.deg2rad(1.0 / 3.6e6) / SECS_PER_JULIAN_YEAR
#: 1 kpc in light-seconds (IAU pc)
_KPC_LS = 3.0856775814913673e19 / 299792458.0


def _unit_vector(lon, lat):
    clat = jnp.cos(lat)
    return jnp.stack(
        [clat * jnp.cos(lon), clat * jnp.sin(lon), jnp.sin(lat)], axis=-1
    )


class AstrometryBase(DelayComponent):
    category = "astrometry"
    register = False

    def prepare(self, toas, model):
        posepoch = model.values.get("POSEPOCH", np.nan)
        if np.isnan(posepoch):
            posepoch = model.values.get("PEPOCH", 0.0)
        t_sec = toas.ticks.astype(np.float64) / 2**32
        return {"dt_pos": jnp.asarray(t_sec - posepoch)}

    def psr_dir(self, values, ctx):
        """Unit vector obs->pulsar in ICRS at each TOA (with PM)."""
        raise NotImplementedError

    def delay(self, values, batch, ctx, delay_accum):
        n = self.psr_dir(values, ctx)
        r = batch.ssb_obs_pos  # light-seconds
        roemer = -jnp.sum(n * r, axis=-1)
        # parallax: (|r|^2 - (r.n)^2) / (2 d).  PX in mas => d = 1/PX kpc,
        # so 1/d [ls^-1] = PX / _KPC_LS; term vanishes smoothly at PX=0.
        r2 = jnp.sum(r * r, axis=-1)
        rn = -roemer  # = (r.n)
        inv_d_ls = values["PX"] / _KPC_LS
        return roemer + 0.5 * (r2 - rn * rn) * inv_d_ls


class AstrometryEquatorial(AstrometryBase):
    register = True
    trigger_params = ("RAJ", "DECJ")

    def __init__(self):
        super().__init__()
        self.add_param(Param("RAJ", kind="angle", hourangle=True,
                             description="Right ascension (J2000)"))
        self.add_param(Param("DECJ", kind="angle",
                             description="Declination (J2000)"))
        self.add_param(Param("PMRA", units="mas/yr", scale=1.0,
                             description="Proper motion in RA*cos(DEC)"))
        self.add_param(Param("PMDEC", units="mas/yr",
                             description="Proper motion in DEC"))
        self.add_param(Param("PX", units="mas", description="Parallax"))
        self.add_param(Param("POSEPOCH", kind="mjd", fittable=False,
                             description="Epoch of position"))

    def build_params(self, pardict):
        pass

    def defaults(self):
        return {"PMRA": 0.0, "PMDEC": 0.0, "PX": 0.0, "POSEPOCH": np.nan}

    def psr_dir(self, values, ctx):
        dt = ctx["dt_pos"]
        ra = values["RAJ"]
        dec = values["DECJ"]
        cosdec = jnp.cos(dec)
        ra_t = ra + values["PMRA"] * _MASYR * dt / jnp.where(
            cosdec == 0, 1.0, cosdec
        )
        dec_t = dec + values["PMDEC"] * _MASYR * dt
        return _unit_vector(ra_t, dec_t)


#: Obliquity of the ecliptic, arcseconds, by par-file ``ECL`` label.
#: Published IAU/IERS constants (same set the reference ships as
#: runtime data ecliptic.dat and resolves in pulsar_ecliptic.py):
#: IAU1976 from Lieske (1977); IERS1992/DE403 from IERS TN 21 p.19;
#: IERS2003 from IERS TN 32 p.19 (tempo2's default); IERS2010/IAU2005
#: from IERS TN 36 p.19 / IAU 2006 Resolution 1.
OBLIQUITY_ARCSEC = {
    "IAU1976": 84381.448,
    "IERS1992": 84381.412,
    "DE403": 84381.412,
    "IERS2003": 84381.4059,
    "IERS2010": 84381.406,
    "IAU2005": 84381.406,
    "DEFAULT": OBLIQUITY_J2000_ARCSEC,
}


def eq_from_ecl_matrix(obliquity_arcsec: float) -> np.ndarray:
    """Rotation matrix taking ecliptic-J2000 vectors to equatorial."""
    ecl = np.deg2rad(obliquity_arcsec / 3600.0)
    return np.array(
        [
            [1.0, 0.0, 0.0],
            [0.0, np.cos(ecl), -np.sin(ecl)],
            [0.0, np.sin(ecl), np.cos(ecl)],
        ]
    )


_EQ_FROM_ECL = jnp.asarray(eq_from_ecl_matrix(OBLIQUITY_J2000_ARCSEC))


class AstrometryEcliptic(AstrometryBase):
    register = True
    trigger_params = ("ELONG", "ELAT")

    def __init__(self):
        super().__init__()
        self.add_param(Param("ELONG", kind="angle",
                             description="Ecliptic longitude",
                             aliases=("LAMBDA",)))
        self.add_param(Param("ELAT", kind="angle",
                             description="Ecliptic latitude",
                             aliases=("BETA",)))
        self.add_param(Param("PMELONG", units="mas/yr",
                             description="PM in ecliptic longitude",
                             aliases=("PMLAMBDA",)))
        self.add_param(Param("PMELAT", units="mas/yr",
                             description="PM in ecliptic latitude",
                             aliases=("PMBETA",)))
        self.add_param(Param("PX", units="mas", description="Parallax"))
        self.add_param(Param("POSEPOCH", kind="mjd", fittable=False,
                             description="Epoch of position"))
        #: par ``ECL`` obliquity selection (reference pulsar_ecliptic.py
        #: + ecliptic.dat); resolved to a static rotation matrix
        self.ecl_name = "IERS2010"

    def consume_parfile(self, pardict, model):
        consumed = set()
        if "ECL" in pardict and pardict["ECL"][0]:
            name = pardict["ECL"][0][0].upper()
            if name not in OBLIQUITY_ARCSEC:
                raise ValueError(
                    f"unknown ECL obliquity {name!r}; known: "
                    f"{sorted(OBLIQUITY_ARCSEC)}"
                )
            self.ecl_name = name
            model.meta["ECL"] = name
            consumed.add("ECL")
        return consumed

    @property
    def eq_from_ecl(self):
        return jnp.asarray(
            eq_from_ecl_matrix(OBLIQUITY_ARCSEC[self.ecl_name]))

    def build_params(self, pardict):
        pass

    def defaults(self):
        return {"PMELONG": 0.0, "PMELAT": 0.0, "PX": 0.0, "POSEPOCH": np.nan}

    def psr_dir(self, values, ctx):
        dt = ctx["dt_pos"]
        lon = values["ELONG"]
        lat = values["ELAT"]
        coslat = jnp.cos(lat)
        lon_t = lon + values["PMELONG"] * _MASYR * dt / jnp.where(
            coslat == 0, 1.0, coslat
        )
        lat_t = lat + values["PMELAT"] * _MASYR * dt
        necl = _unit_vector(lon_t, lat_t)
        return necl @ self.eq_from_ecl.T


def psr_dir_static(model) -> np.ndarray:
    """SSB->pulsar ICRS unit vector from the model's *current* astrometry
    values, as a static numpy array (no proper motion).

    Used for geometry that is effectively constant over a fit: barycentric
    Doppler of the observing frequency, solar elongation for the solar-wind
    delay, altitude for the troposphere delay (the reference likewise
    computes these from the model coordinates once per evaluation,
    e.g. astrometry.py ``sun_angle``, troposphere_delay.py
    ``_get_target_skycoord``)."""
    v = model.values
    if "RAJ" in v and not np.isnan(v.get("RAJ", np.nan)):
        ra, dec = float(v["RAJ"]), float(v["DECJ"])
        return np.array(
            [np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra), np.sin(dec)]
        )
    if "ELONG" in v and not np.isnan(v.get("ELONG", np.nan)):
        lon, lat = float(v["ELONG"]), float(v["ELAT"])
        necl = np.array(
            [np.cos(lat) * np.cos(lon), np.cos(lat) * np.sin(lon),
             np.sin(lat)]
        )
        if model.has_component("AstrometryEcliptic"):
            mat = np.asarray(model.component("AstrometryEcliptic").eq_from_ecl)
        else:
            mat = np.asarray(_EQ_FROM_ECL)
        return mat @ necl
    raise ValueError("model has no astrometry (RAJ/DECJ or ELONG/ELAT)")


def bary_freq_mhz(toas, model) -> np.ndarray:
    """Barycentric observing frequency (MHz) per TOA: first-order Doppler
    ``f * (1 - n.v_obs/c)`` (reference: timing_model
    ``barycentric_radio_freq``; ssb_obs_vel is stored in ls/s so ``n.v``
    is already v/c).  Static per dataset — the change of the Doppler
    factor under astrometry fitting is < 1e-9 relative."""
    try:
        n = psr_dir_static(model)
    except ValueError:
        # no astrometry component (already-barycentered data): the
        # topocentric frequency is all we have (the reference warns and
        # does the same, frequency_dependent.py FD_delay)
        return np.asarray(toas.freq_mhz)
    # many chromatic components call this per prepare(); memoize the O(N)
    # product on the TOAs object, keyed by the direction it was built for
    key = (round(float(n[0]), 14), round(float(n[1]), 14),
           round(float(n[2]), 14))
    memo = getattr(toas, "_bfreq_memo", None)
    if memo is not None and memo[0] == key:
        return memo[1]
    beta = np.asarray(toas.ssb_obs_vel) @ n
    bf = np.asarray(toas.freq_mhz) * (1.0 - beta)
    try:
        toas._bfreq_memo = (key, bf)
    except AttributeError:
        pass
    return bf


# --- frame conversion with covariance (reference: timing_model.py
# as_ECL:2961 / as_ICRS:3011, astrometry.py:651-669) -----------------------

def _dir_and_pm(lon, lat, pmlon, pmlat):
    """Unit vector + proper-motion velocity vector from spherical
    coords (pmlon carries the cos(lat) convention, mas/yr)."""
    cl, sl = jnp.cos(lon), jnp.sin(lon)
    cb, sb = jnp.cos(lat), jnp.sin(lat)
    n = jnp.array([cb * cl, cb * sl, sb])
    e_lon = jnp.array([-sl, cl, 0.0])
    e_lat = jnp.array([-sb * cl, -sb * sl, cb])
    v = pmlon * e_lon + pmlat * e_lat
    return n, v


def _sph_from_dir(n, v):
    lon = jnp.arctan2(n[1], n[0])
    lat = jnp.arcsin(jnp.clip(n[2], -1.0, 1.0))
    cl, sl = jnp.cos(lon), jnp.sin(lon)
    cb, sb = jnp.cos(lat), jnp.sin(lat)
    e_lon = jnp.array([-sl, cl, 0.0])
    e_lat = jnp.array([-sb * cl, -sb * sl, cb])
    return lon % (2.0 * jnp.pi), lat, v @ e_lon, v @ e_lat


def _convert4(params, mat):
    """(lon, lat, pmlon, pmlat) rotated by mat (3,3)."""
    n, v = _dir_and_pm(*params)
    return jnp.stack(_sph_from_dir(mat @ n, mat @ v))


def model_as_ECL(model, ecl="IERS2010"):
    """A copy of the model with equatorial astrometry converted to
    ecliptic (or the ecliptic re-referenced to another obliquity),
    uncertainties propagated through the exact rotation jacobian
    (reference: TimingModel.as_ECL, timing_model.py:2961)."""
    import copy

    import jax

    out = copy.deepcopy(model)
    mat = jnp.asarray(eq_from_ecl_matrix(OBLIQUITY_ARCSEC[ecl.upper()]))
    if out.has_component("AstrometryEcliptic"):
        comp = out.component("AstrometryEcliptic")
        if comp.ecl_name == ecl.upper():
            return out
        old = jnp.asarray(comp.eq_from_ecl)
        rot = mat.T @ old  # old-ecl -> icrs -> new-ecl
        src = ("ELONG", "ELAT", "PMELONG", "PMELAT")
        dst = src
    else:
        comp_old = out.component("AstrometryEquatorial")
        rot = mat.T  # icrs -> ecl
        src = ("RAJ", "DECJ", "PMRA", "PMDEC")
        dst = ("ELONG", "ELAT", "PMELONG", "PMELAT")
        from pint_tpu.models.astrometry import AstrometryEcliptic

        comp = AstrometryEcliptic()
        comp.build_params({})
        # carry PX/POSEPOCH state
        out.components = [
            c if type(c).__name__ != "AstrometryEquatorial" else comp
            for c in out.components
        ]
    comp.ecl_name = ecl.upper()
    out.meta["ECL"] = ecl.upper()
    _apply_frame_rotation(out, model, rot, src, dst)
    return out


def model_as_ICRS(model):
    """A copy of the model with ecliptic astrometry converted to
    equatorial (reference: TimingModel.as_ICRS, timing_model.py:3011)."""
    import copy

    out = copy.deepcopy(model)
    if out.has_component("AstrometryEquatorial"):
        return out
    comp_old = out.component("AstrometryEcliptic")
    rot = jnp.asarray(comp_old.eq_from_ecl)  # ecl -> icrs
    new = AstrometryEquatorial()
    new.build_params({})
    out.components = [
        c if type(c).__name__ != "AstrometryEcliptic" else new
        for c in out.components
    ]
    out.meta.pop("ECL", None)
    _apply_frame_rotation(out, model, rot,
                          ("ELONG", "ELAT", "PMELONG", "PMELAT"),
                          ("RAJ", "DECJ", "PMRA", "PMDEC"))
    return out


def _apply_frame_rotation(out, model, rot, src, dst):
    import jax

    vals = jnp.array([float(model.values[k]) for k in src])
    new_vals = _convert4(vals, rot)
    J = jax.jacfwd(lambda p: _convert4(p, rot))(vals)
    sig = np.array([
        float(model.params[k].uncertainty or 0.0) for k in src
    ])
    # angle params are radians internally, PMs mas/yr — the jacobian is
    # in internal units throughout, so a diagonal input covariance
    # propagates directly
    cov = np.asarray(J) @ np.diag(sig**2) @ np.asarray(J).T
    for k in src:
        if k not in dst:
            out.values.pop(k, None)
    for i, k in enumerate(dst):
        out.values[k] = float(new_vals[i])
        if k in out.params:
            out.params[k].uncertainty = float(np.sqrt(max(cov[i, i],
                                                          0.0)))
    # PX / POSEPOCH are frame-invariant: carry them over
    for k in ("PX", "POSEPOCH"):
        if k in model.values:
            out.values[k] = model.values[k]
