"""Solar-system Shapiro delay (Sun + optionally planets).

Counterpart of the reference SolarSystemShapiro (reference:
src/pint/models/solar_system_shapiro.py:22-124, ``ss_obj_shapiro_delay``
at :59-81): GR log-term delay -2 T_obj ln((r - r.n)/AU) using body masses
in time units (GM/c^3).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import (
    AU_LS,
    T_JUPITER_S,
    T_MARS_S,
    T_NEPTUNE_S,
    T_SATURN_S,
    T_SUN_S,
    T_URANUS_S,
    T_VENUS_S,
)
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import Param

#: order matches TOABatch.planet_pos stacking
_PLANET_T = (T_VENUS_S, T_MARS_S, T_JUPITER_S, T_SATURN_S, T_URANUS_S,
             T_NEPTUNE_S)


def _obj_shapiro(obj_pos_ls, psr_dir, t_obj):
    """-2 T ln((r - r.n)/AU): obj_pos is obs->body [ls], psr_dir obs->psr."""
    r = jnp.sqrt(jnp.sum(obj_pos_ls * obj_pos_ls, axis=-1))
    rcos = jnp.sum(obj_pos_ls * psr_dir, axis=-1)
    return -2.0 * t_obj * jnp.log((r - rcos) / AU_LS)


class SolarSystemShapiro(DelayComponent):
    category = "solar_system_shapiro"
    trigger_params = ("PLANET_SHAPIRO",)
    #: delay() recomputes the pulsar direction from the astrometry
    #: component's position parameters (_psr_dir_from_values) — free
    #: astrometry must keep this component in the trace
    #: (frozen_delay_split), and edits to a fixed position must refresh
    #: its frozen leaf (frozen_param_values)
    reads_params = ("RAJ", "DECJ", "ELONG", "ELAT")

    def __init__(self):
        super().__init__()
        self.add_param(Param("PLANET_SHAPIRO", kind="bool", fittable=False,
                             description="Include planetary Shapiro delays"))

    def build_params(self, pardict):
        pass

    def defaults(self):
        return {"PLANET_SHAPIRO": 0.0}

    def prepare(self, toas, model):
        # the on/off decision must be shape-encoded (static under both
        # jit AND vmap-over-pulsars): an empty planet-index tuple means
        # sun only.  A python bool in ctx would be stacked/traced by the
        # PTA batch path.
        on = bool(model.values.get("PLANET_SHAPIRO", 0.0)) and toas.planets
        ctx = {"planet_idx": tuple(range(len(_PLANET_T))) if on else ()}
        # honor the model's ECL obliquity selection for ecliptic
        # coordinates.  ALWAYS present (default matrix for equatorial
        # models) so the PTA batch path stacks a uniform ctx structure
        # across mixed ecliptic/equatorial pulsar sets.
        from pint_tpu.models.astrometry import _EQ_FROM_ECL

        if model.has_component("AstrometryEcliptic"):
            ctx["eq_from_ecl"] = np.asarray(
                model.component("AstrometryEcliptic").eq_from_ecl)
        else:
            ctx["eq_from_ecl"] = np.asarray(_EQ_FROM_ECL)
        return ctx

    def delay(self, values, batch, ctx, delay_accum):
        # psr direction from the astrometry component's parameters: the
        # chain gives us only accumulated delay, so recompute the unit
        # vector from RAJ/DECJ (or ELONG/ELAT) present in values.
        n = _psr_dir_from_values(values, ctx.get("eq_from_ecl"))
        d = _obj_shapiro(batch.obs_sun_pos, n, T_SUN_S)
        for i in ctx["planet_idx"]:
            d = d + _obj_shapiro(batch.planet_pos[i], n, _PLANET_T[i])
        return d


def _psr_dir_from_values(values, eq_from_ecl=None):
    """Pulsar unit vector (no PM propagation — Shapiro is insensitive at
    the sub-ns level to mas-scale position changes)."""
    from pint_tpu.models.astrometry import _EQ_FROM_ECL, _unit_vector

    if "RAJ" in values:
        return _unit_vector(values["RAJ"], values["DECJ"])
    necl = _unit_vector(values["ELONG"], values["ELAT"])
    mat = _EQ_FROM_ECL if eq_from_ecl is None else jnp.asarray(eq_from_ecl)
    return necl @ mat.T
