"""Frequency-dependent profile-evolution delays: FD, FDJump, FDJumpDM.

Counterparts of the reference components (reference:
src/pint/models/frequency_dependent.py:12 ``FD_delay`` — Arzoumanian+
2015 Eq. 2: delay = sum_k FDk ln(nu/GHz)^k; src/pint/models/fdjump.py:15
— per-system FD terms FDpJUMPq with FDJUMPLOG selecting log- vs
linear-frequency basis; src/pint/models/dispersion_model.py:805 FDJumpDM
— system DM offsets tied to FDJUMP systems, wideband only).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu import DM_CONST
from pint_tpu.models.component import (
    DelayComponent,
    mask_from_select,
)
from pint_tpu.models.parameter import Param, prefix_index


class FD(DelayComponent):
    register = True
    category = "frequency_dependent"
    trigger_params = ("FD1",)

    def __init__(self, num_terms=0):
        super().__init__()
        self.num_terms = num_terms
        for k in range(1, num_terms + 1):
            self.add_param(Param(f"FD{k}", units="s",
                                 description=f"FD coefficient ln^{k}"))

    @classmethod
    def from_parfile(cls, pardict):
        n = 0
        for key in pardict:
            pi = prefix_index(key)
            if pi and pi[0] == "FD" and key[2:].isdigit():
                n = max(n, pi[1])
        return cls(num_terms=n)

    def defaults(self):
        return {f"FD{k}": 0.0 for k in range(1, self.num_terms + 1)}

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import bary_freq_mhz

        bfreq = bary_freq_mhz(toas, model)
        logf = np.log(bfreq / 1000.0)
        logf[~np.isfinite(logf)] = 0.0
        return {"log_freq_ghz": jnp.asarray(logf)}

    def delay(self, values, batch, ctx, delay_accum):
        if not self.num_terms:
            return jnp.zeros_like(batch.freq_mhz)
        y = ctx["log_freq_ghz"]
        # Horner over k = num_terms .. 1 (no constant term)
        acc = jnp.zeros_like(y)
        for k in range(self.num_terms, 0, -1):
            acc = (acc + values[f"FD{k}"]) * y
        return acc

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return tuple(f"FD{k}" for k in range(1, self.num_terms + 1))

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        k = int(name[2:])
        y = ctx["log_freq_ghz"]
        col = y
        for _ in range(k - 1):
            col = col * y
        return col


class FDJump(DelayComponent):
    """Per-system FD polynomials.  Internal names FD{p}JUMP{q}: p = FD
    index, q = system/mask index (reference fdjump.py:44-49 naming)."""

    register = True
    category = "fdjump"
    trigger_params = ()  # builder detects FD\d+JUMP mask keys

    def __init__(self, terms=()):
        """terms: sequence of (p, q, select) triples."""
        super().__init__()
        self.terms = tuple(terms)
        self.add_param(Param("FDJUMPLOG", kind="bool", fittable=False,
                             description="log-freq (Y) vs linear (N) basis"))
        for p, q, sel in self.terms:
            self.add_param(Param(f"FD{p}JUMP{q}", units="s", select=sel,
                                 description=f"FD{p} jump, system {q}"))

    @classmethod
    def from_parfile(cls, pardict):
        masks = pardict.get("__MASKS__", {})
        terms = []
        for key, entries in masks.items():
            if key.startswith("FD") and key.endswith("JUMP"):
                p = int(key[2:-4])
                for q, (sel, _rest) in enumerate(entries, start=1):
                    terms.append((p, q, sel))
        return cls(terms=terms)

    def defaults(self):
        d = {f"FD{p}JUMP{q}": 0.0 for p, q, _ in self.terms}
        d["FDJUMPLOG"] = 1.0
        return d

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import bary_freq_mhz

        bfreq = bary_freq_mhz(toas, model) / 1000.0  # GHz
        use_log = bool(model.values.get("FDJUMPLOG", 1.0))
        y = np.log(bfreq) if use_log else bfreq
        y[~np.isfinite(y)] = 0.0
        masks = [
            np.asarray(mask_from_select(sel, toas))
            for _p, _q, sel in self.terms
        ]
        m = (
            np.stack(masks, 0)
            if masks
            else np.zeros((0, len(toas)), dtype=bool)
        )
        return {"y": jnp.asarray(y), "masks": jnp.asarray(m)}

    def delay(self, values, batch, ctx, delay_accum):
        y = ctx["y"]
        out = jnp.zeros_like(y)
        for j, (p, q, _sel) in enumerate(self.terms):
            out = out + jnp.where(
                ctx["masks"][j], values[f"FD{p}JUMP{q}"] * y**p, 0.0
            )
        return out

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return tuple(f"FD{p}JUMP{q}" for p, q, _sel in self.terms)

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        y = ctx["y"]
        for j, (p, q, _sel) in enumerate(self.terms):
            if f"FD{p}JUMP{q}" == name:
                return jnp.where(ctx["masks"][j], y**p,
                                 jnp.zeros_like(y))
        raise KeyError(name)


class FDJumpDM(DelayComponent):
    """System-dependent DM offsets (FDJUMPDM mask params) — the
    narrow-band counterpart of wideband system DM offsets (reference:
    dispersion_model.py:805-884).  Sign matches DMJUMP: the value is the
    *apparent* DM offset, so the delay contribution is negative."""

    register = True
    category = "fdjumpdm"
    trigger_params = ("FDJUMPDM",)

    def __init__(self, selects=()):
        super().__init__()
        self.selects = tuple(selects)
        for i, sel in enumerate(self.selects, start=1):
            self.add_param(Param(f"FDJUMPDM{i}", units="pc cm^-3",
                                 select=sel,
                                 description=f"System DM offset {i}"))

    @classmethod
    def from_parfile(cls, pardict):
        masks = pardict.get("__MASKS__", {})
        return cls(selects=[s for s, _ in masks.get("FDJUMPDM", [])])

    def defaults(self):
        return {
            f"FDJUMPDM{i}": 0.0 for i in range(1, len(self.selects) + 1)
        }

    def prepare(self, toas, model):
        from pint_tpu.models.astrometry import bary_freq_mhz

        masks = [
            np.asarray(mask_from_select(sel, toas)) for sel in self.selects
        ]
        m = (
            np.stack(masks, 0)
            if masks
            else np.zeros((0, len(toas)), dtype=bool)
        )
        return {
            "masks": jnp.asarray(m),
            "bfreq": jnp.asarray(bary_freq_mhz(toas, model)),
        }

    def dm_value(self, values, batch, ctx):
        if not self.selects:
            return jnp.zeros_like(batch.freq_mhz)
        dj = jnp.stack(
            [
                values[f"FDJUMPDM{i}"]
                for i in range(1, len(self.selects) + 1)
            ]
        )
        # reference fdjump_dm adds -value
        return -jnp.sum(ctx["masks"] * dj[:, None], axis=0)

    def delay(self, values, batch, ctx, delay_accum):
        # unlike DMJUMP, FDJUMPDM does disperse the arrival times
        # (reference fdjump_dm_delay -> dispersion_type_delay)
        return DM_CONST * self.dm_value(values, batch, ctx) \
            / ctx["bfreq"] ** 2

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        return tuple(
            f"FDJUMPDM{i}" for i in range(1, len(self.selects) + 1))

    def _d_dm(self, ctx, name):
        i = int(name[8:])
        return -ctx["masks"][i - 1].astype(jnp.float64)

    def d_delay_d_param(self, values, batch, ctx, delay_accum, name):
        return DM_CONST * self._d_dm(ctx, name) / ctx["bfreq"] ** 2

    def d_dm_d_param(self, values, batch, ctx, name):
        return self._d_dm(ctx, name)
