"""Spindown: rotation-phase Taylor series.

Counterpart of the reference Spindown (reference: src/pint/models/
spindown.py:20-225 ``spindown_phase`` via longdouble taylor_horner).
TPU redesign: the dominant F0*(t-PEPOCH) term goes through the exact
fixed-point path (:func:`pint_tpu.fixedpoint.phase_f0_t` — int64 ticks,
custom-JVP differentiable); every higher-order term F1, F2, ... is
float64, where even sloppy TPU arithmetic leaves < 1e-7 turns (see
fixedpoint module error budget).  The delay enters as
-F0*delay - F1*dt*delay + ... i.e. the series is evaluated at
dt = t - PEPOCH - delay with the large product split off exactly.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu import fixedpoint as fp
from pint_tpu.models.component import PhaseComponent
from pint_tpu.models.parameter import Param, prefix_index


class Spindown(PhaseComponent):
    category = "spindown"
    trigger_params = ("F0",)

    def __init__(self, num_freq_derivs=1):
        super().__init__()
        self.num_freq_derivs = num_freq_derivs
        self.add_param(Param("F0", units="Hz", description="Spin frequency"))
        for k in range(1, num_freq_derivs + 1):
            self.add_param(
                Param(f"F{k}", units=f"Hz/s^{k}",
                      description=f"Spin frequency derivative {k}")
            )
        self.add_param(
            Param("PEPOCH", kind="mjd", fittable=False,
                  description="Epoch of spin parameters")
        )

    @classmethod
    def from_parfile(cls, pardict):
        nderiv = 0
        for key in pardict:
            pi = prefix_index(key)
            if pi and pi[0] == "F":
                nderiv = max(nderiv, pi[1])
        return cls(num_freq_derivs=max(nderiv, 1))

    def defaults(self):
        d = {f"F{k}": 0.0 for k in range(1, self.num_freq_derivs + 1)}
        d["PEPOCH"] = 0.0
        return d

    def prepare(self, toas, model):
        # exact ticks from the par parse when available (f64 seconds would
        # cost ~6e-8 s of epoch rounding — absorbed by TZR/mean, but keep
        # the exact path exact)
        pepoch_ticks = getattr(model, "epoch_ticks", {}).get(
            "PEPOCH", int(round(model.values["PEPOCH"] * 2**32))
        )
        return {
            "dt_ticks": jnp.asarray(toas.ticks) - jnp.int64(pepoch_ticks)
        }

    def phase(self, values, batch, ctx, delay):
        dt_ticks = ctx["dt_ticks"]
        f0 = values["F0"]
        # exact giant term F0*(t - PEPOCH)
        n, frac = fp.phase_f0_t(f0, dt_ticks)
        # remaining terms in f64: -F0*delay + sum_k Fk dt^(k+1)/(k+1)!
        dt = fp.ticks_to_seconds(dt_ticks) - delay
        small = -f0 * delay
        fact = 1.0
        power = dt * dt
        for k in range(1, self.num_freq_derivs + 1):
            fact *= k + 1
            small = small + values[f"F{k}"] * power / fact
            power = power * dt
        return n, frac + small

    # -- hybrid design matrix -------------------------------------------------
    def linear_params(self):
        """F1..Fk enter the phase as Fk * dt^(k+1)/(k+1)! — linear with
        the Taylor monomial as the closed-form column.  F0 stays
        nonlinear: it multiplies the delay term AND divides the
        time-residual conversion, so its column is left to jacfwd."""
        return tuple(f"F{k}" for k in range(1, self.num_freq_derivs + 1))

    def d_phase_d_param(self, values, batch, ctx, delay, name):
        k = int(name[1:])
        dt = fp.ticks_to_seconds(ctx["dt_ticks"]) - delay
        fact = 1.0
        power = dt * dt
        for j in range(1, k):
            power = power * dt
        for j in range(1, k + 1):
            fact *= j + 1
        return power / fact
