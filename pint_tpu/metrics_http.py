"""Live /metrics endpoint: the telemetry layer's scrape surface.

A stdlib-``http.server`` background thread exporting every telemetry
counter, numeric gauge, and :class:`~pint_tpu.telemetry.LogHistogram`
in Prometheus text exposition format (0.0.4), plus the run ledger's
in-flight/completed gauges — the surface the warm fitting service
(ROADMAP item 2) sits behind, and the live view of a long grid or
MCMC run that the JSONL sink only shows after the fact.

Default **off**.  Activation:

- ``PINT_TPU_METRICS_PORT=9464`` — started at first import of
  :mod:`pint_tpu.telemetry` (``0``/``off`` disable; a failed bind
  warns and never breaks imports).
- programmatic: ``metrics_http.start(port=0)`` (0 = an ephemeral
  port; the bound port is returned and exposed by :func:`port`).

Binds ``127.0.0.1`` by default (``PINT_TPU_METRICS_HOST`` overrides —
a scrape endpoint exposed beyond localhost is a deployment decision,
not a default).  Every request renders a fresh snapshot under the
telemetry locks, so concurrent fits can never tear a histogram's
percentiles (telemetry.LogHistogram.percentiles reads its state
once).  Paths:

- ``GET /metrics`` — Prometheus text format.
- ``GET /healthz`` — one JSON object: uptime, readiness, run-ledger
  summary, compile stats.
- ``GET /readyz`` — load-balancer readiness: 200 only for a warm
  serving process (:func:`readiness` — AOT import or explicit warmup
  complete), 503 otherwise.  A cold replica must not receive
  traffic.

Metric naming: ``pint_tpu_`` + the telemetry name with every
non-``[a-zA-Z0-9_]`` character mapped to ``_``; counters get the
conventional ``_total`` suffix; histograms export as summaries
(``{quantile="0.5|0.95|0.99"}`` + ``_sum`` + ``_count``).
Non-numeric gauges (e.g. ``compile_cache.dir``) are skipped — a
label-valued export can join later if a consumer needs it.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time

from pint_tpu import telemetry

__all__ = ["start", "stop", "port", "render_prometheus",
           "readiness", "PORT_ENV", "HOST_ENV"]

PORT_ENV = "PINT_TPU_METRICS_PORT"
HOST_ENV = "PINT_TPU_METRICS_HOST"

_lock = threading.Lock()
_server = None
_thread = None
_t_started = None


def _metric_name(name, suffix=""):
    return "pint_tpu_" + re.sub(r"[^a-zA-Z0-9_]", "_", str(name)) \
        + suffix


def _num(value):
    """Prometheus sample value, or None for unexportable values."""
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, (int, float)):
        if isinstance(value, float) and math.isnan(value):
            return "NaN"
        return repr(float(value))
    return None


def render_prometheus() -> str:
    """One snapshot of counters/gauges/histograms/run-ledger as
    Prometheus text format.  Pure function of telemetry state (also
    used by tests without a live server)."""
    lines = []

    def sample(name, value, mtype, suffix="", labels=""):
        v = _num(value)
        if v is None:
            return
        m = _metric_name(name, suffix)
        lines.append(f"# TYPE {m} {mtype}")
        lines.append(f"{m}{labels} {v}")

    for name, value in sorted(telemetry.counters().items()):
        sample(name, value, "counter", suffix="_total")
    for name, value in sorted(telemetry.gauges().items()):
        # histogram percentiles ride gauges() as flattened hist.*
        # entries for the in-process readout; here they export as
        # proper summaries below instead
        if not name.startswith("hist."):
            sample(name, value, "gauge")
    for name, snap in sorted(telemetry.histograms().items()):
        m = _metric_name("hist_" + name)
        lines.append(f"# TYPE {m} summary")
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = _num(snap.get(key))
            if v is not None:
                lines.append(f'{m}{{quantile="{q}"}} {v}')
        lines.append(f"{m}_sum {_num(snap.get('total', 0.0)) or 0}")
        lines.append(f"{m}_count {int(snap.get('n', 0))}")
    # run ledger: in_flight/completed already live in gauges/counters
    # (runs.in_flight / runs.completed); add the scrape-time clock so
    # a dashboard can rate() against wall time drift-free
    sample("scrape_timestamp_seconds", time.time(), "gauge")
    return "\n".join(lines) + "\n"


def readiness():
    """Load-balancer readiness verdict: ``(ready, doc)``.

    A SERVING process (one that built a :class:`pint_tpu.serve.Server`
    — detected by the ``serve.ready`` gauge) is ready only after its
    AOT import or an explicit warmup completed (``serve.aot_warm``):
    a cold replica must not receive traffic — its first requests
    would each pay a full XLA compile.  A process with no serving
    layer returns ``(None, ...)``: /readyz answers 503 there, which
    is correct (nothing is serving), while /healthz keeps reporting
    liveness for either kind of process."""
    g = telemetry.gauges()
    if "serve.ready" not in g:
        return None, {"ready": None,
                      "detail": "no serving layer in this process"}
    started = bool(g.get("serve.ready"))
    warm = bool(g.get("serve.aot_warm"))
    draining = bool(g.get("serve.draining", 0.0))
    ready = started and warm and not draining
    # the SLO degrade hook is informational here, NOT a readiness
    # input: a degraded replica still serves (with a tighter queue
    # bound) — pulling it from rotation would turn a partial
    # brown-out into a full outage.  Warmth is a latch on the server
    # side (Server.mark_warm), so ready can never flap 200 -> 503
    # once warm while the process serves.  DRAINING is the one
    # deliberate un-ready transition: /drain flips it so a router
    # stops placing new work while in-flight requests and job
    # chunks finish — the rolling-deploy handshake.
    return ready, {"ready": ready, "started": started,
                   "aot_warm": warm, "draining": draining,
                   "queue_depth": g.get("serve.queue_depth", 0),
                   "slo_degraded": bool(g.get("slo.degraded", 0.0))}


def _healthz() -> str:
    ready, rdoc = readiness()
    doc = {
        "uptime_s": (round(time.time() - _t_started, 3)
                     if _t_started else None),
        "ready": ready,
        "readiness": rdoc,
        "runs": telemetry.runs_summary(),
        "compile": telemetry.compile_stats(),
    }
    return json.dumps(doc, separators=(",", ":"))


def start(port=None, host=None):
    """Start the background metrics server (idempotent: a live server
    keeps its port).  port=None reads ``$PINT_TPU_METRICS_PORT``;
    port=0 binds an ephemeral port.  Returns the bound port."""
    global _server, _thread, _t_started
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    with _lock:
        if _server is not None:
            return _server.server_address[1]
        if port is None:
            raw = os.environ.get(PORT_ENV, "").strip()
            try:
                port = int(raw)
            except ValueError:
                raise ValueError(
                    f"{PORT_ENV}={raw!r} is not a port number") from None
        if host is None:
            host = os.environ.get(HOST_ENV, "").strip() or "127.0.0.1"

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                status = 200
                if path in ("/", "/metrics"):
                    body = render_prometheus().encode()
                    ctype = ("text/plain; version=0.0.4; "
                             "charset=utf-8")
                elif path == "/healthz":
                    body = _healthz().encode()
                    ctype = "application/json"
                elif path == "/readyz":
                    # the LB gate: 200 only for a warm serving
                    # process (AOT import / explicit warmup done)
                    ready, doc = readiness()
                    status = 200 if ready else 503
                    body = json.dumps(
                        doc, separators=(",", ":")).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam
                pass

        server = ThreadingHTTPServer((host, int(port)), _Handler)
        server.daemon_threads = True
        thread = threading.Thread(target=server.serve_forever,
                                  name="pint-tpu-metrics",
                                  daemon=True)
        thread.start()
        _server, _thread, _t_started = server, thread, time.time()
        bound = server.server_address[1]
        telemetry.gauge_set("metrics_http.port", bound)
        return bound


def stop():
    """Shut the server down (tests / clean service teardown)."""
    global _server, _thread, _t_started
    with _lock:
        server, thread = _server, _thread
        _server = _thread = _t_started = None
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None:
        thread.join(timeout=5)


def port():
    """The live server's bound port, or None when stopped."""
    with _lock:
        return _server.server_address[1] if _server is not None \
            else None
