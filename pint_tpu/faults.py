"""Deterministic fault-injection harness (``$PINT_TPU_FAULTS``).

Every degradation path of the guard layer (:mod:`pint_tpu.guard`) is
exercised by chaos tests instead of trusted on faith: this module
injects known fault classes at the library's host-side data boundaries
— never inside a traced function, so a fault-active dataset is just
different *data* under the same shared trace and can never poison the
jit registry.

Fault classes (spec grammar: comma-separated ``name[:key=val...]``):

- ``nan_resid[:index=K]`` — NaN one TOA's observing frequency, making
  that row's dispersion delay (and, through the weighted mean, every
  residual) NaN: the classic corrupted-input fit.  Applied where
  :class:`pint_tpu.residuals.Residuals` builds its dataset pytree.
- ``inf_sigma[:index=K]`` — one TOA uncertainty becomes +inf (a
  corrupted ``.tim`` error column).  Same hook.
- ``rank_deficient_phi`` — the cross-pulsar ORF matrix becomes the
  all-ones rank-1 matrix, giving the dense GW prior an exact null
  space (the monopole-ORF degeneracy class the per-diagonal Cholesky
  jitter in ``linalg._phi_terms`` exists for).  Applied where
  :class:`pint_tpu.gw.common.CommonProcess` builds its ORF.
- ``clock_corrupt[:index=K]`` — one parsed clock-file row's offset
  becomes NaN (a corrupted tabulation).  Applied in
  ``ClockFile.read_tempo2``; the ``ClockFile`` finiteness validation
  must turn it into a structured error, never silent NaN
  interpolation.
- ``kill[:after=N][:site=S][:code=C]`` — deterministic process death:
  the Nth call to :func:`maybe_kill` at site ``S`` hard-exits (default
  code 137), simulating a mid-chain kill for checkpoint/resume tests.
  Known sites: ``sampler.chunk`` (mid-MCMC-chain) and ``serve.flush``
  (the warm fitting service — mid-batch dispatch and the grid-job
  chunk loop, so a killed replica's resume story is testable).  The
  fleet chaos harness (:mod:`pint_tpu.fleet.chaos`) aims this same
  spec at ONE replica subprocess via its spawn env, so a
  whole-process death mid-batch exercises router re-route and
  supervisor restart.
- ``glitch_toas[:night=K][:offset_us=U][:ramp_us_per_day=R]`` — a
  glitch-shaped corruption of a streaming append: every campaign
  night >= ``night`` (default 1) arrives late by a one-sided phase
  ramp (``offset_us`` + ``ramp_us_per_day`` x days-into-night
  microseconds — the post-glitch linear drift signature of
  arXiv 2010.10322).  Applied where the corpus campaign generator
  realizes a night's TOAs (:meth:`pint_tpu.corpus.spec.Scenario.
  realize_nights`); the streaming triage
  (``Fitter._stream_triage``) must QUARANTINE the night, never
  absorb it into the warm fit.
- ``slow_flush[:ms=N][:site=S]`` — deterministic latency injection:
  every call to :func:`maybe_delay` at site ``S`` (default: any site)
  sleeps ``ms`` milliseconds (default 50).  The serve plane's batched
  dispatch calls it at ``serve.flush``, so an injected slow flush
  drives per-request latency past a declared SLO objective — the
  harness the ``/slo`` verdict-flip and admission-degrade tests run
  on.  The fleet router calls it at ``router.forward`` before every
  proxied backend request, so injected proxy latency tests the
  router-side SLO windows and spread policy without touching a
  replica.

Faults activate via the environment variable (read per call, so a
subprocess harness controls them) or programmatically
(:func:`inject`/:func:`clear` — tests MUST clear in teardown).  Every
injection ticks ``faults.injected`` / ``faults.injected.<name>``.
"""

from __future__ import annotations

import os
import time

import numpy as np

from pint_tpu import telemetry

__all__ = ["parse", "config", "active", "any_active", "inject", "clear",
           "corrupt_batch", "corrupt_orf", "corrupt_append_toas",
           "corrupt_clock_rows", "maybe_kill", "maybe_delay",
           "suspend"]

ENV = "PINT_TPU_FAULTS"

_programmatic: dict = {}
_site_counts: dict = {}
_suspended = 0


def _coerce(v: str):
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            pass
    return v


def parse(spec: str) -> dict:
    """``"nan_resid:index=3,kill:after=2:site=sampler.chunk"`` ->
    ``{"nan_resid": {"index": 3}, "kill": {"after": 2, "site": ...}}``."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        params = {}
        for b in bits[1:]:
            k, _, v = b.partition("=")
            params[k.strip()] = _coerce(v.strip())
        out[bits[0].strip()] = params
    return out


def config() -> dict:
    """Active faults: the env spec overlaid with programmatic ones."""
    cfg = parse(os.environ.get(ENV, ""))
    cfg.update(_programmatic)
    return cfg


def active(name):
    """The fault's param dict when active, else None."""
    return config().get(name)


def any_active() -> bool:
    return bool(config())


def inject(name, **params):
    """Activate a fault programmatically (tests/datacheck)."""
    _programmatic[name] = params


def clear():
    """Deactivate every programmatic fault and reset kill counters."""
    _programmatic.clear()
    _site_counts.clear()


def _tick(name):
    telemetry.counter_add("faults.injected")
    telemetry.counter_add(f"faults.injected.{name}")


# --------------------------------------------------------------------------
# hooks (each a no-op returning its input when the fault is inactive)
# --------------------------------------------------------------------------

def _batch_with(batch, **repl):
    """Rebuild a TOABatch with replaced fields.  NOT ``_replace``:
    TOABatch overrides ``__len__`` (TOA count), which breaks
    NamedTuple._make's field-count sanity check."""
    return type(batch)(**{**batch._asdict(), **repl})


def _member_match(params, member):
    """Batched-path targeting: a fault carrying ``pulsar=K`` applies
    ONLY to batch member K — including never to a standalone
    (member=None) dataset built while it is active; without the key it
    applies everywhere."""
    want = params.get("pulsar")
    if want is None:
        return True
    return member is not None and int(want) == int(member)


def corrupt_batch(batch, member=None):
    """Apply ``nan_resid``/``inf_sigma`` to a TOABatch (host-side,
    concrete arrays — the corrupted dataset flows through the shared
    traces as ordinary dynamic data).  member: the pulsar index on the
    batched PTA path (see :func:`_member_match`)."""
    import jax.numpy as jnp

    p = active("nan_resid")
    if p is not None and _member_match(p, member):
        idx = int(p.get("index", 0))
        f = np.array(batch.freq_mhz, dtype=np.float64)
        f[idx % max(f.shape[0], 1)] = np.nan
        batch = _batch_with(batch, freq_mhz=jnp.asarray(f))
        _tick("nan_resid")
    p = active("inf_sigma")
    if p is not None and _member_match(p, member):
        idx = int(p.get("index", 0))
        e = np.array(batch.error_s, dtype=np.float64)
        e[idx % max(e.shape[0], 1)] = np.inf
        batch = _batch_with(batch, error_s=jnp.asarray(e))
        _tick("inf_sigma")
    return batch


def corrupt_orf(orf):
    """``rank_deficient_phi``: replace the ORF with the all-ones rank-1
    matrix (an exact null space in the dense kron(ORF, phi) prior)."""
    if active("rank_deficient_phi") is not None:
        import jax.numpy as jnp

        _tick("rank_deficient_phi")
        return jnp.ones_like(orf)
    return orf


def corrupt_append_toas(toas, night=0):
    """``glitch_toas``: make one campaign night's appended TOAs arrive
    late by a one-sided phase ramp (host-side tick shift, exactly how
    the simulator injects white noise) — the glitch/acceleration
    residual signature the streaming triage quarantines.  Nights
    before ``night`` pass through untouched; returns ``toas``."""
    p = active("glitch_toas")
    if p is None or int(night) < int(p.get("night", 1)):
        return toas
    offset_us = float(p.get("offset_us", 100.0))
    ramp = float(p.get("ramp_us_per_day", 50.0))
    mjds = np.asarray(toas.mjd_float, dtype=np.float64)
    days = mjds - float(mjds.min()) if mjds.size else mjds
    shift_s = (offset_us + ramp * days) * 1e-6
    toas.ticks = toas.ticks + np.round(
        shift_s * 2**32).astype(np.int64)
    toas._compute_posvels()
    _tick("glitch_toas")
    return toas


def corrupt_clock_rows(mjds, offsets):
    """``clock_corrupt``: NaN one parsed clock row's offset in place
    (python lists, called from the clock-file parsers)."""
    p = active("clock_corrupt")
    if p is not None and offsets:
        idx = int(p.get("index", len(offsets) // 2)) % len(offsets)
        offsets[idx] = float("nan")
        _tick("clock_corrupt")


class _Suspend:
    def __enter__(self):
        global _suspended
        _suspended += 1
        return self

    def __exit__(self, *exc):
        global _suspended
        _suspended -= 1
        return False


def suspend():
    """Context manager pausing site-fault injection process-wide:
    :func:`maybe_kill` / :func:`maybe_delay` are no-ops inside it and
    do NOT advance their ``after=N`` site counters.  The serve plane
    wraps its boot-time warm rehearsal in this — ``kill:after=K``
    means the Kth *served* flush, so a replica spawned with a fault
    armed must not burn the budget (or die) warming itself up."""
    return _Suspend()


def maybe_kill(site):
    """``kill``: hard-exit on the Nth call at the named site (default
    site = any, after=1, code=137).  ``os._exit`` — no atexit, no
    cleanup — the honest simulation of a SIGKILL mid-job."""
    if _suspended:
        return
    p = active("kill")
    if p is None:
        return
    want = p.get("site")
    if want is not None and want != site:
        return
    n = _site_counts[site] = _site_counts.get(site, 0) + 1
    if n >= int(p.get("after", 1)):
        _tick("kill")
        telemetry.flush()
        os._exit(int(p.get("code", 137)))


def maybe_delay(site):
    """``slow_flush``: sleep ``ms`` milliseconds at the named site
    (host-side only — the delay happens before any device work, so it
    is pure added latency, never a traced-program change)."""
    if _suspended:
        return
    p = active("slow_flush")
    if p is None:
        return
    want = p.get("site")
    if want is not None and want != site:
        return
    _tick("slow_flush")
    time.sleep(float(p.get("ms", 50.0)) / 1e3)
