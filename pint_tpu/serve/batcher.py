"""Deadline-coalescing batcher: the serving layer's perf core.

Requests whose TOAs land in the same geometric bucket (and share a
fitter kind, model structure, and ``maxiter``) can be served by ONE
compiled device program with a pulsar batch axis — so instead of
dispatching each request alone, the batcher holds same-group requests
until either

- the group reaches ``max_batch`` members (a full batch), or
- the OLDEST member has waited ``flush_ms``
  (``$PINT_TPU_SERVE_FLUSH_MS`` — the latency price of coalescing,
  bounded and explicit),

then pops up to ``max_batch`` of them and hands the group to the
dispatch function (:func:`pint_tpu.serve.state.dispatch_batch`) on
the single batcher thread — device work is serialized by design (one
queue in front of one accelerator), which is what makes the queue
bound of :mod:`pint_tpu.serve.admission` meaningful.

Throughput model: per-request host cost is one registry lookup and a
future; per-FLUSH cost (stacking, program dispatch, guard readout,
write-back) is amortized over batch occupancy.  At occupancy ``B``
the service does ~``1/B`` of the per-request dispatch work of a
batch-size-1 server, which is where the measured >= 2x req/s of
``bench.py serve_reqs_per_sec`` comes from.
"""

from __future__ import annotations

import threading
import time

from pint_tpu import telemetry
from pint_tpu.obs import slo as _slo
from pint_tpu.serve import admission
from pint_tpu.serve.state import ServeError, Shed, dispatch_batch

__all__ = ["CoalescingBatcher"]

#: drain-rate window: flushes completed in the last N seconds feed
#: the observed requests/s that Retry-After hints derive from
_DRAIN_WINDOW_S = 5.0


class CoalescingBatcher:
    """Holds pending requests per group key, flushes by deadline or
    occupancy.  ``dispatch`` is injectable for tests; the default is
    the real batched device dispatch."""

    def __init__(self, flush_ms=5.0, max_batch=8, queue_max=64,
                 dispatch=None):
        self.flush_ms = float(flush_ms)
        self.max_batch = max(int(max_batch), 1)
        self.queue_max = int(queue_max)
        self._dispatch = dispatch or (
            lambda key, reqs: dispatch_batch(key, reqs,
                                             self.max_batch,
                                             flush_ms=self.flush_ms))
        self._pending: dict = {}   # group key -> [Request] (FIFO)
        self._n_pending = 0
        self._drained: list = []   # (t_done, n_reqs) recent flushes
        self._cond = threading.Condition()
        self._stopped = False
        self._draining = False
        self._in_flush = 0         # flushes currently dispatching
        self._thread = threading.Thread(
            target=self._worker, name="pintserve-batcher", daemon=True)
        self._thread.start()

    # -- client side --------------------------------------------------------
    def submit(self, req):
        """Admit and enqueue one request; returns its future.  Raises
        :class:`~pint_tpu.serve.state.Shed` when the queue is at its
        bound and :class:`ServeError` after :meth:`stop`.

        The bound admission checks against is the SLO engine's
        *effective* queue_max — shrunk while the 1-minute error-budget
        burn is hot (:func:`pint_tpu.obs.slo.effective_queue_max`), so
        a replica missing its objective sheds early instead of
        queueing work it will also miss on.  Sheds count against the
        op's availability."""
        eff_queue_max = _slo.effective_queue_max(self.queue_max)
        with self._cond:
            if self._stopped:
                raise ServeError("server is shutting down")
            if self._draining:
                # a draining replica refuses NEW work with a
                # structured, immediately-retryable 503: the router's
                # readyz probe already (or imminently) pulled it from
                # rotation, so the client's retry lands on a sibling
                raise ServeError("server is draining",
                                 retry_after_s=1.0)
            try:
                admission.admit(self._n_pending, eff_queue_max,
                                self.flush_ms,
                                drain_rate=self._drain_rate_locked())
            except Shed:
                _slo.record(req.op, 0.0, ok=False)
                raise
            req.t_enqueue = time.perf_counter()
            self._pending.setdefault(req.group_key, []).append(req)
            self._n_pending += 1
            telemetry.gauge_set("serve.queue_depth", self._n_pending)
            self._cond.notify()
        telemetry.counter_add("serve.requests")
        telemetry.counter_add(f"serve.requests.{req.op}")
        return req.future

    def depth(self) -> int:
        with self._cond:
            return self._n_pending

    def _drain_rate_locked(self) -> float:
        """Observed service rate (requests/s) over the recent flush
        history; 0.0 before the first flush completes."""
        now = time.perf_counter()
        self._drained = [(t, n) for t, n in self._drained
                         if now - t <= _DRAIN_WINDOW_S]
        if not self._drained:
            return 0.0
        n = sum(c for _, c in self._drained)
        span = max(now - self._drained[0][0], self._flush_s(), 1e-3)
        return n / span

    def queue_info(self) -> dict:
        """The ``/v1/stats`` queue block: current depth, oldest
        queued request's age, per-group occupancy, observed drain
        rate."""
        with self._cond:
            now = time.perf_counter()
            oldest = None
            groups = {}
            for key, reqs in self._pending.items():
                label = ":".join(str(x) for x in key[:3])
                groups[label] = len(reqs)
                if reqs and (oldest is None
                             or reqs[0].t_enqueue < oldest):
                    oldest = reqs[0].t_enqueue
            return {
                "depth": self._n_pending,
                "oldest_age_s": (None if oldest is None
                                 else round(now - oldest, 6)),
                "groups": groups,
                "drain_rate_rps": round(self._drain_rate_locked(), 3),
                "queue_max": self.queue_max,
                "queue_max_effective":
                    _slo.effective_queue_max(self.queue_max),
            }

    def drain(self, timeout=30.0) -> bool:
        """Graceful quiesce: stop ADMITTING (new submits get a
        structured 503 whose retry lands on a sibling via the
        router), then wait until every already-admitted request has
        been flushed — served or failed, but never dropped.  Unlike
        :meth:`stop`, in-flight work completes; unlike a timeout'd
        stop, nothing is failed wholesale.  Returns True when the
        queue fully quiesced within ``timeout``."""
        deadline = time.perf_counter() + float(timeout)
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._n_pending > 0 or self._in_flush > 0:
                left = deadline - time.perf_counter()
                if left <= 0:
                    return False
                self._cond.wait(min(left, 0.1))
        return True

    def stop(self, timeout=10.0):
        """Stop the worker; pending requests fail with a structured
        503 (a draining flush would hold shutdown hostage under a
        saturated queue)."""
        with self._cond:
            self._stopped = True
            pending = [r for reqs in self._pending.values()
                       for r in reqs]
            self._pending.clear()
            self._n_pending = 0
            telemetry.gauge_set("serve.queue_depth", 0)
            self._cond.notify_all()
        for r in pending:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(
                    ServeError("server shut down before dispatch"))
        self._thread.join(timeout=timeout)

    # -- worker side --------------------------------------------------------
    def _flush_s(self):
        return self.flush_ms / 1e3

    def _ready_key_locked(self):
        """A group ready to flush: full, or its oldest member past the
        flush deadline.  Full groups win (they flush at zero added
        latency); ties resolve to the longest-waiting group."""
        now = time.perf_counter()
        oldest_key, oldest_t = None, None
        for key, reqs in self._pending.items():
            if len(reqs) >= self.max_batch:
                return key
            if oldest_t is None or reqs[0].t_enqueue < oldest_t:
                oldest_key, oldest_t = key, reqs[0].t_enqueue
        if oldest_t is not None \
                and now - oldest_t >= self._flush_s():
            return oldest_key
        return None

    def _next_wait_locked(self):
        if not self._pending:
            return None
        oldest = min(reqs[0].t_enqueue
                     for reqs in self._pending.values())
        return max(oldest + self._flush_s() - time.perf_counter(),
                   0.0)

    def _worker(self):
        while True:
            with self._cond:
                key = None
                while not self._stopped:
                    key = self._ready_key_locked()
                    if key is not None:
                        break
                    self._cond.wait(self._next_wait_locked())
                if self._stopped:
                    return
                group = self._pending[key]
                reqs = group[:self.max_batch]
                rest = group[self.max_batch:]
                if rest:
                    self._pending[key] = rest
                else:
                    del self._pending[key]
                self._n_pending -= len(reqs)
                self._in_flush += 1
                telemetry.gauge_set("serve.queue_depth",
                                    self._n_pending)
            try:
                self._dispatch(key, reqs)
            except BaseException as e:  # noqa: BLE001 — a flush crash
                # must fail ITS requests (structured 503), never the
                # worker: the next flush must still serve
                telemetry.counter_add("serve.errors")
                err = (e if isinstance(e, ServeError)
                       else ServeError(f"{type(e).__name__}: {e}"))
                for r in reqs:
                    _slo.record(r.op, 0.0, ok=False)
                    if r.future.set_running_or_notify_cancel():
                        r.future.set_exception(err)
            finally:
                # flush completed (served or failed): the requests
                # left the queue either way — that is the drain rate
                # Retry-After hints are derived from (and the
                # in-flush count drain() waits on)
                with self._cond:
                    self._drained.append(
                        (time.perf_counter(), len(reqs)))
                    self._in_flush -= 1
                    self._cond.notify_all()
