"""Serving data plane: datasets, requests, and batched dispatch.

The request lifecycle of the warm fitting service
(:mod:`pint_tpu.serve`):

1. A dataset is **registered** once (``POST /v1/load`` or
   ``pintserve --dataset``): the par file is parsed, the TOAs are
   padded to their geometric bucket (``compile_cache.bucket_size``,
   64·1.25^k), the model is ``prepare()``-d and a ``Residuals`` is
   built — all the per-pulsar host work happens HERE, never per
   request.
2. A **request** (fit / residuals / lnlike) references a dataset id
   plus per-request knobs (start-value overrides, ``maxiter``, a
   deadline).  It is assigned a **group key** — ``(op, fitter kind,
   bucket, structure fingerprint, maxiter)`` — the identity of the
   ONE compiled device program that can serve it.
3. The coalescing batcher (:mod:`pint_tpu.serve.batcher`) holds
   same-group requests up to a flush deadline, then hands the group to
   :func:`dispatch_batch`: member count is padded up to a geometric
   **size class** (1, 2, 4, ... ``max_batch`` — occupancy padding
   clones the last member, results sliced off), the cached prepared
   pairs are stacked into a :class:`~pint_tpu.parallel.pta.PTABatch`
   via ``from_prepared`` (no re-prepare), and ONE batched device call
   serves every member.  Per-member results are bit-identical to a
   batch-of-1 fit of the same request (the vmapped program computes
   members independently), so coalescing is invisible to clients.

Bounded compile surface: the only device programs this layer ever
builds are the existing PTA-batch registry keys (``pta.batched_fit``,
``pta.chisq``, ``pta.resid``) at (bucket x size-class x structure)
points — quantized on BOTH data axes, so a warm replica (or an
AOT-import manifest) covers the whole request space with a handful of
executables and a served flush after the first performs zero new XLA
compiles.  Every ``PINT_TPU_SERVE_*`` knob is host-only by
construction (enforced by ``tools/check_jit_gates.py``).

Degradation contract: a member that trips the guard ladder is served
at its rung (``status="degraded"``, the rung named); a member that
diverges past every rung — or carries fault-injected data — gets
``status="diverged"`` with its health record while its batch-mates
are served normally (the per-pulsar ladder merge of
``PTABatch._run_batched``).  No request outcome is ever a 500.
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
import time

import numpy as np

from pint_tpu import telemetry
from pint_tpu.obs import slo as _slo
from pint_tpu.obs import trace as _obs_trace

__all__ = [
    "ServeError", "Shed", "DeadlineMiss",
    "Dataset", "DatasetRegistry", "Request", "StreamSession",
    "serve_config", "size_classes", "size_class_for",
    "dispatch_batch", "warm_serve", "warm_append",
    "clear_batch_cache",
    "FLUSH_MS_ENV", "MAX_BATCH_ENV", "QUEUE_MAX_ENV", "DEADLINE_MS_ENV",
    "GRID_CHUNK_ENV", "PORT_ENV", "HOST_ENV", "JOB_DIR_ENV",
    "AOT_DIR_ENV",
]

# host-only knobs (tools/check_jit_gates.py HOST_ONLY): none of these
# may change a traced program — the batcher's compiled surface is the
# existing PTA-batch keys, quantized by bucket and size class
FLUSH_MS_ENV = "PINT_TPU_SERVE_FLUSH_MS"
MAX_BATCH_ENV = "PINT_TPU_SERVE_MAX_BATCH"
QUEUE_MAX_ENV = "PINT_TPU_SERVE_QUEUE_MAX"
DEADLINE_MS_ENV = "PINT_TPU_SERVE_DEADLINE_MS"
GRID_CHUNK_ENV = "PINT_TPU_SERVE_GRID_CHUNK"
PORT_ENV = "PINT_TPU_SERVE_PORT"
HOST_ENV = "PINT_TPU_SERVE_HOST"
JOB_DIR_ENV = "PINT_TPU_SERVE_JOB_DIR"
AOT_DIR_ENV = "PINT_TPU_SERVE_AOT_DIR"

#: residual payloads are capped (a 10k-TOA dataset must not ship a
#: megabyte of JSON per request); the full array stays device-side
RESID_PAYLOAD_CAP = 256


def _env_num(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def serve_config(**overrides) -> dict:
    """The serving knobs: env defaults overlaid with explicit
    (non-None) overrides — the one place the ``PINT_TPU_SERVE_*``
    defaults live."""
    cfg = {
        "flush_ms": _env_num(FLUSH_MS_ENV, 5.0),
        "max_batch": int(_env_num(MAX_BATCH_ENV, 8)),
        "queue_max": int(_env_num(QUEUE_MAX_ENV, 64)),
        "deadline_ms": _env_num(DEADLINE_MS_ENV, 0.0),
        "grid_chunk": int(_env_num(GRID_CHUNK_ENV, 16)),
    }
    cfg.update({k: v for k, v in overrides.items() if v is not None})
    return cfg


# --------------------------------------------------------------------------
# structured request outcomes (never a 500)
# --------------------------------------------------------------------------

class ServeError(Exception):
    """A structured serving refusal: carries the HTTP status and an
    optional Retry-After hint.  Every error path of the service maps
    to one of these — an unexpected exception becomes the base class
    (503), never a 500."""

    status = 503

    def __init__(self, detail, retry_after_s=None):
        self.detail = str(detail)
        self.retry_after_s = retry_after_s
        super().__init__(self.detail)


class Shed(ServeError):
    """Admission control refused the request (queue saturated):
    429 + Retry-After."""

    status = 429


class DeadlineMiss(ServeError):
    """The request's deadline expired before its batch dispatched:
    504 (the work was never started — safe to retry)."""

    status = 504


# --------------------------------------------------------------------------
# size classes: quantized batch occupancy
# --------------------------------------------------------------------------

def size_classes(max_batch) -> tuple:
    """Geometric member-count classes (1, 2, 4, ... max_batch): the
    pulsar-axis analogue of the TOA buckets.  Each (bucket, class)
    pair is ONE compiled program; occupancy padding clones the last
    member up to the class size so batch occupancy can vary without
    minting new executables."""
    out = []
    c = 1
    while c < int(max_batch):
        out.append(c)
        c *= 2
    out.append(int(max_batch))
    return tuple(out)


def size_class_for(n, max_batch) -> int:
    """Smallest size class >= n (n above max_batch is the caller's
    bug — the batcher never pops more than max_batch)."""
    for c in size_classes(max_batch):
        if n <= c:
            return c
    raise ValueError(f"batch of {n} exceeds max_batch={max_batch}")


# --------------------------------------------------------------------------
# datasets
# --------------------------------------------------------------------------

_dataset_tokens = iter(range(1, 1 << 62))


class Dataset:
    """One registered pulsar dataset: the prepared, bucket-padded
    (model, toas) pair every request against this id reuses.  The
    registry values/meta are snapshotted so fit write-backs can be
    rolled back after every flush — served datasets are immutable.
    ``token`` is process-unique (keys the stacked-batch cache; a
    reloaded dataset gets a fresh token, so stale stacks can never be
    served)."""

    __slots__ = ("dataset_id", "model", "toas", "prepared", "resid",
                 "bucket", "n_real", "kind", "structure", "token",
                 "noise_owned", "version", "_values_snapshot",
                 "_rung_snapshot")

    def __init__(self, dataset_id, model, toas):
        from pint_tpu import compile_cache as _cc
        from pint_tpu.residuals import Residuals

        self.dataset_id = str(dataset_id)
        self.version = 1
        self.n_real = len(toas)
        toas = _cc.pad_toas(toas)
        self.model = model
        self.toas = toas
        self.bucket = len(toas)
        self.prepared = model.prepare(toas)
        self.resid = Residuals(toas, self.prepared,
                               track_mode="nearest")
        self.kind = "gls" if model.has_correlated_errors else "wls"
        # the group fingerprint: component structure + the exact
        # free-parameter set (the PTA batch free-union must be stable
        # across flush compositions) + the bucket
        self.structure = _cc.fingerprint((
            _cc.model_structure_key(model),
            tuple(model.free_params), self.bucket))
        self.noise_owned = {
            par.name for c in model.noise_components
            for par in c.params}
        self.token = next(_dataset_tokens)
        self._values_snapshot = dict(model.values)
        self._rung_snapshot = model.meta.get("GUARD_RUNG")

    def restore(self):
        """Roll the model back to its registry state (values + guard
        rung flag) after a flush's write-back."""
        self.model.values.clear()
        self.model.values.update(self._values_snapshot)
        if self._rung_snapshot is None:
            self.model.meta.pop("GUARD_RUNG", None)
        else:
            self.model.meta["GUARD_RUNG"] = self._rung_snapshot

    @classmethod
    def published(cls, prev, fitter):
        """The streaming-append publish: a NEW version wrapping the
        session fitter's CURRENT (toas, prepared, resids) — no
        re-prepare — with a PRIVATE model clone (own values/meta
        dicts), so later appends (which keep mutating the session
        model) can never leak into this version's in-flight requests.
        The prepared/resids wrappers are shallow copies re-pointed at
        the clone; their arrays and jit caches are shared (immutable /
        registry-backed)."""
        import copy as _copy

        from pint_tpu import compile_cache as _cc

        self = cls.__new__(cls)
        src = fitter.model
        clone = _copy.copy(src)
        clone.values = dict(src.values)
        clone.meta = dict(src.meta)
        prep = _copy.copy(fitter.prepared)
        prep.model = clone
        resid = _copy.copy(fitter.resids)
        resid.prepared = prep
        resid.model = clone
        self.dataset_id = prev.dataset_id
        self.version = prev.version + 1
        self.model = clone
        self.toas = fitter.toas
        self.prepared = prep
        self.resid = resid
        self.n_real = resid.n_real
        self.bucket = len(fitter.toas)
        self.kind = prev.kind
        self.structure = _cc.fingerprint((
            _cc.model_structure_key(clone),
            tuple(clone.free_params), self.bucket))
        self.noise_owned = prev.noise_owned
        self.token = next(_dataset_tokens)
        self._values_snapshot = dict(clone.values)
        self._rung_snapshot = clone.meta.get("GUARD_RUNG")
        return self

    def info(self) -> dict:
        return {"dataset": self.dataset_id, "n_toas": self.n_real,
                "bucket": self.bucket, "kind": self.kind,
                "version": self.version,
                "free_params": list(self.model.free_params),
                "structure": self.structure}


#: synthetic-TOA spec defaults for /v1/load without a tim file
_TOA_SPEC_DEFAULTS = {
    "n": 64, "start_mjd": 53000.0, "duration_days": 1500.0,
    "freq_mhz": 1400.0, "obs": "gbt", "error_us": 1.0, "seed": 0,
    "add_noise": True,
}


def _build_toas(model, toas=None, tim=None, flags=None,
                defaults=None):
    """TOAs from a request body: a server-local ``tim`` path, or a
    synthetic spec dict over ``model`` (shared by /v1/load and the
    append endpoint — appends use the same vocabulary to describe a
    night's new arrivals)."""
    if tim is not None:
        from pint_tpu.toa import get_TOAs

        return get_TOAs(tim)
    from pint_tpu.simulation import make_fake_toas_uniform

    spec = dict(defaults if defaults is not None
                else _TOA_SPEC_DEFAULTS)
    spec.update(toas or {})
    return make_fake_toas_uniform(
        float(spec["start_mjd"]),
        float(spec["start_mjd"]) + float(spec["duration_days"]),
        int(spec["n"]), model,
        freq_mhz=float(spec["freq_mhz"]),
        obs=str(spec["obs"]),
        error_us=float(spec["error_us"]),
        add_noise=bool(spec["add_noise"]),
        rng=np.random.default_rng(int(spec["seed"])),
        flags=flags)


class StreamSession:
    """Per-dataset persistent streaming state: a PRIVATE fitter (own
    model clone over the dataset's padded TOAs) that absorbs appends
    through the rank-k Woodbury path (:meth:`Fitter.append_refit`).
    Each successful append is snapshotted into a fresh immutable
    :meth:`Dataset.published` version; the session itself is never
    served, so the refit write-backs can't race a flush's
    values-rollback window.

    A model whose streaming path is unsupported (free noise
    parameters — the capture needs the frozen-noise leaves) degrades
    to append + full refit: same versioned publish, no incremental
    speedup."""

    def __init__(self, ds, maxiter=3):
        import copy as _copy

        from pint_tpu.fitter import GLSFitter, WLSFitter

        model = _copy.copy(ds.model)
        model.values = dict(ds.model.values)
        model.meta = dict(ds.model.meta)
        cls = GLSFitter if ds.kind == "gls" else WLSFitter
        self.fitter = cls(ds.toas, model, bucket=True)
        self.maxiter = int(maxiter)
        self.fitter.fit_toas(maxiter=self.maxiter)
        self.incremental = True
        try:
            self.fitter.stream_prepare()
        except NotImplementedError:
            self.incremental = False
        telemetry.counter_add("stream.sessions")

    def append(self, delta, triage_sigma=None) -> dict:
        """Absorb one delta; returns the fitter's append report."""
        if not self.incremental:
            self.fitter.append(delta)
            chi2 = self.fitter.fit_toas(maxiter=self.maxiter)
            return {"mode": "refit_full", "chi2": float(chi2),
                    "triage": {"verdict": "skipped",
                               "quarantine": []}}
        return self.fitter.append_refit(
            delta, triage_sigma=triage_sigma, maxiter=self.maxiter)


class DatasetRegistry:
    """id -> :class:`Dataset`; the control plane the data plane serves
    from.  Registration is the expensive host-side work (parse,
    prepare, pad) and happens outside the request hot path.

    ``generation`` increments on every (re)load — it keys the stacked
    batch cache, so replacing a dataset can never serve a stale
    stack."""

    def __init__(self):
        self._datasets: dict = {}
        self.generation = 0
        self._streams: dict = {}
        self._append_lock = threading.Lock()

    def load(self, dataset_id, par, toas=None, tim=None,
             flags=None) -> dict:
        """Register a dataset: ``par`` is par-file text; the TOAs come
        from ``tim`` (a server-local ``.tim`` path) or a synthetic
        spec dict (``{"n", "start_mjd", "duration_days", "error_us",
        "freq_mhz", "obs", "seed", "add_noise"}``; missing keys
        default).  Returns the dataset info dict.  Re-registering an
        id replaces it."""
        from pint_tpu.models.builder import get_model

        model = get_model(par)
        toas_obj = _build_toas(model, toas=toas, tim=tim, flags=flags)
        ds = Dataset(dataset_id, model, toas_obj)
        self._datasets[ds.dataset_id] = ds
        # a re-load is a NEW dataset: any streaming session over the
        # replaced one is linearized against dead data
        self._streams.pop(ds.dataset_id, None)
        self.generation += 1
        telemetry.counter_add("serve.datasets_loaded")
        telemetry.gauge_set("serve.datasets", len(self._datasets))
        return ds.info()

    def append(self, dataset_id, toas=None, tim=None, flags=None,
               maxiter=3, triage_sigma=None) -> dict:
        """The streaming ingest pipeline: triage -> incremental refit
        -> atomic version publish.

        The session fitter absorbs the delta (anomaly triage
        quarantines glitch/acceleration-shaped outliers into the
        zero-weight guard ladder; a bucket-boundary crossing falls
        back to a full re-prepare), then a NEW dataset version is
        published as a single dict swap — in-flight requests keep the
        version object they were admitted against, new requests see
        the appended one.  The fitter mutates only session-private
        state, so a crash anywhere before the swap leaves the served
        version untouched (the chaos site ``stream.append`` kills
        exactly there to prove it) and the session is simply rebuilt
        from the registry on the next append."""
        from pint_tpu import faults as _faults

        t0_wall = time.time()
        t0 = time.perf_counter()
        ds = self.get(dataset_id)
        with self._append_lock:
            try:
                session = self._streams.get(ds.dataset_id)
                if session is None:
                    session = StreamSession(ds, maxiter=maxiter)
                    self._streams[ds.dataset_id] = session
                spec_defaults = dict(_TOA_SPEC_DEFAULTS)
                spec_defaults.update({
                    "n": 8, "duration_days": 1.0,
                    "start_mjd": float(np.max(
                        np.asarray(ds.toas.mjd_float))) + 1.0,
                })
                delta = _build_toas(session.fitter.model, toas=toas,
                                    tim=tim, flags=flags,
                                    defaults=spec_defaults)
                rep = session.append(delta,
                                     triage_sigma=triage_sigma)
                new_ds = Dataset.published(ds, session.fitter)
                # the atomicity probe: a kill HERE (after the session
                # mutated, before the publish) must leave the served
                # version untouched and the retry must succeed
                _faults.maybe_kill("stream.append")
                _faults.maybe_delay("stream.append")
                with SERVING_LOCK:
                    self._datasets[ds.dataset_id] = new_ds
                    self.generation += 1
            except ServeError:
                raise
            except Exception:
                # a torn session must not survive: rebuild from the
                # (unchanged) served version on the next append
                self._streams.pop(ds.dataset_id, None)
                telemetry.counter_add("stream.append_errors")
                _slo.record("append", 0.0, ok=False)
                raise
        freshness_s = time.time() - t0_wall
        wall_s = time.perf_counter() - t0
        telemetry.counter_add("stream.publishes")
        telemetry.gauge_set("stream.freshness_s", freshness_s)
        telemetry.gauge_set("stream.version", float(new_ds.version))
        _slo.record("append", wall_s, ok=True)
        tri = rep.get("triage") or {}
        quarantined = [int(i) for i in
                       np.asarray(tri.get("quarantine", []),
                                  dtype=np.int64).tolist()]
        doc = {
            "dataset": new_ds.dataset_id,
            "version": new_ds.version,
            "n_toas": new_ds.n_real,
            "n_appended": len(delta),
            "bucket": new_ds.bucket,
            "mode": rep.get("mode"),
            "verdict": tri.get("verdict", "skipped"),
            "quarantined": quarantined,
            "chi2": (float(rep["chi2"])
                     if rep.get("chi2") is not None else None),
            "freshness_s": round(freshness_s, 6),
            "latency_ms": round(wall_s * 1e3, 3),
        }
        return doc

    def get(self, dataset_id) -> Dataset:
        try:
            return self._datasets[str(dataset_id)]
        except KeyError:
            raise ValueError(
                f"unknown dataset {dataset_id!r} (register it via "
                "/v1/load first)") from None

    def ids(self):
        return sorted(self._datasets)

    def build_request(self, op, params, default_deadline_ms=0.0,
                      trace=None) -> "Request":
        """Validate one request body into a :class:`Request` (raises
        ValueError on a malformed request — the 400 path).

        ``trace`` is the admission-time
        :class:`~pint_tpu.obs.trace.TraceContext` (continued from the
        client's ``traceparent`` header or freshly minted); every
        serve-plane call site must pass it — pintlint rule PTL105
        flags a handler that drops it."""
        if op not in ("fit", "residuals", "lnlike"):
            raise ValueError(f"unknown op {op!r}")
        if not isinstance(params, dict):
            raise ValueError("request body must be a JSON object")
        ds = self.get(params.get("dataset"))
        maxiter = int(params.get("maxiter", 3)) if op == "fit" else 0
        if op == "fit" and not 1 <= maxiter <= 50:
            raise ValueError(f"maxiter {maxiter} out of range [1, 50]")
        overrides = params.get("values") or {}
        if not isinstance(overrides, dict):
            raise ValueError("'values' must be an object")
        for name, v in overrides.items():
            if name not in ds.model.values:
                raise ValueError(
                    f"override {name!r} is not a parameter of "
                    f"dataset {ds.dataset_id!r}")
            if name in ds.noise_owned:
                raise ValueError(
                    f"override {name!r} is a noise-model parameter — "
                    "the GLS basis/weights are gathered at registry "
                    "values (the chisq_grid restriction)")
            float(v)  # must be numeric
        deadline_ms = float(params.get("deadline_ms",
                                       default_deadline_ms) or 0.0)
        deadline = (time.time() + deadline_ms / 1e3
                    if deadline_ms > 0 else None)
        return Request(op, ds, params, maxiter, deadline, trace=trace)


class Request:
    """One in-flight request: its dataset, knobs, coalescing group
    key, trace context, and the future its response lands on."""

    __slots__ = ("op", "dataset", "params", "maxiter", "deadline",
                 "group_key", "future", "t_submit", "t_submit_wall",
                 "t_enqueue", "trace")

    def __init__(self, op, dataset, params, maxiter, deadline,
                 trace=None):
        self.op = op
        self.dataset = dataset
        self.params = params
        self.maxiter = maxiter
        self.deadline = deadline
        self.group_key = (op, dataset.kind, dataset.bucket,
                          dataset.structure, maxiter)
        self.future = concurrent.futures.Future()
        self.t_submit = time.perf_counter()
        self.t_submit_wall = time.time()
        self.t_enqueue = None
        # every request rides a trace (defensive mint: a caller that
        # somehow bypassed admission still yields traceable spans)
        self.trace = trace if trace is not None else _obs_trace.mint()


# --------------------------------------------------------------------------
# batched dispatch: the device hot path
# --------------------------------------------------------------------------

def _finish_error(req, exc):
    if not req.future.set_running_or_notify_cancel():
        return
    req.future.set_exception(exc)


#: stacked-batch LRU: the serving hot path's memoization.  One entry
#: per (ordered member-token tuple) — a steady request mix re-serves
#: the same hot member combinations, so the per-flush stacking cost
#:(~3 ms/member of eager device puts) collapses to a dict hit.
#: Entries hold pristine (values0, base_values) refs so per-request
#: overrides (which REPLACE those attributes) roll back on the next
#: hit.  Mutated only under :data:`SERVING_LOCK`.  Skipped entirely
#: while fault injection is active: a corrupt stack must neither be
#: cached nor masked by a clean cached one.
_batch_cache: "dict" = {}
_BATCH_CACHE_CAP = 64

#: serializes every touch of the shared serving state — the stacked
#: batch cache, the cached PTABatch objects, and the registry models'
#: write-back/rollback window.  Normally only the batcher thread
#: dispatches, but explicit warmup (:func:`warm_serve` from the boot
#: thread, possibly while the listener already accepts requests) and
#: the jobs worker (which snapshots a dataset's model for its own
#: isolated copy) must not observe — or tear — a flush in progress.
SERVING_LOCK = threading.RLock()


def clear_batch_cache():
    _batch_cache.clear()


def _stacked_batch(sorted_datasets):
    from pint_tpu import faults as _faults
    from pint_tpu.parallel.pta import PTABatch

    if _faults.any_active():
        return PTABatch.from_prepared(
            [d.prepared for d in sorted_datasets],
            [d.resid for d in sorted_datasets])
    key = tuple(d.token for d in sorted_datasets)
    got = _batch_cache.get(key)
    if got is not None:
        batch, pristine = got
        batch.values0, batch.base_values = pristine
        telemetry.counter_add("serve.batch_cache_hits")
        return batch
    batch = PTABatch.from_prepared(
        [d.prepared for d in sorted_datasets],
        [d.resid for d in sorted_datasets])
    _batch_cache[key] = (batch, (batch.values0, batch.base_values))
    while len(_batch_cache) > _BATCH_CACHE_CAP:
        del _batch_cache[next(iter(_batch_cache))]
    telemetry.counter_add("serve.batch_cache_misses")
    return batch


def _apply_overrides(batch, members, rows):
    """Patch per-request start-value overrides into the stacked
    ``values0`` / ``base_values`` rows (never into the shared model
    objects — two requests on one dataset may override differently
    inside one flush).  ``rows[k]`` is member k's stacked row."""
    import jax.numpy as jnp

    if not any(m.params.get("values") for m in members):
        return
    v0 = np.asarray(batch.values0).copy()
    base = dict(batch.base_values)
    patched = {}
    for k, m in enumerate(members):
        for name, val in (m.params.get("values") or {}).items():
            val = float(val)
            if name in batch.free_names:
                v0[rows[k], batch.free_names.index(name)] = val
            if name in base:
                arr = patched.get(name)
                if arr is None:
                    arr = patched[name] = np.asarray(base[name]).copy()
                arr[rows[k]] = val
    batch.values0 = jnp.asarray(v0)
    for name, arr in patched.items():
        base[name] = jnp.asarray(arr)
    batch.base_values = base


def _health_slice(health, k):
    """Member k's rows of a batched host-side health record dict."""
    return {name: (v[k] if isinstance(v, list) and k < len(v) else v)
            for name, v in (health or {}).items()}


def _member_values(batch, vec_np, k, ds):
    """The fitted values a member's response reports: the dataset's
    OWN free parameters (the union may be wider on a mixed group)."""
    own = set(ds.model.free_params)
    return {name: float(vec_np[k, i])
            for i, name in enumerate(batch.free_names) if name in own}


def _run_fit(batch, live, rows, maxiter):
    """The batched fit plus per-member outcome assembly.  A
    FitDivergedError is the PER-MEMBER degradation path here, never a
    request failure: healthy members are served from the partial
    results the error carries."""
    from pint_tpu import guard as _guard

    kind_fit = (batch.fit_gls
                if batch.prepareds[0].model.has_correlated_errors
                else batch.fit_wls)
    bad, health = set(), {}
    try:
        vec, chi2, cov = kind_fit(maxiter=maxiter)
    except _guard.FitDivergedError as e:
        if e.results is None:
            raise
        vec, chi2, cov = e.results
        bad = set(int(i) for i in (e.bad_indices or ()))
        health = e.health or {}
    vec_np = np.asarray(vec)
    chi2_np = np.asarray(chi2)
    # per-ROW rung readout (batch.fit_rungs), never model.meta: with
    # dedup/occupancy padding one model may occupy several rows, and
    # its shared meta dict would report the LAST row's rung for all
    rungs = getattr(batch, "fit_rungs", {})
    out = []
    for k, req in enumerate(live):
        row = rows[k]
        rung = rungs.get(row)
        if row in bad:
            telemetry.counter_add("serve.diverged")
            out.append({
                "status": "diverged",
                "rung": rung,
                "detail": "fit diverged past every guard rung; "
                          "values unchanged",
                "health": _health_slice(health, row),
            })
            continue
        if rung is not None:
            telemetry.counter_add("serve.degraded")
        out.append({
            "status": "degraded" if rung else "ok",
            "rung": rung,
            "chi2": float(chi2_np[row]),
            "values": _member_values(batch, vec_np, row, req.dataset),
        })
    return out


def _run_residuals(batch, live, rows):
    r = batch.residuals_shared()
    out = []
    for k, req in enumerate(live):
        n = req.dataset.n_real
        row = np.asarray(r[rows[k], :n], dtype=np.float64)
        rec = {"status": "ok", "n": int(n),
               "rms_s": float(np.sqrt(np.mean(row ** 2)))}
        if n <= RESID_PAYLOAD_CAP:
            rec["resid_s"] = [float(x) for x in row]
        else:
            rec["resid_s_truncated"] = RESID_PAYLOAD_CAP
            rec["resid_s"] = [float(x)
                              for x in row[:RESID_PAYLOAD_CAP]]
        out.append(rec)
    return out


def _run_lnlike(batch, live, rows):
    chi2 = batch.chisq()
    return [{"status": "ok", "chi2": float(chi2[rows[k]]),
             "lnlike": -0.5 * float(chi2[rows[k]])}
            for k in range(len(live))]


def dispatch_batch(group_key, reqs, max_batch, flush_ms=0.0,
                   record_slo=True):
    """Serve one coalesced group as ONE batched device call.

    The batcher's flush handler: drops deadline-expired members
    (504), pads the member count to a size class, stacks the cached
    prepared pairs (``PTABatch.from_prepared`` — no re-prepare),
    applies per-request value overrides into the stacked rows, runs
    the op's shared program, and fulfills every member's future with
    a structured outcome.  Model write-backs are rolled back before
    returning, so served datasets stay immutable.

    Observability: the device call is recorded as ONE shared
    ``trace_span`` fanning into a per-member request span each (one
    atomic :func:`~pint_tpu.telemetry.emit_group`), every member's
    result carries its ``trace`` doc plus a ``phase_s`` decomposition
    — ``queue`` (backlog wait beyond the coalescing hold), ``coalesce``
    (the deliberate flush hold, bounded by ``flush_ms``), ``build``
    (stack/override share), ``device``, ``writeback`` — and each
    outcome lands in the SLO tracker (``record_slo=False`` for warmup
    flushes, whose compile-heavy walls must not burn the budget).

    Also the chaos kill site ``serve.flush``: a deterministic
    mid-batch kill (``PINT_TPU_FAULTS=kill:site=serve.flush``)
    exercises the restart/resubmit story, and the slow-flush delay
    site (``PINT_TPU_FAULTS=slow_flush:ms=...``) the SLO-violation
    one."""
    from pint_tpu import faults as _faults

    _faults.maybe_kill("serve.flush")
    _faults.maybe_delay("serve.flush")
    op = group_key[0]
    now = time.time()
    live = []
    for r in reqs:
        if r.deadline is not None and now > r.deadline:
            telemetry.counter_add("serve.deadline_misses")
            if record_slo:
                _slo.record(op, 0.0, ok=False)
            _finish_error(r, DeadlineMiss(
                "deadline expired before the batch dispatched"))
        else:
            live.append(r)
    if not live:
        return
    t_build0 = time.perf_counter()
    # request dedup: same-dataset requests with identical value
    # overrides are the SAME computation — they share one stacked row
    # (and therefore one slice of device work), and a hot-dataset
    # burst collapses to a small batch.  Dedup also shrinks the
    # member-combination space from multisets to subsets, so the
    # stacked-batch cache reaches steady-state hits within a few
    # flushes even on a mixed stream.
    unique: dict = {}
    uniq = []
    req_uniq = []
    for r in live:
        ov = r.params.get("values") or {}
        okey = (r.dataset.token,
                tuple(sorted((n, float(v)) for n, v in ov.items())))
        idx = unique.get(okey)
        if idx is None:
            idx = unique[okey] = len(uniq)
            uniq.append(r)
        req_uniq.append(idx)
    if len(uniq) < len(live):
        telemetry.counter_add("serve.deduped",
                              float(len(live) - len(uniq)))
    size = size_class_for(len(uniq), max_batch)
    members = uniq + [uniq[-1]] * (size - len(uniq))
    datasets = {id(m.dataset): m.dataset for m in members}
    # canonical member order (by dataset id): flush composition
    # becomes order-insensitive, so the stacked-batch cache hits on
    # any permutation of a hot member set
    order = sorted(range(size),
                   key=lambda k: (members[k].dataset.dataset_id, k))
    member_rows = [0] * size
    for rank, k in enumerate(order):
        member_rows[k] = rank
    rows = [member_rows[i] for i in req_uniq]
    with SERVING_LOCK:
        try:
            batch = _stacked_batch(
                [members[k].dataset for k in order])
            _apply_overrides(batch, members, member_rows)
            build_s = time.perf_counter() - t_build0
            with telemetry.run_scope(
                    "serve.batch", op=op, bucket=group_key[2],
                    occupancy=len(live), unique=len(uniq),
                    size=size) as run, \
                    _obs_trace.collect_programs() as progs:
                batch_run = run.run_id
                t_dev0_wall = time.time()
                t_dev0 = time.perf_counter()
                if op == "fit":
                    results = _run_fit(batch, live, rows,
                                       group_key[4])
                elif op == "residuals":
                    results = _run_residuals(batch, live, rows)
                else:
                    results = _run_lnlike(batch, live, rows)
                device_s = time.perf_counter() - t_dev0
        finally:
            for ds in datasets.values():
                ds.restore()
    telemetry.counter_add("serve.batches")
    if len(live) > 1:
        telemetry.counter_add("serve.coalesced", float(len(live) - 1))
    telemetry.hist_record("serve.batch_occupancy", float(len(live)))
    total_req = telemetry.counter_get("serve.requests")
    if total_req:
        telemetry.gauge_set(
            "serve.coalesce_ratio",
            telemetry.counter_get("serve.coalesced") / total_req)
    t_done = time.perf_counter()
    dev_share = device_s / len(live)
    build_share = build_s / len(live)
    # write-back: guard readout + outcome assembly + rollback, from
    # device completion to response fulfillment (shared by members)
    writeback_s = max(t_done - (t_dev0 + device_s), 0.0)
    flush_hold = max(float(flush_ms), 0.0) / 1e3
    sink_on = telemetry.sink_active()
    span_group = []
    dev_span = _obs_trace.new_span_id() if sink_on else None
    for k, req in enumerate(live):
        rec = dict(results[k])
        wait_s = (t_build0 - req.t_enqueue
                  if req.t_enqueue is not None else 0.0)
        wait_s = max(wait_s, 0.0)
        # the coalescing hold is policy (bounded by flush_ms); any
        # wait beyond it is backlog — the queue/coalesce split is
        # what makes "slow because saturated" and "slow because
        # batching" distinguishable per response
        coalesce_s = min(wait_s, flush_hold)
        queue_s = wait_s - coalesce_s
        wall_s = t_done - req.t_submit
        rec["batch"] = {"run": batch_run, "occupancy": len(live),
                        "unique": len(uniq), "size": size,
                        "bucket": group_key[2]}
        rec["phase_s"] = {"queue": round(queue_s, 6),
                          "coalesce": round(coalesce_s, 6),
                          "build": round(build_share, 6),
                          "device": round(dev_share, 6),
                          "writeback": round(writeback_s, 6),
                          "total": round(wall_s, 6)}
        rec["trace"] = req.trace.to_doc()
        # one request span per member, joined both to the batch's run
        # id (ledger: compile/phase attribution) and — via the span
        # link — to the shared device span; emitted as ONE group
        # below so rotation can never split the batch's tree
        if sink_on:
            span_group.append(_obs_trace.request_span_record(
                req.trace, ts=round(req.t_submit_wall, 6),
                dur_s=round(wall_s, 6), device_span=dev_span,
                phase_s=rec["phase_s"], op=op, run=batch_run,
                dataset=req.dataset.dataset_id,
                status=rec.get("status")))
        telemetry.hist_record("serve.queue_s", max(wait_s, 0.0))
        telemetry.hist_record("serve.device_s", dev_share)
        telemetry.hist_record("serve.wall_s", wall_s)
        if record_slo:
            _slo.record(op, wall_s, ok=True)
        results[k] = rec
    if sink_on:
        span_group.insert(0, _obs_trace.device_span_record(
            dev_span, ts=round(t_dev0_wall, 6),
            dur_s=round(device_s, 6),
            links=[{"trace": r.trace.trace_id,
                    "span": r.trace.span_id} for r in live],
            op=op, run=batch_run, bucket=group_key[2],
            occupancy=len(live), size=size,
            programs=list(progs.labels)))
        telemetry.counter_add("obs.trace_spans",
                              float(len(span_group)))
        telemetry.emit_group(span_group)
    for k, req in enumerate(live):
        if req.future.set_running_or_notify_cancel():
            req.future.set_result(results[k])


def warm_serve(registry, dataset_id, max_batch, ops=("fit",),
               sizes=None, maxiter=3):
    """Explicit warmup: run one synchronous flush per (op, size
    class) against a registered dataset, compiling (or AOT-serving)
    every program the configured request space can reach.  The
    export rehearsal (``pintserve --export``) and a replica booted
    with ``--warm`` both run this; a cold replica that imported an
    AOT manifest instead reaches the same state with zero uncached
    compiles.  Returns per-program records."""
    out = []
    classes = sizes if sizes is not None else size_classes(max_batch)
    ds = registry.get(dataset_id)
    # distinct per-member start-value jitter: without it the dedup
    # pass would collapse c identical warm requests into ONE stacked
    # row and the size-c program would never build.  The jitter is
    # dynamic data (same program), far below fit precision, and the
    # warm results are discarded anyway.
    jit_name = ds.model.free_params[0]
    jit_base = float(ds.model.values[jit_name])
    for op in ops:
        for c in classes:
            reqs = [registry.build_request(
                op, {"dataset": dataset_id, "maxiter": maxiter,
                     "values": {jit_name: jit_base
                                + (abs(jit_base) + 1.0)
                                * 1e-13 * i}},
                trace=_obs_trace.mint())
                for i in range(c)]
            for r in reqs:
                r.t_enqueue = time.perf_counter()
            t0 = time.perf_counter()
            # warm flushes are compile-heavy by design: keep their
            # walls out of the SLO windows (a booting replica must
            # not burn its own error budget)
            dispatch_batch(reqs[0].group_key, reqs, max_batch,
                           record_slo=False)
            for r in reqs:
                r.future.result()  # surface warmup failures loudly
            out.append({"op": op, "size": c,
                        "wall_s": round(time.perf_counter() - t0, 3)})
    telemetry.counter_add("serve.warm_flushes", float(len(out)))
    return out


def warm_append(registry, dataset_id, maxiter=3):
    """Warm the streaming-append compile surface for a dataset
    WITHOUT mutating it: a THROWAWAY session (private model clone over
    the same padded bucket) absorbs one tiny synthetic append and is
    discarded.  The programs it builds — the session fit ladder, the
    stream capture, the mini-delta evaluation, and the rank-k refit —
    are registry-shared by structure, so the real session created by
    the first client append reuses every one of them and a
    sanitizer-armed replica streams appends with zero steady-state
    compiles.  Best-effort: an unsupported model shape just skips."""
    ds = registry.get(dataset_id)
    t0 = time.perf_counter()
    try:
        session = StreamSession(ds, maxiter=maxiter)
        start = float(np.max(np.asarray(ds.toas.mjd_float))) + 1.0
        # carry the dataset's frontend flag so the warm delta lands in
        # the same noise-mask groups a real night's arrivals would
        fl = (ds.toas.flags[0] or {}).get("f") \
            if getattr(ds.toas, "flags", None) else None
        delta = _build_toas(
            session.fitter.model,
            toas={"n": 4, "start_mjd": start, "duration_days": 1.0,
                  "seed": 1},
            flags={"f": fl} if fl else None)
        session.append(delta)
    except Exception as e:  # noqa: BLE001 — warmup is best-effort
        return {"dataset": dataset_id, "warmed": False,
                "detail": f"{type(e).__name__}: {e}"}
    telemetry.counter_add("stream.warm_appends")
    return {"dataset": dataset_id, "warmed": True,
            "wall_s": round(time.perf_counter() - t0, 3)}
