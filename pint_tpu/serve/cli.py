"""``pintserve``: boot a warm fitting replica (or build its deploy
artifact).

Examples::

    # dev replica on an ephemeral port, warmed by compiling
    pintserve --port 0 --warm

    # build the deploy artifact: dress-rehearse the serve programs,
    # serialize them, exit
    PINT_TPU_CACHE_DIR=/fast/xla pintserve --export /fast/aot

    # production replica: import the artifact, reach warm serving
    # with zero uncached XLA backend compiles, expose Prometheus
    PINT_TPU_CACHE_DIR=/fast/xla PINT_TPU_METRICS_PORT=9464 \\
        pintserve --import /fast/aot --port 8470 \\
        --dataset J1855=J1855.par,J1855.tim

Knobs default from ``$PINT_TPU_SERVE_*`` (flush deadline, max batch,
queue bound, default request deadline, job dir, AOT dir); flags
override.  See docs/serving.md.
"""

from __future__ import annotations

import argparse
import os
import sys

__all__ = ["main"]


def main(argv=None):
    from pint_tpu.serve.state import (
        AOT_DIR_ENV,
        HOST_ENV,
        PORT_ENV,
        serve_config,
    )

    p = argparse.ArgumentParser(
        prog="pintserve",
        description="Warm fitting service: coalesced batched "
                    "fit/residual/lnlike serving + async jobs")
    p.add_argument("--host", default=None,
                   help=f"bind host (default ${HOST_ENV} or "
                        "127.0.0.1)")
    p.add_argument("--port", type=int, default=None,
                   help=f"bind port (default ${PORT_ENV} or 8470; "
                        "0 = ephemeral)")
    p.add_argument("--flush-ms", type=float, default=None,
                   help="coalescing flush deadline "
                        "[$PINT_TPU_SERVE_FLUSH_MS, default 5]")
    p.add_argument("--max-batch", type=int, default=None,
                   help="max members per batched dispatch "
                        "[$PINT_TPU_SERVE_MAX_BATCH, default 8]")
    p.add_argument("--queue-max", type=int, default=None,
                   help="admission bound on pending requests "
                        "[$PINT_TPU_SERVE_QUEUE_MAX, default 64]")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline, 0 = none "
                        "[$PINT_TPU_SERVE_DEADLINE_MS]")
    p.add_argument("--job-dir", default=None,
                   help="job/checkpoint directory "
                        "[$PINT_TPU_SERVE_JOB_DIR]")
    p.add_argument("--import", dest="import_dir", metavar="DIR",
                   default=None,
                   help="AOT manifest to import at boot (zero-"
                        f"uncached-compile cold start) "
                        f"[${AOT_DIR_ENV}]")
    p.add_argument("--export", dest="export_dir", metavar="DIR",
                   default=None,
                   help="dress-rehearse the serve programs, "
                        "serialize executables to DIR, exit (the "
                        "deploy artifact for --import replicas)")
    p.add_argument("--warm", action="store_true",
                   help="explicit warmup at boot (compile every "
                        "(op, size-class) program now instead of on "
                        "first request); implied by --export")
    p.add_argument("--dataset", action="append", default=[],
                   metavar="ID=PAR[,TIM]",
                   help="register a dataset at boot: par file path "
                        "(+ optional tim path; synthetic TOAs "
                        "otherwise); repeatable")
    args = p.parse_args(argv)

    from pint_tpu import telemetry
    from pint_tpu.serve.server import Server

    cfg = serve_config(flush_ms=args.flush_ms,
                       max_batch=args.max_batch,
                       queue_max=args.queue_max,
                       deadline_ms=args.deadline_ms)
    aot_dir = args.import_dir or os.environ.get(AOT_DIR_ENV) or None
    srv = Server(flush_ms=cfg["flush_ms"],
                 max_batch=cfg["max_batch"],
                 queue_max=cfg["queue_max"],
                 deadline_ms=cfg["deadline_ms"],
                 job_dir=args.job_dir, aot_dir=aot_dir)

    for spec in args.dataset:
        name, _, paths = spec.partition("=")
        if not paths:
            p.error(f"--dataset {spec!r}: expected ID=PAR[,TIM]")
        par_path, _, tim_path = paths.partition(",")
        with open(par_path) as fh:
            par = fh.read()
        info = srv.registry.load(name, par,
                                 tim=tim_path or None)
        print(f"pintserve: dataset {name}: {info['n_toas']} TOAs "
              f"(bucket {info['bucket']}, {info['kind']})",
              file=sys.stderr)

    report = srv.startup(warm=args.warm or bool(args.export_dir),
                         progress=lambda s: print(
                             f"pintserve: {s}", file=sys.stderr))
    if report is not None:
        print(f"pintserve: AOT import: {report.get('loaded', 0)} "
              f"executable(s), {len(report.get('rejected', []))} "
              "rejected", file=sys.stderr)

    if args.export_dir:
        from pint_tpu import compile_cache as _cc

        out = _cc.export_executables(
            args.export_dir,
            progress=lambda s: print(f"pintserve: {s}",
                                     file=sys.stderr))
        print(f"pintserve: exported {len(out['exported'])} "
              f"executable(s) to {args.export_dir} "
              f"({len(out['skipped'])} skipped)", file=sys.stderr)
        srv.stop()
        return 0

    host = args.host or os.environ.get(HOST_ENV, "").strip() \
        or "127.0.0.1"
    raw_port = os.environ.get(PORT_ENV, "").strip()
    port = args.port if args.port is not None else (
        int(raw_port) if raw_port else 8470)
    bound = srv.start(host, port)
    ready = bool(telemetry.gauges().get("serve.aot_warm"))
    print(f"pintserve: serving on {host}:{bound} "
          f"(flush {cfg['flush_ms']}ms, max_batch "
          f"{cfg['max_batch']}, queue_max {cfg['queue_max']}; "
          f"{'warm' if ready else 'COLD — /readyz will gate'})",
          file=sys.stderr, flush=True)
    try:
        # the graceful-shutdown handshake: a POST /drain quiesces the
        # replica (in-flight flushes served, running job checkpointed)
        # and sets this event — the process then exits 0, which is
        # what the fleet supervisor's rolling deploy waits for
        while not srv.drained.wait(timeout=3600):
            pass
        print("pintserve: drained; exiting", file=sys.stderr)
    except KeyboardInterrupt:
        print("pintserve: shutting down", file=sys.stderr)
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
