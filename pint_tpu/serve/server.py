"""The asyncio HTTP front door of the warm fitting service.

A long-lived replica process: stdlib-only HTTP/1.1 over
``asyncio.start_server`` (keep-alive, JSON bodies), with the
coalescing batcher (:mod:`pint_tpu.serve.batcher`) as the data plane
and the job store (:mod:`pint_tpu.serve.jobs`) for long work.  The
event loop never runs device code — handlers await
``concurrent.futures`` futures the batcher thread fulfills, so a slow
batch stalls nothing but its own clients.

Routes (all JSON):

- ``POST /v1/load``        — register a dataset (par text + tim path
  or synthetic TOA spec); control plane, allowed before readiness.
- ``POST /v1/datasets/<id>/append`` — streaming ingest: a night's new
  TOAs (tim path or synthetic spec) ride the rank-k Woodbury append
  path — anomaly triage, incremental refit, and an atomic version
  publish (:meth:`~pint_tpu.serve.state.DatasetRegistry.append`).
  In-flight requests keep the version they were admitted against;
  the response carries the new version, the triage verdict, and the
  freshness (``stream.freshness_s`` also lands on the SLO gauge).
- ``POST /v1/fit``         — coalesced batched fit (``dataset``,
  ``maxiter``, ``values`` start overrides, ``deadline_ms``).
- ``POST /v1/residuals``   — coalesced batched residuals.
- ``POST /v1/lnlike``      — coalesced batched white-noise lnlike.
- ``POST /v1/jobs``        — submit a grid/mcmc job; ``GET
  /v1/jobs/<id>`` polls it.
- ``POST /drain``          — graceful quiesce: ``/readyz`` flips to
  503 (the router pulls the replica), new work gets structured 503s,
  in-flight flushes finish and the running job checkpoints at its
  chunk boundary; the CLI process then exits 0.  The rolling-deploy
  handshake.
- ``GET /healthz``         — the metrics_http health document plus
  serving state.
- ``GET /readyz``          — 200 only after the AOT import (or an
  explicit warmup) completed: the load-balancer gate that keeps
  traffic off a cold replica.
- ``GET /metrics``         — Prometheus text (same renderer as the
  standalone metrics port; ``serve.*`` and ``slo.*`` series
  included).
- ``GET /slo``             — the SLO engine's full snapshot: rolling
  1m/10m/1h per-op quantiles, availability, burn rates, verdict
  (:mod:`pint_tpu.obs.slo`).
- ``GET /v1/stats``        — the serve counters/gauges as JSON, plus
  the ``queue`` block (depth, oldest-request age, per-group
  occupancy, observed drain rate) and the compact ``slo`` verdict.

Every op response (fit/residuals/lnlike) carries a ``traceparent``
header (the request's trace id — minted at admission or continued
from the client's own header) and a ``Server-Timing`` phase
decomposition (queue/coalesce/build/device/writeback), so "where did
my 11 ms go" is answerable per response even though the device work
was shared by a coalesced batch (:mod:`pint_tpu.obs.trace`).

Status discipline: 429 + Retry-After on shed, 504 on a missed
deadline, 503 + Retry-After on shutdown or an internal failure, 400
on a malformed request — **no handler path returns 500**, and a
diverging fit is a 200 whose body names its guard rung.  Keeping a
COLD replica out of rotation is the load balancer's job via
``/readyz``; a direct request to a cold replica is served (paying
its compiles) rather than refused, so dev loops and smoke tests need
no warmup ceremony.

Cold start: ``Server.startup()`` imports AOT-serialized executables
(``compile_cache.import_executables``) when an export directory is
configured, and/or runs an explicit warmup
(:func:`pint_tpu.serve.state.warm_serve`); the ``serve.aot_warm`` and
``serve.ready`` gauges drive ``/readyz`` (shared logic:
:func:`pint_tpu.metrics_http.readiness`).  The export directory is
the deploy artifact: one ``pintserve --export`` rehearsal produces
the manifest N replicas import, each reaching its first served fit
with zero uncached XLA backend compiles.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time

from pint_tpu import telemetry
from pint_tpu.obs import slo as _slo
from pint_tpu.obs import trace as _obs_trace
from pint_tpu.serve.batcher import CoalescingBatcher
from pint_tpu.serve.jobs import JobStore
from pint_tpu.serve.state import (
    DatasetRegistry,
    ServeError,
    serve_config,
    size_classes,
    warm_append,
    warm_serve,
)

__all__ = ["Server", "cold_replica_probe"]

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            405: "Method Not Allowed", 429: "Too Many Requests",
            503: "Service Unavailable", 504: "Gateway Timeout"}

#: absolute ceiling on request bodies (a front door must bound them)
_MAX_BODY = 8 << 20


class Server:
    """One replica: registry + batcher + jobs + HTTP listener."""

    def __init__(self, flush_ms=None, max_batch=None, queue_max=None,
                 deadline_ms=None, grid_chunk=None, job_dir=None,
                 aot_dir=None):
        cfg = serve_config(flush_ms=flush_ms, max_batch=max_batch,
                           queue_max=queue_max,
                           deadline_ms=deadline_ms,
                           grid_chunk=grid_chunk)
        self.cfg = cfg
        self.aot_dir = aot_dir
        self.registry = DatasetRegistry()
        self.batcher = CoalescingBatcher(
            flush_ms=cfg["flush_ms"], max_batch=cfg["max_batch"],
            queue_max=cfg["queue_max"])
        self.jobs = JobStore(self.registry, job_dir=job_dir,
                             grid_chunk=cfg["grid_chunk"])
        self.aot_report = None
        self._warm = False
        self._warm_lock = threading.Lock()
        self._drain_lock = threading.Lock()
        self._draining = False
        #: set once a POST /drain fully quiesced the replica — the
        #: CLI waits on it to exit 0 (the rolling-deploy handshake)
        self.drained = threading.Event()
        self._loop = None
        self._aserver = None
        self._thread = None
        self._port = None
        self._started = threading.Event()
        telemetry.gauge_set("serve.ready", 0.0)
        telemetry.gauge_set("serve.aot_warm", 0.0)
        telemetry.gauge_set("serve.flush_ms", cfg["flush_ms"])
        telemetry.gauge_set("serve.max_batch", cfg["max_batch"])
        telemetry.gauge_set("serve.queue_max", cfg["queue_max"])

    # -- lifecycle ----------------------------------------------------------
    def startup(self, warm=False, warm_dataset=None, progress=None):
        """Bring the replica to serving state: import the AOT
        manifest when configured (counts as warm when it loads
        executables), and/or run an explicit warmup flush sweep.
        Idempotent; sets the ``serve.aot_warm`` readiness gauge."""
        warmed = False
        if self.aot_dir:
            from pint_tpu import compile_cache as _cc

            self.aot_report = _cc.import_executables(
                self.aot_dir, progress=progress)
            if self.aot_report.get("loaded", 0) > 0:
                warmed = True
        if warm:
            from pint_tpu import faults as _faults

            ids = ([warm_dataset] if warm_dataset is not None
                   else self.registry.ids())
            # the rehearsal is self-inflicted work: site faults
            # (kill/slow_flush) must neither fire here nor burn
            # their after=N budget, or a fault-armed replica dies
            # warming itself up instead of mid-served-batch
            with _faults.suspend():
                if ids:
                    # warm what this replica will actually serve:
                    # every registered dataset, all three ops, and
                    # the grid-job path.  Over an AOT import this is
                    # the cheap pre-arm dress rehearsal that also
                    # absorbs the serving path's first-use eager
                    # compiles — without it an --import replica's
                    # first real requests compile AFTER the
                    # sanitizer armed
                    for ds_id in ids:
                        warm_serve(self.registry, ds_id,
                                   self.cfg["max_batch"],
                                   ops=("fit", "residuals",
                                        "lnlike"),
                                   maxiter=3)
                        self._warm_grid_path(ds_id, progress)
                        # streaming-append rehearsal: a throwaway
                        # session absorbs one synthetic night so the
                        # capture/delta/refit programs exist before
                        # the sanitizer arms (state.warm_append)
                        rec = warm_append(self.registry, ds_id)
                        if progress is not None:
                            progress(
                                f"warm append ({ds_id}): "
                                + ("ok" if rec.get("warmed")
                                   else rec.get("detail", "skipped")))
                else:
                    # no datasets yet: the synthetic single-program
                    # warmup keeps a bare `pintserve --warm`
                    # meaningful (and cheap) without pretending to
                    # cover real data
                    from pint_tpu.compile_cache import WARM_WLS_PAR

                    self.registry.load("_warm", par=WARM_WLS_PAR,
                                       toas={"n": 64, "seed": 0})
                    warm_serve(self.registry, "_warm",
                               self.cfg["max_batch"], ops=("fit",),
                               maxiter=3)
            warmed = True
        self.mark_warm(warmed)
        telemetry.gauge_set("serve.ready", 1.0)
        self._arm_sanitizer(warmed)
        return self.aot_report

    def _warm_grid_path(self, ds_id, progress=None):
        """One-point grid job against a throwaway checkpoint dir: the
        grid path's model snapshot + chunk glue do host-side eager
        jax ops that compile once per process — without this
        rehearsal a replica's FIRST real grid job takes those
        compiles after the sanitizer armed (and pays them inside the
        job).  Best-effort: a dataset with no free parameters simply
        skips."""
        import tempfile

        from pint_tpu.serve import jobs as _jobs

        ds = self.registry.get(ds_id)
        free = list(getattr(ds.model, "free_params", ()) or ())
        if not free:
            return
        p0 = free[0]
        v0 = float(ds.model.values[p0])
        spec = {"kind": "grid", "dataset": ds_id, "params": [p0],
                "n_steps": 1, "chunk": 1,
                "axes": {p0: {"start": v0, "stop": v0, "n": 1}}}
        try:
            with tempfile.TemporaryDirectory(
                    prefix="pintserve_warmgrid_") as jd:
                _jobs.run_job(
                    self.registry,
                    {"job": f"_warmgrid_{ds_id}", "kind": "grid",
                     "spec": spec}, jd, grid_chunk=1)
            if progress is not None:
                progress(f"warm grid path ({ds_id})")
        except Exception as e:
            if progress is not None:
                progress(f"warm grid path skipped ({ds_id}): {e}")

    @staticmethod
    def _arm_sanitizer(warmed):
        """Arm the recompile sanitizer once the replica believes
        itself warm — from here on a steady-state XLA compile is a
        counted (warn) or raised (raise-mode, surfaces as that
        request's structured 503) violation with the offending
        program named.  Opt-in via $PINT_TPU_RECOMPILE_SANITIZER;
        a replica that never warmed must not arm (its first flushes
        legitimately compile)."""
        from pint_tpu.lint import sanitizer as _san

        if warmed and _san.mode() != "off":
            _san.arm(note="serve.startup")

    def mark_warm(self, warm=True):
        """Latch the readiness gauge (``/readyz`` gates on it): a
        replica is warm after an AOT import or an explicit warmup.
        Warmth is a LATCH — ``mark_warm(False)`` from a concurrent
        ``startup(warm=False)`` must never un-warm a replica another
        thread just warmed, or ``/readyz`` would flap 200 -> 503
        under a load balancer mid-rollout.  The lock makes the
        read-or-write-then-export sequence atomic: without it a
        concurrent ``mark_warm(False)`` could read the pre-warm value
        and overwrite a just-latched True."""
        with self._warm_lock:
            self._warm = bool(warm) or self._warm
            telemetry.gauge_set("serve.aot_warm",
                                1.0 if self._warm else 0.0)

    def warmup(self, dataset_id, ops=("fit",), sizes=None, maxiter=3):
        """Explicit warmup against a registered dataset (compiles —
        or AOT-serves — every (op, size-class) program), then marks
        the replica warm."""
        out = warm_serve(self.registry, dataset_id,
                         self.cfg["max_batch"], ops=ops, sizes=sizes,
                         maxiter=maxiter)
        self.mark_warm(True)
        self._arm_sanitizer(True)
        return out

    def start(self, host="127.0.0.1", port=0) -> int:
        """Start the listener on a background thread; returns the
        bound port (port=0 binds an ephemeral one)."""
        if self._thread is not None:
            return self._port
        self._thread = threading.Thread(
            target=self._run_loop, args=(host, int(port)),
            name="pintserve-http", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("pintserve listener failed to start")
        telemetry.gauge_set("serve.ready", 1.0)
        return self._port

    def _run_loop(self, host, port):
        # the teardown below uses a LOCAL loop reference: stop() nulls
        # self._loop from another thread, so dereferencing the
        # attribute here would race it (AttributeError noise in every
        # test teardown)
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)

        async def _boot():
            self._aserver = await asyncio.start_server(
                self._handle, host, port)
            self._port = self._aserver.sockets[0].getsockname()[1]
            telemetry.gauge_set("serve.port", self._port)
            self._started.set()

        try:
            loop.run_until_complete(_boot())
            loop.run_forever()
        finally:
            try:
                if self._aserver is not None:
                    self._aserver.close()
                    loop.run_until_complete(
                        self._aserver.wait_closed())
                # drain connection-handler tasks so interpreter exit
                # never logs "Task was destroyed but it is pending"
                pending = [t for t in asyncio.all_tasks(loop)
                           if not t.done()]
                for t in pending:
                    t.cancel()
                if pending:
                    loop.run_until_complete(asyncio.gather(
                        *pending, return_exceptions=True))
            finally:
                loop.close()

    def run(self, host="127.0.0.1", port=8470):
        """Blocking serve (the CLI path): start + wait forever."""
        self.start(host, port)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def drain(self, timeout=60.0) -> dict:
        """Graceful quiesce (the ``POST /drain`` body, and the
        rolling-deploy primitive): flip ``serve.draining`` so
        ``/readyz`` answers 503 and the router pulls this replica
        from rotation; refuse NEW requests/jobs with structured 503s
        (their retries land on siblings); wait for every in-flight
        flush to complete and the running job to checkpoint-stop at
        its next chunk boundary.  Idempotent.  The listener stays up
        throughout — health/metrics scrapes and job polls still
        answer — and the process itself exits via the CLI loop
        watching :attr:`drained`."""
        t0 = time.perf_counter()
        with self._drain_lock:
            if not self._draining:
                self._draining = True
                telemetry.gauge_set("serve.draining", 1.0)
                telemetry.counter_add("serve.drains")
        queue_ok = self.batcher.drain(timeout=timeout)
        jobs_ok = self.jobs.drain(timeout=timeout)
        doc = {
            "draining": True,
            "queue_quiesced": bool(queue_ok),
            "jobs_quiesced": bool(jobs_ok),
            "wall_s": round(time.perf_counter() - t0, 3),
        }
        return doc

    def stop(self):
        """Stop listener, batcher, and job worker (idempotent — a
        second call must be a no-op, not a closed-loop error)."""
        loop, self._loop = self._loop, None
        if loop is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass  # closed between the check and the call
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.batcher.stop()
        self.jobs.stop()
        telemetry.gauge_set("serve.ready", 0.0)

    # -- HTTP plumbing ------------------------------------------------------
    async def _handle(self, reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    return
                try:
                    method, path, _ = line.decode(
                        "latin1").split(None, 2)
                except ValueError:
                    return
                headers = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                n = int(headers.get("content-length", 0) or 0)
                if n > _MAX_BODY:
                    return
                body = await reader.readexactly(n) if n else b""
                status, payload, ctype, extra = await self._route(
                    method.upper(), path.split("?", 1)[0], body,
                    headers)
                keep = headers.get("connection",
                                   "keep-alive").lower() != "close"
                head = [f"HTTP/1.1 {status} "
                        f"{_REASONS.get(status, 'OK')}",
                        f"Content-Type: {ctype}",
                        f"Content-Length: {len(payload)}"]
                head += [f"{k}: {v}" for k, v in extra]
                head.append("Connection: "
                            + ("keep-alive" if keep else "close"))
                writer.write(("\r\n".join(head) + "\r\n\r\n")
                             .encode() + payload)
                await writer.drain()
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    @staticmethod
    def _json(status, obj, extra=()):
        return (status, json.dumps(obj).encode(), "application/json",
                list(extra))

    def _err(self, exc: ServeError):
        extra = []
        if exc.retry_after_s is not None:
            extra.append(("Retry-After",
                          str(max(1, int(round(exc.retry_after_s
                                               + 0.5))))))
        body = {"error": type(exc).__name__, "detail": exc.detail}
        if exc.retry_after_s is not None:
            body["retry_after_ms"] = int(exc.retry_after_s * 1e3)
        return self._json(exc.status, body, extra)

    async def _route(self, method, path, body, headers=None):
        try:
            return await self._route_inner(method, path, body,
                                           headers or {})
        except ServeError as e:
            return self._err(e)
        except (ValueError, KeyError, TypeError) as e:
            return self._json(400, {"error": "BadRequest",
                                    "detail": str(e)})
        except Exception as e:  # noqa: BLE001 — the no-500 contract:
            # an unexpected failure is a structured, retryable 503
            telemetry.counter_add("serve.errors")
            return self._err(ServeError(
                f"{type(e).__name__}: {e}", retry_after_s=1.0))

    async def _route_inner(self, method, path, body, headers):
        path = path.rstrip("/") or "/"
        if method == "GET":
            if path == "/healthz":
                return self._json(200, self._health_doc())
            if path == "/slo":
                return self._json(200, _slo.tracker().snapshot())
            if path == "/readyz":
                from pint_tpu import metrics_http

                ready, doc = metrics_http.readiness()
                if ready:
                    return self._json(200, doc)
                return self._json(503, doc, [("Retry-After", "1")])
            if path == "/metrics":
                from pint_tpu import metrics_http

                # burn-rate/quantile gauges are computed on demand:
                # refresh them so a scrape always reads current windows
                _slo.tracker().snapshot()
                return (200, metrics_http.render_prometheus()
                        .encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                        [])
            if path == "/":
                return self._json(200, {"routes": [
                    "POST /v1/load", "POST /v1/fit",
                    "POST /v1/residuals", "POST /v1/lnlike",
                    "POST /v1/datasets/<id>/append",
                    "POST /v1/jobs", "GET /v1/jobs/<id>",
                    "POST /drain",
                    "GET /healthz", "GET /readyz", "GET /metrics",
                    "GET /slo", "GET /v1/stats",
                ]})
            if path == "/v1/stats":
                return self._json(200, self._stats_doc())
            if path.startswith("/v1/jobs/"):
                jid = path.rsplit("/", 1)[1]
                doc = self.jobs.status(jid)
                if doc is None:
                    return self._json(404, {"error": "NotFound"})
                # "live": will THIS replica progress the job?  The
                # doc comes from the shared job dir and outlives its
                # writer, so a dead owner's "running" needs this bit
                # for the router to tell lost from in-flight
                return self._json(200,
                                  {**doc,
                                   "live": self.jobs.is_live(jid)})
            return self._json(404, {"error": "NotFound"})
        if method != "POST":
            return self._json(405, {"error": "MethodNotAllowed"})
        params = json.loads(body.decode() or "{}")
        if path == "/drain":
            loop = asyncio.get_running_loop()
            doc = await loop.run_in_executor(
                None, lambda: self.drain(
                    timeout=float(params.get("timeout_s", 60.0))))
            # signal the CLI's exit-0 loop only after this handler
            # has had time to write the response (the callback runs
            # on this same loop, after the handler resumed + wrote)
            loop.call_later(0.25, self.drained.set)
            return self._json(200, doc)
        if path == "/v1/load":
            loop = asyncio.get_running_loop()
            info = await loop.run_in_executor(
                None, lambda: self.registry.load(
                    params.get("dataset"), params.get("par"),
                    toas=params.get("toas"), tim=params.get("tim"),
                    flags=params.get("flags")))
            return self._json(200, info)
        if path.startswith("/v1/datasets/") and \
                path.endswith("/append"):
            if self._draining:
                raise ServeError("replica is draining",
                                 retry_after_s=1.0)
            ds_id = path[len("/v1/datasets/"):-len("/append")]
            if not ds_id or "/" in ds_id:
                return self._json(404, {"error": "NotFound"})
            ts = params.get("triage_sigma")
            loop = asyncio.get_running_loop()
            doc = await loop.run_in_executor(
                None, lambda: self.registry.append(
                    ds_id, toas=params.get("toas"),
                    tim=params.get("tim"),
                    flags=params.get("flags"),
                    maxiter=int(params.get("maxiter", 3)),
                    triage_sigma=(float(ts) if ts is not None
                                  else None)))
            return self._json(200, doc)
        if path == "/v1/jobs":
            ctx = _obs_trace.from_headers(headers)
            doc = self.jobs.submit(params, trace=ctx.trace_id)
            return self._json(200, doc,
                              [("traceparent", ctx.traceparent())])
        if path in ("/v1/fit", "/v1/residuals", "/v1/lnlike"):
            op = path.rsplit("/", 1)[1]
            # admission: the trace context is minted HERE (or
            # continued from the client's traceparent) and rides the
            # request through batcher -> flush -> response
            ctx = _obs_trace.from_headers(headers)
            req = self.registry.build_request(
                op, params, self.cfg["deadline_ms"], trace=ctx)
            fut = self.batcher.submit(req)  # Shed -> 429 upstream
            try:
                result = await asyncio.wait_for(
                    asyncio.wrap_future(fut),
                    timeout=max(self.cfg["flush_ms"] / 1e3, 0.05)
                    + 600.0)
            except asyncio.TimeoutError:
                raise ServeError("batch dispatch timed out",
                                 retry_after_s=5.0) from None
            return self._json(200, result,
                              _obs_trace.response_headers(result))
        return self._json(404, {"error": "NotFound"})

    # -- documents ----------------------------------------------------------
    def _health_doc(self):
        from pint_tpu import metrics_http

        ready, rdoc = metrics_http.readiness()
        return {
            "ready": ready,
            "readiness": rdoc,
            "runs": telemetry.runs_summary(),
            "compile": telemetry.compile_stats(),
            "serve": self._stats_doc(),
        }

    def _stats_doc(self):
        ctr = telemetry.counters()
        g = telemetry.gauges()
        serve_ctr = {k: v for k, v in ctr.items()
                     if k.startswith(("serve.", "stream."))}
        serve_g = {k: v for k, v in g.items()
                   if k.startswith(("serve.", "hist.serve.",
                                    "stream."))}
        return {
            "config": dict(self.cfg),
            "queue_depth": self.batcher.depth(),
            "queue": self.batcher.queue_info(),
            "slo": _slo.tracker().verdict_doc(),
            "datasets": self.registry.ids(),
            "size_classes": list(size_classes(self.cfg["max_batch"])),
            "counters": serve_ctr,
            "gauges": serve_g,
            "aot": ({"loaded": self.aot_report.get("loaded"),
                     "rejected": len(self.aot_report.get(
                         "rejected", []))}
                    if self.aot_report else None),
            "sanitizer": self._sanitizer_doc(),
        }

    @staticmethod
    def _sanitizer_doc():
        from pint_tpu.lint import sanitizer as _san

        if _san.mode() == "off":
            return {"mode": "off"}
        doc = _san.stats()
        doc["recent_violations"] = [
            {k: v.get(k) for k in ("program", "kind", "compile_s")}
            for v in _san.violations()[-5:]]
        return doc


def cold_replica_probe(mode, path, t_start=None, maxiter=3):
    """The serve-layer cold-start probe (the ``cold_replica_warm_s``
    bench child; mirrors ``compile_cache.aot_cold_start_probe``).

    mode="export": boot a replica, register the standard warm
    dataset, serve one fit over real HTTP (the dress rehearsal that
    records every program + eager-op shape), then serialize this
    process's executables into ``path`` — the deploy artifact.
    mode="import": pre-load ``path``, boot a fresh replica, serve the
    SAME first fit — the zero-uncached-compile path under test.
    Returns a record with wall seconds, the served chi^2 (bit-exact
    across JSON), and the compile/AOT counters."""
    t0 = time.perf_counter()
    telemetry.compile_stats()  # listener before any compile
    from pint_tpu import compile_cache as _cc

    _cc._auto_enable()
    imported = {"loaded": 0, "rejected": []}
    srv = Server(flush_ms=2.0, max_batch=1, queue_max=32,
                 aot_dir=(path if mode == "import" else None))
    srv.startup(warm=False)
    if mode == "import":
        imported = srv.aot_report or imported
    port = srv.start(port=0)
    try:
        srv.registry.load("warm", par=_cc.WARM_WLS_PAR,
                          toas={"n": 64, "seed": 0})
        from pint_tpu.serve.client import request_json

        status, resp, _ = request_json(
            "127.0.0.1", port, "POST", "/v1/fit",
            {"dataset": "warm", "maxiter": maxiter}, timeout=300.0)
        if status != 200 or resp.get("status") not in ("ok",
                                                       "degraded"):
            raise RuntimeError(
                f"probe fit failed: HTTP {status} {resp}")
    finally:
        srv.stop()
    wall = (time.time() - t_start if t_start is not None
            else time.perf_counter() - t0)
    rec = {"mode": mode, "wall_s": round(wall, 3),
           "chi2": float(resp["chi2"]),
           "loaded": imported.get("loaded", 0),
           "rejected": len(imported.get("rejected", []))}
    if mode == "export":
        out = _cc.export_executables(path)
        rec["exported"] = len(out["exported"])
        rec["skipped"] = len(out["skipped"])
    cs = telemetry.compile_stats()
    rec.update({
        "backend_compiles": cs["backend_events"],
        "uncached_backend_compiles": cs["uncached_backend_events"],
        "cache_hits": cs["cache_hits"],
        "aot_hits": cs["aot_hits"],
        "aot_rejects": cs["aot_rejects"],
        "monitoring": cs["source"] == "jax.monitoring",
    })
    return rec
