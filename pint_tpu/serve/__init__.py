"""Warm fitting service: the production front door (ROADMAP item 1).

Composes the stack's serving-enablers into one long-lived process:

- **TOA bucketing** (PR 2) quantizes request shapes to 64·1.25^k, so
  unrelated requests become same-program work;
- the **PTA batch path** (PR 7/11) fits many pulsars as one device
  program, so same-bucket requests coalesce into ONE dispatch
  (:mod:`~pint_tpu.serve.batcher` — deadline-based flush,
  ``$PINT_TPU_SERVE_FLUSH_MS``);
- **AOT-serialized executables** (PR 8) make a replica's first served
  fit run with zero uncached XLA backend compiles — the export
  directory is the deploy artifact N replicas share
  (``pintserve --export`` / ``--import``);
- the **guard ladder** (PR 4) degrades a diverging request to its
  serving rung instead of failing it, per batch member;
- **admission control** (:mod:`~pint_tpu.serve.admission`) bounds the
  device queue and sheds with 429 + Retry-After;
- **jobs** (:mod:`~pint_tpu.serve.jobs`) run grid/MCMC work behind
  job-id polling with PR-4 checkpointed resume;
- the **run ledger + /metrics endpoint** (PR 10) record every
  request (``serve.*`` counters, per-request phase splits), so the
  service's p99 story is measurable, not asserted.

Entry points: the ``pintserve`` CLI (:mod:`pint_tpu.serve.cli`), the
embeddable :class:`~pint_tpu.serve.server.Server`, and
``bench.py``'s ``serve_reqs_per_sec`` / ``cold_replica_warm_s``
metrics.  See docs/serving.md for the request lifecycle and the
deploy recipe.
"""

from pint_tpu.serve.state import (  # noqa: F401
    DatasetRegistry,
    DeadlineMiss,
    Request,
    ServeError,
    Shed,
    serve_config,
    size_class_for,
    size_classes,
)

__all__ = [
    "Server", "DatasetRegistry", "Request", "ServeError", "Shed",
    "DeadlineMiss", "serve_config", "size_classes",
    "size_class_for",
]


def __getattr__(name):
    # Server pulls in the batcher/jobs stack; keep `import
    # pint_tpu.serve` light for consumers that only need the types
    if name == "Server":
        from pint_tpu.serve.server import Server

        return Server
    raise AttributeError(name)
