"""Async jobs: long device work behind job-id polling, with
checkpointed resume.

A grid scan or a sampler chain does not belong on the request/response
path — a client should not hold an HTTP connection open for minutes,
and a replica restart must not throw the work away.  This layer gives
long work the submit/poll/resume shape:

- ``POST /v1/jobs`` validates a spec, persists it as
  ``<job_dir>/<id>.json`` (atomic write), and enqueues it; the
  response is the job document (state ``queued``).  The client may
  supply the ``job`` id — resubmitting the SAME id after a replica
  death is the resume path.
- ``GET /v1/jobs/<id>`` returns the live document: state
  (``queued|running|done|failed``), progress, and the result when
  done.
- Every job checkpoints through the PR-4 path
  (:func:`pint_tpu.guard.save_checkpoint` — atomic tmp+replace, a
  structure fingerprint validated on restore): the **grid** kind
  saves after every chunk of points, so a killed replica resumes
  losing at most one chunk; the **mcmc** kind rides
  :meth:`pint_tpu.sampler.EnsembleSampler.run_mcmc_autocorr`'s
  built-in per-chunk checkpoint (the NUTS/HMC jobs of ``gw/hmc`` plug
  into the same submit/poll/checkpoint plumbing by adding a kind).

Job kinds:

- ``grid`` — chi^2 over an explicit point list (or dense axes) of a
  registered dataset, ``grid_chisq_tuple`` per chunk
  (``$PINT_TPU_SERVE_GRID_CHUNK`` points each; the grid programs are
  data-dynamic, so chunk boundaries never retrace).  The chunk loop is
  a ``serve.flush`` kill site — the chaos harness kills mid-job and
  asserts the resume loses <= 1 chunk.
- ``mcmc`` — an ensemble chain over the dataset's white-noise
  posterior (``-chi^2/2`` through the shared ``pta.chisq`` pure
  function), checkpointed per chunk by the sampler itself.

Jobs run on ONE worker thread (device work serializes anyway); the
job model is deliberately isolated from the registry — a grid run
deep-copies its dataset's model (so a fitting request flushed
concurrently can never observe the grid's parameter pins) and an
mcmc run snapshots the values into its stacked batch at build time;
both snapshots happen under ``state.SERVING_LOCK`` so they can never
capture the batcher thread's transient mid-flush write-back.
"""

from __future__ import annotations

import copy
import json
import os
import queue
import tempfile
import threading
import time

import numpy as np

from pint_tpu import telemetry

__all__ = ["JobStore", "JobInterrupted", "run_job", "main"]


class JobInterrupted(Exception):
    """A drain stopped the job at a chunk boundary — its checkpoint
    is on disk, its document state becomes ``interrupted``, and
    resubmitting the same id (on this replica after restart, or on a
    sibling sharing the job dir) resumes losing zero chunks."""

#: result payloads are capped like residual payloads — a 10^5-point
#: grid reports its minimum and shape, not every chi^2
RESULT_POINT_CAP = 4096

#: hard bound on grid-job size, checked ARITHMETICALLY before any
#: axis is materialized: submit validation runs on the HTTP event
#: loop, and a hostile {"n": 1e9} axis spec must be a 400, not an
#: allocation that stalls the whole replica
MAX_GRID_POINTS = 1_000_000


def _atomic_write_json(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
    os.replace(tmp, path)


def _grid_points(spec) -> np.ndarray:
    """The (n_points, n_params) array of a grid spec: explicit
    ``values`` rows, or dense ``axes`` ({name: {start, stop, n}} in
    ``params`` order)."""
    params = list(spec.get("params") or ())
    if not params:
        raise ValueError("grid job needs 'params' (parameter names)")
    if spec.get("values") is not None:
        pts = np.asarray(spec["values"], dtype=np.float64)
        pts = np.atleast_2d(pts)
        if pts.shape[1] != len(params):
            raise ValueError(
                f"grid values shape {pts.shape} does not match "
                f"{len(params)} parameter(s)")
        if pts.shape[0] > MAX_GRID_POINTS:
            raise ValueError(
                f"grid too large (> {MAX_GRID_POINTS} points); "
                "split it into several jobs")
        return pts
    axes_spec = spec.get("axes")
    if not isinstance(axes_spec, dict):
        raise ValueError("grid job needs 'values' rows or 'axes'")
    # size check BEFORE any allocation (see MAX_GRID_POINTS)
    total = 1
    for name in params:
        a = axes_spec.get(name)
        if not isinstance(a, dict):
            raise ValueError(f"axes entry for {name!r} missing")
        n = int(a["n"])
        if n < 1:
            raise ValueError(f"axes entry for {name!r}: n {n} < 1")
        total *= n
        if total > MAX_GRID_POINTS:
            raise ValueError(
                f"grid too large (> {MAX_GRID_POINTS} points); "
                "split it into several jobs")
    axes = []
    for name in params:
        a = axes_spec[name]
        axes.append(np.linspace(float(a["start"]), float(a["stop"]),
                                int(a["n"])))
    grids = np.meshgrid(*axes, indexing="ij")
    return np.stack([g.ravel() for g in grids], axis=1)


def _check_grid_params(ds, params):
    for p in params:
        if p not in ds.model.free_params:
            raise ValueError(
                f"grid parameter {p!r} is not free in dataset "
                f"{ds.dataset_id!r}")


def run_job(registry, doc, job_dir, grid_chunk=16, progress=None,
            should_stop=None):
    """Run one job document to completion (resuming from its
    checkpoint when one exists); returns the result dict.  Raises on
    failure — the worker (or the CLI child) records the failure
    state.  ``should_stop`` (a callable) is polled at chunk
    boundaries: returning True raises :class:`JobInterrupted` AFTER
    the chunk's checkpoint landed — the drain path."""
    kind = doc["kind"]
    spec = doc["spec"]
    if kind == "grid":
        return _run_grid(registry, doc, job_dir, grid_chunk, progress,
                         should_stop)
    if kind == "mcmc":
        return _run_mcmc(registry, doc, job_dir, progress)
    raise ValueError(f"unknown job kind {kind!r} "
                     "(supported: grid, mcmc)")


def _run_grid(registry, doc, job_dir, grid_chunk, progress,
              should_stop=None):
    from pint_tpu import compile_cache as _cc
    from pint_tpu import faults as _faults
    from pint_tpu import guard as _guard
    from pint_tpu.grid import grid_chisq_tuple

    spec = doc["spec"]
    ds = registry.get(spec["dataset"])
    params = list(spec["params"])
    _check_grid_params(ds, params)
    points = _grid_points(spec)
    n_steps = int(spec.get("n_steps", 2))
    chunk = int(spec.get("chunk", grid_chunk))
    n = points.shape[0]
    # jobs never touch the registry's model: a concurrent fit flush
    # must not see grid-pinned values (and vice versa).  The snapshot
    # itself happens under SERVING_LOCK so it can never capture the
    # transient mid-flush write-back state of the batcher thread.
    from pint_tpu.serve.state import SERVING_LOCK

    with SERVING_LOCK:
        model = copy.deepcopy(ds.model)
    ckpt = os.path.join(job_dir, doc["job"] + ".ckpt.npz")
    fp = _cc.fingerprint((ds.structure, tuple(params),
                          points.shape, n_steps, chunk))
    chi2 = np.full(n, np.nan)
    done = 0
    loaded = _guard.load_checkpoint(ckpt, fingerprint=fp)
    if loaded is not None:
        arrays, _head = loaded
        done = int(arrays["n_done"][()])
        chi2[:done] = arrays["chi2"][:done]
        doc["resumed_from"] = done
        telemetry.counter_add("serve.job_resumes")
    while done < n:
        # the chaos kill site: a mid-job death here loses at most the
        # chunk in flight — everything before it is checkpointed
        _faults.maybe_kill("serve.flush")
        hi = min(done + chunk, n)
        c, _fitted = grid_chisq_tuple(ds.toas, model, params,
                                      points[done:hi],
                                      n_steps=n_steps)
        chi2[done:hi] = np.asarray(c)
        done = hi
        _guard.save_checkpoint(
            ckpt, {"chi2": chi2, "n_done": np.int64(done)},
            fingerprint=fp, meta={"job": doc["job"],
                                  "trace": doc.get("trace")})
        doc["progress"] = {"done": done, "total": n}
        if progress is not None:
            progress(doc)
        # drain check AFTER the checkpoint write: an interrupted job
        # is always resumable from exactly where it stopped
        if done < n and should_stop is not None and should_stop():
            raise JobInterrupted(
                f"drained at {done}/{n} points (checkpointed)")
    finite = np.isfinite(chi2)
    result = {
        "n_points": int(n),
        "n_finite": int(finite.sum()),
        "min_chi2": (float(np.nanmin(chi2)) if finite.any()
                     else None),
        "argmin": (
            {p: float(v) for p, v in
             zip(params, points[int(np.nanargmin(chi2))])}
            if finite.any() else None),
    }
    if n <= RESULT_POINT_CAP:
        result["chi2"] = [float(x) for x in chi2]
    try:
        os.unlink(ckpt)  # done: the checkpoint has served its purpose
    except OSError:
        pass
    return result


def _run_mcmc(registry, doc, job_dir, progress):
    import jax

    from pint_tpu.parallel.pta import PTABatch
    from pint_tpu.sampler import EnsembleSampler

    spec = doc["spec"]
    ds = registry.get(spec["dataset"])
    nwalkers = int(spec.get("nwalkers", 16))
    maxsteps = int(spec.get("maxsteps", 500))
    chunk = int(spec.get("chunk", 100))
    scale = float(spec.get("scale", 1e-8))
    # the stacked batch snapshots the model's values at build time
    # (values0/base_values device rows; the chain only ever reads
    # those) — build it under SERVING_LOCK so the snapshot can't
    # capture a concurrent flush's transient write-back
    from pint_tpu.serve.state import SERVING_LOCK

    with SERVING_LOCK:
        batch = PTABatch.from_prepared([ds.prepared], [ds.resid])

    def _sl(tree):
        return (None if tree is None
                else jax.tree.map(lambda a: a[0], tree))

    args = (_sl(batch.base_values), _sl(batch.batch), _sl(batch.ctx),
            _sl(batch.tzr_batch), _sl(batch.tzr_ctx), batch.valid[0],
            batch.free_mask[0])

    def lnpost(vec):
        return -0.5 * batch._chisq_one(vec, *args)

    s = EnsembleSampler(lnpost, nwalkers=nwalkers,
                        seed=int(spec.get("seed", 0)),
                        jit_key=("serve.mcmc", ds.structure))
    center = np.asarray(batch.values0[0])
    x0 = s.initial_ball(center, scale * (np.abs(center) + 1e-12))
    ckpt = os.path.join(job_dir, doc["job"] + ".ckpt.npz")
    chain, converged, tau = s.run_mcmc_autocorr(
        x0, chunk=chunk, maxsteps=maxsteps, checkpoint=ckpt,
        checkpoint_meta={"job": doc["job"],
                         "trace": doc.get("trace")})
    flat = s.flatchain(burn=min(len(chain) // 4, 100))
    return {
        "n_steps": int(np.asarray(chain).shape[0]),
        "converged": bool(converged),
        "tau_max": (float(np.max(tau))
                    if np.all(np.isfinite(tau)) else None),
        "acceptance": float(s.acceptance),
        "mean": {p: float(m) for p, m in
                 zip(batch.free_names, flat.mean(axis=0))},
        "std": {p: float(v) for p, v in
                zip(batch.free_names, flat.std(axis=0))},
    }


class JobStore:
    """Persistent job documents + one worker thread.

    ``job_dir`` holds one ``<id>.json`` per job (the document of
    record — it survives the process) and the job's checkpoint.  A
    replica restart rebuilds its view lazily from disk: resubmitting
    a completed id returns the stored result; resubmitting an
    interrupted id re-enqueues it and the kind's checkpoint resume
    picks up where the dead replica stopped."""

    def __init__(self, registry, job_dir=None, grid_chunk=16):
        from pint_tpu.serve.state import JOB_DIR_ENV

        self.registry = registry
        self.job_dir = (job_dir or os.environ.get(JOB_DIR_ENV)
                        or tempfile.mkdtemp(prefix="pintserve_jobs_"))
        os.makedirs(self.job_dir, exist_ok=True)
        self.grid_chunk = int(grid_chunk)
        self._q: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self._stopped = False
        self._draining = False
        self._active = None  # job id the worker is running right now
        self._pending: set = set()  # ids enqueued, not yet picked up
        self._thread = threading.Thread(
            target=self._worker, name="pintserve-jobs", daemon=True)
        self._thread.start()

    def _doc_path(self, job_id):
        if not str(job_id).replace("-", "").replace("_", "").isalnum():
            raise ValueError(f"invalid job id {job_id!r}")
        return os.path.join(self.job_dir, str(job_id) + ".json")

    def _write(self, doc):
        _atomic_write_json(self._doc_path(doc["job"]), doc)

    def submit(self, spec, trace=None) -> dict:
        """Validate + persist + enqueue one job spec; returns the job
        document.  Client-supplied ``job`` ids make resubmission the
        resume path; a finished id returns its stored document
        without re-running.

        ``trace`` is the admission-time trace id: it is stamped into
        the document AND into every checkpoint header the job writes,
        so a job resumed after a replica death keeps its original
        trace (the resubmit's own trace id does NOT replace it — the
        story of the work is one trace)."""
        if not isinstance(spec, dict):
            raise ValueError("job spec must be a JSON object")
        if self._draining:
            from pint_tpu.serve.state import ServeError

            raise ServeError("server is draining", retry_after_s=1.0)
        kind = spec.get("kind")
        if kind not in ("grid", "mcmc"):
            raise ValueError(
                f"unknown job kind {kind!r} (supported: grid, mcmc)")
        ds = self.registry.get(spec.get("dataset"))  # must exist
        if kind == "grid":
            # validate geometry + parameter names up front: a bad
            # spec is the submitter's 400, not a later job failure
            _check_grid_params(ds, list(spec.get("params") or ()))
            _grid_points(spec)
        job_id = str(spec.get("job") or f"job{int(time.time() * 1e3):x}"
                     f"{os.getpid() % 997:03d}")
        spec = {**spec, "job": job_id}
        existing = self.status(job_id)
        if existing is not None and existing.get("state") == "done":
            return existing  # resume-complete: never re-run
        doc = {"job": job_id, "kind": kind, "state": "queued",
               "spec": spec, "submitted_ts": round(time.time(), 3),
               "progress": (existing or {}).get("progress"),
               "trace": (existing or {}).get("trace") or trace}
        with self._lock:
            self._write(doc)
            self._pending.add(job_id)
        self._q.put(job_id)
        telemetry.counter_add("serve.jobs_submitted")
        return doc

    def status(self, job_id) -> dict | None:
        """The job document, or None for an unknown id."""
        try:
            with open(self._doc_path(job_id)) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def is_live(self, job_id) -> bool:
        """True when THIS process will make progress on the job — it
        is on the worker right now or waiting in this store's queue.
        The document of record lives in the (shared) job dir and
        survives any replica, so a doc saying "running" proves
        nothing about who is running it: a respawned replica serves
        the dead process's last write.  This is the disambiguator
        the router's failover needs."""
        job_id = str(job_id)
        with self._lock:
            return job_id == self._active or job_id in self._pending

    def stop(self, timeout=10.0):
        self._stopped = True
        self._q.put(None)
        self._thread.join(timeout=timeout)

    def drain(self, timeout=60.0) -> bool:
        """Graceful quiesce: refuse new submits, leave queued jobs
        queued (their documents of record survive on disk — the
        router's failover or the post-deploy replica resubmits them),
        and wait for the RUNNING job to stop at its next chunk
        boundary (:class:`JobInterrupted` after its checkpoint
        landed).  Returns True when the worker went idle within
        ``timeout``."""
        self._draining = True
        deadline = time.time() + float(timeout)
        while time.time() < deadline:
            if self._active is None:
                return True
            time.sleep(0.05)
        return self._active is None

    def _worker(self):
        while True:
            job_id = self._q.get()
            if job_id is None or self._stopped:
                return
            # claim BEFORE leaving the pending set so is_live never
            # sees the job in neither place mid-handoff
            self._active = job_id
            with self._lock:
                self._pending.discard(job_id)
            if self._draining:
                self._active = None
                continue  # stays 'queued' on disk: resubmit resumes
            doc = self.status(job_id)
            if doc is None or doc.get("state") == "done":
                self._active = None
                continue  # a raced resubmit of a finished job
            doc["state"] = "running"
            doc["started_ts"] = round(time.time(), 3)
            with self._lock:
                self._write(doc)

            def _progress(d):
                with self._lock:
                    self._write(d)

            attrs = {"job": job_id, "job_kind": doc["kind"]}
            if doc.get("trace"):
                attrs["trace"] = doc["trace"]
            try:
                with telemetry.run_scope("serve.job", **attrs):
                    result = run_job(
                        self.registry, doc, self.job_dir,
                        grid_chunk=self.grid_chunk,
                        progress=_progress,
                        should_stop=lambda: self._draining)
                doc["state"] = "done"
                doc["result"] = result
                telemetry.counter_add("serve.jobs_done")
            except JobInterrupted as e:  # drained at a chunk
                doc["state"] = "interrupted"  # boundary: resumable
                doc["detail"] = str(e)
                telemetry.counter_add("serve.jobs_interrupted")
            except Exception as e:  # job failure is a document state,
                doc["state"] = "failed"  # never a worker death
                doc["error"] = f"{type(e).__name__}: {e}"
                telemetry.counter_add("serve.jobs_failed")
            finally:
                self._active = None
            doc["finished_ts"] = round(time.time(), 3)
            with self._lock:
                self._write(doc)


def main(argv=None):
    """Hidden CLI for the chaos harness: run ONE job inline in this
    process (``python -m pint_tpu.serve.jobs JOB_DIR SPEC_JSON``) —
    the subprocess the kill-site tests murder and restart.  The spec
    must carry a ``par`` entry (the dataset is registered in-process).
    Prints the final job document as JSON."""
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 2:
        print("usage: python -m pint_tpu.serve.jobs JOB_DIR SPEC_JSON",
              file=sys.stderr)
        return 2
    job_dir, spec_raw = argv
    spec = json.loads(spec_raw)
    from pint_tpu.serve.state import DatasetRegistry, serve_config

    registry = DatasetRegistry()
    registry.load(spec["dataset"], par=spec.pop("par"),
                  toas=spec.pop("toas", None))
    doc = {"job": str(spec.get("job", "chaosjob")),
           "kind": spec.get("kind", "grid"), "state": "running",
           "spec": spec}
    result = run_job(registry, doc, job_dir,
                     grid_chunk=serve_config()["grid_chunk"])
    doc["state"] = "done"
    doc["result"] = result
    _atomic_write_json(os.path.join(job_dir, doc["job"] + ".json"),
                       doc)
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
