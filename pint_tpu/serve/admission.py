"""Admission control: bound the device queue, shed load, honor
deadlines.

A long-lived replica's failure mode under overload is an unbounded
queue: every request is eventually served, every response is useless
(its client timed out long ago), and the process OOMs on buffered
work.  This layer refuses work at the front door instead:

- **Queue bound** — at most ``queue_max`` requests may be pending in
  the coalescing batcher (``$PINT_TPU_SERVE_QUEUE_MAX``).  Request
  ``queue_max + 1`` is **shed**: a structured
  :class:`~pint_tpu.serve.state.Shed` that the HTTP layer maps to
  ``429`` with a ``Retry-After`` hint derived from the flush cadence
  (~two flush periods: by then the queue has drained at least one
  full batch per group).  Shedding is O(1) host work — a saturated
  replica stays responsive ABOUT being saturated.
- **Per-request deadlines** — a request may carry ``deadline_ms``
  (default ``$PINT_TPU_SERVE_DEADLINE_MS``; 0 disables).  A request
  whose deadline expires while still queued is answered ``504``
  without touching the device (the work never started, so retrying
  elsewhere is safe); the miss ticks ``serve.deadline_misses``.

Neither knob ever reaches a traced program — admission decisions are
pure host arithmetic over queue depth and wall clocks.
"""

from __future__ import annotations

from pint_tpu import telemetry
from pint_tpu.serve.state import Shed

__all__ = ["admit", "retry_after_s"]


def retry_after_s(flush_ms, n_pending=0, drain_rate=0.0) -> float:
    """The Retry-After hint for a shed, floored at 50 ms (a 0-ms dev
    flush must not advertise retry-immediately to a client loop) and
    capped at 30 s.

    With an **observed drain rate** (requests/s actually served over
    the batcher's recent flush history) the hint is the time to drain
    the CURRENT backlog — ``n_pending / drain_rate`` — which tracks
    real service capacity under load.  Before the first flush has
    completed (no observation yet) it falls back to the static
    ~two-flush-period guess."""
    if drain_rate > 0.0 and n_pending > 0:
        return min(max(n_pending / drain_rate, 0.05), 30.0)
    return max(2.0 * float(flush_ms) / 1e3, 0.05)


def admit(n_pending, queue_max, flush_ms, drain_rate=0.0):
    """Raise :class:`Shed` when the pending queue is at its bound;
    otherwise admit (return None).  Called under the batcher lock so
    the bound is exact, never racy.  ``queue_max`` is the caller's
    EFFECTIVE bound — the SLO degrade hook may have shrunk it below
    the configured value."""
    if queue_max and n_pending >= int(queue_max):
        telemetry.counter_add("serve.sheds")
        raise Shed(
            f"device queue saturated ({n_pending} pending >= "
            f"queue_max {queue_max})",
            retry_after_s=retry_after_s(flush_ms, n_pending,
                                        drain_rate))
