"""Minimal JSON-over-HTTP client for the serving front door.

Stdlib ``http.client`` with keep-alive — the helper every in-repo
consumer (bench load generator, datacheck smoke, tests, examples)
uses so none of them hand-rolls HTTP.  Production clients can use any
HTTP stack; the wire format is plain JSON.
"""

from __future__ import annotations

import http.client
import json

__all__ = ["ServeClient", "request_json"]


class ServeClient:
    """One keep-alive connection to a replica."""

    def __init__(self, host="127.0.0.1", port=8470, timeout=60.0):
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self._conn = None

    def _connection(self):
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        return self._conn

    def request(self, method, path, body=None, headers=None):
        """Returns ``(status, parsed_json, headers_dict)``; retries
        once on a dropped keep-alive connection.  ``headers`` are
        extra request headers (e.g. a ``traceparent`` to continue a
        distributed trace across the front door)."""
        payload = (None if body is None
                   else json.dumps(body).encode())
        headers = {"Content-Type": "application/json",
                   **(headers or {})}
        for attempt in (0, 1):
            conn = self._connection()
            try:
                conn.request(method, path, body=payload,
                             headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                break
            except (http.client.HTTPException, ConnectionError,
                    BrokenPipeError, OSError):
                self.close()
                if attempt:
                    raise
        try:
            obj = json.loads(raw) if raw else {}
        except ValueError:
            obj = {"raw": raw.decode(errors="replace")}
        return resp.status, obj, {k.lower(): v
                                  for k, v in resp.getheaders()}

    # convenience verbs
    def get(self, path):
        return self.request("GET", path)

    def post(self, path, body, headers=None):
        return self.request("POST", path, body, headers=headers)

    def close(self):
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None


def request_json(host, port, method, path, body=None, timeout=60.0,
                 headers=None):
    """One-shot request (fresh connection, closed after)."""
    c = ServeClient(host, port, timeout=timeout)
    try:
        return c.request(method, path, body, headers=headers)
    finally:
        c.close()
