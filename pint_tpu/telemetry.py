"""Structured telemetry: spans, counters, FLOP accounting, JSONL sink.

Observability layer for the fit hot path.  Every perf-relevant event in
the library — a GLS fit, a jit retrace, a backend-probe timeout, an XLA
compile — becomes a structured record instead of a print statement or a
number hand-assembled inside bench.py.  Zero dependencies beyond the
stdlib; importing this module never touches a JAX backend.

Four surfaces:

- **Spans** — ``with span("gls_fit", n_toa=...):`` records wall time,
  nesting (depth + parent), and structured attributes.  Disabled by
  default: the disabled path is one module-global check returning a
  shared no-op object, so instrumented library code pays one dict
  lookup per enter.  Spans wrap *dispatch boundaries only* — never
  code inside ``jax.jit`` (a span in traced code would measure trace
  time once and nothing thereafter).
- **Counters/gauges** — in-memory accumulators (always on; one dict
  add) for jit compile events and compile seconds (via
  ``jax.monitoring`` where available, graceful no-op fallback), jit
  cache hits/misses at the library's own caches, device-transfer
  bytes, probe attempts/timeouts, and per-fit FLOP estimates
  (:mod:`pint_tpu.flops`).
- **JSONL sink** — ``PINT_TPU_TRACE=path`` (read at first import) or
  :func:`configure` emits one machine-parseable JSON object per span
  exit / counter flush / metric record.  ``pinttrace`` (the
  :mod:`pint_tpu.scripts.pinttrace` CLI) summarizes a trace file.
- **Reporting** — :func:`summary` renders the session's spans and
  counters as a text table; :func:`compile_stats` exposes the compile
  counters (``pint_tpu.datacheck`` prints both).

An optional :func:`xprof_trace` passthrough wraps
``jax.profiler.trace`` for deep-dive profiling with the same on/off
switch.
"""

from __future__ import annotations

import atexit
import json
import math as _math
import os
import threading
import time

__all__ = [
    "span", "configure", "enabled", "emit", "emit_group", "flush",
    "sink_active", "sink_info",
    "counter_add", "counter_get", "counters", "gauge_set", "gauges",
    "LogHistogram", "hist_record", "histograms",
    "add_span_hook", "add_flush_hook",
    "record_transfer", "compile_stats", "summary", "summary_lines",
    "render_stats_lines", "reset", "xprof_trace",
    "run_scope", "current_run_id", "new_run_id", "run_note_program",
    "run_note_phase", "runs_summary", "iter_trace_record",
]

_TRACE_ENV = "PINT_TPU_TRACE"
_TRACE_MAX_ENV = "PINT_TPU_TRACE_MAX_MB"

#: process-global state; guarded by _lock for the mutating paths.  The
#: hot path (span() with telemetry disabled) reads one attribute
#: lock-free — stale reads only mean a span near the configure() call
#: is dropped or kept, never corruption.
_lock = threading.RLock()


class _State:
    __slots__ = ("enabled", "sink", "sink_owned", "span_stats",
                 "counters", "gauges", "hists", "t_session",
                 "sink_path", "sink_bytes", "sink_max_bytes")

    def __init__(self):
        self.enabled = False
        self.sink = None          # file-like with .write(str)
        self.sink_owned = False   # close on reconfigure/exit
        #: name -> [count, total_s, max_s]
        self.span_stats: dict = {}
        self.counters: dict = {}
        self.gauges: dict = {}
        self.hists: dict = {}     # name -> LogHistogram
        self.t_session = time.time()
        self.sink_path = None     # path of an owned sink (rotation)
        self.sink_bytes = 0       # bytes written since open/rotate
        self.sink_max_bytes = 0   # 0 = unbounded (the default)


_state = _State()

_tls = threading.local()  # per-thread span stack for nesting

#: extension hooks — profiling (and tests) register callables here;
#: failures inside a hook must never take a span or a flush down.
_span_hooks: list = []    # fn(name, dur_s) on every span exit
_flush_hooks: list = []   # fn() at the start of every flush()


def add_span_hook(fn):
    """Register ``fn(name, dur_s)`` to run on every span exit (only
    while spans are enabled).  Idempotent per function object."""
    if fn not in _span_hooks:
        _span_hooks.append(fn)
    return fn


def add_flush_hook(fn):
    """Register ``fn()`` to run at the start of every :func:`flush`
    (profiling uses this to mirror its program registry into the
    sink).  Idempotent per function object."""
    if fn not in _flush_hooks:
        _flush_hooks.append(fn)
    return fn


# --------------------------------------------------------------------------
# configuration
# --------------------------------------------------------------------------

def _max_bytes_from(max_mb):
    """Resolve the sink size cap: an explicit ``max_mb`` wins, else
    ``$PINT_TPU_TRACE_MAX_MB``; 0/unset/unparseable = unbounded."""
    raw = max_mb if max_mb is not None else os.environ.get(
        _TRACE_MAX_ENV, "")
    try:
        mb = float(raw)
    except (TypeError, ValueError):
        return 0
    return int(mb * 1e6) if mb > 0 else 0


def configure(sink=None, enabled=None, max_mb=None):
    """(Re)configure the telemetry layer.

    sink: a path (opened append-mode, line-buffered), a file-like
    object with ``.write``, or None to detach the sink.  enabled:
    force spans on/off; defaults to "on iff a sink is attached".
    max_mb: rotate an owned (path) sink once it grows past this many
    MB (default ``$PINT_TPU_TRACE_MAX_MB``; 0/unset = unbounded — a
    long-lived warm service should set a cap).  Returns the module for
    chaining."""
    global _state
    with _lock:
        if _state.sink is not None and _state.sink_owned:
            try:
                _state.sink.close()
            except OSError:
                pass
        _state.sink_path = None
        _state.sink_bytes = 0
        _state.sink_max_bytes = _max_bytes_from(max_mb)
        if sink is None:
            _state.sink = None
            _state.sink_owned = False
        elif hasattr(sink, "write"):
            _state.sink = sink
            _state.sink_owned = False
        else:
            path = os.fspath(sink)
            _state.sink = open(path, "a", buffering=1)
            _state.sink_owned = True
            _state.sink_path = path
            try:  # append mode: the cap covers the file, not the session
                _state.sink_bytes = os.path.getsize(path)
            except OSError:
                _state.sink_bytes = 0
        _state.enabled = bool(
            _state.sink is not None if enabled is None else enabled
        )
    import sys

    return sys.modules[__name__]


def _rotate_sink_locked():
    """Rotate the owned sink file (caller holds ``_lock``): close,
    move aside as ``<path>.1`` (one generation — the live file plus
    one keeps disk bounded at ~2x the cap), reopen fresh.  Recorded as
    the ``telemetry.sink_rotations`` counter plus one record in the
    new file.

    A failed rename (target is a directory, parent permissions, some
    overlay mounts) must not be reported as a rotation that happened:
    the cap is disabled for this sink (otherwise every emit would
    retry the doomed rename AND the byte counter would restart on an
    untruncated file, growing it a full cap per cycle), a
    ``telemetry.sink_rotation_failures`` counter ticks, and the file
    keeps appending."""
    path = _state.sink_path
    try:
        _state.sink.close()
    except OSError:
        pass
    try:
        os.replace(path, path + ".1")
        rotated = True
    except OSError:
        rotated = False
    try:
        _state.sink = open(path, "a", buffering=1)
    except OSError:
        _state.sink = None
        _state.sink_owned = False
        _state.sink_path = None
        return
    if rotated:
        _state.sink_bytes = 0
        _state.counters["telemetry.sink_rotations"] = \
            _state.counters.get("telemetry.sink_rotations", 0.0) + 1.0
        rec = {"type": "sink_rotation", "rotated_to": path + ".1",
               "ts": round(time.time(), 6)}
    else:
        _state.sink_max_bytes = 0  # cap unenforceable: stop pretending
        _state.counters["telemetry.sink_rotation_failures"] = \
            _state.counters.get(
                "telemetry.sink_rotation_failures", 0.0) + 1.0
        rec = {"type": "sink_rotation_failed",
               "detail": "rename to .1 failed; size cap disabled",
               "ts": round(time.time(), 6)}
    line = json.dumps(rec, separators=(",", ":"))
    try:
        _state.sink.write(line + "\n")
        _state.sink_bytes += len(line) + 1
    except (OSError, ValueError):
        pass


def enabled() -> bool:
    """Whether spans are live (cheap; safe to call anywhere)."""
    return _state.enabled


def reset():
    """Drop accumulated stats/counters (tests; the sink is kept)."""
    with _lock:
        _state.span_stats.clear()
        _state.counters.clear()
        _state.gauges.clear()
        _state.hists.clear()
        _state.t_session = time.time()
        _tls.stack = []
        _recent_runs.clear()


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

class _NullSpan:
    """Shared do-nothing span: the disabled-path object.  __slots__ so
    even attribute writes fail loudly instead of accumulating state."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "t0", "wall0", "depth", "parent")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs

    def set(self, **attrs):
        """Attach attributes discovered mid-span."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self.depth = len(stack)
        self.parent = stack[-1].name if stack else None
        stack.append(self)
        self.wall0 = time.time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self.t0
        stack = getattr(_tls, "stack", [])
        if stack and stack[-1] is self:
            stack.pop()
        rec = {
            "type": "span",
            "name": self.name,
            "ts": round(self.wall0, 6),
            "dur_s": round(dur, 9),
            "depth": self.depth,
            "parent": self.parent,
            # nesting (depth/parent and the span stack) is per-thread;
            # consumers that lay spans on tracks (chrome_trace) need
            # the thread identity or concurrent spans garble a track
            "tid": threading.get_ident(),
        }
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        if self.attrs:
            rec["attrs"] = _jsonable(self.attrs)
        with _lock:
            st = _state.span_stats.setdefault(self.name, [0, 0.0, 0.0])
            st[0] += 1
            st[1] += dur
            st[2] = max(st[2], dur)
        for hook in _span_hooks:
            try:
                hook(self.name, dur)
            except Exception:
                pass  # a broken hook must never take a span down
        emit(rec)
        return False


def span(name, **attrs):
    """Open a telemetry span.  With telemetry disabled this returns a
    shared no-op object — the whole call is one global load, one bool
    check, and zero allocation beyond the kwargs dict."""
    if not _state.enabled:
        return _NULL_SPAN
    return _Span(name, attrs)


# --------------------------------------------------------------------------
# counters / gauges
# --------------------------------------------------------------------------

def counter_add(name, value=1.0):
    """Accumulate into a named counter (always on; in-memory)."""
    with _lock:
        _state.counters[name] = _state.counters.get(name, 0.0) + value


def counter_get(name, default=0.0):
    return _state.counters.get(name, default)


def counters() -> dict:
    """Snapshot of all counters."""
    with _lock:
        return dict(_state.counters)


def gauge_set(name, value):
    """Set a named gauge (last-value-wins)."""
    with _lock:
        _state.gauges[name] = value


def gauges() -> dict:
    """Snapshot of all gauges.  Histogram percentiles ride along as
    flattened ``hist.<name>.{p50,p95,p99,n}`` entries — the one
    readout surface for latency distributions."""
    with _lock:
        out = dict(_state.gauges)
        snaps = {name: h.snapshot()
                 for name, h in _state.hists.items()}
    for name, snap in snaps.items():
        for k in ("p50", "p95", "p99", "n"):
            out[f"hist.{name}.{k}"] = snap[k]
    return out


class LogHistogram:
    """Host-side log-bucketed histogram of positive values (latencies,
    byte counts): O(1) record into sparse geometric buckets, p50/p95/
    p99 readout from the cumulative counts.  A bucket's estimate is
    its geometric midpoint, clamped to the exactly-tracked [min, max]
    — so a single-value histogram reports that value at every
    percentile, and p50 <= p95 <= p99 always holds (ranks and bucket
    indices are both monotone)."""

    __slots__ = ("base", "_log_growth", "counts", "n", "total",
                 "vmin", "vmax")

    #: default resolution: ~19% bucket width from 1 ns up — 2 decades
    #: of latency span ~26 buckets
    BASE = 1e-9
    GROWTH = 1.1892071150027210667  # 2**0.25

    def __init__(self, base=BASE, growth=GROWTH):
        self.base = float(base)
        self._log_growth = _math.log(float(growth))
        self.counts: dict = {}       # bucket index -> count
        self.n = 0
        self.total = 0.0
        self.vmin = None
        self.vmax = None

    def record(self, value):
        v = float(value)
        if v <= self.base:
            idx = 0                  # underflow bucket (v <= base)
        else:
            idx = 1 + int(_math.log(v / self.base) / self._log_growth)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.n += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)

    def _estimate(self, idx, vmin, vmax):
        if idx == 0:
            est = self.base
        else:  # geometric midpoint of bucket idx
            est = self.base * _math.exp((idx - 0.5) * self._log_growth)
        return min(max(est, vmin), vmax)

    def percentiles(self, qs) -> dict:
        """Value estimates at each percentile in ``qs`` (0-100), all
        computed from ONE copy of the bucket table and one (n, vmin,
        vmax) read — so the returned set is mutually consistent
        (p50 <= p95 <= p99 always) even when a concurrent ``record``
        or ``reset`` lands between the individual reads.  Snapshot
        paths that flush mid-fit depend on this: the old
        one-percentile-at-a-time readout could pair a pre-reset p50
        with a post-reset p99."""
        n, vmin, vmax = self.n, self.vmin, self.vmax
        if n == 0 or vmin is None:
            return {q: None for q in qs}
        items = sorted(self.counts.items())
        out = {}
        for q in sorted(qs):
            rank = max(1, _math.ceil(q / 100.0 * n))
            cum = 0
            est = vmax  # fallback if counts mutated under us
            for idx, c in items:
                cum += c
                if cum >= rank:
                    est = self._estimate(idx, vmin, vmax)
                    break
            out[q] = est
        return out

    def percentile(self, q):
        """Value estimate at percentile ``q`` (0-100); None if empty.
        For several percentiles of one histogram use
        :meth:`percentiles` — it reads the state once."""
        return self.percentiles((q,))[q]

    def snapshot(self) -> dict:
        ps = self.percentiles((50, 95, 99))
        return {
            "n": self.n,
            "total": self.total,
            "min": self.vmin,
            "max": self.vmax,
            "p50": ps[50],
            "p95": ps[95],
            "p99": ps[99],
        }


def hist_record(name, value):
    """Record one sample into the named log-bucketed histogram.
    The record happens under the module lock — LogHistogram itself is
    not thread-safe, and concurrent recorders (profiled calls + span
    hooks) share these instances."""
    with _lock:
        h = _state.hists.get(name)
        if h is None:
            h = _state.hists[name] = LogHistogram()
        h.record(value)


def histograms() -> dict:
    """Snapshot of every histogram: name -> {n, total, min, max, p50,
    p95, p99}.  Snapshots are taken under the lock so a concurrent
    record can never be observed half-applied."""
    with _lock:
        return {name: h.snapshot()
                for name, h in _state.hists.items()}


def record_transfer(arr, direction="d2h"):
    """Account device<->host transfer bytes for an array-like (anything
    with ``.nbytes``); silently ignores scalars/None."""
    nbytes = getattr(arr, "nbytes", None)
    if nbytes:
        counter_add(f"transfer.{direction}_bytes", float(nbytes))


# --------------------------------------------------------------------------
# run ledger
# --------------------------------------------------------------------------
#
# Six record types flow through the sink (spans, counters, programs,
# health, AOT, metrics) with nothing joining them per fit.  A *run* is
# one top-level library operation — a fit, a grid, a likelihood
# surface, an MCMC chain, a bench metric — identified by a
# process-unique ``run_id`` minted at the entry point.  Every record
# emitted while a run is active is tagged with it automatically
# (:func:`emit`), so ``pinttrace --runs`` can reconstruct one fit end
# to end: inputs fingerprint -> compile/AOT events -> phase split ->
# per-iteration convergence -> final rung/health.

#: process-unique id prefix: pid + import-time microseconds, so two
#: concurrent processes writing one trace file can never collide
_RUN_PREFIX = f"{os.getpid():x}{int(time.time() * 1e6) & 0xFFFFF:05x}"
_run_seq = 0
_runs_in_flight = 0

#: recently completed run summaries (the ledger's in-memory tail —
#: datacheck and the /metrics endpoint read it); bounded
_RECENT_RUNS_CAP = 64
_recent_runs: list = []

#: counters whose per-run delta the run record reports (the
#: compile/AOT half of the ledger join; names -> record field)
_RUN_COMPILE_COUNTERS = (
    ("jit.backend_compile_events", "backend_compiles"),
    ("jit.persistent_cache_hits", "cache_hits"),
    ("jit.aot_import_hits", "aot_hits"),
    ("jit.aot_served_calls", "aot_served"),
    ("compile_cache.registry_misses", "registry_misses"),
    ("compile_cache.registry_hits", "registry_hits"),
)

#: cumulative / process-global record types that must NOT be
#: attributed to whatever run happens to be active at flush time
_RUN_UNTAGGED_TYPES = frozenset((
    "counter", "gauge", "hist", "program", "sink_rotation",
    "sink_rotation_failed",
))


class _Run:
    """One live run: identity plus the joinable state accumulated by
    the note hooks (programs dispatched, profiled phase split)."""

    __slots__ = ("run_id", "kind", "attrs", "t0", "wall0", "programs",
                 "_progset", "compile0", "phase")

    _PROGRAMS_CAP = 32

    def __init__(self, run_id, kind, attrs):
        self.run_id = run_id
        self.kind = kind
        self.attrs = attrs
        self.t0 = time.perf_counter()
        self.wall0 = time.time()
        self.programs: list = []
        self._progset: set = set()
        self.compile0 = {name: counter_get(name)
                         for name, _ in _RUN_COMPILE_COUNTERS}
        self.phase = None  # {"trace_s","dispatch_s","device_s"} or None

    def note_program(self, label):
        if label not in self._progset \
                and len(self.programs) < self._PROGRAMS_CAP:
            self._progset.add(label)
            self.programs.append(label)

    def note_phase(self, trace_s, dispatch_s, device_s):
        if self.phase is None:
            self.phase = {"trace_s": 0.0, "dispatch_s": 0.0,
                          "device_s": 0.0}
        self.phase["trace_s"] += trace_s
        self.phase["dispatch_s"] += dispatch_s
        self.phase["device_s"] += device_s


def new_run_id() -> str:
    """Mint a process-unique run id (``r<pid+epoch hex>-<seq>``)."""
    global _run_seq
    with _lock:
        _run_seq += 1
        return f"r{_RUN_PREFIX}-{_run_seq:04d}"


def _run_stack():
    stack = getattr(_tls, "runs", None)
    if stack is None:
        stack = _tls.runs = []
    return stack


def current_run_id():
    """The active run's id (this thread), or None outside any run."""
    stack = getattr(_tls, "runs", None)
    return stack[-1].run_id if stack else None


def run_note_program(label):
    """Attach a dispatched program label to the active run (no-op
    outside a run).  Called by the profiling proxy on every shared-jit
    dispatch — one thread-local read when no run is active."""
    stack = getattr(_tls, "runs", None)
    if stack:
        stack[-1].note_program(label)


def run_note_phase(trace_s, dispatch_s, device_s):
    """Accumulate a profiled call's phase split into the active run
    (no-op outside a run / with profiling off)."""
    stack = getattr(_tls, "runs", None)
    if stack:
        stack[-1].note_phase(trace_s, dispatch_s, device_s)


class _RunScope:
    """Context manager for one run.  Nested entry points JOIN the
    active run instead of minting a new id (a fit inside a bench
    metric, a chunked grid inside grid_chisq_vectorized): only the
    outermost scope owns the id, emits the run record, and moves the
    in-flight/completed ledger gauges."""

    __slots__ = ("kind", "attrs", "run", "_owner")

    def __init__(self, kind, attrs):
        self.kind = kind
        self.attrs = attrs
        self.run = None
        self._owner = False

    def __enter__(self):
        global _runs_in_flight
        stack = _run_stack()
        if stack:
            run = stack[-1]
        else:
            self._owner = True
            run = _Run(new_run_id(), self.kind, dict(self.attrs))
            with _lock:
                _runs_in_flight += 1
                _state.gauges["runs.in_flight"] = _runs_in_flight
        stack.append(run)
        self.run = run
        return run

    def __exit__(self, exc_type, exc, tb):
        global _runs_in_flight
        stack = _run_stack()
        if stack and stack[-1] is self.run:
            stack.pop()
        if not self._owner:
            return False
        run = self.run
        status = "ok" if exc_type is None else exc_type.__name__
        dur = time.perf_counter() - run.t0
        delta = {field: counter_get(name) - run.compile0[name]
                 for name, field in _RUN_COMPILE_COUNTERS}
        rec = {
            "type": "run",
            "run": run.run_id,
            "kind": run.kind,
            "ts": round(run.wall0, 6),
            "dur_s": round(dur, 6),
            "status": status,
            "compile": {k: v for k, v in delta.items() if v},
        }
        if run.attrs:
            rec["attrs"] = _jsonable(run.attrs)
        if run.programs:
            rec["programs"] = list(run.programs)
        if run.phase is not None:
            rec["phase_s"] = {k: round(v, 6)
                              for k, v in run.phase.items()}
        with _lock:
            _runs_in_flight = max(_runs_in_flight - 1, 0)
            _state.gauges["runs.in_flight"] = _runs_in_flight
            _state.counters["runs.completed"] = \
                _state.counters.get("runs.completed", 0.0) + 1.0
            if status != "ok":
                _state.counters["runs.failed"] = \
                    _state.counters.get("runs.failed", 0.0) + 1.0
            _recent_runs.append({k: rec[k] for k in
                                 ("run", "kind", "ts", "dur_s",
                                  "status")})
            del _recent_runs[:-_RECENT_RUNS_CAP]
        emit(rec)
        return False


def run_scope(kind, **attrs):
    """Open (or join) a run: the ledger identity every entry point —
    ``fit_toas``, the grid callables, the batched PTA fits,
    ``lnlike_grid``, ``run_mcmc``, each bench metric — wraps its work
    in.  Nested scopes reuse the outer run's id, so one bench metric's
    internal fits all join one ledger row.  Yields the run object
    (``.run_id``); at the outermost exit one ``{"type": "run"}``
    record is emitted carrying duration, status, per-run compile/AOT
    counter deltas, the programs dispatched, and (when profiling was
    on) the accumulated phase split."""
    return _RunScope(kind, attrs)


def runs_summary() -> dict:
    """The in-memory ledger tail: ``{"in_flight", "completed",
    "failed", "recent": [...]}`` (datacheck / the /metrics
    endpoint)."""
    with _lock:
        return {
            "in_flight": _runs_in_flight,
            "completed": int(_state.counters.get("runs.completed", 0)),
            "failed": int(_state.counters.get("runs.failed", 0)),
            "recent": [dict(r) for r in _recent_runs],
        }


def iter_trace_record(program, entries, *, kind="fit", **extra) -> dict:
    """Assemble one ``{"type": "iter_trace"}`` record from decoded
    per-iteration entries (each a dict with ``i``/``chi2``/
    ``step_norm``/``max_dpar``/``ok``/``guard_eps``/``rung`` and, on
    batched programs, reduction extras) — the record
    ``pinttrace --convergence`` renders.  Extra keyword fields
    (``n_points``, ``n_pulsars``, ``rungs``) ride along; None values
    are dropped."""
    rec = {"type": "iter_trace", "program": program, "kind": kind,
           "ts": round(time.time(), 6), "n_iter": len(entries),
           "iters": [_jsonable(e) for e in entries]}
    for k, v in extra.items():
        if v is not None:
            rec[k] = v
    return rec


# --------------------------------------------------------------------------
# emission
# --------------------------------------------------------------------------

def _jsonable(obj):
    """Best-effort conversion to JSON-serializable values (numpy
    scalars, arrays-as-shapes) without importing numpy."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if hasattr(obj, "item") and getattr(obj, "ndim", 1) == 0:
        try:
            return obj.item()
        except (TypeError, ValueError):
            pass
    if hasattr(obj, "shape"):
        return {"shape": list(obj.shape),
                "dtype": str(getattr(obj, "dtype", "?"))}
    return repr(obj)


def sink_active() -> bool:
    """Whether a JSONL sink is attached (cheap) — callers with
    expensive records to assemble (iteration-trace decodes force a
    device sync) check this before building them."""
    return _state.sink is not None


def sink_info() -> dict:
    """Describe the attached sink so a caller that temporarily swaps
    it (``datacheck --runs``) can RESTORE it afterwards:
    ``{"path": ..., "sink": ..., "enabled": ...}`` — ``path`` for an
    owned path-opened sink (reattach with ``configure(sink=path)``,
    which reopens append-mode), ``sink`` for a caller-provided
    file-like, both None when detached."""
    with _lock:
        return {
            "path": _state.sink_path if _state.sink_owned else None,
            "sink": (None if _state.sink_owned else _state.sink),
            "enabled": _state.enabled,
        }


def emit(record: dict):
    """Write one JSONL record to the sink (no-op without a sink).

    Records emitted while a run is active are tagged with its
    ``run_id`` (the ledger join key) unless they carry one already or
    are process-cumulative types (counter/gauge/hist/program flush
    mirrors describe the whole session, not the run that happened to
    be active at flush time)."""
    sink = _state.sink
    if sink is None:
        return
    rid = current_run_id()
    if rid is not None and "run" not in record \
            and record.get("type") not in _RUN_UNTAGGED_TYPES:
        record = {**record, "run": rid}
    try:
        line = json.dumps(_jsonable(record), separators=(",", ":"))
    except (TypeError, ValueError):
        line = json.dumps({"type": "emit_error", "repr": repr(record)})
    with _lock:
        if _state.sink is not sink:
            # concurrent reconfigure swapped the sink while this record
            # was being serialized: drop the record, never the new sink
            return
        try:
            sink.write(line + "\n")
        except (OSError, ValueError):  # closed/broken sink: detach
            if _state.sink_owned:
                try:
                    sink.close()
                except OSError:
                    pass
            _state.sink = None
            _state.sink_owned = False
            return
        _state.sink_bytes += len(line) + 1
        if (_state.sink_owned and _state.sink_max_bytes
                and _state.sink_path
                and _state.sink_bytes >= _state.sink_max_bytes):
            _rotate_sink_locked()


def emit_group(records):
    """Write several related JSONL records as ONE atomic sink write
    (no-op without a sink).

    :func:`emit` checks the rotation cap after every record, so a
    record *group* — a batched device span plus the N request spans
    that link to it — could straddle a rotation boundary, leaving
    ``pinttrace --chrome-trace`` a dangling track whose link target
    lives in the rotated-out file.  This path serializes the whole
    group first, writes it under one lock hold, and checks the cap
    only at the group boundary: every record of the group lands in
    the same sink file (the group may overshoot ``sink_max_bytes`` by
    at most its own size — bounded by max_batch, not by load).

    Run-id tagging matches :func:`emit` record-for-record."""
    sink = _state.sink
    if sink is None:
        return
    rid = current_run_id()
    lines = []
    for record in records:
        if rid is not None and "run" not in record \
                and record.get("type") not in _RUN_UNTAGGED_TYPES:
            record = {**record, "run": rid}
        try:
            lines.append(json.dumps(_jsonable(record),
                                    separators=(",", ":")))
        except (TypeError, ValueError):
            lines.append(json.dumps({"type": "emit_error",
                                     "repr": repr(record)}))
    if not lines:
        return
    blob = "\n".join(lines) + "\n"
    with _lock:
        if _state.sink is not sink:
            return  # concurrent reconfigure: drop the group
        try:
            sink.write(blob)
        except (OSError, ValueError):
            if _state.sink_owned:
                try:
                    sink.close()
                except OSError:
                    pass
            _state.sink = None
            _state.sink_owned = False
            return
        _state.sink_bytes += len(blob)
        if (_state.sink_owned and _state.sink_max_bytes
                and _state.sink_path
                and _state.sink_bytes >= _state.sink_max_bytes):
            _rotate_sink_locked()


def flush():
    """Emit one record per counter, gauge, and histogram (the
    periodic/exit flush), then flush the sink's buffer.  Flush hooks
    (profiling's program-registry mirror) run first so their records
    land in the same flush."""
    for hook in _flush_hooks:
        try:
            hook()
        except Exception:
            pass  # a broken hook must never take the flush down
    ts = round(time.time(), 6)
    with _lock:
        items = list(_state.counters.items())
        gitems = list(_state.gauges.items())
        hitems = [(name, h.snapshot())
                  for name, h in _state.hists.items()]
        sink = _state.sink
    for name, value in items:
        emit({"type": "counter", "name": name, "value": value, "ts": ts})
    for name, value in gitems:
        emit({"type": "gauge", "name": name, "value": _jsonable(value),
              "ts": ts})
    for name, snap in hitems:
        emit({"type": "hist", "name": name, "ts": ts, **snap})
    if sink is not None and hasattr(sink, "flush"):
        try:
            sink.flush()
        except (OSError, ValueError):
            pass


@atexit.register
def _exit_flush():
    if _state.sink is not None:
        flush()
        if _state.sink_owned:
            try:
                _state.sink.close()
            except OSError:
                pass


# --------------------------------------------------------------------------
# compile counters (jax.monitoring hook, graceful fallback)
# --------------------------------------------------------------------------

_compile_listener_installed = False
_compile_listener_source = "uninstalled"


def _install_compile_listener(monitoring="auto"):
    """Hook ``jax.monitoring`` duration events into the counters.

    JAX's internal instrumentation reports every backend compile as a
    ``/jax/.../compile`` duration event; registering a listener costs
    nothing when no events fire.  When the API is absent (older/newer
    jax, stubbed environment) the layer degrades to the counters that
    the library increments itself (``jit.retrace`` etc.) — callers see
    ``compile_stats()["source"] == "fallback"``.

    monitoring: "auto" imports ``jax.monitoring``; pass an object (or
    None) to override in tests."""
    global _compile_listener_installed, _compile_listener_source
    with _lock:
        if _compile_listener_installed:
            return _compile_listener_source
        _compile_listener_installed = True
        if monitoring == "auto":
            try:
                from jax import monitoring as _mon  # defers jax import cost
                monitoring = _mon
            except Exception:
                monitoring = None
        reg = getattr(monitoring,
                      "register_event_duration_secs_listener", None)
        if reg is None:
            _compile_listener_source = "fallback"
            return _compile_listener_source

        def _on_duration(event, duration, **kw):
            if "compil" in event:  # compile/compilation event keys
                counter_add("jit.compile_events")
                counter_add("jit.compile_seconds", float(duration))
                # refined split: the broad counters above also count
                # tracing/lowering and persistent-cache bookkeeping;
                # these separate the actual XLA backend compiles from
                # the disk-cache hits that AVOIDED one
                if "backend_compile" in event:
                    counter_add("jit.backend_compile_events")
                    counter_add("jit.backend_compile_seconds",
                                float(duration))
                elif "compile_time_saved" in event:
                    counter_add("jit.persistent_cache_hits")
                    counter_add("jit.persistent_cache_saved_seconds",
                                float(duration))

        try:
            reg(_on_duration)
            _compile_listener_source = "jax.monitoring"
        except Exception:
            _compile_listener_source = "fallback"
        return _compile_listener_source


def compile_stats() -> dict:
    """Compile-event stats for this session.  ``events``/``seconds``
    are the broad counters (every jax compile-phase event: tracing,
    lowering, backend compile, cache bookkeeping);
    ``backend_events``/``backend_seconds`` count only actual XLA
    backend compiles, and ``cache_hits``/``cache_saved_seconds``
    count persistent-cache retrievals that avoided one.
    ``uncached_backend_events`` is the derived count of backend
    compiles that actually ran XLA: jax fires the backend_compile
    duration event even when the persistent cache serves the
    executable (measured on jax 0.4.37 — every cache hit pairs a
    backend_compile event with a compile_time_saved event), so the
    honest "did XLA really compile" number is events minus cache
    hits.  The ``aot_*`` fields mirror the imported-executable store
    counters (``jit.aot_import_{hits,misses,rejects}``) — an
    AOT-served program never traces, so it ticks none of the compile
    counters at all.  Installs the jax.monitoring listener on first
    call (so merely importing telemetry never imports jax)."""
    source = _install_compile_listener()
    backend_events = int(counter_get("jit.backend_compile_events"))
    cache_hits = int(counter_get("jit.persistent_cache_hits"))
    return {
        "events": int(counter_get("jit.compile_events")),
        "seconds": float(counter_get("jit.compile_seconds")),
        "backend_events": backend_events,
        "backend_seconds": float(
            counter_get("jit.backend_compile_seconds")),
        "cache_hits": cache_hits,
        "cache_saved_seconds": float(
            counter_get("jit.persistent_cache_saved_seconds")),
        "uncached_backend_events": max(backend_events - cache_hits, 0),
        "aot_hits": int(counter_get("jit.aot_import_hits")),
        "aot_misses": int(counter_get("jit.aot_import_misses")),
        "aot_rejects": int(counter_get("jit.aot_import_rejects")),
        "source": source,
    }


# --------------------------------------------------------------------------
# xprof passthrough
# --------------------------------------------------------------------------

class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def xprof_trace(log_dir):
    """Context manager: ``jax.profiler.trace`` when available (xprof/
    tensorboard deep dives), a no-op context otherwise — callers keep
    one code path whether or not the profiler exists."""
    try:
        import jax.profiler

        return jax.profiler.trace(str(log_dir))
    except Exception:
        return _NullCtx()


# --------------------------------------------------------------------------
# reporting
# --------------------------------------------------------------------------

def _fmt_value(v):
    try:
        return (f"{int(v):d}" if float(v).is_integer()
                else f"{float(v):.4f}")
    except (TypeError, ValueError):
        return repr(v)


def render_stats_lines(span_stats, counters=None, gauges=None,
                       indent=""):
    """Render span/counter/gauge aggregates as table lines — the ONE
    place the table format lives, shared by the in-process
    :func:`summary` and the ``pinttrace`` CLI.

    span_stats: name -> (count, total_s, max_s[, max_depth]); a DEPTH
    column appears when any entry carries the 4th element."""
    lines = []
    with_depth = any(len(st) > 3 for st in span_stats.values())
    if span_stats:
        hdr = (f"{indent}{'SPAN':<28s} {'COUNT':>7s} {'TOTAL_S':>10s} "
               f"{'MEAN_S':>10s} {'MAX_S':>10s}")
        if with_depth:
            hdr += f" {'DEPTH':>6s}"
        lines.append(hdr)
        for name in sorted(span_stats, key=lambda n: -span_stats[n][1]):
            st = span_stats[name]
            cnt, tot, mx = st[0], st[1], st[2]
            row = (f"{indent}{name:<28s} {cnt:>7d} {tot:>10.4f} "
                   f"{tot / max(cnt, 1):>10.4f} {mx:>10.4f}")
            if with_depth:
                row += f" {(st[3] if len(st) > 3 else 0):>6d}"
            lines.append(row)
    if counters:
        lines.append(f"{indent}{'COUNTER':<40s} {'VALUE':>14s}")
        for name in sorted(counters):
            lines.append(
                f"{indent}{name:<40s} {_fmt_value(counters[name]):>14s}")
    for name in sorted(gauges or {}):
        lines.append(f"{indent}gauge {name} = {gauges[name]!r}")
    return lines


def summary_lines():
    """The session summary as a list of text lines (spans table +
    counters + gauges)."""
    with _lock:
        stats = {k: list(v) for k, v in _state.span_stats.items()}
        ctrs = dict(_state.counters)
        gs = dict(_state.gauges)
    lines = []
    lines.append("telemetry session summary "
                 f"(spans {'enabled' if _state.enabled else 'disabled'}, "
                 f"sink {'attached' if _state.sink is not None else 'none'})")
    if not stats:
        lines.append("  (no spans recorded)")
    lines.extend(render_stats_lines(stats, ctrs, gs, indent="  "))
    return lines


def summary() -> str:
    """Pretty text table of the session's spans and counters."""
    return "\n".join(summary_lines())


# --------------------------------------------------------------------------
# env activation
# --------------------------------------------------------------------------

_env_path = os.environ.get(_TRACE_ENV)
if _env_path:
    try:
        configure(sink=_env_path)
    except OSError as e:  # unwritable path must not break imports
        import sys

        print(f"pint_tpu.telemetry: cannot open {_TRACE_ENV}="
              f"{_env_path!r}: {e}", file=sys.stderr)

# live metrics endpoint ($PINT_TPU_METRICS_PORT, default off): the
# scrape surface over the counters/gauges/histograms and the run
# ledger — see pint_tpu/metrics_http.py.  A failed bind must never
# break library imports.
_env_mport = os.environ.get("PINT_TPU_METRICS_PORT", "").strip()
if _env_mport and _env_mport.lower() not in ("0", "off", "none",
                                             "disabled"):
    try:
        from pint_tpu import metrics_http as _metrics_http

        _metrics_http.start()
    except Exception as e:
        import sys

        print(f"pint_tpu.telemetry: cannot start metrics endpoint "
              f"(PINT_TPU_METRICS_PORT={_env_mport!r}): {e}",
              file=sys.stderr)
