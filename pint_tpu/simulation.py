"""Simulation: fake TOAs with zero (or noisy) residuals.

Counterpart of the reference simulation module (reference:
src/pint/simulation.py:218 ``make_fake_toas_uniform``, :29
``zero_residuals`` — the 2-iteration phase inversion).  Fake data is the
framework's primary self-consistency oracle (SURVEY section 4): simulate
from a model, perturb, fit, recover.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.residuals import Residuals
from pint_tpu.toa import TOA, TOAs

__all__ = ["make_fake_toas_uniform", "zero_residuals",
           "calculate_random_models"]


def zero_residuals(toas: TOAs, model, iterations=2):
    """Shift TOA ticks so model residuals vanish (phase inversion by
    Newton iteration; 2 passes reach sub-ns like the reference)."""
    for _ in range(iterations):
        # track_mode pinned: fake TOAs never carry -pn flags, and a
        # TRACK -2 par must not make simulation crash (the reference
        # pins nearest in its simulation path too)
        r = Residuals(toas, model, subtract_mean=False,
                      track_mode="nearest")
        resid_sec = r.time_resids
        toas.ticks = toas.ticks - np.round(resid_sec * 2**32).astype(np.int64)
        toas._compute_posvels()
    return toas


def make_fake_toas_uniform(
    start_mjd,
    end_mjd,
    ntoas,
    model,
    freq_mhz=1400.0,
    obs="@",
    error_us=1.0,
    add_noise=False,
    rng=None,
    wideband=False,
    dm_error=1e-4,
    flags=None,
):
    """Evenly-spaced TOAs with zero residuals under ``model``
    (+ optional white noise scaled by the TOA errors).  ``flags`` is an
    optional per-TOA flag dict applied to every TOA (so mask parameters
    like EFAC ``-f`` selectors have something to select on).

    ``wideband=True`` attaches ``-pp_dm``/``-pp_dme`` flags carrying the
    model's total DM (+ noise when add_noise) with uncertainty
    ``dm_error`` [pc cm^-3] (reference: update_fake_dms,
    simulation.py:183)."""
    mjds = np.linspace(float(start_mjd), float(end_mjd), int(ntoas))
    freqs = np.broadcast_to(np.asarray(freq_mhz, dtype=np.float64), (ntoas,))
    flags = dict(flags or {})
    toa_list = []
    for mjd, f in zip(mjds, freqs):
        day = int(np.floor(mjd))
        frac = mjd - day
        num = int(round(frac * 10**12))
        toa_list.append(
            TOA(day, num, 10**12, float(error_us), float(f), obs,
                dict(flags), "fake")
        )
    planets = bool(model.values.get("PLANET_SHAPIRO", 0.0))
    toas = TOAs(toa_list, ephem=model.meta.get("EPHEM", "builtin"),
                planets=planets)
    zero_residuals(toas, model)
    if add_noise:
        rng = rng or np.random.default_rng(0)
        noise = rng.standard_normal(int(ntoas)) * error_us * 1e-6
        toas.ticks = toas.ticks + np.round(noise * 2**32).astype(np.int64)
        toas._compute_posvels()
    if wideband:
        prepared = model.prepare(toas)
        dm = np.asarray(
            prepared.total_dm_fn(prepared._values_pytree())
        )
        if add_noise:
            rng = rng or np.random.default_rng(0)
            dm = dm + rng.standard_normal(int(ntoas)) * dm_error
        for i, f in enumerate(toas.flags):
            f["pp_dm"] = repr(float(dm[i]))
            f["pp_dme"] = repr(float(dm_error))
    return toas


def calculate_random_models(fitter, toas, n_models=100, rng=None,
                            return_time=True):
    """Residual spread of models drawn from the fit covariance
    (reference: calculate_random_models, simulation.py:532).

    Samples ``n_models`` parameter vectors from N(fitted, covariance)
    and evaluates the phase (or time) difference of each sampled model
    against the fitted one at ``toas`` — vmapped, one device program,
    replacing the reference's per-model Python loop.

    Returns an (n_models, ntoas) array.
    """
    import jax
    import jax.numpy as jnp

    model = fitter.model
    cov = np.asarray(fitter.covariance)
    names = list(getattr(fitter, "_traced_free", model.free_params))
    center = np.array([model.values[k] for k in names])
    rng = rng or np.random.default_rng(0)
    # sample via Cholesky with a jitter fallback for semi-definite cov
    try:
        L = np.linalg.cholesky(cov)
    except np.linalg.LinAlgError:
        w, Q = np.linalg.eigh(cov)
        L = Q @ np.diag(np.sqrt(np.clip(w, 0, None)))
    draws = center + rng.standard_normal((n_models, len(names))) @ L.T

    prepared = model.prepare(toas)
    r = Residuals(toas, prepared, track_mode="nearest")
    base = prepared._values_pytree()

    def resid_of(vec):
        values = dict(base)
        for i, k in enumerate(names):
            values[k] = vec[i]
        return (r.time_resids_fn(values) if return_time
                else r.phase_resids_fn(values))

    ref = resid_of(jnp.asarray(center))
    out = jax.jit(jax.vmap(resid_of))(jnp.asarray(draws))
    return np.asarray(out - ref[None, :])
