"""Simulation: fake TOAs with zero (or noisy) residuals.

Counterpart of the reference simulation module (reference:
src/pint/simulation.py:218 ``make_fake_toas_uniform``, :29
``zero_residuals`` — the 2-iteration phase inversion).  Fake data is the
framework's primary self-consistency oracle (SURVEY section 4): simulate
from a model, perturb, fit, recover.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.residuals import Residuals
from pint_tpu.toa import TOA, TOAs

__all__ = ["make_fake_toas_uniform", "make_fake_toas_fromMJDs",
           "make_fake_toas_fromtim", "add_correlated_noise",
           "zero_residuals", "calculate_random_models"]


def zero_residuals(toas: TOAs, model, iterations=2):
    """Shift TOA ticks so model residuals vanish (phase inversion by
    Newton iteration; 2 passes reach sub-ns like the reference)."""
    for _ in range(iterations):
        # track_mode pinned: fake TOAs never carry -pn flags, and a
        # TRACK -2 par must not make simulation crash (the reference
        # pins nearest in its simulation path too)
        r = Residuals(toas, model, subtract_mean=False,
                      track_mode="nearest")
        resid_sec = r.time_resids
        toas.ticks = toas.ticks - np.round(resid_sec * 2**32).astype(np.int64)
        toas._compute_posvels()
    return toas


def make_fake_toas_uniform(
    start_mjd,
    end_mjd,
    ntoas,
    model,
    freq_mhz=1400.0,
    obs="@",
    error_us=1.0,
    add_noise=False,
    rng=None,
    wideband=False,
    dm_error=1e-4,
    flags=None,
    fuzz_days=0.0,
    multifreq=False,
    add_correlated=False,
):
    """Evenly-spaced TOAs with zero residuals under ``model``
    (+ optional white noise scaled by the TOA errors).  ``flags`` is an
    optional per-TOA flag dict applied to every TOA (so mask parameters
    like EFAC ``-f`` selectors have something to select on).

    ``wideband=True`` attaches ``-pp_dm``/``-pp_dme`` flags carrying the
    model's total DM (+ noise when add_noise) with uncertainty
    ``dm_error`` [pc cm^-3] (reference: update_fake_dms,
    simulation.py:183).  ``fuzz_days`` jitters the even spacing
    (reference zima --fuzzdays); ``multifreq=True`` emits one TOA per
    frequency at every epoch instead of cycling (reference zima
    --multifreq); ``add_correlated=True`` adds a realization of the
    model's correlated-noise components (reference
    make_fake_toas_uniform add_correlated_noise path)."""
    mjds = np.linspace(float(start_mjd), float(end_mjd), int(ntoas))
    if fuzz_days:
        rng = rng or np.random.default_rng(0)
        fuzz = rng.normal(0.0, float(fuzz_days), int(ntoas))
        mjds = np.sort(np.clip(mjds + fuzz, float(start_mjd),
                               float(end_mjd)))
    if multifreq:
        nf = np.atleast_1d(np.asarray(freq_mhz, np.float64)).size
        mjds = np.repeat(mjds, nf)
        freq_mhz = np.tile(np.atleast_1d(np.asarray(freq_mhz)), int(ntoas))
    return make_fake_toas_fromMJDs(
        mjds, model, freq_mhz=freq_mhz, obs=obs, error_us=error_us,
        add_noise=add_noise, rng=rng, wideband=wideband,
        dm_error=dm_error, flags=flags, add_correlated=add_correlated)


def make_fake_toas_fromMJDs(
    mjds,
    model,
    freq_mhz=1400.0,
    obs="@",
    error_us=1.0,
    add_noise=False,
    rng=None,
    wideband=False,
    dm_error=1e-4,
    flags=None,
    add_correlated=False,
):
    """Zero-residual TOAs at explicit MJDs (reference:
    make_fake_toas_fromMJDs, simulation.py:353)."""
    mjds = np.asarray(mjds, dtype=np.float64)
    ntoas = len(mjds)
    freqs = np.broadcast_to(np.asarray(freq_mhz, dtype=np.float64), (ntoas,))
    flags = dict(flags or {})
    toa_list = []
    for mjd, f in zip(mjds, freqs):
        day = int(np.floor(mjd))
        frac = mjd - day
        num = int(round(frac * 10**12))
        toa_list.append(
            TOA(day, num, 10**12, float(error_us), float(f), obs,
                dict(flags), "fake")
        )
    from pint_tpu.models.builder import planets_requested

    toas = TOAs(toa_list, ephem=model.meta.get("EPHEM", "builtin"),
                planets=planets_requested(model))
    zero_residuals(toas, model)
    return _apply_noise_products(toas, model, add_noise, wideband,
                                 dm_error, add_correlated, rng)


def _apply_noise_products(toas, model, add_noise, wideband, dm_error,
                          add_correlated, rng):
    """Shared fake-TOA post-processing: white noise (scaled by each
    TOA's own error), wideband -pp_dm/-pp_dme flags, correlated
    realization."""
    if add_noise:
        rng = rng or np.random.default_rng(0)
        noise = rng.standard_normal(len(toas)) * toas.error_us * 1e-6
        toas.ticks = toas.ticks + np.round(noise * 2**32).astype(np.int64)
        toas._compute_posvels()
    if wideband:
        prepared = model.prepare(toas)
        dm = np.asarray(
            prepared.total_dm_fn(prepared._values_pytree())
        )
        if add_noise:
            rng = rng or np.random.default_rng(0)
            dm = dm + rng.standard_normal(len(toas)) * dm_error
        for i, f in enumerate(toas.flags):
            f["pp_dm"] = repr(float(dm[i]))
            f["pp_dme"] = repr(float(dm_error))
    if add_correlated:
        add_correlated_noise(toas, model, rng=rng)
    return toas


def add_correlated_noise(toas: TOAs, model, rng=None):
    """Add one realization of the model's correlated-noise components
    (ECORR / red / DM noise) to the TOA ticks (reference:
    simulation.py add_correlated_noise): draw c = U @ (sqrt(phi) * z)
    with z ~ N(0, 1) over the noise basis U and weights phi.  Raises
    when the model has no correlated components (like the reference) —
    a silent no-op would let --addcorrnoise lie about its output."""
    if not model.has_correlated_errors:
        raise ValueError(
            "add_correlated_noise: the model has no correlated-noise "
            "components (ECORR / red / DM / chromatic noise)")
    r = Residuals(toas, model, subtract_mean=False,
                  track_mode="nearest")
    values = r._values()
    U = np.asarray(r.prepared.noise_basis)
    phi = np.asarray(r.prepared.noise_weights_fn(values))
    rng = rng or np.random.default_rng(0)
    z = rng.standard_normal(U.shape[1])
    noise_sec = U @ (np.sqrt(np.maximum(phi, 0.0)) * z)
    toas.ticks = toas.ticks + np.round(
        noise_sec * 2**32).astype(np.int64)
    toas._compute_posvels()
    return toas


def make_fake_toas_fromtim(timfile, model, add_noise=False, rng=None,
                           wideband=False, dm_error=1e-4,
                           add_correlated=False):
    """Zero-residual TOAs at the epochs/frequencies/errors/observatories
    of an existing tim file (reference: make_fake_toas_fromtim,
    simulation.py:481) — the standard way to simulate a dataset with a
    real observing cadence."""
    from pint_tpu.toa import get_TOAs

    toas = get_TOAs(timfile, ephem=model.meta.get("EPHEM", "builtin"),
                    planets=bool(model.values.get("PLANET_SHAPIRO", 0.0)))
    zero_residuals(toas, model)
    return _apply_noise_products(toas, model, add_noise, wideband,
                                 dm_error, add_correlated, rng)


def calculate_random_models(fitter, toas, n_models=100, rng=None,
                            return_time=True):
    """Residual spread of models drawn from the fit covariance
    (reference: calculate_random_models, simulation.py:532).

    Samples ``n_models`` parameter vectors from N(fitted, covariance)
    and evaluates the phase (or time) difference of each sampled model
    against the fitted one at ``toas`` — vmapped, one device program,
    replacing the reference's per-model Python loop.

    Returns an (n_models, ntoas) array.
    """
    import jax
    import jax.numpy as jnp

    model = fitter.model
    cov = np.asarray(fitter.covariance)
    names = list(getattr(fitter, "_traced_free", model.free_params))
    center = np.array([model.values[k] for k in names])
    rng = rng or np.random.default_rng(0)
    # sample via Cholesky with a jitter fallback for semi-definite cov
    try:
        L = np.linalg.cholesky(cov)
    except np.linalg.LinAlgError:
        w, Q = np.linalg.eigh(cov)
        L = Q @ np.diag(np.sqrt(np.clip(w, 0, None)))
    draws = center + rng.standard_normal((n_models, len(names))) @ L.T

    prepared = model.prepare(toas)
    r = Residuals(toas, prepared, track_mode="nearest")
    base = prepared._values_pytree()

    def resid_of(vec):
        values = dict(base)
        for i, k in enumerate(names):
            values[k] = vec[i]
        return (r.time_resids_fn(values) if return_time
                else r.phase_resids_fn(values))

    ref = resid_of(jnp.asarray(center))
    out = jax.jit(jax.vmap(resid_of))(jnp.asarray(draws))
    return np.asarray(out - ref[None, :])
