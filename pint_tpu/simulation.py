"""Simulation: fake TOAs with zero (or noisy) residuals.

Counterpart of the reference simulation module (reference:
src/pint/simulation.py:218 ``make_fake_toas_uniform``, :29
``zero_residuals`` — the 2-iteration phase inversion).  Fake data is the
framework's primary self-consistency oracle (SURVEY section 4): simulate
from a model, perturb, fit, recover.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.residuals import Residuals
from pint_tpu.toa import TOA, TOAs

__all__ = ["make_fake_toas_uniform", "make_fake_toas_fromMJDs",
           "make_fake_toas_fromtim", "make_fake_pta",
           "pta_white_noise_seed", "pta_injection_seed", "substream",
           "gwb_amp_linear", "add_correlated_noise", "add_gwb",
           "zero_residuals", "calculate_random_models"]


def _as_rng(rng, default_seed=0):
    """Normalize an rng argument: None -> default_rng(default_seed),
    int seed -> default_rng(seed), Generator passes through.  An int
    seed of 0 is honored (the old ``rng or default_rng(0)`` idiom would
    treat a passed-in 0 as falsy)."""
    if rng is None:
        return np.random.default_rng(default_seed)
    if isinstance(rng, (int, np.integer)):
        return np.random.default_rng(int(rng))
    return rng


def substream(seed, label) -> np.random.Generator:
    """A named rng stream derived from ``(seed, label)`` — the
    generalization of the PR-3 integer conventions
    (:func:`pta_white_noise_seed` / :func:`pta_injection_seed`) to
    arbitrarily many noise processes.

    Streams with different labels are disjoint by construction
    (``np.random.SeedSequence`` spawn keyed on the label's CRC32 —
    stable across processes and python versions, unlike builtin
    ``hash``), so a scenario's white-noise draw never shifts when a
    correlated component is added, and per-component correlated draws
    never alias each other.  The corpus generator keys every draw
    through here (labels ``"white"``, ``"dm"``, ``"fuzz"``,
    ``"corr.<Component>"``)."""
    import zlib

    label = str(label)
    ss = np.random.SeedSequence(
        entropy=[int(seed) & 0xFFFFFFFFFFFFFFFF,
                 zlib.crc32(label.encode("utf-8"))])
    return np.random.default_rng(ss)


def zero_residuals(toas: TOAs, model, iterations=2):
    """Shift TOA ticks so model residuals vanish (phase inversion by
    Newton iteration; 2 passes reach sub-ns like the reference)."""
    for _ in range(iterations):
        # track_mode pinned: fake TOAs never carry -pn flags, and a
        # TRACK -2 par must not make simulation crash (the reference
        # pins nearest in its simulation path too)
        r = Residuals(toas, model, subtract_mean=False,
                      track_mode="nearest")
        resid_sec = r.time_resids
        toas.ticks = toas.ticks - np.round(resid_sec * 2**32).astype(np.int64)
        toas._compute_posvels()
    return toas


def make_fake_toas_uniform(
    start_mjd,
    end_mjd,
    ntoas,
    model,
    freq_mhz=1400.0,
    obs="@",
    error_us=1.0,
    add_noise=False,
    rng=None,
    wideband=False,
    dm_error=1e-4,
    flags=None,
    fuzz_days=0.0,
    multifreq=False,
    add_correlated=False,
):
    """Evenly-spaced TOAs with zero residuals under ``model``
    (+ optional white noise scaled by the TOA errors).  ``flags`` is an
    optional per-TOA flag dict applied to every TOA (so mask parameters
    like EFAC ``-f`` selectors have something to select on).

    ``wideband=True`` attaches ``-pp_dm``/``-pp_dme`` flags carrying the
    model's total DM (+ noise when add_noise) with uncertainty
    ``dm_error`` [pc cm^-3] (reference: update_fake_dms,
    simulation.py:183).  ``fuzz_days`` jitters the even spacing
    (reference zima --fuzzdays); ``multifreq=True`` emits one TOA per
    frequency at every epoch instead of cycling (reference zima
    --multifreq); ``add_correlated=True`` adds a realization of the
    model's correlated-noise components (reference
    make_fake_toas_uniform add_correlated_noise path)."""
    mjds = np.linspace(float(start_mjd), float(end_mjd), int(ntoas))
    if fuzz_days:
        rng = _as_rng(rng)
        fuzz = rng.normal(0.0, float(fuzz_days), int(ntoas))
        mjds = np.sort(np.clip(mjds + fuzz, float(start_mjd),
                               float(end_mjd)))
    if multifreq:
        nf = np.atleast_1d(np.asarray(freq_mhz, np.float64)).size
        mjds = np.repeat(mjds, nf)
        freq_mhz = np.tile(np.atleast_1d(np.asarray(freq_mhz)), int(ntoas))
    return make_fake_toas_fromMJDs(
        mjds, model, freq_mhz=freq_mhz, obs=obs, error_us=error_us,
        add_noise=add_noise, rng=rng, wideband=wideband,
        dm_error=dm_error, flags=flags, add_correlated=add_correlated)


def make_fake_toas_fromMJDs(
    mjds,
    model,
    freq_mhz=1400.0,
    obs="@",
    error_us=1.0,
    add_noise=False,
    rng=None,
    wideband=False,
    dm_error=1e-4,
    flags=None,
    add_correlated=False,
):
    """Zero-residual TOAs at explicit MJDs (reference:
    make_fake_toas_fromMJDs, simulation.py:353)."""
    mjds = np.asarray(mjds, dtype=np.float64)
    ntoas = len(mjds)
    freqs = np.broadcast_to(np.asarray(freq_mhz, dtype=np.float64), (ntoas,))
    # flags: one dict applied to every TOA, or a per-TOA list of dicts
    # (the corpus generator's flag_cycle — mask selectors like JUMP
    # must see the final flags BEFORE zero_residuals inverts phase)
    if isinstance(flags, (list, tuple)):
        if len(flags) != ntoas:
            raise ValueError(
                f"per-TOA flags list has {len(flags)} entries for "
                f"{ntoas} TOAs")
        flag_list = [dict(f or {}) for f in flags]
    else:
        flag_list = [dict(flags or {}) for _ in range(ntoas)]
    toa_list = []
    for mjd, f, fl in zip(mjds, freqs, flag_list):
        day = int(np.floor(mjd))
        frac = mjd - day
        num = int(round(frac * 10**12))
        toa_list.append(
            TOA(day, num, 10**12, float(error_us), float(f), obs,
                fl, "fake")
        )
    from pint_tpu.models.builder import planets_requested

    toas = TOAs(toa_list, ephem=model.meta.get("EPHEM", "builtin"),
                planets=planets_requested(model))
    zero_residuals(toas, model)
    return _apply_noise_products(toas, model, add_noise, wideband,
                                 dm_error, add_correlated, rng)


def _apply_noise_products(toas, model, add_noise, wideband, dm_error,
                          add_correlated, rng):
    """Shared fake-TOA post-processing: white noise (scaled by each
    TOA's own error), wideband -pp_dm/-pp_dme flags, correlated
    realization."""
    if add_noise:
        rng = _as_rng(rng)
        noise = rng.standard_normal(len(toas)) * toas.error_us * 1e-6
        toas.ticks = toas.ticks + np.round(noise * 2**32).astype(np.int64)
        toas._compute_posvels()
    if wideband:
        prepared = model.prepare(toas)
        dm = np.asarray(
            prepared.total_dm_fn(prepared._values_pytree())
        )
        if add_noise:
            rng = _as_rng(rng)
            dm = dm + rng.standard_normal(len(toas)) * dm_error
        for i, f in enumerate(toas.flags):
            f["pp_dm"] = repr(float(dm[i]))
            f["pp_dme"] = repr(float(dm_error))
    if add_correlated:
        add_correlated_noise(toas, model, rng=rng)
    return toas


def add_correlated_noise(toas: TOAs, model, rng=None,
                         per_component_seed=None):
    """Add one realization of the model's correlated-noise components
    (ECORR / red / DM noise) to the TOA ticks (reference:
    simulation.py add_correlated_noise): draw c = U @ (sqrt(phi) * z)
    with z ~ N(0, 1) over the noise basis U and weights phi.  Raises
    when the model has no correlated components (like the reference) —
    a silent no-op would let --addcorrnoise lie about its output.

    ``rng`` may be a Generator, an int seed (0 included), or None
    (seed 0).  Returns ``(toas, noise_sec)`` — the exact drawn
    realization [s] per TOA, so injection tests can assert against the
    draw instead of reverse-engineering it from the ticks.

    ``per_component_seed``: when given, each component's z-block is
    drawn from the disjoint :func:`substream` ``corr.<Component>``
    instead of one stream over the concatenated basis, making every
    component's realization invariant to which OTHER components the
    model carries (the seed-determinism gap the corpus generator
    exposed: under a single stream, adding band noise to a par file
    silently shifts the red-noise draw).  ``rng`` is ignored in this
    mode."""
    if not model.has_correlated_errors:
        raise ValueError(
            "add_correlated_noise: the model has no correlated-noise "
            "components (ECORR / red / DM / chromatic noise)")
    r = Residuals(toas, model, subtract_mean=False,
                  track_mode="nearest")
    values = r._values()
    U = np.asarray(r.prepared.noise_basis)
    phi = np.asarray(r.prepared.noise_weights_fn(values))
    if per_component_seed is not None:
        z = np.empty(U.shape[1])
        for name, (start, nb) in \
                r.prepared.noise_dimensions().items():
            z[start:start + nb] = substream(
                per_component_seed, f"corr.{name}").standard_normal(nb)
    else:
        rng = _as_rng(rng)
        z = rng.standard_normal(U.shape[1])
    noise_sec = U @ (np.sqrt(np.maximum(phi, 0.0)) * z)
    toas.ticks = toas.ticks + np.round(
        noise_sec * 2**32).astype(np.int64)
    toas._compute_posvels()
    return toas, noise_sec


def make_fake_pta(n_psr, ntoa, start_mjd=53000.0, duration_days=3000.0,
                  error_us=1.0, seed=0, extra_par="", obs="@",
                  name_prefix="FAKE", f0_base=100.0, f0_step=10.0):
    """A sky-scattered synthetic pulsar array: ``[(model, toas), ...]``,
    deterministic in ``seed`` — THE shared builder behind every
    synthetic-PTA consumer (the ``pintgw`` CLI's --simulate mode, the
    bench.py OS metric, the multichip dry run, and tests), so the par
    template and sky-scatter formulas exist once.

    Pulsar i sits at RA ``i * 24h / n_psr`` and declination
    ``(i * 37) % 120 - 60`` degrees (a deterministic scatter with no
    two pulsars co-located for n_psr <= 120 — the Hellings–Downs curve
    gets sampled across its full range).  ``extra_par`` appends par
    lines to every pulsar (e.g. TNRed* intrinsic red noise); per-TOA
    white noise is drawn from ``default_rng(seed * 1000 + i)``.

    A caller that then injects signals (``add_gwb``) must draw from a
    DISJOINT stream — the convention is ``rng = seed * 1000 + n_psr``
    (see :func:`pta_injection_seed`): reusing the bare ``seed`` would
    make the injection draw bit-identical normals to pulsar 0's white
    noise at seed 0.
    """
    from pint_tpu.models.builder import get_model

    mid = start_mjd + duration_days / 2.0
    pairs = []
    for i in range(int(n_psr)):
        ra_h = (i * 24.0 / n_psr) % 24
        dec = int(((i * 37) % 120) - 60)
        par = (f"PSR {name_prefix}{i:02d}\nRAJ {int(ra_h):02d}:"
               f"{int((ra_h % 1) * 60):02d}:00\nDECJ {dec:+03d}:00:00\n"
               f"F0 {f0_base + f0_step * i!r} 1\nF1 -1e-15 1\n"
               f"PEPOCH {mid:.1f}\nDM {10 + i * 0.5}\n"
               f"TZRMJD {mid:.1f}\nTZRSITE @\nTZRFRQ 1400\n"
               f"UNITS TDB\nEPHEM builtin\n" + extra_par)
        m = get_model(par)
        toas = make_fake_toas_uniform(
            start_mjd, start_mjd + duration_days, ntoa, m, obs=obs,
            error_us=error_us, add_noise=True,
            rng=np.random.default_rng(pta_white_noise_seed(seed, i)))
        pairs.append((m, toas))
    return pairs


def pta_white_noise_seed(seed, i) -> int:
    """Pulsar i's white-noise stream seed in a synthetic array — THE
    convention :func:`make_fake_pta` draws from, shared so external
    TOA builders (the pintgw par-file path) stay disjoint from
    :func:`pta_injection_seed` by construction."""
    return int(seed) * 1000 + int(i)


def pta_injection_seed(seed, n_psr) -> int:
    """The injection-stream seed matching a :func:`make_fake_pta`
    array: disjoint from every per-pulsar white-noise stream
    (:func:`pta_white_noise_seed`, i < n_psr)."""
    return pta_white_noise_seed(seed, n_psr)


def gwb_amp_linear(amp) -> float:
    """THE amp-argument convention of the GWB surface (add_gwb, the
    pintgw CLI, zima --gwbamp): linear when positive, log10 when
    negative.  amp = 0 means a zero-amplitude injection."""
    amp = float(amp)
    return 10.0 ** amp if amp < 0 else amp


def add_gwb(toas_list, models, amp, gamma=13.0 / 3.0, rng=None,
            nmodes=30, tspan_s=None, orf="hd"):
    """Inject one realization of an ORF-correlated gravitational-wave
    background across a whole pulsar array, in place.

    Draws Fourier coefficients with the exact cross-pulsar covariance
    ``Gamma (x) diag(phi)`` — ``a[p, i] = sum_q L[p, q] sqrt(phi_i)
    z[q, i]`` with ``L`` the Cholesky factor of the (N, N) ORF matrix
    of the array's sky positions and ``phi`` the power-law prior
    weights at (amp, gamma) — then adds ``F_p @ a[p]`` to each
    pulsar's TOA ticks.  All pulsars share one frequency comb
    ``k / T`` over the array-wide span on the absolute TDB time axis,
    so the injected process is phase-coherent across the array — the
    signal the optimal statistic (:mod:`pint_tpu.gw.os`) estimates.

    amp: GWB characteristic-strain amplitude (linear; a negative value
    is read as log10).  ``rng``: Generator | int seed | None (seed 0).
    Returns ``(noise_sec_list, coeffs)``: the per-pulsar injected
    series [s] and the (N, 2*nmodes) coefficient draw, so tests can
    assert against the exact realization.
    """
    from pint_tpu.gw.common import common_tspan_s, gwb_phi
    from pint_tpu.gw.orf import orf_matrix, pulsar_positions
    from pint_tpu.models.noise import toa_fourier_basis
    from pint_tpu.telemetry import span

    if len(toas_list) != len(models) or not toas_list:
        raise ValueError(
            "add_gwb needs matched, non-empty toas_list and models")
    amp = gwb_amp_linear(amp)
    with span("gw.inject", n_pulsars=len(models), nmodes=nmodes,
              amp=amp, gamma=float(gamma)):
        T = float(tspan_s) if tspan_s else common_tspan_s(toas_list)
        pos = pulsar_positions(models)
        gam_mat = np.asarray(orf_matrix(pos, orf), dtype=np.float64)
        # eigendecomposition instead of plain Cholesky: a pair of
        # (near-)co-located pulsars makes the ORF matrix semidefinite
        w, Q = np.linalg.eigh(gam_mat)
        L = Q @ np.diag(np.sqrt(np.clip(w, 0.0, None)))
        rng = _as_rng(rng)
        n_psr = len(models)
        phi = None
        noise_list = []
        z = None
        coeffs = None
        for k, toas in enumerate(toas_list):
            F, freqs = toa_fourier_basis(toas, nmodes, tspan_s=T)
            if phi is None:
                phi = np.asarray(
                    gwb_phi(freqs, amp, float(gamma), freqs[0]),
                    dtype=np.float64)
                z = rng.standard_normal((n_psr, len(freqs)))
                coeffs = (L @ z) * np.sqrt(phi)[None, :]
            noise_sec = F @ coeffs[k]
            toas.ticks = toas.ticks + np.round(
                noise_sec * 2**32).astype(np.int64)
            toas._compute_posvels()
            noise_list.append(noise_sec)
    return noise_list, coeffs


def make_fake_toas_fromtim(timfile, model, add_noise=False, rng=None,
                           wideband=False, dm_error=1e-4,
                           add_correlated=False):
    """Zero-residual TOAs at the epochs/frequencies/errors/observatories
    of an existing tim file (reference: make_fake_toas_fromtim,
    simulation.py:481) — the standard way to simulate a dataset with a
    real observing cadence."""
    from pint_tpu.toa import get_TOAs

    toas = get_TOAs(timfile, ephem=model.meta.get("EPHEM", "builtin"),
                    planets=bool(model.values.get("PLANET_SHAPIRO", 0.0)))
    zero_residuals(toas, model)
    return _apply_noise_products(toas, model, add_noise, wideband,
                                 dm_error, add_correlated, rng)


def calculate_random_models(fitter, toas, n_models=100, rng=None,
                            return_time=True):
    """Residual spread of models drawn from the fit covariance
    (reference: calculate_random_models, simulation.py:532).

    Samples ``n_models`` parameter vectors from N(fitted, covariance)
    and evaluates the phase (or time) difference of each sampled model
    against the fitted one at ``toas`` — vmapped, one device program,
    replacing the reference's per-model Python loop.

    Returns an (n_models, ntoas) array.
    """
    import jax
    import jax.numpy as jnp

    model = fitter.model
    cov = np.asarray(fitter.covariance)
    names = list(getattr(fitter, "_traced_free", model.free_params))
    center = np.array([model.values[k] for k in names])
    rng = _as_rng(rng)
    # sample via Cholesky with a jitter fallback for semi-definite cov
    try:
        L = np.linalg.cholesky(cov)
    except np.linalg.LinAlgError:
        w, Q = np.linalg.eigh(cov)
        L = Q @ np.diag(np.sqrt(np.clip(w, 0, None)))
    draws = center + rng.standard_normal((n_models, len(names))) @ L.T

    prepared = model.prepare(toas)
    r = Residuals(toas, prepared, track_mode="nearest")
    base = prepared._values_pytree()

    def resid_of(vec):
        values = dict(base)
        for i, k in enumerate(names):
            values[k] = vec[i]
        return (r.time_resids_fn(values) if return_time
                else r.phase_resids_fn(values))

    ref = resid_of(jnp.asarray(center))
    # pintlint: allow=PTL101 -- one-shot Monte-Carlo over a closure of
    # THIS model's residual fn; a registry entry would be keyed to a
    # single simulation call and never reused
    out = jax.jit(jax.vmap(resid_of))(jnp.asarray(draws))
    return np.asarray(out - ref[None, :])
