"""Double-double (dd) float64 arithmetic for TPU.

TPU has no extended-precision float type, but pulsar timing needs ~1e-15
relative precision on pulse phase (F0 ~ 700 Hz x 20 yr ~ 4e11 turns resolved
to <1e-4 turns).  The reference package solves this with ``numpy.longdouble``
(x87 80-bit, eps < 2e-19) and ships compensated-arithmetic primitives
(reference: src/pint/pulsar_mjd.py:529-664 ``two_sum``/``two_product``/
``split``/``day_frac``).  Here the same idea is taken further: every
precision-critical quantity is an unevaluated sum of two float64s
``hi + lo`` with ``|lo| <= ulp(hi)/2``, giving ~32 significant digits —
more than longdouble.

**Backend validity (measured; see TPU_PRECISION.md):** error-free
transformations require correctly-rounded IEEE f64 arithmetic.  That
holds on the CPU backend (XLA does not re-associate floats, so the
error terms survive jit) — dd arithmetic is fully accurate there, and
it is the longdouble-replacement used in tests and host-side oracles.
On TPU, f64 is *emulated at ~49-bit effective precision* (adds measured
up to 16 ulps off correctly rounded), which silently breaks the Dekker/
Knuth error terms: dd degrades to ~1e-16 relative on TPU and MUST NOT
be trusted beyond plain f64 there.  That is why the on-device
precision-critical path (F0*t phase accumulation) is exact int64 fixed
point instead — see :mod:`pint_tpu.fixedpoint`, whose module docstring
states the same division of labor.

Algorithms are the classical error-free transformations (Dekker 1971,
Knuth TAOCP v2, Shewchuk 1997) as used in the QD library of Hida, Li &
Bailey (2000).  All functions are shape-polymorphic, jit-safe, vmap-safe and
differentiable (a dd is a NamedTuple pytree of two arrays).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

# Dekker splitter for 53-bit significands: 2^27 + 1.
_SPLITTER = 134217729.0


class DD(NamedTuple):
    """A double-double number: value = hi + lo (unevaluated, non-overlapping).

    Being a NamedTuple, DD is automatically a JAX pytree: DDs can be passed
    through jit/vmap/grad, stored in larger pytrees, and stacked.
    """

    hi: jnp.ndarray
    lo: jnp.ndarray

    # Convenience operator sugar (thin wrappers over module functions).
    def __add__(self, other):
        return add(self, _as_dd(other))

    def __radd__(self, other):
        return add(_as_dd(other), self)

    def __sub__(self, other):
        return sub(self, _as_dd(other))

    def __rsub__(self, other):
        return sub(_as_dd(other), self)

    def __mul__(self, other):
        return mul(self, _as_dd(other))

    def __rmul__(self, other):
        return mul(_as_dd(other), self)

    def __truediv__(self, other):
        return div(self, _as_dd(other))

    def __rtruediv__(self, other):
        return div(_as_dd(other), self)

    def __neg__(self):
        return DD(-self.hi, -self.lo)

    @property
    def shape(self):
        return jnp.shape(self.hi)

    @property
    def dtype(self):
        return jnp.result_type(self.hi)


def _as_dd(x) -> DD:
    if isinstance(x, DD):
        return x
    return from_f64(x)


# --- Error-free transformations --------------------------------------------


def two_sum(a, b):
    """s, err such that s = fl(a+b) and a + b = s + err exactly (Knuth)."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def quick_two_sum(a, b):
    """two_sum assuming |a| >= |b| (Dekker); cheaper, same guarantee."""
    s = a + b
    err = b - (s - a)
    return s, err


def split(a):
    """Split a float64 into 26+27-bit halves hi+lo = a exactly (Dekker)."""
    t = _SPLITTER * a
    hi = t - (t - a)
    lo = a - hi
    return hi, lo


def two_prod(a, b):
    """p, err such that p = fl(a*b) and a*b = p + err exactly (Dekker)."""
    p = a * b
    ahi, alo = split(a)
    bhi, blo = split(b)
    err = ((ahi * bhi - p) + ahi * blo + alo * bhi) + alo * blo
    return p, err


# --- Construction / normalization ------------------------------------------


def from_f64(x) -> DD:
    """Promote a float64 array (or python scalar) to dd with lo = 0."""
    x = jnp.asarray(x, dtype=jnp.float64)
    return DD(x, jnp.zeros_like(x))


def from_sum(a, b) -> DD:
    """dd representing a + b exactly, for arbitrary float64 a, b."""
    s, e = two_sum(jnp.asarray(a, jnp.float64), jnp.asarray(b, jnp.float64))
    return DD(s, e)


def normalize(hi, lo) -> DD:
    """Renormalize an (hi, lo) pair into canonical non-overlapping form."""
    s, e = quick_two_sum(hi, lo)
    return DD(s, e)


def to_f64(x: DD):
    return x.hi + x.lo


# --- Arithmetic -------------------------------------------------------------


def add(x: DD, y: DD) -> DD:
    """Accurate dd + dd (IEEE-style add from the QD library)."""
    s1, s2 = two_sum(x.hi, y.hi)
    t1, t2 = two_sum(x.lo, y.lo)
    s2 = s2 + t1
    s1, s2 = quick_two_sum(s1, s2)
    s2 = s2 + t2
    return normalize(s1, s2)


def add_f64(x: DD, y) -> DD:
    y = jnp.asarray(y, jnp.float64)
    s1, s2 = two_sum(x.hi, y)
    s2 = s2 + x.lo
    return normalize(s1, s2)


def sub(x: DD, y: DD) -> DD:
    return add(x, DD(-y.hi, -y.lo))


def sub_f64(x: DD, y) -> DD:
    return add_f64(x, -jnp.asarray(y, jnp.float64))


def mul(x: DD, y: DD) -> DD:
    p1, p2 = two_prod(x.hi, y.hi)
    p2 = p2 + (x.hi * y.lo + x.lo * y.hi)
    return normalize(p1, p2)


def mul_f64(x: DD, y) -> DD:
    y = jnp.asarray(y, jnp.float64)
    p1, p2 = two_prod(x.hi, y)
    p2 = p2 + x.lo * y
    return normalize(p1, p2)


def div(x: DD, y: DD) -> DD:
    """dd / dd by long division with one Newton correction."""
    q1 = x.hi / y.hi
    r = sub(x, mul_f64(y, q1))
    q2 = r.hi / y.hi
    r = sub(r, mul_f64(y, q2))
    q3 = r.hi / y.hi
    q, e = quick_two_sum(q1, q2)
    return add_f64(DD(q, e), q3)


def neg(x: DD) -> DD:
    return DD(-x.hi, -x.lo)


def abs_(x: DD) -> DD:
    s = jnp.where(x.hi < 0, -1.0, 1.0)
    return DD(x.hi * s, x.lo * s)


def sqr(x: DD) -> DD:
    p1, p2 = two_prod(x.hi, x.hi)
    p2 = p2 + 2.0 * (x.hi * x.lo)
    return normalize(p1, p2)


# --- Comparisons (on canonical dds, hi dominates; ties broken by lo) --------


def lt(x: DD, y: DD):
    return (x.hi < y.hi) | ((x.hi == y.hi) & (x.lo < y.lo))


def le(x: DD, y: DD):
    return (x.hi < y.hi) | ((x.hi == y.hi) & (x.lo <= y.lo))


def gt(x: DD, y: DD):
    return lt(y, x)


def ge(x: DD, y: DD):
    return le(y, x)


# --- Rounding / phase splitting ---------------------------------------------


def round_nearest(x: DD):
    """Nearest integer to a dd, as float64, with the dd tie/carry handled.

    round(hi) can be off by one when hi sits within lo of a half-integer;
    fixing with one comparison on the exact remainder keeps the fractional
    part in [-0.5, 0.5) — the invariant the reference's Phase class enforces
    (src/pint/phase.py:7-116).
    """
    n = jnp.round(x.hi)
    frac = add_f64(x, -n)
    # carry decisions must see the full dd (hi exactly +/-0.5 with a
    # compensating lo is reachable and flips the nearest integer)
    up = (frac.hi > 0.5) | ((frac.hi == 0.5) & (frac.lo >= 0.0))
    dn = (frac.hi < -0.5) | ((frac.hi == -0.5) & (frac.lo < 0.0))
    n = jnp.where(up, n + 1.0, n)
    n = jnp.where(dn, n - 1.0, n)
    return n


def split_int_frac(x: DD):
    """(integer part as float64, fractional dd in [-0.5, 0.5))."""
    n = round_nearest(x)
    return n, add_f64(x, -n)


def floor_(x: DD):
    """Floor of a dd as float64 (exact for |x| < 2^52)."""
    n = jnp.floor(x.hi)
    r = add_f64(x, -n)
    n = jnp.where(r.hi >= 1.0, n + 1.0, n)
    n = jnp.where(r.hi < 0.0, n - 1.0, n)
    return n


# --- Polynomial evaluation ---------------------------------------------------


def horner(x: DD, coeffs) -> DD:
    """Evaluate sum_k coeffs[k] x^k in dd via Horner's rule.

    ``coeffs`` is a sequence of DD or float64 scalars, lowest order first
    (the dd counterpart of the reference's ``taylor_horner``,
    src/pint/utils.py:419, which runs in longdouble).  The loop is over a
    static python list, so it unrolls at trace time — no dynamic control flow
    reaches XLA.
    """
    acc = _as_dd(coeffs[-1])
    for c in reversed(coeffs[:-1]):
        acc = add(mul(acc, x), _as_dd(c))
    return acc


def taylor_horner(x: DD, coeffs) -> DD:
    """sum_k coeffs[k] x^(k+0) / k!  — Taylor evaluation like the reference's
    taylor_horner (src/pint/utils.py:419): coeffs[k] multiplies x^k/k!."""
    fact = 1.0
    scaled = []
    for k, c in enumerate(coeffs):
        if k > 0:
            fact *= k
        # divide in dd: 1.0/fact is inexact in f64 for k >= 3 and would cap
        # the term at ~1e-16 relative; fact itself is exact while < 2^53
        scaled.append(div(_as_dd(c), from_f64(fact)))
    return horner(x, scaled)


# --- Host-side exact construction -------------------------------------------


def from_longdouble(x) -> DD:
    """Host-only: split numpy longdouble(s) into an exact dd pair."""
    import numpy as np

    x = np.asarray(x, dtype=np.longdouble)
    hi = x.astype(np.float64)
    lo = (x - hi.astype(np.longdouble)).astype(np.float64)
    return DD(jnp.asarray(hi), jnp.asarray(lo))


def to_longdouble(x: DD):
    """Host-only: recombine a dd into numpy longdouble."""
    import numpy as np

    return np.asarray(x.hi, dtype=np.longdouble) + np.asarray(
        x.lo, dtype=np.longdouble
    )
