"""Derived pulsar quantities: P/F conversions, age, B-field, masses, PK
parameters.

Counterpart of the reference derived_quantities module (reference:
src/pint/derived_quantities.py — same formulas, same names), in plain
float64 with explicit units in the names instead of astropy Quantities:
frequencies in Hz, periods/times in seconds, masses in solar masses,
angles in radians unless suffixed otherwise.  All functions accept
numpy arrays (and jax arrays — nothing here branches on values).
"""

from __future__ import annotations

import numpy as np

from pint_tpu import SECS_PER_DAY, T_SUN_S

__all__ = [
    "p_to_f", "f_to_p", "pferrs", "pulsar_age_yr", "pulsar_edot",
    "pulsar_B_gauss", "pulsar_B_lightcyl_gauss", "mass_funct",
    "mass_funct2", "pulsar_mass", "companion_mass", "pbdot", "gamma",
    "omdot_deg_per_yr", "sini", "omdot_to_mtot", "a1sini",
    "shklovskii_factor", "dispersion_slope", "orbital_phase",
]

_SECS_PER_YEAR = 365.25 * 86400.0
_C = 299792458.0


def p_to_f(p, pd=None, pdd=None):
    """Period (s) derivatives -> frequency (Hz) derivatives and back
    (the transformation is an involution; reference
    derived_quantities.py:37)."""
    f = 1.0 / p
    if pd is None:
        return f
    fd = -pd / p**2
    if pdd is None:
        return f, fd
    fdd = 2.0 * pd**2 / p**3 - pdd / p**2
    return f, fd, fdd


f_to_p = p_to_f  # the same involution


def pferrs(por_f, porferr, pdorfd=None, pdorfderr=None):
    """Uncertainty propagation for p/f conversions (reference :88)."""
    if pdorfd is None:
        return 1.0 / por_f, porferr / por_f**2
    forp = 1.0 / por_f
    fdorpd = -pdorfd / por_f**2
    fdorpderr = np.sqrt(
        (4.0 * pdorfd**2 * porferr**2 / por_f**6)
        + pdorfderr**2 / por_f**4
    )
    return forp, porferr / por_f**2, fdorpd, fdorpderr


def pulsar_age_yr(f_hz, fdot, n=3, fo_hz=1e99):
    """Characteristic age [yr] with braking index n (reference :140)."""
    return (
        -f_hz / ((n - 1.0) * fdot) * (1.0 - (f_hz / fo_hz) ** (n - 1.0))
    ) / _SECS_PER_YEAR


def pulsar_edot(f_hz, fdot, I=1e45):
    """Spin-down luminosity [erg/s] for moment of inertia I [g cm^2]
    (reference :185)."""
    return -4.0 * np.pi**2 * I * f_hz * fdot


def pulsar_B_gauss(f_hz, fdot):
    """Surface dipole field [G] (reference :223)."""
    return 3.2e19 * np.sqrt(-fdot / f_hz**3)


def pulsar_B_lightcyl_gauss(f_hz, fdot):
    """Light-cylinder field [G] (reference :258)."""
    p, pd = p_to_f(f_hz, fdot)
    return 2.9e8 * p ** (-5.0 / 2.0) * np.sqrt(pd)


def mass_funct(pb_s, x_ls):
    """Binary mass function [Msun] from PB [s] and A1 [ls]
    (reference :300)."""
    return 4.0 * np.pi**2 * x_ls**3 / (T_SUN_S * pb_s**2)


def mass_funct2(mp, mc, i_rad):
    """Mass function [Msun] from component masses and inclination
    (reference :341)."""
    return (mc * np.sin(i_rad)) ** 3 / (mc + mp) ** 2


def pulsar_mass(pb_s, x_ls, mc, i_rad):
    """Pulsar mass [Msun] from PB/A1/companion mass/inclination
    (reference :386; closed-form root of the mass function cubic)."""
    massfunct = mass_funct(pb_s, x_ls)
    # f = (mc sinI)^3/(mp+mc)^2  =>  mp = sqrt((mc sinI)^3/f) - mc
    return np.sqrt((mc * np.sin(i_rad)) ** 3 / massfunct) - mc


def companion_mass(pb_s, x_ls, i_rad=np.pi / 2, mp=1.4):
    """Companion mass [Msun] by solving the mass-function cubic
    (reference :453; real root via numpy.roots per element)."""
    massfunct = mass_funct(pb_s, x_ls)
    sini = np.sin(i_rad)

    def one(mf, s, m):
        # (mc s)^3 = mf (m + mc)^2
        roots = np.roots([s**3, -mf, -2 * mf * m, -mf * m**2])
        real = roots[np.isreal(roots) & (roots.real > 0)].real
        return float(real.max()) if real.size else np.nan

    mf = np.atleast_1d(massfunct)
    s = np.broadcast_to(np.atleast_1d(sini), mf.shape)
    m = np.broadcast_to(np.atleast_1d(mp), mf.shape)
    out = np.array([one(a, b, c) for a, b, c in zip(mf, s, m)])
    return out[0] if np.isscalar(pb_s) or np.ndim(pb_s) == 0 else out


def pbdot(mp, mc, pb_s, e):
    """GR orbital decay PBDOT [s/s] (reference :557; Peters 1964)."""
    nb = 2.0 * np.pi / pb_s
    fe = (1.0 + 73.0 / 24.0 * e**2 + 37.0 / 96.0 * e**4) \
        / (1.0 - e**2) ** 3.5
    return (
        -192.0 * np.pi / 5.0
        * (nb * T_SUN_S) ** (5.0 / 3.0)
        * fe * mp * mc / (mp + mc) ** (1.0 / 3.0)
    )


def gamma(mp, mc, pb_s, e):
    """Einstein delay amplitude GAMMA [s] (reference :622)."""
    nb = 2.0 * np.pi / pb_s
    return (
        e * T_SUN_S ** (2.0 / 3.0) * nb ** (-1.0 / 3.0)
        * mc * (mp + 2 * mc) / (mp + mc) ** (4.0 / 3.0)
    )


def omdot_deg_per_yr(mp, mc, pb_s, e):
    """GR periastron advance [deg/yr] (reference :683)."""
    nb = 2.0 * np.pi / pb_s
    rad_per_s = (
        3.0 * nb ** (5.0 / 3.0) * (T_SUN_S * (mp + mc)) ** (2.0 / 3.0)
        / (1.0 - e**2)
    )
    return np.rad2deg(rad_per_s) * _SECS_PER_YEAR


def sini(mp, mc, pb_s, x_ls):
    """GR SINI from masses (reference :743)."""
    nb = 2.0 * np.pi / pb_s
    return (
        T_SUN_S ** (-1.0 / 3.0) * nb ** (2.0 / 3.0)
        * x_ls * (mp + mc) ** (2.0 / 3.0) / mc
    )


def omdot_to_mtot(omdot_deg_yr, pb_s, e):
    """Invert the GR periastron advance for MTOT [Msun]
    (reference :899)."""
    omdot_rad_s = np.deg2rad(omdot_deg_yr) / _SECS_PER_YEAR
    nb = 2.0 * np.pi / pb_s
    return (
        (omdot_rad_s * (1.0 - e**2) / (3.0 * nb ** (5.0 / 3.0)))
        ** (3.0 / 2.0) / T_SUN_S
    )


def a1sini(mp, mc, pb_s):
    """Projected semi-major axis [ls] from masses (reference :963)."""
    nb = 2.0 * np.pi / pb_s
    return (T_SUN_S * mc**3 / (mp + mc) ** 2) ** (1.0 / 3.0) \
        * nb ** (-2.0 / 3.0)


def shklovskii_factor(pmtot_mas_yr, d_kpc):
    """Shklovskii acceleration a_s [1/s]: Pdot_shk = a_s * P
    (reference :1017)."""
    _KPC_M = 3.0856775814913673e19
    pm_rad_s = np.deg2rad(pmtot_mas_yr / 3.6e6) / _SECS_PER_YEAR
    return pm_rad_s**2 * d_kpc * _KPC_M / _C


def dispersion_slope(dm):
    """Dispersion slope [s Hz^2] (reference :1055): delay = slope /
    nu_Hz^2.  DM_CONST carries MHz^2, hence the 1e12."""
    from pint_tpu import DM_CONST

    return DM_CONST * dm * 1e12


def orbital_phase(model, ticks):
    """Mean orbital phase in [0, 1) at TDB ticks (reference:
    photonphase --addorbphase / pintk orbital-phase view): the mean
    anomaly fraction from T0 (or TASC for ELL1-family models), with the
    orbital frequency from PB or FB0.  Raises ValueError when the model
    has no binary component."""
    vals = model.values
    t0 = vals.get("T0", vals.get("TASC"))
    if t0 is None or not ("PB" in vals or "FB0" in vals):
        raise ValueError(
            "orbital phase needs a binary model (T0/TASC and PB/FB0)")
    # internal units: PB seconds (Param scale converts par-file days),
    # FB0 Hz, T0/TASC seconds since J2000
    fb = (float(vals["FB0"]) if "FB0" in vals
          else 1.0 / float(vals["PB"]))
    sec = np.asarray(ticks, np.float64) / 2**32
    return ((sec - float(t0)) * fb) % 1.0
