"""pintlint, static half: the unified trace-safety analyzer.

Every correctness contract this repo built around the shared-jit
registry is easy to hold and easy to break silently: a trace gate left
out of a key serves a STALE program when the gate flips; a raw
``jax.jit`` call bypasses the registry and with it profiling, AOT
export, and the zero-recompile contract; a fresh ``lambda`` handed to
``shared_jit`` without ``fn_token`` has fresh identity per call, so
the registry misses every time (the exact PR-2 ``jax.jit(lambda *a:
fit(*a))`` bug); an ``os.environ`` read inside a traced function bakes
one process's gate state into a shared executable; an undocumented
telemetry counter is invisible to the people reading docs/telemetry.md
to debug an incident.  Each of these was found the hard way at least
once (CHANGES.md); this module makes all of them checkable, in one
rule framework, as a tier-1 test and a CLI (``pintlint``).

The module is deliberately self-contained and stdlib-only (``ast``,
``os``, ``re``): it must run without jax and without importing
``pint_tpu`` (whose ``__init__`` imports jax), so CI, the
``tools/check_jit_gates.py`` compatibility shim, and editors can load
it by file path.  The runtime half — the recompile sanitizer that
watches the same contracts while the process is live — is
:mod:`pint_tpu.lint.sanitizer`.

Rules (select/ignore by id; see docs/lint.md for the catalog):

- **PTL001 gate-key-site** — every registered trace-changing gate's
  declared key-construction functions carry the token that folds the
  gate into the shared-jit key (:data:`KEY_SITES`).
- **PTL002 gate-callsite-sweep** — a module that reads a gate resolver
  AND builds shared-jit keys must be a declared KEY_SITE or EXEMPT
  with a recorded reason.
- **PTL003 env-classification** — every ``PINT_TPU_*`` name in library
  source is a registered trace gate or a known host-only knob.
- **PTL004 mesh-axis** — PartitionSpec-rule axis literals exist in
  ``parallel/mesh.AXIS_NAMES``; ``mesh_jit_key`` stays generic.
- **PTL101 raw-jit** — ``jax.jit``/``jax.pmap``/``pjit`` calls outside
  the registry module: the program escapes profiling, the AOT store,
  and the zero-recompile contract.  Suppress per-site with an inline
  allow comment carrying a reason.
- **PTL102 anonymous-shared-jit** — ``shared_jit(lambda ...)`` without
  ``fn_token``: lambda identity is fresh per call, so every call is a
  registry miss that builds (and compiles) a new entry.
- **PTL103 env-in-trace** — ``os.environ``/``os.getenv`` read inside a
  function passed to a tracing transform: the gate must resolve at
  key-build time, not trace time (a traced read bakes one process's
  state into a shared executable and never re-reads).
- **PTL104 host-sync-in-trace** — ``.item()`` / ``jax.device_get``
  inside a traced function: forces a host sync (or a tracer-leak
  error) inside the program.
- **PTL105 trace-propagation** — a serve-plane handler constructs a
  ``Request`` / calls ``build_request`` / submits a job without
  passing the inbound trace context: the request is orphaned from
  its distributed trace (a defensively-minted id keeps records
  flowing but severs the client's traceparent linkage).
- **PTL201 undocumented-telemetry** — every literal counter / gauge /
  histogram name in library source appears in docs/telemetry.md
  (family wildcards, brace/slash lists, ``<kind>`` placeholders and
  ``..._suffix`` elisions in the doc all count).

Suppression: an inline comment on the flagged line (or the line
directly above) of the form ``# pintlint: allow=PTL101 -- reason``.
The reason is mandatory — an allow without one is itself a finding
(PTL000), the same "exemption without a reason is a lint bug"
discipline :data:`EXEMPT` already enforces.
"""

from __future__ import annotations

import ast
import os
import re
from collections import OrderedDict, namedtuple

__all__ = [
    "Finding", "RULES", "run", "check", "main", "repo_root",
    "TRACE_GATES", "KEY_SITES", "EXEMPT", "HOST_ONLY",
    "RAW_JIT_EXEMPT_FILES", "TRACING_CALLS",
]

#: one analyzer verdict.  ``line`` is 1-based (0 = whole file).
Finding = namedtuple("Finding", "rule file line message")


# --------------------------------------------------------------------------
# gate / env / exemption tables (the check_jit_gates registry, moved
# here verbatim; tools/check_jit_gates.py re-exports them)
# --------------------------------------------------------------------------

#: trace-changing gates: env var -> source tokens that resolve it.
#: A file "uses" the gate when any token appears in its source.
TRACE_GATES = {
    "PINT_TPU_GUARD": ("_guard.enabled()", "guard.enabled()"),
    "PINT_TPU_SCAN_ITERS": ("scan_iters_default()",),
    "PINT_TPU_ITER_TRACE": ("iter_trace_default()",),
    "PINT_TPU_HYBRID_DESIGN": ("hybrid_design_default()",),
    "PINT_TPU_FROZEN_DELAY": ("frozen_delay_default()",),
    "PINT_TPU_SEGMENT_ECORR": ("segment_ecorr_default()",),
    "PINT_TPU_KRON_PHI": ("kron_phi_default()",),
}

#: key sites: file -> {dotted function path: {gate: token that must
#: appear in that function's source}}.  The token is how the gate
#: rides the key at that site (a resolver call, or the local/attr
#: name its trace-build-time resolution was stored under).
KEY_SITES = {
    "pint_tpu/fitter.py": {
        "Fitter._step_key": {
            "PINT_TPU_GUARD": "self._guard_on",
            "PINT_TPU_ITER_TRACE": "self._iter_trace",
            # the design gates enter through the partition/frozen
            # tuples they deterministically derive
            "PINT_TPU_HYBRID_DESIGN": "self._partition",
            "PINT_TPU_FROZEN_DELAY": "self._frozen_names",
        },
    },
    "pint_tpu/downhill.py": {
        "_DownhillMixin._retrace": {
            "PINT_TPU_GUARD": "self._guard_on",
            "PINT_TPU_ITER_TRACE": "self._iter_trace",
            "PINT_TPU_HYBRID_DESIGN": "self._partition",
            "PINT_TPU_FROZEN_DELAY": "self._frozen_names",
        },
    },
    "pint_tpu/lmfitter.py": {
        "LMFitter._retrace": {
            "PINT_TPU_GUARD": "self._guard_on",
            "PINT_TPU_HYBRID_DESIGN": "self._partition",
            "PINT_TPU_FROZEN_DELAY": "self._frozen_names",
        },
        "PowellFitter._retrace": {
            "PINT_TPU_FROZEN_DELAY": "self._frozen_names",
        },
    },
    "pint_tpu/grid.py": {
        "make_grid_fn": {
            "PINT_TPU_SCAN_ITERS": "scan",
            "PINT_TPU_ITER_TRACE": "trace",
            "PINT_TPU_HYBRID_DESIGN": "hybrid_design_default()",
            "PINT_TPU_FROZEN_DELAY": "frozen_delay_default()",
        },
    },
    "pint_tpu/parallel/pta.py": {
        "PTABatch._batched_fit_jit": {
            "PINT_TPU_GUARD": "with_health",
            "PINT_TPU_SCAN_ITERS": "scan",
            "PINT_TPU_ITER_TRACE": "trace",
        },
        # the 2-D pulsar x grid scan resolves the scan flag itself
        "PTABatch._chisq_grid_jit": {
            "PINT_TPU_SCAN_ITERS": "scan",
        },
        # the design partition rides _structure_key
        "PTABatch._structure_key": {
            "PINT_TPU_HYBRID_DESIGN": "self._partition",
        },
    },
    "pint_tpu/residuals.py": {
        # segment-ECORR changes every Woodbury trace; it keys through
        # the StructuredU-vs-dense bit of the structure key
        "Residuals._structure_key": {
            "PINT_TPU_SEGMENT_ECORR": "StructuredU",
        },
    },
    "pint_tpu/gw/common.py": {
        # the kron/dense prior selection is a different traced
        # program (different argument layouts entirely); the gate
        # resolves once at CommonProcess build into self._kron, which
        # both lnlike keys carry
        "CommonProcess._lnlike_jit": {
            "PINT_TPU_KRON_PHI": "self._kron",
        },
        "CommonProcess.lnlike_grid": {
            "PINT_TPU_KRON_PHI": "self._kron",
        },
    },
    "pint_tpu/gw/hmc.py": {
        # the HMC chunk scan resolves the scan flag itself and keys
        # it (scan vs unroll are different programs); the kron flag
        # rides the key via posterior.kron (resolved upstream at
        # CommonProcess build)
        "run_nuts": {
            "PINT_TPU_SCAN_ITERS": "scan_flag",
        },
    },
}

#: modules that call a gate resolver AND build shared-jit keys but
#: are deliberately NOT key sites for it — each with the reason the
#: exemption is sound.  An exemption without a reason is a lint bug.
EXEMPT = {
    ("pint_tpu/sampler.py", "PINT_TPU_GUARD"):
        "chain health always rides the traced program (kept OUT of "
        "the key by design); guard gate is honored host-side only",
    ("pint_tpu/gw/common.py", "PINT_TPU_GUARD"):
        "lnlike health always rides the traced program; the gate "
        "changes only the host-side raise",
    ("pint_tpu/datacheck.py", "*"):
        "reporting only: resolvers are read to PRINT gate state, "
        "never to build a traced program",
    ("pint_tpu/models/timing_model.py", "*"):
        "defines the design-gate resolvers; its own shared_jit use "
        "is none (prepare() is host-side)",
    ("pint_tpu/compile_cache.py", "*"):
        "defines scan/iter-trace resolvers and the registry itself; "
        "iterate_fixed receives the resolved flag from callers",
    ("pint_tpu/fitter.py", "PINT_TPU_SCAN_ITERS"):
        "the single-pulsar fit loop is host-driven (no iterate_fixed "
        "inside its trace)",
    ("pint_tpu/residuals.py", "PINT_TPU_GUARD"):
        "residuals accessors compute no health output; the guard "
        "gate never reaches their traces",
    ("pint_tpu/gw/hmc.py", "PINT_TPU_ITER_TRACE"):
        "HMC per-draw records always ride the scan ys (they ARE the "
        "returned chain, gate on or off — one traced program); the "
        "gate controls only host-side iter_trace telemetry emission",
    ("pint_tpu/gw/hmc.py", "PINT_TPU_GUARD"):
        "chain health is read from the returned draws host-side (the "
        "sampler.py convention); the gate changes only the host-side "
        "raise, never the traced chunk program",
    ("pint_tpu/lint/static.py", "*"):
        "the lint's own rule tables spell every gate token and key "
        "idiom as string literals; it builds no traced program",
}

#: known host-only PINT_TPU_* env vars: they change behavior outside
#: any traced program (paths, timeouts, reporting, process harness),
#: so key participation is not required.
HOST_ONLY = {
    "PINT_TPU_CACHE_DIR", "PINT_TPU_CLOCK_DIR", "PINT_TPU_IERS_DIR",
    "PINT_TPU_EPHEM_DIR", "PINT_TPU_EPHEM_BUILTIN",
    "PINT_TPU_NO_BUILTIN_DATA", "PINT_TPU_OBS", "PINT_TPU_LOG",
    "PINT_TPU_TRACE", "PINT_TPU_TRACE_MAX_MB", "PINT_TPU_PROFILE",
    "PINT_TPU_METRICS_PORT", "PINT_TPU_METRICS_HOST",
    "PINT_TPU_JIT_REGISTRY_CAP", "PINT_TPU_DONATE_CPU",
    "PINT_TPU_AOT_CODEC", "PINT_TPU_FAULTS",
    "PINT_TPU_PROBE_TIMEOUT", "PINT_TPU_PROBE_RETRIES",
    "PINT_TPU_PROBE_BACKOFF",
    "PINT_TPU_BENCH_CPU", "PINT_TPU_BENCH_FALLBACK",
    "PINT_TPU_BENCH_PROBE_TIMEOUT", "PINT_TPU_BENCH_METRIC_TIMEOUT",
    "PINT_TPU_BENCH_FALLBACK_TIMEOUT",
    "PINT_TPU_MEASURED_PEAK_F64", "PINT_TPU_MEASURED_PEAK_BACKEND",
    # bucketing pads the DATASET host-side; the padded shape reaches
    # the key through the avals/structure, not through the gate
    "PINT_TPU_BUCKET_TOAS",
    # the warm fitting service (pint_tpu/serve/): every knob is
    # host-only BY DESIGN — the batcher must never create traced
    # programs beyond the existing PTA-batch registry keys
    # (pta.batched_fit / pta.chisq / pta.resid), whose identities are
    # carried by bucket, size class, structure, and maxiter through
    # the ordinary aval/key machinery.  Flush cadence, queue bounds,
    # deadlines, ports, and directories shape WHEN and HOW MANY
    # requests share a program, never the program itself
    # (tests/test_serve.py asserts the zero-new-compile contract on a
    # repeated same-bucket flush).
    "PINT_TPU_SERVE_FLUSH_MS", "PINT_TPU_SERVE_MAX_BATCH",
    "PINT_TPU_SERVE_QUEUE_MAX", "PINT_TPU_SERVE_DEADLINE_MS",
    "PINT_TPU_SERVE_GRID_CHUNK", "PINT_TPU_SERVE_PORT",
    "PINT_TPU_SERVE_HOST", "PINT_TPU_SERVE_JOB_DIR",
    "PINT_TPU_SERVE_AOT_DIR",
    # the token the regex extracts from the docstring wildcard
    # spelling ``PINT_TPU_SERVE_*`` (prose about the family, not a
    # variable); every real member is enumerated above
    "PINT_TPU_SERVE_",
    # the recompile sanitizer (pint_tpu/lint/sanitizer.py) observes
    # compiles; it never creates or alters a traced program, so the
    # mode knob cannot need key participation
    "PINT_TPU_RECOMPILE_SANITIZER",
    # the SLO engine (pint_tpu/obs/slo.py) classifies request
    # latencies AFTER dispatch — objectives shape verdicts and the
    # admission queue bound, never a traced program
    "PINT_TPU_SLO_P99_MS", "PINT_TPU_SLO_AVAIL",
    # the scenario corpus (pint_tpu/corpus/): reference-PINT mount
    # point, parity-mode selector, and the on-disk corpus directory
    # all steer host-side generation/subprocess plumbing; scenarios
    # reach traced programs only as ordinary datasets whose shapes
    # flow through the aval/key machinery like any other TOA table
    "PINT_TPU_CORPUS_REFERENCE", "PINT_TPU_CORPUS_MODE",
    "PINT_TPU_CORPUS_DIR",
    # fleet orchestration (pint_tpu/fleet/): router placement/retry
    # policy and supervisor process management are PURE harness — the
    # router process runs no device code at all, and the supervisor
    # only spawns/drains/restarts pintserve subprocesses.  Replica
    # counts, backoffs, probe cadence, and retry budgets shape which
    # PROCESS serves a request, never a traced program inside one.
    "PINT_TPU_ROUTER_PORT", "PINT_TPU_ROUTER_HOST",
    "PINT_TPU_ROUTER_RETRY", "PINT_TPU_ROUTER_PROBE_S",
    "PINT_TPU_ROUTER_SPREAD_PENDING",
    "PINT_TPU_FLEET_REPLICAS", "PINT_TPU_FLEET_MIN_REPLICAS",
    "PINT_TPU_FLEET_MAX_REPLICAS", "PINT_TPU_FLEET_BACKOFF_S",
    "PINT_TPU_FLEET_CRASH_LOOP_K", "PINT_TPU_FLEET_AUTOSCALE_S",
    "PINT_TPU_FLEET_RETRIES", "PINT_TPU_FLEET_RETRY_BUDGET_S",
    # the tokens the regex extracts from the docstring wildcard
    # spellings ``PINT_TPU_ROUTER_*`` / ``PINT_TPU_FLEET_*`` (prose
    # about the families); every real member is enumerated above
    "PINT_TPU_ROUTER_", "PINT_TPU_FLEET_",
    # streaming appends (Fitter.append_refit / linalg block solver):
    # the mini-batch block size pads the DELTA host-side — like
    # PINT_TPU_BUCKET_TOAS the padded shape reaches the key through
    # the avals, not through a gate; recapture cadence and the triage
    # threshold steer host-side control flow between already-keyed
    # programs (tests/test_stream.py pins the zero-new-compile
    # contract on a steady-state same-bucket append)
    "PINT_TPU_STREAM_BLOCK", "PINT_TPU_STREAM_RECAPTURE",
    "PINT_TPU_STREAM_TRIAGE_SIGMA", "PINT_TPU_STREAM_",
}

#: files where raw jax.jit is the point, not a registry bypass —
#: reason recorded, same discipline as EXEMPT.
RAW_JIT_EXEMPT_FILES = {
    "pint_tpu/compile_cache.py":
        "the registry itself: shared_jit's jax.jit is the ONE "
        "sanctioned call, and the AOT import/export codecs must "
        "wrap deserialized executables directly",
}

#: call names whose function-valued arguments are traced.  Both bare
#: names (``vmap`` after ``from jax import vmap``) and attribute tails
#: (``jax.vmap``, ``lax.scan``) resolve here.
TRACING_CALLS = {
    "jit", "pmap", "pjit", "vmap", "jacfwd", "jacrev", "grad",
    "value_and_grad", "scan", "while_loop", "fori_loop", "cond",
    "switch", "checkpoint", "shared_jit", "iterate_fixed",
}

_ENV_RE = re.compile(r"PINT_TPU_[A-Z0-9_]+")

#: function names whose string-literal arguments name mesh axes
_AXIS_CALLS = {"P", "PartitionSpec", "_P", "make_mesh",
               "resolve_axis", "axis_size", "RowShard"}

_ALLOW_RE = re.compile(
    r"#\s*pintlint:\s*allow=([A-Z0-9,]+)\s*(?:--\s*(\S.*))?")

_TELEMETRY_FNS = {"counter_add", "gauge_set", "hist_record"}


# --------------------------------------------------------------------------
# source loading + suppression
# --------------------------------------------------------------------------

class _Ctx:
    """Parsed view of one source tree: relpath -> source / AST /
    per-line allow directives."""

    def __init__(self, root):
        self.root = root
        self.sources: "OrderedDict[str, str]" = OrderedDict()
        self.trees: dict = {}
        self.lines: dict = {}         # rel -> list of source lines
        self.allows: dict = {}        # rel -> {line: set(rule ids)}
        self.bad_allows: list = []    # (rel, line) missing a reason
        py_files = []
        for base in ("pint_tpu",):
            for dirpath, dirnames, filenames in os.walk(
                    os.path.join(root, base)):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                py_files.extend(os.path.join(dirpath, f)
                                for f in filenames if f.endswith(".py"))
        for path in sorted(py_files):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path) as fh:
                src = fh.read()
            self.sources[rel] = src
            self.lines[rel] = src.splitlines()
            try:
                self.trees[rel] = ast.parse(src)
            except SyntaxError:
                self.trees[rel] = None
            allows = {}
            for lineno, line in enumerate(self.lines[rel], 1):
                m = _ALLOW_RE.search(line)
                if not m:
                    continue
                if not m.group(2):
                    self.bad_allows.append((rel, lineno))
                allows[lineno] = set(m.group(1).split(","))
            if allows:
                self.allows[rel] = allows
        doc_path = os.path.join(root, "docs", "telemetry.md")
        try:
            with open(doc_path) as fh:
                self.telemetry_doc = fh.read()
        except OSError:
            self.telemetry_doc = None

    def allowed(self, rel, line, rule) -> bool:
        """Whether an allow directive covers ``rule`` at ``line``:
        trailing on the flagged line itself, or anywhere in the
        contiguous comment block directly above it (multi-line
        reasons are encouraged)."""
        allows = self.allows.get(rel)
        if not allows:
            return False

        def hit(at):
            ids = allows.get(at)
            return bool(ids and (rule in ids or "*" in ids))

        if hit(line):
            return True
        src_lines = self.lines.get(rel) or []
        at = line - 1
        while at >= 1 and at <= len(src_lines) and \
                src_lines[at - 1].lstrip().startswith("#"):
            if hit(at):
                return True
            at -= 1
        return False


def _function_source(tree, src, dotted):
    """Source segment of a (possibly class-nested) function."""
    parts = dotted.split(".")
    node = tree
    for name in parts:
        found = None
        for child in ast.walk(node) if node is tree else \
                ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef,
                                  ast.ClassDef)) \
                    and child.name == name:
                found = child
                break
        if found is None:
            return None
        node = found
    return ast.get_source_segment(src, node)


def _call_name(node):
    """The terminal name of a Call's callee: ``jax.jit`` -> ``jit``,
    ``shared_jit`` -> ``shared_jit``; None for computed callees."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _attr_path(node):
    """Dotted path of an Attribute/Name chain (``jax.experimental.
    pjit`` -> "jax.experimental.pjit"), or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_exempt(rel, gate):
    return (rel, gate) in EXEMPT or (rel, "*") in EXEMPT


# --------------------------------------------------------------------------
# PTL001-004: the migrated jit-gate / env / mesh checks
# --------------------------------------------------------------------------

def _rule_gate_key_site(ctx, notes):
    out = []
    for rel, funcs in sorted(KEY_SITES.items()):
        src = ctx.sources.get(rel)
        if src is None:
            out.append(Finding("PTL001", rel, 0,
                               "key-site file missing"))
            continue
        tree = ctx.trees.get(rel)
        for dotted, needs in sorted(funcs.items()):
            seg = _function_source(tree, src, dotted) if tree else None
            if seg is None:
                out.append(Finding(
                    "PTL001", rel, 0,
                    f"{dotted}: key function not found (renamed? "
                    "update KEY_SITES)"))
                continue
            for gate, token in sorted(needs.items()):
                if token in seg:
                    notes.append(f"OK   {rel}:{dotted}: {gate} via "
                                 f"{token!r}")
                else:
                    out.append(Finding(
                        "PTL001", rel, 0,
                        f"{dotted}: {gate} token {token!r} missing "
                        "from the key function — a flipped gate "
                        "would serve a stale trace"))
    return out


def _rule_gate_callsite_sweep(ctx, notes):
    out = []
    for rel, src in sorted(ctx.sources.items()):
        if "shared_jit(" not in src:
            continue
        for gate, tokens in sorted(TRACE_GATES.items()):
            if not any(tok in src for tok in tokens):
                continue
            declared = gate in {
                g for funcs in (KEY_SITES.get(rel) or {}).values()
                for g in funcs}
            if declared or _is_exempt(rel, gate):
                continue
            out.append(Finding(
                "PTL002", rel, 0,
                f"reads trace gate {gate} and builds shared-jit "
                "keys, but is neither a declared KEY_SITE nor "
                "EXEMPT (with a reason) for it"))
    return out


def _rule_env_classification(ctx, notes):
    out = []
    known = set(TRACE_GATES) | HOST_ONLY
    for rel, src in sorted(ctx.sources.items()):
        for var in sorted(set(_ENV_RE.findall(src))):
            if var not in known:
                out.append(Finding(
                    "PTL003", rel, 0,
                    f"unclassified env var {var} — add it to "
                    "TRACE_GATES (and a KEY_SITE) if it changes a "
                    "traced program, else to HOST_ONLY"))
    return out


def _axis_names_from_source(src):
    """The AXIS_NAMES tuple parsed out of parallel/mesh.py source
    (ast, not import — the lint must run without jax)."""
    tree = ast.parse(src)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "AXIS_NAMES"
                for t in node.targets):
            return tuple(
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str))
    return None


def _axis_literals(tree):
    """Mesh-axis string literals used in PartitionSpec rule tables and
    mesh-construction calls of one module: ``(lineno, name)`` pairs.
    Only direct str/tuple-of-str arguments count — computed axis
    names resolve at runtime through resolve_axis, which validates."""
    out = []
    if tree is None:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name not in _AXIS_CALLS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords
                                      if kw.arg in ("axes", "axis")]:
            elts = (arg.elts if isinstance(arg, (ast.Tuple, ast.List))
                    else [arg])
            for e in elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, str):
                    out.append((node.lineno, e.value))
    return out


def _rule_mesh_axis(ctx, notes):
    out = []
    mesh_rel = "pint_tpu/parallel/mesh.py"
    mesh_src = ctx.sources.get(mesh_rel)
    axis_names = (_axis_names_from_source(mesh_src)
                  if mesh_src else None)
    if axis_names is None:
        out.append(Finding(
            "PTL004", mesh_rel, 0,
            "AXIS_NAMES literal not found (renamed? the axis lint "
            "needs it)"))
        return out
    tree = ctx.trees.get(mesh_rel)
    key_src = _function_source(tree, mesh_src, "mesh_jit_key")
    if key_src is None:
        out.append(Finding("PTL004", mesh_rel, 0,
                           "mesh_jit_key not found"))
    elif "axis_names" in key_src or all(
            f'"{a}"' in key_src or f"'{a}'" in key_src
            for a in axis_names):
        notes.append(
            f"OK   {mesh_rel}:mesh_jit_key covers every axis "
            "(generic over mesh.axis_names)")
    else:
        out.append(Finding(
            "PTL004", mesh_rel, 0,
            "mesh_jit_key no longer derives its entries from "
            "mesh.axis_names and does not name every axis in "
            f"AXIS_NAMES {axis_names} — a rule-table axis could "
            "miss the jit key and poison the zero-recompile "
            "contract"))
    allowed = set(axis_names)
    for rel, tree in sorted(ctx.trees.items()):
        for lineno, name in _axis_literals(tree):
            if name in allowed:
                continue
            out.append(Finding(
                "PTL004", rel, lineno,
                f"mesh-axis literal {name!r} is not in "
                f"parallel/mesh.AXIS_NAMES {axis_names} — a typo'd "
                "or undeclared axis silently mis-shards; add it to "
                "AXIS_NAMES or fix the name"))
    return out


# --------------------------------------------------------------------------
# PTL101/102: registry-bypass rules
# --------------------------------------------------------------------------

def _jax_jit_imports(tree):
    """Bare names this module binds to jax's jit/pmap via
    ``from jax import jit`` (incl. aliases) — bare ``pjit`` is
    always matched, these two only when actually imported, so an
    unrelated local ``jit()`` helper stays clean."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                (node.module == "jax" or node.module.startswith("jax.")):
            for alias in node.names:
                if alias.name in ("jit", "pmap"):
                    names.add(alias.asname or alias.name)
    return names


def _raw_jit_hit(expr, bare_names):
    """The offending dotted path if ``expr`` names a raw tracing
    entry point (call target, bare decorator, or partial() arg)."""
    path = _attr_path(expr)
    if path is None:
        return None
    if path in ("jax.jit", "jax.pmap", "pjit") or \
            path.endswith(".pjit") or path in bare_names:
        return path
    return None


def _rule_raw_jit(ctx, notes):
    out = []
    for rel, tree in sorted(ctx.trees.items()):
        if tree is None or rel in RAW_JIT_EXEMPT_FILES:
            continue
        bare = _jax_jit_imports(tree)
        hits = []   # (lineno, path, spelling)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                path = _raw_jit_hit(node.func, bare)
                if path is not None:
                    hits.append((node.lineno, path, f"{path}()"))
                elif _call_name(node) == "partial":
                    # partial(jax.jit, ...) builds the same raw
                    # program factory one hop removed
                    for arg in node.args:
                        p = _raw_jit_hit(arg, bare)
                        if p is not None:
                            hits.append((node.lineno, p,
                                         f"partial({p}, ...)"))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                # bare @jax.jit decorators are Attributes, not Calls
                for dec in node.decorator_list:
                    p = _raw_jit_hit(dec, bare)
                    if p is not None:
                        hits.append((dec.lineno, p, f"@{p}"))
        for lineno, path, spelling in hits:
            out.append(Finding(
                "PTL101", rel, lineno,
                f"raw {spelling} bypasses compile_cache."
                "shared_jit — the program escapes the registry "
                "(profiling, AOT export/import, zero-recompile "
                "contract); route through shared_jit or add an "
                "inline allow with the reason"))
    return out


def _rule_anonymous_shared_jit(ctx, notes):
    out = []
    for rel, tree in sorted(ctx.trees.items()):
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or \
                    _call_name(node) != "shared_jit":
                continue
            if not node.args or not isinstance(node.args[0],
                                               ast.Lambda):
                continue
            if any(kw.arg == "fn_token" for kw in node.keywords):
                continue
            out.append(Finding(
                "PTL102", rel, node.lineno,
                "shared_jit(lambda ...) without fn_token: a lambda "
                "built at the call site has fresh identity per "
                "call, so the registry misses every time and "
                "re-traces (the PR-2 jax.jit(lambda *a: fit(*a)) "
                "bug class) — pass fn_token naming the computation"))
    return out


# --------------------------------------------------------------------------
# PTL103/104: traced-function hygiene
# --------------------------------------------------------------------------

def _decorated_by_transform(node):
    """Whether a def carries a tracing-transform decorator:
    ``@jax.jit``, ``@jit``, ``@jax.jit(...)``, or
    ``@partial(jax.jit, ...)``."""
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = target.attr if isinstance(target, ast.Attribute) \
            else getattr(target, "id", None)
        if name in TRACING_CALLS:
            return True
        if isinstance(dec, ast.Call) and name == "partial":
            for arg in dec.args:
                inner = arg.attr if isinstance(arg, ast.Attribute) \
                    else getattr(arg, "id", None)
                if inner in TRACING_CALLS:
                    return True
    return False


def _traced_functions(tree):
    """Function bodies traced by a jax transform in this module:
    local ``def``s whose NAME is passed to a tracing call, defs
    decorated with a transform, plus lambdas passed directly.
    Conservative by construction — only bare-name and inline-lambda
    arguments resolve."""
    if tree is None:
        return []
    traced_names = set()
    lambdas = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_name(node) not in TRACING_CALLS:
            continue
        cands = list(node.args) + [
            kw.value for kw in node.keywords
            if kw.arg in ("body", "fun", "f", "cond_fun", "body_fun")]
        for arg in cands:
            if isinstance(arg, ast.Name):
                traced_names.add(arg.id)
            elif isinstance(arg, ast.Lambda):
                lambdas.append(arg)
    defs = [node for node in ast.walk(tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and (node.name in traced_names
                 or _decorated_by_transform(node))]
    return defs + lambdas


def _rule_env_in_trace(ctx, notes):
    out = []
    for rel, tree in sorted(ctx.trees.items()):
        for fn in _traced_functions(tree):
            label = getattr(fn, "name", "<lambda>")
            for node in ast.walk(fn):
                bad = None
                if isinstance(node, ast.Attribute) and \
                        node.attr == "environ" and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "os":
                    bad = "os.environ"
                elif isinstance(node, ast.Call) and \
                        _call_name(node) == "getenv":
                    bad = "os.getenv()"
                if bad is None:
                    continue
                out.append(Finding(
                    "PTL103", rel, node.lineno,
                    f"{bad} read inside traced function "
                    f"{label!r}: the value is baked into the "
                    "shared executable at trace time and never "
                    "re-read — resolve the gate at key-build time "
                    "and fold it into the jit key"))
    return out


def _rule_host_sync_in_trace(ctx, notes):
    out = []
    for rel, tree in sorted(ctx.trees.items()):
        for fn in _traced_functions(tree):
            label = getattr(fn, "name", "<lambda>")
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                bad = None
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item" and not node.args:
                    bad = ".item()"
                elif _attr_path(node.func) == "jax.device_get":
                    bad = "jax.device_get()"
                if bad is None:
                    continue
                out.append(Finding(
                    "PTL104", rel, node.lineno,
                    f"{bad} inside traced function {label!r}: "
                    "forces a host sync (or a tracer-leak error) "
                    "inside the program — keep host reads outside "
                    "the trace, or return the value and read it "
                    "after dispatch"))
    return out


# --------------------------------------------------------------------------
# PTL105: serve-plane trace-context propagation
# --------------------------------------------------------------------------

#: call shapes that admit a request into the serve plane, with the
#: positional slot the ``trace`` parameter occupies (a call passing
#: at least that many positionals carried it positionally).  Matching
#: is by terminal callee name — serve-plane files only, so an
#: unrelated ``submit`` elsewhere in the library never matches.
_TRACE_CARRIERS = {
    # ServeState.build_request(op, params, default_deadline_ms, trace)
    "build_request": 4,
    # Request(op, dataset, params, maxiter, deadline, trace)
    "Request": 6,
    # JobStore.submit(spec, trace)
    "submit": 2,
}


def _rule_trace_context(ctx, notes):
    """PTL105: a serve-plane call that admits a request (or job)
    without the inbound trace id drops the client's traceparent —
    the defensive mint in ``Request.__init__`` keeps span records
    flowing, but the distributed trace silently forks."""
    out = []
    for rel, tree in sorted(ctx.trees.items()):
        if tree is None or not rel.startswith("pint_tpu/serve/"):
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            slot = _TRACE_CARRIERS.get(name)
            if slot is None:
                continue
            if name == "submit":
                # only job-store submissions carry trace; executor
                # submit() and the like do not
                path = _attr_path(node.func) or ""
                if not path.endswith("jobs.submit"):
                    continue
            if any(kw.arg == "trace" for kw in node.keywords) or \
                    any(kw.arg is None for kw in node.keywords):
                continue   # explicit trace=..., or **kwargs passthrough
            if len(node.args) >= slot:
                continue   # carried positionally
            out.append(Finding(
                "PTL105", rel, node.lineno,
                f"serve-plane {name}() without the inbound trace "
                "context: the request/job is minted a fresh trace id "
                "and the client's traceparent linkage is silently "
                "dropped — pass trace= from obs.trace.from_headers "
                "(or the job doc), or add an inline allow with the "
                "reason"))
    return out


# --------------------------------------------------------------------------
# PTL201: telemetry-name doc coverage
# --------------------------------------------------------------------------

def _literal_telemetry_names(tree):
    """(lineno, name) for every literal first argument of a
    counter_add / gauge_set / hist_record call.  f-strings and
    computed names are skipped — they are families whose static
    prefix the doc covers with a wildcard row."""
    out = []
    if tree is None:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or \
                _call_name(node) not in _TELEMETRY_FNS:
            continue
        if not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((node.lineno, arg.value))
    return out


def _expand_braces(tok):
    """``a.{b,c}_d`` -> ["a.b_d", "a.c_d"] (one level per pass,
    fixed-point)."""
    toks = [tok]
    while True:
        nxt = []
        changed = False
        for t in toks:
            m = re.search(r"\{([^{}]*)\}", t)
            if m is None:
                nxt.append(t)
                continue
            changed = True
            for alt in m.group(1).split(","):
                nxt.append(t[:m.start()] + alt + t[m.end():])
        toks = nxt
        if not changed:
            return toks


class _DocVocab:
    """Matcher over the telemetry doc's code-span vocabulary.

    Doc spellings understood (all appear in docs/telemetry.md today):
    exact names; brace lists ``registry_{hits,misses}``; slash lists
    ``backend_probe.attempts/ok/failures``; ``<kind>`` placeholders
    (one dotted-segment wildcard); ``family.*`` wildcards; and
    ``..._misses`` elisions (same prefix as a sibling row)."""

    def __init__(self, doc):
        self.exact = set()
        self.prefixes = []
        self.regexes = []
        self.suffixes = []
        for raw in re.findall(r"`([^`\s]+)`", doc or ""):
            for tok in _expand_braces(raw):
                parts = tok.split("/")
                head = parts[0]
                stem = (head.rsplit(".", 1)[0] + "."
                        if "." in head else "")
                for i, t in enumerate(parts):
                    name = t if i == 0 or "." in t else stem + t
                    self._add(name)

    def _add(self, tok):
        if tok.startswith("..."):
            self.suffixes.append(tok[3:])
        elif tok.endswith(".*"):
            self.prefixes.append(tok[:-1])   # keep the dot
        elif "<" in tok:
            pat = re.escape(tok)
            pat = re.sub(r"<[^<>]*>", r"[A-Za-z0-9_]+", pat)
            self.regexes.append(re.compile(pat + r"\Z"))
        else:
            self.exact.add(tok)

    def covers(self, name) -> bool:
        if name in self.exact:
            return True
        if any(name.startswith(p) for p in self.prefixes):
            return True
        if any(name.endswith(s) for s in self.suffixes):
            return True
        return any(r.match(name) for r in self.regexes)


def _rule_undocumented_telemetry(ctx, notes):
    out = []
    all_names = []
    for rel, tree in sorted(ctx.trees.items()):
        for lineno, name in _literal_telemetry_names(tree):
            if "." in name:       # library convention: dotted names
                all_names.append((rel, lineno, name))
    if not all_names:
        return out
    if ctx.telemetry_doc is None:
        if not os.path.isdir(os.path.join(ctx.root, "docs")):
            # installed wheel, not a checkout: the doc is not
            # shipped, so its absence is a skip, not a finding
            notes.append("SKIP PTL201: no docs/ tree at this root "
                         "(installed package?) — run from a checkout "
                         "to verify telemetry-name coverage")
            return out
        out.append(Finding(
            "PTL201", "docs/telemetry.md", 0,
            "telemetry doc missing but library source emits "
            f"{len(all_names)} literal counter/gauge/hist names"))
        return out
    vocab = _DocVocab(ctx.telemetry_doc)
    seen = set()
    for rel, lineno, name in all_names:
        if name in seen or vocab.covers(name):
            continue
        seen.add(name)
        out.append(Finding(
            "PTL201", rel, lineno,
            f"telemetry name {name!r} is not documented in "
            "docs/telemetry.md — add a row (family wildcards like "
            f"`{name.rsplit('.', 1)[0]}.*` count)"))
    return out


# --------------------------------------------------------------------------
# the rule registry + runner
# --------------------------------------------------------------------------

#: id -> (title, fn).  Order is report order.
RULES = OrderedDict([
    ("PTL001", ("gate-key-site", _rule_gate_key_site)),
    ("PTL002", ("gate-callsite-sweep", _rule_gate_callsite_sweep)),
    ("PTL003", ("env-classification", _rule_env_classification)),
    ("PTL004", ("mesh-axis", _rule_mesh_axis)),
    ("PTL101", ("raw-jit", _rule_raw_jit)),
    ("PTL102", ("anonymous-shared-jit", _rule_anonymous_shared_jit)),
    ("PTL103", ("env-in-trace", _rule_env_in_trace)),
    ("PTL104", ("host-sync-in-trace", _rule_host_sync_in_trace)),
    ("PTL105", ("trace-propagation", _rule_trace_context)),
    ("PTL201", ("undocumented-telemetry", _rule_undocumented_telemetry)),
])


def repo_root(start=None):
    """Locate the source tree this module belongs to: the directory
    holding the ``pint_tpu`` package that contains this file."""
    here = start or os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def run(root=None, select=None, ignore=None):
    """Run the analyzer over the tree at ``root``.

    Returns ``(findings, notes)``: surviving :class:`Finding`s in
    report order, and the human "OK" notes the gate rules emit for
    verified key-site tokens.  ``select``/``ignore`` are iterables of
    rule ids; suppressed-by-comment findings are filtered here, and a
    malformed allow (no reason) surfaces as PTL000."""
    root = root or repo_root()
    ctx = _Ctx(root)
    selected = set(select) if select else set(RULES) | {"PTL000"}
    if ignore:
        selected -= set(ignore)
    findings, notes = [], []
    for rule_id, (_title, fn) in RULES.items():
        if rule_id not in selected:
            continue
        for f in fn(ctx, notes):
            if not ctx.allowed(f.file, f.line, f.rule):
                findings.append(f)
    if "PTL000" in selected:
        for rel, lineno in ctx.bad_allows:
            findings.append(Finding(
                "PTL000", rel, lineno,
                "pintlint allow directive without a reason — spell "
                "it `# pintlint: allow=<id> -- why this is sound`"))
    return findings, notes


def check(root):
    """Back-compat entry preserved for ``tools/check_jit_gates.py``
    and its tier-1 tests: returns ``(lines, rc)`` — "OK"-prefixed
    notes plus one "FAIL ..." line per finding, rc nonzero iff any
    finding survived."""
    findings, notes = run(root)
    lines = list(notes)
    for f in findings:
        where = f"{f.file}:{f.line}" if f.line else f.file
        lines.append(f"FAIL {where}: [{f.rule}] {f.message}")
    return lines, (1 if findings else 0)


def main(argv=None):
    """CLI body shared by ``pintlint`` and the tools shim."""
    import argparse
    import json as _json

    p = argparse.ArgumentParser(
        prog="pintlint",
        description="pint_tpu trace-safety static analyzer "
                    "(docs/lint.md)")
    p.add_argument("root", nargs="?", default=None,
                   help="source tree to analyze (default: the tree "
                        "this installation was loaded from)")
    p.add_argument("--select", default=None,
                   help="comma-separated rule ids to run")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rule ids to skip")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the OK notes")
    args = p.parse_args(argv)
    if args.list_rules:
        for rule_id, (title, fn) in RULES.items():
            print(f"{rule_id}  {title}")
        return 0
    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None
    findings, notes = run(args.root, select=select, ignore=ignore)
    if args.json:
        print(_json.dumps([f._asdict() for f in findings], indent=2))
        return 1 if findings else 0
    if not args.quiet and not findings:
        for ln in notes:
            print(ln)
    for f in findings:
        where = f"{f.file}:{f.line}" if f.line else f.file
        print(f"{where}: {f.rule} {f.message}")
    verdict = (f"FAILED ({len(findings)} findings)" if findings
               else "OK")
    print(f"pintlint: {verdict} ({len(notes)} key-site tokens "
          f"verified, {len(RULES)} rules)")
    return 1 if findings else 0


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv[1:]))
