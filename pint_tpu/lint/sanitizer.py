"""pintlint, runtime half: the recompile sanitizer
(``$PINT_TPU_RECOMPILE_SANITIZER``).

The static analyzer proves the *source* cannot break the shared-trace
contract; this module watches the *process*.  The failure it exists
for is the one no AST rule can see: a warm replica — or a bench
steady-state loop, or the second same-shaped fitter — performs an XLA
compile it should not have needed.  Today that failure is only
visible as a global counter delta (``telemetry.compile_stats()``),
which says *that* something compiled but never *what*: the debugging
session starts from zero every time.  The sanitizer attributes every
backend compile to the registry program that triggered it, classifies
it, and — when armed — turns it into a structured violation instead
of a silent latency cliff.

Mechanics.  The profiling proxy around every registry program
(:func:`pint_tpu.profiling.wrap_program`) brackets each dispatch in a
thread-local scope; a ``jax.monitoring`` duration listener marks the
innermost scope when a ``backend_compile`` event fires (compilation
is synchronous on the dispatching thread, so attribution is exact).
After the underlying call returns, the proxy hands the scope back
here, where the compile is classified against a per-program history
of argument-spec fingerprints:

- ``first`` — the program's first compile at this spec.  Expected on
  any cold path.
- ``new_shape`` — a known program compiled for a spec it had not
  seen.  Expected while unarmed (structure-only keys serve several
  aval sets); a violation while armed (a warm process has no business
  meeting new shapes).
- ``same_shape_recompile`` — a program compiled AGAIN for a spec it
  had already compiled.  Always a violation: the registry entry was
  evicted, the key aliased, or jax's trace cache was invalidated —
  the stale-trace/recompile bug class the whole architecture exists
  to prevent.
- compiles with no scope on the thread (eager ops, code outside the
  registry) are counted ``unattributed`` and become violations only
  while armed.

Modes (host-only knob, never part of any jit key): ``off`` (default
— the proxy hot path pays one module-attribute check), ``warn``
(violations tick counters, emit ``{"type": "sanitizer"}`` records,
and ``warnings.warn``), ``raise`` (additionally raise
:class:`RecompileError` from the dispatching call AFTER the result
is computed — never from inside jax's compile machinery).

Arming: :func:`arm` after warmup declares "this process believes
itself warm; any compile from here on is a bug".  The serving replica
arms itself after its AOT import / warmup sweep when the mode knob is
set (docs/serving.md); tests and datacheck use the
:func:`sanitized` context manager.  Every compile — armed or not —
lands in a bounded in-memory ledger (:func:`ledger`) and the
telemetry sink, so ``pinttrace --sanitizer`` reconstructs the compile
story of a run after the fact.
"""

from __future__ import annotations

import contextlib
import os
import threading
import warnings
from collections import OrderedDict, deque

from pint_tpu import telemetry

__all__ = [
    "MODE_ENV", "RecompileError", "mode", "configure", "active",
    "arm", "disarm", "armed", "sanitized", "begin_dispatch",
    "end_dispatch", "stats", "ledger", "violations", "reset",
]

MODE_ENV = "PINT_TPU_RECOMPILE_SANITIZER"

_MODES = ("off", "warn", "raise")

#: hot-path flag read by the profiling proxy: one attribute load per
#: dispatch when the sanitizer is off.  Kept in sync with _mode by
#: configure()/sanitized().
ACTIVE = False

_lock = threading.RLock()
_tls = threading.local()

_mode = "off"
_armed = False
_armed_note = None
_listener_state = "uninstalled"   # uninstalled | jax.monitoring | fallback

_LEDGER_CAP = 256
_ledger: "deque" = deque(maxlen=_LEDGER_CAP)
_violations: list = []
_VIOLATIONS_CAP = 64

#: program id -> set of arg-spec fingerprints already compiled.
#: LRU-capped like the profiling registry (a long-lived service
#: cycles structures); fingerprints per program capped too — past the
#: cap a program is treated as open-ended (no same-shape verdicts),
#: which only under-reports, never false-positives.
_history: "OrderedDict[str, set]" = OrderedDict()
_HISTORY_CAP = 512
_SPECS_PER_PROGRAM_CAP = 64


class RecompileError(RuntimeError):
    """An armed process compiled, or any process re-compiled a
    program for a spec it had already compiled.  Raised from the
    dispatching call (raise mode) after the underlying computation
    finished — the result of the call is intact, the raise is the
    contract's alarm."""


class _Scope:
    __slots__ = ("label", "key_hash", "compile_s", "n_compiles",
                 "cached")

    def __init__(self, label, key_hash):
        self.label = label
        self.key_hash = key_hash
        self.compile_s = 0.0
        self.n_compiles = 0
        self.cached = False


def _parse_mode(raw) -> str:
    tok = str(raw or "").strip().lower()
    if tok in ("", "0", "off", "none", "false", "disabled"):
        return "off"
    if tok in ("raise", "strict", "fatal"):
        return "raise"
    # "1"/"on"/"true"/"warn"/anything else explicit -> observe mode
    return "warn"


def mode() -> str:
    """The active mode: "off", "warn", or "raise"."""
    return _mode


def active() -> bool:
    return ACTIVE


def _on_duration(event, duration, **kw):
    """The jax.monitoring compile listener.  Registration is
    permanent (jax.monitoring has no deregister), so the mode guard
    lives here: an "off" sanitizer must not count anything — without
    it, every post-sanitized() compile in the process would tick
    sanitizer.unattributed_compiles against a sanitizer that is off."""
    if not ACTIVE or "compil" not in event:
        return
    stack = getattr(_tls, "stack", None)
    scope = stack[-1] if stack else None
    if "backend_compile" in event:
        if scope is not None:
            scope.n_compiles += 1
            scope.compile_s += float(duration)
        else:
            _note_unattributed(float(duration))
    elif "compile_time_saved" in event and scope is not None:
        # the persistent disk cache served this executable:
        # still a registry/trace-cache miss, but cheaper
        scope.cached = True


def _install_listener():
    """Register the compile listener with ``jax.monitoring`` (once).
    When the API is absent the sanitizer degrades to "fallback":
    scopes never see compiles, stats says so, nothing crashes."""
    global _listener_state
    with _lock:
        if _listener_state != "uninstalled":
            return _listener_state
        try:
            from jax import monitoring as _mon

            reg = _mon.register_event_duration_secs_listener
        except Exception:
            _listener_state = "fallback"
            return _listener_state

        try:
            reg(_on_duration)
            _listener_state = "jax.monitoring"
        except Exception:
            _listener_state = "fallback"
        # keep telemetry's own compile counters coherent alongside
        telemetry.compile_stats()
        return _listener_state


def configure(mode=None):
    """Set the sanitizer mode; ``mode=None`` re-resolves the env var.
    Returns the active mode.  Activating installs the jax.monitoring
    listener (graceful fallback when absent)."""
    global _mode, ACTIVE
    with _lock:
        _mode = _parse_mode(os.environ.get(MODE_ENV)
                            if mode is None else mode)
        ACTIVE = _mode != "off"
        if ACTIVE:
            _install_listener()
    return _mode


def arm(note="armed"):
    """Declare the process warm: from here on EVERY compile is a
    violation (warn/raise per mode).  Implies the sanitizer is
    active — an explicit arm() while the mode knob is off enables
    warn mode (the caller asked for watching; off would make arm a
    silent no-op)."""
    global _armed, _armed_note
    with _lock:
        if not ACTIVE:
            configure("warn")
        _armed = True
        _armed_note = str(note)
    telemetry.gauge_set("sanitizer.armed", 1.0)
    telemetry.emit({"type": "sanitizer", "event": "armed",
                    "note": str(note)})
    return True


def disarm():
    global _armed, _armed_note
    with _lock:
        _armed = False
        _armed_note = None
    telemetry.gauge_set("sanitizer.armed", 0.0)


def armed() -> bool:
    return _armed


@contextlib.contextmanager
def sanitized(mode="raise", arm_now=True):
    """Sanitizer forced to ``mode`` (armed by default) inside the
    block, previous state fully restored after — the test/datacheck/
    bench harness entry point."""
    global _mode, ACTIVE, _armed, _armed_note
    with _lock:
        prev = (_mode, ACTIVE, _armed, _armed_note)
    configure(mode)
    if arm_now:
        arm(note="sanitized()")
    try:
        yield
    finally:
        with _lock:
            _mode, ACTIVE, _armed, _armed_note = prev
        telemetry.gauge_set("sanitizer.armed",
                            1.0 if _armed else 0.0)


# --------------------------------------------------------------------------
# the dispatch protocol (called by profiling._ProfiledProgram)
# --------------------------------------------------------------------------

def begin_dispatch(stats):
    """Push a dispatch scope for one profiled-proxy call.  ``stats``
    is the program's :class:`~pint_tpu.profiling.ProgramStats`."""
    scope = _Scope(stats.label, stats.key_hash)
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(scope)
    return scope


def _spec_fingerprint(args, kwargs):
    """Cheap stable fingerprint of a call's abstract argument spec.
    Only computed on the compile path (dispatches that compiled
    nothing never pay it)."""
    try:
        from pint_tpu import profiling

        spec = profiling._arg_spec(args)
        kspec = (profiling._arg_spec(tuple(sorted(kwargs.items())))
                 if kwargs else None)
        return repr((spec, kspec))
    except Exception:
        return None


def end_dispatch(scope, args, kwargs):
    """Pop the scope; classify any compiles it absorbed.  Returns an
    exception instance to raise (raise mode + violation), a warning
    message string (warn mode + violation), or None — the caller
    raises/warns OUTSIDE its finally block so the sanitizer can
    never mask an in-flight exception from the call itself (a
    warnings-as-errors filter may still escalate the warn-mode
    warning after the result computed — the filter's own request)."""
    stack = getattr(_tls, "stack", None)
    if stack:
        try:
            stack.remove(scope)
        except ValueError:
            pass
    if scope.n_compiles == 0 and not scope.cached:
        return None
    # scope.cached with zero backend compiles: the persistent disk
    # cache served a rebuilt executable — still a registry/trace-cache
    # miss (the violation class), just cheaper; classify it like a
    # compile instead of dropping it
    fp = _spec_fingerprint(args, kwargs)
    pid = f"{scope.label}#{scope.key_hash}"
    with _lock:
        hist = _history.get(pid)
        if hist is None:
            hist = _history[pid] = set()
            while len(_history) > _HISTORY_CAP:
                _history.popitem(last=False)
        else:
            _history.move_to_end(pid)
        known = fp is not None and fp in hist
        if fp is not None and not known and \
                len(hist) < _SPECS_PER_PROGRAM_CAP:
            hist.add(fp)
        if known:
            kind = "same_shape_recompile"
        elif len(hist) <= 1:
            kind = "first"
        else:
            kind = "new_shape"
        is_violation = known or _armed
        armed_now, note = _armed, _armed_note
    telemetry.counter_add("sanitizer.compiles", scope.n_compiles)
    record = {
        "type": "sanitizer", "event": "compile",
        "program": scope.label, "key": scope.key_hash, "kind": kind,
        "n_compiles": scope.n_compiles,
        "compile_s": round(scope.compile_s, 6),
        "cache_served": scope.cached,
        "armed": armed_now, "violation": is_violation,
    }
    with _lock:
        _ledger.append(record)
    if not is_violation:
        telemetry.emit(record)
        return None
    telemetry.counter_add("sanitizer.violations")
    if kind == "same_shape_recompile":
        telemetry.counter_add("sanitizer.same_shape_recompiles")
    why = ("recompiled a spec it had already compiled (registry "
           "eviction, key aliasing, or trace-cache invalidation)"
           if kind == "same_shape_recompile" else
           f"compiled while the process was armed ({note})")
    msg = (f"recompile sanitizer: program {scope.label}"
           f"#{scope.key_hash} {why} — {scope.n_compiles} backend "
           f"compile(s), {scope.compile_s:.3f}s"
           + (" (served from the persistent disk cache)"
              if scope.cached else ""))
    record["message"] = msg
    telemetry.emit(record)
    with _lock:
        if len(_violations) < _VIOLATIONS_CAP:
            _violations.append(record)
    if _mode == "raise":
        return RecompileError(msg)
    return msg


def _note_unattributed(seconds):
    """A backend compile with no registry dispatch on this thread:
    eager ops, raw-jit escapes, or jax internals.  Counted always;
    a violation record only while armed (no exception — there is no
    dispatching proxy to raise from)."""
    telemetry.counter_add("sanitizer.unattributed_compiles")
    if not _armed:
        return
    record = {
        "type": "sanitizer", "event": "compile",
        "program": "(unattributed)", "key": "-",
        "kind": "unattributed", "n_compiles": 1,
        "compile_s": round(float(seconds), 6),
        "cache_served": False, "armed": True, "violation": True,
        "message": "recompile sanitizer: backend compile outside "
                   "any registry program while armed — eager op or "
                   "raw-jit escape (run pintlint PTL101)",
    }
    telemetry.counter_add("sanitizer.violations")
    with _lock:
        _ledger.append(record)
        if len(_violations) < _VIOLATIONS_CAP:
            _violations.append(record)
    telemetry.emit(record)
    if _mode != "off":
        # the strictest mode must not be QUIETER than warn: there is
        # no dispatching proxy to raise from, so raise mode warns too.
        # The warn happens inside jax's monitoring listener — swallow
        # a warnings-as-errors escalation rather than break the
        # compile that triggered it.
        try:
            warnings.warn(record["message"], RuntimeWarning,
                          stacklevel=2)
        except Exception:
            pass


# --------------------------------------------------------------------------
# readout
# --------------------------------------------------------------------------

def ledger(tail=None) -> list:
    """The bounded in-memory compile ledger (every attributed compile,
    violation or not), oldest first."""
    with _lock:
        out = list(_ledger)
    return out[-tail:] if tail else out


def violations() -> list:
    with _lock:
        return list(_violations)


def stats() -> dict:
    """One-call readout for /v1/stats, datacheck, and tests."""
    with _lock:
        return {
            "mode": _mode,
            "armed": _armed,
            "armed_note": _armed_note,
            "listener": _listener_state,
            "compiles": int(telemetry.counter_get(
                "sanitizer.compiles")),
            "violations": int(telemetry.counter_get(
                "sanitizer.violations")),
            "same_shape_recompiles": int(telemetry.counter_get(
                "sanitizer.same_shape_recompiles")),
            "unattributed_compiles": int(telemetry.counter_get(
                "sanitizer.unattributed_compiles")),
            "programs_tracked": len(_history),
            "ledger_len": len(_ledger),
        }


def reset():
    """Drop history/ledger/violations and disarm (tests).  Mode and
    listener survive — re-resolve with configure()."""
    global _armed, _armed_note
    with _lock:
        _history.clear()
        _ledger.clear()
        del _violations[:]
        _armed = False
        _armed_note = None
    telemetry.gauge_set("sanitizer.armed", 0.0)


# resolve the env knob at import so harness subprocesses that export
# PINT_TPU_RECOMPILE_SANITIZER before python starts are live without
# any code change; in-process callers use configure()/sanitized()
configure(None)
