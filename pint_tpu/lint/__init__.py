"""pintlint: trace-safety analysis for the shared-jit architecture.

Two halves, one contract.  Everything fast in this repo rests on
traced programs being *shared* — one executable per (structure key x
gate state x mesh layout), reused across fitters, requests, and
processes.  The static half (:mod:`pint_tpu.lint.static`, the
``pintlint`` CLI) proves at review time that source code cannot break
that contract silently: gates ride their keys, nothing bypasses the
registry, traced functions stay free of host reads, telemetry names
stay documented.  The runtime half (:mod:`pint_tpu.lint.sanitizer`,
``$PINT_TPU_RECOMPILE_SANITIZER``) watches the live process for the
failures no static rule can see — an XLA compile in a process that
believed itself warm — and attributes every compile to the program
that caused it.

``static`` is stdlib-only and importable without jax (also loadable
by file path — ``tools/check_jit_gates.py`` does exactly that);
``sanitizer`` needs only :mod:`pint_tpu.telemetry`.  Neither is
imported here eagerly: the profiling hot path imports the sanitizer
directly, and pulling the analyzer into every ``pint_tpu.lint``
import would be dead weight for a serving replica.
"""

__all__ = ["static", "sanitizer"]


def __getattr__(name):
    if name in __all__:
        import importlib

        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(name)
