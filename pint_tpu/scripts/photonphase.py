"""Compute model phases for photon events + pulsation tests
(reference: src/pint/scripts/photonphase.py)."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="photonphase",
        description="Phase-fold photon events with a timing model",
    )
    p.add_argument("eventfile")
    p.add_argument("parfile")
    p.add_argument("--mission", default="nicer")
    p.add_argument("--extname", default=None,
                   help="events extension (default: per-mission, "
                        "usually EVENTS)")
    p.add_argument("--orbfile", default=None,
                   help="FPorbit/FT2 spacecraft orbit file: use real "
                        "orbital geometry instead of the geocenter")
    p.add_argument("--maxh", type=int, default=20,
                   help="max harmonics for the H-test")
    p.add_argument("--outphases", default=None,
                   help="write phases to this .npy")
    p.add_argument("--outfile", default=None,
                   help="write a phased events FITS carrying "
                        "TIME/PULSE_PHASE(/ORBIT_PHASE) columns (a "
                        "compact product, not a full copy of the "
                        "input's columns)")
    p.add_argument("--addorbphase", action="store_true",
                   help="also write an ORBIT_PHASE column (needs a "
                        "binary model)")
    p.add_argument("--plotfile", default=None,
                   help="write a phaseogram to this image file")
    p.add_argument("--binned", action="store_true",
                   help="binned (2-D histogram) phaseogram style")
    p.add_argument("--minMJD", type=float, default=None,
                   help="keep only events at/after this MJD")
    p.add_argument("--maxMJD", type=float, default=None,
                   help="keep only events at/before this MJD")
    p.add_argument("--polycos", action="store_true",
                   help="use generated polycos instead of exact phases")
    args = p.parse_args(argv)

    from pint_tpu.event_toas import load_event_TOAs
    from pint_tpu.eventstats import hm, hmw, sf_hm, sig2sigma
    from pint_tpu.models import get_model

    model = get_model(args.parfile)
    if "TZRMJD" not in model.values and "TZRMJD" not in model.meta:
        raise ValueError(
            "photon phases need an absolute reference: the par file "
            "must carry TZRMJD/TZRSITE/TZRFRQ (AbsPhase; reference "
            "photonphase raises the same way)")
    toas = load_event_TOAs(args.eventfile, args.mission,
                           extname=args.extname,
                           ephem=model.meta.get("EPHEM", "builtin"),
                           orbfile=args.orbfile)
    print(f"Read {len(toas)} events")
    # original FITS row per TOA (the loader may filter/reorder rows);
    # --outfile indexes the raw event table through this, never with a
    # len(toas)-sized boolean mask
    fits_rows = np.asarray(getattr(toas, "fits_rows",
                                   np.arange(len(toas))))
    if args.minMJD is not None or args.maxMJD is not None:
        keep = np.ones(len(toas), dtype=bool)
        mf = np.asarray(toas.mjd_float)
        if args.minMJD is not None:
            keep &= mf >= args.minMJD
        if args.maxMJD is not None:
            keep &= mf <= args.maxMJD
        if not keep.any():
            raise SystemExit(
                f"no events in MJD range [{args.minMJD}, {args.maxMJD}]")
        toas = toas[keep]
        fits_rows = fits_rows[keep]
        print(f"Kept {len(toas)} events in [{args.minMJD}, {args.maxMJD}]")
    if args.polycos:
        if not all(o == "barycenter" for o in toas.obs_names):
            raise SystemExit(
                "--polycos requires barycentered events (TIMEREF="
                "SOLARSYSTEM): polycos are evaluated at the recorded "
                "MJD label, which for geocentric events omits the "
                "Roemer delay entirely — use the exact path instead"
            )
        from pint_tpu.polycos import generate_polycos

        mjds = toas.mjd_float
        pcs = generate_polycos(model, mjds.min() - 0.05,
                               mjds.max() + 0.05, "@")
        phases = pcs.eval_phase(mjds) % 1.0
    else:
        prepared = model.prepare(toas)
        _, frac = prepared.phase()
        phases = np.asarray(frac) % 1.0
    wf = toas.get_flag_values("weight", default=None, astype=float)
    weights = (
        np.array([1.0 if w is None else w for w in wf])
        if any(w is not None for w in wf) else None
    )
    h = hm(phases, m=args.maxh) if weights is None else \
        hmw(phases, weights, m=args.maxh)
    sf = sf_hm(h, m=args.maxh)
    print(f"Htest: {h:.2f} (sf {sf:.3g}, "
          f"~{sig2sigma(max(sf, 1e-300)):.1f} sigma)")
    if args.outphases:
        np.save(args.outphases, phases)
        print(f"wrote {args.outphases}")
    orb_ph = None
    if args.addorbphase:
        from pint_tpu.derived_quantities import orbital_phase

        # raises ValueError without a binary model (reference
        # test_OrbPhase_exception semantics), outfile or not
        orb_ph = orbital_phase(model, toas.ticks)
    if args.outfile:
        from pint_tpu.fits import read_events as _re, write_events
        from pint_tpu.event_toas import _MISSION_EXTNAME, mjdref_from_header

        hdr, dat = _re(args.eventfile, extname=args.extname or
                       _MISSION_EXTNAME.get(args.mission.lower(),
                                            "EVENTS"))
        met = np.asarray(dat["TIME"], np.float64)[fits_rows]
        extra = {"PULSE_PHASE": phases}
        if orb_ph is not None:
            extra["ORBIT_PHASE"] = orb_ph
        refi, reff = mjdref_from_header(hdr)
        write_events(args.outfile, met, mjdref=(refi, reff),
                     timesys=str(hdr.get("TIMESYS", "TT")),
                     timeref=str(hdr.get("TIMEREF", "LOCAL")),
                     timezero=float(hdr.get("TIMEZERO", 0.0)),
                     extra_cols=extra)
        print(f"wrote {args.outfile}")
    if args.plotfile:
        import matplotlib

        matplotlib.use("Agg")
        from pint_tpu.plot_utils import phaseogram, phaseogram_binned

        plot = phaseogram_binned if args.binned else phaseogram
        plot(toas.mjd_float, phases, weights=weights,
             title=f"{args.eventfile}  H={h:.1f}",
             plotfile=args.plotfile)
        print(f"wrote {args.plotfile}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
