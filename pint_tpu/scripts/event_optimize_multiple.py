"""Joint photon-domain MCMC over multiple event datasets
(reference: src/pint/scripts/event_optimize_multiple.py — one timing
model, several event files each with its own template/weights)."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="event_optimize_multiple",
        description="Jointly MCMC-fit timing parameters against the "
                    "photon likelihood of several event datasets",
    )
    p.add_argument("eventfiles",
                   help="text file: one 'eventfile [weightcol]' per line")
    p.add_argument("parfile")
    p.add_argument("--mission", default="nicer")
    p.add_argument("--ngauss", type=int, default=2)
    p.add_argument("--nwalkers", type=int, default=32)
    p.add_argument("--nsteps", type=int, default=500)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("-o", "--outpar", default=None)
    args = p.parse_args(argv)

    from pint_tpu.event_toas import load_event_TOAs
    from pint_tpu.mcmc_fitter import CompositeMCMCFitter
    from pint_tpu.models import get_model
    from pint_tpu.templates import LCFitter, LCGaussian, LCTemplate

    model = get_model(args.parfile)
    toas_list, templates, weights_list = [], [], []
    with open(args.eventfiles) as f:
        specs = [ln.split() for ln in f if ln.strip()
                 and not ln.startswith("#")]
    for spec in specs:
        evt = spec[0]
        wcol = spec[1] if len(spec) > 1 else None
        toas = load_event_TOAs(evt, args.mission, weights=wcol,
                               ephem=model.meta.get("EPHEM", "builtin"))
        print(f"{evt}: {len(toas)} events")
        prepared = model.prepare(toas)
        _, frac = prepared.phase()
        phases = np.asarray(frac) % 1.0
        tpl = LCTemplate(
            [LCGaussian(sigma=0.05, loc=(i + 0.5) / args.ngauss)
             for i in range(args.ngauss)]
        )
        wf = toas.get_flag_values("weight", default=None, astype=float)
        weights = (np.array([1.0 if w is None else w for w in wf])
                   if any(w is not None for w in wf) else None)
        LCFitter(tpl, phases, weights=weights).fit()
        toas_list.append(toas)
        templates.append(tpl)
        weights_list.append(weights)

    fitter = CompositeMCMCFitter(toas_list, model, templates,
                                 weights_list=weights_list)
    lnp = fitter.fit_toas(nwalkers=args.nwalkers, nsteps=args.nsteps,
                          seed=args.seed)
    print(f"max-posterior lnL = {lnp:.2f}")
    for name in fitter.param_names:
        print(f"  {name} = {model.values[name]!r} "
              f"+- {model.params[name].uncertainty}")
    if args.outpar:
        from pint_tpu.models.builder import model_to_parfile

        with open(args.outpar, "w") as f:
            f.write(model_to_parfile(model))
        print(f"wrote {args.outpar}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
