"""Cross-pulsar GW analysis driver: injection -> optimal statistic ->
S/N over a par/tim set (no reference counterpart — the reference has
no cross-pulsar engine at all).

Examples (docs/gw.md):

    # real data: one par+tim per pulsar, template gamma 13/3
    pintgw A.par B.par C.par --tim A.tim B.tim C.tim

    # end-to-end validation: simulate a 16-pulsar array, inject a GWB
    # at 2e-14, recover it with the OS
    pintgw --simulate 16 --ntoa 200 --inject-amp 2e-14 --seed 3

    # systematics triage: monopole/dipole ORFs instead of HD
    pintgw ... --orf monopole
"""

from __future__ import annotations

import argparse
import json

import numpy as np


def _simulated_pairs(n_psr, ntoa, start, duration, error_us, seed,
                     red=""):
    """A synthetic sky-scattered array (deterministic in seed) via the
    shared :func:`pint_tpu.simulation.make_fake_pta` builder.
    ``red``: extra per-pulsar noise par lines — an injection run adds
    an intrinsic red-noise term at the injected spectrum so each
    pulsar's covariance carries the GW auto-power and the OS sigma is
    honest (the docs/gw.md caveat)."""
    from pint_tpu.simulation import make_fake_pta

    return make_fake_pta(n_psr, ntoa, start_mjd=start,
                         duration_days=duration, error_us=error_us,
                         seed=seed, extra_par=red)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="pintgw",
        description="Cross-pulsar GW background analysis: optional "
                    "GWB injection, then the pair-wise optimal "
                    "statistic over the array")
    p.add_argument("parfiles", nargs="*", help="one par file per pulsar")
    p.add_argument("--tim", nargs="*", default=None,
                   help="matching tim files (else TOAs are simulated "
                        "per par with --ntoa/--start/--duration)")
    p.add_argument("--simulate", type=int, default=None, metavar="N",
                   help="ignore parfiles; simulate an N-pulsar "
                        "sky-scattered array")
    p.add_argument("--ntoa", type=int, default=200)
    p.add_argument("--start", type=float, default=53000.0)
    p.add_argument("--duration", type=float, default=3000.0,
                   help="days")
    p.add_argument("--error", type=float, default=1.0,
                   help="simulated TOA uncertainty [us]")
    p.add_argument("--inject-amp", type=float, default=None,
                   help="inject a GWB at this amplitude before the OS "
                        "(linear; negative = log10)")
    p.add_argument("--inject-gamma", type=float, default=13.0 / 3.0)
    p.add_argument("--gamma", type=float, default=13.0 / 3.0,
                   help="OS template spectral index")
    p.add_argument("--nmodes", type=int, default=10)
    p.add_argument("--orf", default="hd",
                   choices=("hd", "monopole", "dipole"))
    p.add_argument("--fit", action="store_true",
                   help="batched WLS-fit every pulsar before the OS")
    p.add_argument("--crn-grid", action="store_true",
                   help="also print a coarse common-process "
                        "likelihood grid over log10 amplitude")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", default=None, metavar="FILE",
                   help="write the full result record as JSON")
    args = p.parse_args(argv)

    from pint_tpu.gw import OptimalStatistic
    from pint_tpu.simulation import add_gwb, gwb_amp_linear

    amp_lin = (gwb_amp_linear(args.inject_amp)
               if args.inject_amp is not None else None)
    # one mode count for injection AND the matched red model, so C_a
    # carries the auto-power of every injected mode (a model narrower
    # than the injection would leak unmodeled power into the OS sigma)
    inj_modes = max(args.nmodes, 15)
    if args.simulate:
        red = ""
        if amp_lin:
            # matched intrinsic red noise: C_a must carry the GW
            # auto-power for the weak-signal sigma to be honest
            red = (f"TNRedAmp {np.log10(amp_lin):.4f}\n"
                   f"TNRedGam {args.inject_gamma:.6f}\n"
                   f"TNRedC {inj_modes}\n")
        pairs = _simulated_pairs(args.simulate, args.ntoa, args.start,
                                 args.duration, args.error, args.seed,
                                 red=red)
    elif args.parfiles:
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_uniform
        from pint_tpu.toa import get_TOAs

        models = [get_model(f) for f in args.parfiles]
        if args.tim:
            if len(args.tim) != len(models):
                p.error(f"{len(models)} par files but "
                        f"{len(args.tim)} tim files")
            toas_list = [
                get_TOAs(t, ephem=m.meta.get("EPHEM", "builtin"))
                for t, m in zip(args.tim, models)
            ]
        else:
            from pint_tpu.simulation import pta_white_noise_seed

            # the make_fake_pta stream convention: disjoint from
            # pta_injection_seed by construction
            toas_list = [
                make_fake_toas_uniform(
                    args.start, args.start + args.duration, args.ntoa,
                    m, obs="@", error_us=args.error, add_noise=True,
                    rng=np.random.default_rng(
                        pta_white_noise_seed(args.seed, i)))
                for i, m in enumerate(models)
            ]
        pairs = list(zip(models, toas_list))
    else:
        p.error("give par files or --simulate N")
    n_psr = len(pairs)
    if n_psr < 2:
        p.error("a cross-correlation analysis needs >= 2 pulsars")

    if amp_lin is not None:
        from pint_tpu.simulation import pta_injection_seed

        add_gwb([t for _, t in pairs], [m for m, _ in pairs],
                amp_lin, gamma=args.inject_gamma,
                rng=pta_injection_seed(args.seed, n_psr),
                nmodes=inj_modes)
        print(f"injected GWB: amp={amp_lin:.3e} "
              f"gamma={args.inject_gamma:.3f}")
        n_no_red = sum(
            1 for m, _ in pairs
            if not any(getattr(c, "category", "") == "pl_red_noise"
                       for c in m.components))
        if n_no_red and amp_lin:
            # the --simulate path adds a matched TNRed* term itself;
            # user par files are never mutated, so say what that
            # means (a null --inject-amp 0 control adds no auto-power
            # — the sigma stays honest and no note fires)
            print(f"note: {n_no_red}/{n_psr} model(s) carry no "
                  "intrinsic red-noise term — their covariance omits "
                  "the injected GW auto-power, so the quoted OS sigma "
                  "is optimistic (docs/gw.md, honest-sigma caveat)")

    if args.fit:
        from pint_tpu.parallel import PTABatch

        batch = PTABatch(pairs)
        batch.fit_wls(maxiter=3)
        os_ = batch.optimal_statistic(nmodes=args.nmodes,
                                      gamma=args.gamma, orf=args.orf)
    else:
        os_ = OptimalStatistic(pairs, nmodes=args.nmodes,
                               gamma=args.gamma, orf=args.orf)
    res = os_.compute()
    print(f"array: {n_psr} pulsars, {os_.n_pairs} pairs, "
          f"{args.nmodes} modes, ORF={args.orf}")
    print(f"optimal statistic: Ahat^2 = {res.ahat2:.4e} "
          f"+/- {res.sigma_ahat2:.4e}")
    print(f"  Ahat = {res.ahat:.4e}  S/N = {res.snr:.2f}")
    rec = {
        "n_pulsars": n_psr,
        "n_pairs": int(os_.n_pairs),
        "nmodes": int(args.nmodes),
        "orf": args.orf,
        "template_gamma": float(args.gamma),
        "ahat2": res.ahat2,
        "sigma_ahat2": res.sigma_ahat2,
        "snr": res.snr,
        "pairs": res.pairs.tolist(),
        "rho": res.rho.tolist(),
        "sig": res.sig.tolist(),
        "orf_vals": res.orf_vals.tolist(),
    }
    if args.inject_amp is not None:
        rec["injected_amp"] = amp_lin
        rec["injected_gamma"] = float(args.inject_gamma)
    if args.crn_grid:
        crn = os_.common_process()
        grid = np.linspace(-16.0, -12.5, 8)
        lnl = crn.lnlike_grid(grid, [args.gamma])[:, 0]
        best = grid[int(np.argmax(lnl))]
        print("common-process lnlike grid (gamma fixed at "
              f"{args.gamma:.3f}):")
        for a, v in zip(grid, lnl):
            mark = " <-- max" if a == best else ""
            print(f"  log10A={a:+.2f}  lnL={v:.2f}{mark}")
        rec["crn_grid"] = {"log10_amp": grid.tolist(),
                           "lnlike": lnl.tolist(),
                           "best_log10_amp": float(best)}
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rec, f, indent=1)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
