"""Compare two par files (reference:
src/pint/scripts/compare_parfiles.py) using TimingModel.compare."""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(prog="compare_parfiles")
    p.add_argument("par1")
    p.add_argument("par2")
    p.add_argument("--sigma", type=float, default=3.0,
                   help="threshold for the '!' marker")
    p.add_argument("--verbosity", default="max",
                   choices=["max", "med", "min"])
    args = p.parse_args(argv)

    from pint_tpu.models import get_model

    m1 = get_model(args.par1)
    m2 = get_model(args.par2)
    print(m1.compare(m2, threshold_sigma=args.sigma,
                     verbosity=args.verbosity))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
