"""Tk interface for pulsar timing (reference: src/pint/scripts/pintk.py)."""

from __future__ import annotations

import argparse
import os


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="pintk", description="Interactive pulsar-timing GUI")
    p.add_argument("parfile")
    p.add_argument("timfile")
    p.add_argument("--ephem", default=None)
    args = p.parse_args(argv)
    if not os.environ.get("DISPLAY") and os.name != "nt":
        raise SystemExit(
            "pintk needs a display ($DISPLAY is not set). The same "
            "operations are scriptable via pint_tpu.pintk.Pulsar.")
    from pint_tpu.pintk.plk import run

    run(args.parfile, args.timfile, ephem=args.ephem)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
