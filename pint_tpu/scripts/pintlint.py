"""``pintlint`` console entry point.

Thin wrapper: the analyzer body lives in
:mod:`pint_tpu.lint.static` (stdlib-only, also loadable by file path
— ``tools/check_jit_gates.py`` and editors do exactly that).  This
module exists so the installed console script resolves through the
package like every other ``pint*`` tool.
"""

from __future__ import annotations

import sys


def main(argv=None):
    from pint_tpu.lint import static

    return static.main(sys.argv[1:] if argv is None else argv)


if __name__ == "__main__":
    raise SystemExit(main())
