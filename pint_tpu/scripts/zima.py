"""Simulate fake TOAs from a timing model (reference:
src/pint/scripts/zima.py)."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="zima", description="Simulate TOAs from a par file"
    )
    p.add_argument("parfile")
    p.add_argument("timfile", help="output .tim")
    p.add_argument("--ntoa", type=int, default=100)
    p.add_argument("--startMJD", type=float, default=56000.0)
    p.add_argument("--duration", type=float, default=400.0,
                   help="days")
    p.add_argument("--obs", default="GBT")
    p.add_argument("--freq", type=float, nargs="+", default=[1400.0])
    p.add_argument("--error", type=float, default=1.0,
                   help="TOA uncertainty [us]")
    p.add_argument("--addnoise", action="store_true")
    p.add_argument("--wideband", action="store_true")
    p.add_argument("--dmerror", type=float, default=1e-4)
    p.add_argument("--seed", type=int, default=None)
    args = p.parse_args(argv)

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform
    from pint_tpu.toa import write_tim

    model = get_model(args.parfile)
    freqs = np.array(args.freq)[np.arange(args.ntoa) % len(args.freq)]
    toas = make_fake_toas_uniform(
        args.startMJD, args.startMJD + args.duration, args.ntoa, model,
        freq_mhz=freqs, obs=args.obs, error_us=args.error,
        add_noise=args.addnoise, wideband=args.wideband,
        dm_error=args.dmerror,
        rng=np.random.default_rng(args.seed),
    )
    write_tim(toas, args.timfile)
    print(f"wrote {len(toas)} simulated TOAs to {args.timfile}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
