"""Simulate fake TOAs from a timing model (reference:
src/pint/scripts/zima.py)."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="zima", description="Simulate TOAs from a par file"
    )
    p.add_argument("parfile")
    p.add_argument("timfile", help="output .tim")
    p.add_argument("--ntoa", type=int, default=100)
    p.add_argument("--startMJD", type=float, default=56000.0)
    p.add_argument("--duration", type=float, default=400.0,
                   help="days")
    p.add_argument("--obs", default="GBT")
    p.add_argument("--freq", type=float, nargs="+", default=[1400.0])
    p.add_argument("--error", type=float, default=1.0,
                   help="TOA uncertainty [us]")
    p.add_argument("--addnoise", action="store_true")
    p.add_argument("--addcorrnoise", action="store_true",
                   help="add a correlated-noise realization from the "
                        "model's ECORR/red/DM noise components")
    p.add_argument("--gwbamp", type=float, default=None,
                   help="inject a GWB realization at this amplitude "
                        "(linear, e.g. 2e-15; a negative value is "
                        "read as log10)")
    p.add_argument("--gwbgamma", type=float, default=13.0 / 3.0,
                   help="GWB spectral index (default 13/3)")
    p.add_argument("--wideband", action="store_true")
    p.add_argument("--dmerror", type=float, default=1e-4)
    p.add_argument("--inputtim", default=None,
                   help="simulate at this tim file's epochs/freqs/"
                        "errors instead of a uniform span")
    p.add_argument("--fuzzdays", type=float, default=0.0,
                   help="jitter the uniform spacing by N(0, fuzzdays)")
    p.add_argument("--multifreq", action="store_true",
                   help="one TOA per --freq value at every epoch")
    p.add_argument("--plot", default=None, metavar="FILE",
                   help="write a residual plot of the simulated TOAs")
    p.add_argument("--seed", type=int, default=None)
    args = p.parse_args(argv)

    from pint_tpu.models import get_model
    from pint_tpu.simulation import (
        make_fake_toas_fromtim,
        make_fake_toas_uniform,
    )
    from pint_tpu.toa import write_tim

    model = get_model(args.parfile)
    rng = np.random.default_rng(args.seed)
    if args.inputtim:
        toas = make_fake_toas_fromtim(
            args.inputtim, model, add_noise=args.addnoise,
            wideband=args.wideband, dm_error=args.dmerror,
            add_correlated=args.addcorrnoise, rng=rng,
        )
    else:
        freqs = (np.asarray(args.freq) if args.multifreq else
                 np.array(args.freq)[np.arange(args.ntoa) % len(args.freq)])
        toas = make_fake_toas_uniform(
            args.startMJD, args.startMJD + args.duration, args.ntoa,
            model, freq_mhz=freqs, obs=args.obs, error_us=args.error,
            add_noise=args.addnoise, wideband=args.wideband,
            dm_error=args.dmerror, fuzz_days=args.fuzzdays,
            multifreq=args.multifreq, add_correlated=args.addcorrnoise,
            rng=rng,
        )
    if args.gwbamp is not None:
        from pint_tpu.simulation import add_gwb

        # a single-pulsar "array": the 1x1 ORF is the pure
        # auto-correlation — a GWB-spectrum red-noise realization
        add_gwb([toas], [model], args.gwbamp, gamma=args.gwbgamma,
                rng=rng)
        print(f"injected GWB realization (amp={args.gwbamp!r}, "
              f"gamma={args.gwbgamma:.3f})")
    write_tim(toas, args.timfile)
    print(f"wrote {len(toas)} simulated TOAs to {args.timfile}")
    if args.plot:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        from pint_tpu.residuals import Residuals

        # nearest tracking pinned: fake TOAs carry no -pn flags, and a
        # TRACK -2 par must not crash the plot (same as zero_residuals)
        r = Residuals(toas, model, track_mode="nearest")
        fig, ax = plt.subplots()
        ax.errorbar(np.asarray(toas.mjd_float),
                    np.asarray(r.time_resids) * 1e6,
                    yerr=np.asarray(r.scaled_errors) * 1e6, fmt=".")
        ax.set_xlabel("MJD")
        ax.set_ylabel("residual [us]")
        fig.savefig(args.plot)
        print(f"wrote {args.plot}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
