"""Phase-fold Fermi LAT photons with weights (reference:
src/pint/scripts/fermiphase.py)."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(prog="fermiphase")
    p.add_argument("eventfile")
    p.add_argument("parfile")
    p.add_argument("--weightcol", default="WEIGHT")
    p.add_argument("--minWeight", type=float, default=0.0,
                   help="drop photons below this weight")
    p.add_argument("--maxh", type=int, default=20,
                   help="max harmonics for the H-test")
    p.add_argument("--outphases", default=None,
                   help="write phases to this .npy")
    p.add_argument("--outfile", default=None,
                   help="write a phased events FITS carrying "
                        "TIME/PULSE_PHASE/WEIGHT columns (a compact "
                        "product, not a full FT1 copy — the reference "
                        "--addphase appends in place)")
    p.add_argument("--plotfile", default=None,
                   help="write a phaseogram image")
    args = p.parse_args(argv)

    from pint_tpu.event_toas import load_Fermi_TOAs
    from pint_tpu.eventstats import hmw, hm, sf_hm, sig2sigma
    from pint_tpu.models import get_model

    model = get_model(args.parfile)
    toas = load_Fermi_TOAs(args.eventfile, weightcolumn=args.weightcol,
                           ephem=model.meta.get("EPHEM", "builtin"))
    print(f"Read {len(toas)} events")
    # original FITS row per TOA (the loader may filter/reorder rows);
    # --outfile indexes the raw event table through this
    fits_rows = np.asarray(getattr(toas, "fits_rows",
                                   np.arange(len(toas))))
    if args.minWeight > 0.0:
        w = np.array(toas.get_flag_values("weight", default=1.0,
                                          astype=float))
        keep = w >= args.minWeight
        toas = toas[keep]
        fits_rows = fits_rows[keep]
        print(f"Kept {len(toas)} events with weight >= {args.minWeight}")
    prepared = model.prepare(toas)
    _, frac = prepared.phase()
    phases = np.asarray(frac) % 1.0
    wf = toas.get_flag_values("weight", default=None, astype=float)
    weights = None
    if any(w is not None for w in wf):
        weights = np.array([1.0 if w is None else w for w in wf])
        h = hmw(phases, weights, m=args.maxh)
    else:
        h = hm(phases, m=args.maxh)
    sf = sf_hm(h, m=args.maxh)
    print(f"Htest: {h:.2f} (sf {sf:.3g}, "
          f"~{sig2sigma(max(sf, 1e-300)):.1f} sigma)")
    if args.outphases:
        np.save(args.outphases, phases)
        print(f"wrote {args.outphases}")
    if args.outfile:
        from pint_tpu.event_toas import mjdref_from_header
        from pint_tpu.fits import read_events, write_events

        hdr, dat = read_events(args.eventfile)
        met = np.asarray(dat["TIME"], np.float64)[fits_rows]
        refi, reff = mjdref_from_header(hdr)
        extra = {"PULSE_PHASE": phases}
        if weights is not None:
            extra["WEIGHT"] = weights
        write_events(args.outfile, met, mjdref=(refi, reff),
                     timesys=str(hdr.get("TIMESYS", "TT")),
                     timeref=str(hdr.get("TIMEREF", "LOCAL")),
                     timezero=float(hdr.get("TIMEZERO", 0.0)),
                     extra_cols=extra)
        print(f"wrote {args.outfile}")
    if args.plotfile:
        import matplotlib

        matplotlib.use("Agg")
        from pint_tpu.plot_utils import phaseogram

        phaseogram(toas.mjd_float, phases, weights=weights,
                   title=f"{args.eventfile}  H={h:.1f}",
                   plotfile=args.plotfile)
        print(f"wrote {args.plotfile}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
