"""Phase-fold Fermi LAT photons with weights (reference:
src/pint/scripts/fermiphase.py)."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(prog="fermiphase")
    p.add_argument("eventfile")
    p.add_argument("parfile")
    p.add_argument("--weightcol", default="WEIGHT")
    p.add_argument("--outphases", default=None)
    args = p.parse_args(argv)

    from pint_tpu.event_toas import load_Fermi_TOAs
    from pint_tpu.eventstats import hmw, hm, sf_hm, sig2sigma
    from pint_tpu.models import get_model

    model = get_model(args.parfile)
    toas = load_Fermi_TOAs(args.eventfile, weightcolumn=args.weightcol,
                           ephem=model.meta.get("EPHEM", "builtin"))
    prepared = model.prepare(toas)
    _, frac = prepared.phase()
    phases = np.asarray(frac) % 1.0
    wf = toas.get_flag_values("weight", default=None, astype=float)
    if any(w is not None for w in wf):
        weights = np.array([1.0 if w is None else w for w in wf])
        h = hmw(phases, weights)
    else:
        h = hm(phases)
    print(f"Htest: {h:.2f} (sf {sf_hm(h):.3g}, "
          f"~{sig2sigma(max(sf_hm(h), 1e-300)):.1f} sigma)")
    if args.outphases:
        np.save(args.outphases, phases)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
