"""Normalize / convert a par file (reference:
src/pint/scripts/convert_parfile.py): round-trip through the model
(canonical aliases, formatting), optionally converting the binary
parameterization or units."""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(prog="convert_parfile")
    p.add_argument("input")
    p.add_argument("-o", "--out", default=None,
                   help="output par (default stdout)")
    p.add_argument("--binary", default=None,
                   help="convert binary model (e.g. ELL1, DD, DDS)")
    p.add_argument("--nharms", type=int, default=None,
                   help="NHARMS to write (ELL1H output only)")
    p.add_argument("--usestigma", action="store_true",
                   help="emit STIGMA instead of H4 (ELL1H output only)")
    p.add_argument("--kom", type=float, default=None,
                   help="longitude of ascending node [deg] (DDK output)")
    p.add_argument("--lossy", action="store_true",
                   help="allow a binary conversion that sheds physics "
                        "the target engine cannot represent (e.g. "
                        "DD->ELL1 drops GAMMA/DR/DTH/A0/B0)")
    p.add_argument("--allow-tcb", action="store_true")
    args = p.parse_args(argv)

    from pint_tpu.models import get_model

    model = get_model(args.input, allow_tcb=args.allow_tcb)
    if args.binary:
        from pint_tpu.binaryconvert import convert_binary

        model = convert_binary(model, args.binary, nharms=args.nharms,
                               use_stigma=args.usestigma,
                               kom_deg=args.kom, lossy=args.lossy)
    text = model.as_parfile()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
