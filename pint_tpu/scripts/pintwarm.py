"""pintwarm: AOT-warm the persistent XLA compilation cache and
export/import serialized executables.

``pintwarm`` ``lower().compile()``s the standard fit shapes (or a real
dataset's shapes via ``--par/--tim``) into the on-disk compilation
cache (:mod:`pint_tpu.compile_cache`), so production processes start
with their fit executables on disk instead of paying a 30-second XLA
compile on the first request.  The offline half of the
compile-amortization story; the online half is the in-process shared
jit registry plus TOA-count bucketing (``--no-bucket`` to warm exact
sizes instead of bucketed ones).

``--export DIR`` additionally serializes the warmed executables
themselves (``compile_cache.export_executables`` — manifest + pickled
PJRT payloads), and ``--import DIR`` pre-loads them in a fresh process
and then runs REAL verification fits over the requested shapes,
reporting the AOT hit and uncached-backend-compile counters — the
zero-retrace cold-start path (docs/compile_cache.md, "AOT executable
serialization").

Examples::

    pintwarm                           # standard WLS+GLS shapes
    pintwarm --toas 500,1000,5000 --kinds gls,downhill_gls
    pintwarm --par J0613.par --tim J0613.tim
    PINT_TPU_CACHE_DIR=/fast/cache pintwarm
    pintwarm --export /fast/aot       # warm + serialize executables
    pintwarm --import /fast/aot       # cold replica: deserialize + verify
"""

from __future__ import annotations

import argparse
import sys

__all__ = ["main"]


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="pintwarm",
        description="Pre-populate the persistent XLA compile cache "
                    "with the standard pulsar-fit shapes")
    p.add_argument("--toas", default="500,1000",
                   help="comma-separated TOA counts to warm "
                        "(default 500,1000; bucketed unless "
                        "--no-bucket)")
    p.add_argument("--kinds", default="wls,gls",
                   help="comma-separated fitter kinds: wls, gls, "
                        "downhill_wls, downhill_gls (default wls,gls)")
    p.add_argument("--cache-dir", default=None,
                   help="persistent cache directory (default "
                        "$PINT_TPU_CACHE_DIR or ~/.cache/pint_tpu/xla)")
    g = p.add_mutually_exclusive_group()
    g.add_argument("--bucket", action="store_true", default=None,
                   dest="bucket",
                   help="warm the geometric-bucket shapes (for "
                        "deployments fitting with bucket=True / "
                        "PINT_TPU_BUCKET_TOAS=1)")
    g.add_argument("--no-bucket", action="store_false", default=None,
                   dest="bucket",
                   help="warm the exact TOA counts (default follows "
                        "$PINT_TPU_BUCKET_TOAS, so warmed shapes match "
                        "what default-configured fits will request)")
    p.add_argument("--par", default=None,
                   help="warm a real dataset's shapes: par file "
                        "(requires --tim)")
    p.add_argument("--tim", default=None,
                   help="tim file for --par")
    p.add_argument("--export", dest="export_dir", metavar="DIR",
                   default=None,
                   help="after warmup, serialize the compiled "
                        "executables to DIR (manifest + payloads) for "
                        "a fresh process to --import")
    p.add_argument("--import", dest="import_dir", metavar="DIR",
                   default=None,
                   help="pre-load serialized executables from DIR, "
                        "then run real verification fits over the "
                        "requested shapes and report the AOT/compile "
                        "counters (instead of compiling)")
    args = p.parse_args(argv)

    if (args.par is None) != (args.tim is None):
        p.error("--par and --tim must be given together")
    if args.export_dir and args.import_dir:
        p.error("--export and --import are mutually exclusive")

    from pint_tpu import compile_cache

    cache = compile_cache.enable_persistent_cache(args.cache_dir)
    if cache:
        print(f"persistent cache: {cache} "
              f"({compile_cache.cache_entries()} entries before warmup)")
    else:
        print("persistent cache DISABLED (unwritable dir or disabled "
              "by env); warming in-process registry only",
              file=sys.stderr)

    kinds = tuple(k.strip() for k in args.kinds.split(",") if k.strip())
    counts = tuple(int(t) for t in args.toas.split(",") if t.strip())

    pairs = None
    if args.par is not None:
        from pint_tpu.models.builder import get_model_and_toas

        model, toas = get_model_and_toas(args.par, args.tim)
        pairs = [(model, toas)]
        print(f"warming {args.par} ({len(toas)} TOAs)")

    bucket = (compile_cache.bucketing_default() if args.bucket is None
              else args.bucket)
    if bucket and not compile_cache.bucketing_default():
        print("note: warming BUCKETED shapes — they serve fits made "
              "with bucket=True or PINT_TPU_BUCKET_TOAS=1",
              file=sys.stderr)

    if args.import_dir:
        return _import_and_verify(args.import_dir, kinds, counts,
                                  bucket, pairs)

    # build each (kind, model, toas) job ONCE: warmup and the export
    # path's dress-rehearsal fits share the same datasets
    jobs = _jobs(kinds, counts, pairs)
    records = compile_cache.warmup(jobs=jobs, bucket=bucket,
                                   progress=print)

    total = sum(r["compile_s"] for r in records)
    print(f"warmed {len(records)} shape(s) in {total:.1f}s of compile")
    if cache:
        print(f"persistent cache: {compile_cache.cache_entries()} "
              "entries after warmup")
    if args.export_dir:
        # dress-rehearsal fits: warmup only lower().compile()s, so the
        # tiny execute-time eager kernels (output conversions etc.)
        # never hit the persistent cache — one real fit per shape
        # leaves the cold replica genuinely zero-uncached-compile
        for kind, model, toas in jobs:
            _make_fitter(kind, model, toas, bucket).fit_toas(maxiter=2)
        out = compile_cache.export_executables(args.export_dir,
                                               progress=print)
        print(f"exported {len(out['exported'])} executable(s) to "
              f"{args.export_dir} "
              f"({len(out['skipped'])} skipped)")
        for label, why in out["skipped"]:
            print(f"  skipped {label}: {why}", file=sys.stderr)
    return 0


def _jobs(kinds, counts, pairs):
    """The (kind, model, toas) triples a warm/verify pass covers."""
    from pint_tpu.compile_cache import _warm_pairs

    out = []
    for kind in kinds:
        if pairs is not None:
            out.extend((kind, m, t) for m, t in pairs)
        else:
            for n in counts:
                model, toas = _warm_pairs(n, kind)
                out.append((kind, model, toas))
    return out


def _make_fitter(kind, model, toas, bucket):
    from pint_tpu import compile_cache

    if bucket:
        toas = compile_cache.pad_toas(toas)
    return compile_cache.fitter_class(kind)(toas, model)


def _import_and_verify(import_dir, kinds, counts, bucket, pairs):
    """The ``--import`` path: deserialize the AOT manifest, then run a
    real fit per requested shape (warmup's lower().compile() would
    bypass the imported executables — only __call__ dispatch serves
    them) and report the served/compile counters.  Exit 0 even when
    entries were rejected: graceful per-entry fallback to retrace is
    the contract, and the printed counters say what happened."""
    import time as _time

    from pint_tpu import compile_cache, telemetry

    telemetry.compile_stats()  # listener before anything compiles
    got = compile_cache.import_executables(import_dir, progress=print)
    print(f"imported {got['loaded']} executable(s) from "
          f"{import_dir} ({len(got['rejected'])} rejected)")
    for label, why in got["rejected"]:
        print(f"  rejected {label}: {why}", file=sys.stderr)

    for kind, model, toas in _jobs(kinds, counts, pairs):
        f = _make_fitter(kind, model, toas, bucket)
        t0 = _time.perf_counter()
        f.fit_toas(maxiter=2)
        print(f"verified {kind} n_toas={len(f.toas)}: first fit "
              f"{_time.perf_counter() - t0:.2f}s")
    cs = telemetry.compile_stats()
    print(f"aot: {cs['aot_hits']} hit(s), {cs['aot_misses']} miss(es),"
          f" {cs['aot_rejects']} reject(s); backend compiles "
          f"{cs['backend_events']} ({cs['uncached_backend_events']} "
          f"uncached, {cs['cache_hits']} disk-cache hit(s))")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
