"""pintwarm: AOT-warm the persistent XLA compilation cache.

``pintwarm`` ``lower().compile()``s the standard fit shapes (or a real
dataset's shapes via ``--par/--tim``) into the on-disk compilation
cache (:mod:`pint_tpu.compile_cache`), so production processes start
with their fit executables on disk instead of paying a 30-second XLA
compile on the first request.  The offline half of the
compile-amortization story; the online half is the in-process shared
jit registry plus TOA-count bucketing (``--no-bucket`` to warm exact
sizes instead of bucketed ones).

Examples::

    pintwarm                           # standard WLS+GLS shapes
    pintwarm --toas 500,1000,5000 --kinds gls,downhill_gls
    pintwarm --par J0613.par --tim J0613.tim
    PINT_TPU_CACHE_DIR=/fast/cache pintwarm
"""

from __future__ import annotations

import argparse
import sys

__all__ = ["main"]


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="pintwarm",
        description="Pre-populate the persistent XLA compile cache "
                    "with the standard pulsar-fit shapes")
    p.add_argument("--toas", default="500,1000",
                   help="comma-separated TOA counts to warm "
                        "(default 500,1000; bucketed unless "
                        "--no-bucket)")
    p.add_argument("--kinds", default="wls,gls",
                   help="comma-separated fitter kinds: wls, gls, "
                        "downhill_wls, downhill_gls (default wls,gls)")
    p.add_argument("--cache-dir", default=None,
                   help="persistent cache directory (default "
                        "$PINT_TPU_CACHE_DIR or ~/.cache/pint_tpu/xla)")
    g = p.add_mutually_exclusive_group()
    g.add_argument("--bucket", action="store_true", default=None,
                   dest="bucket",
                   help="warm the geometric-bucket shapes (for "
                        "deployments fitting with bucket=True / "
                        "PINT_TPU_BUCKET_TOAS=1)")
    g.add_argument("--no-bucket", action="store_false", default=None,
                   dest="bucket",
                   help="warm the exact TOA counts (default follows "
                        "$PINT_TPU_BUCKET_TOAS, so warmed shapes match "
                        "what default-configured fits will request)")
    p.add_argument("--par", default=None,
                   help="warm a real dataset's shapes: par file "
                        "(requires --tim)")
    p.add_argument("--tim", default=None,
                   help="tim file for --par")
    args = p.parse_args(argv)

    if (args.par is None) != (args.tim is None):
        p.error("--par and --tim must be given together")

    from pint_tpu import compile_cache

    cache = compile_cache.enable_persistent_cache(args.cache_dir)
    if cache:
        print(f"persistent cache: {cache} "
              f"({compile_cache.cache_entries()} entries before warmup)")
    else:
        print("persistent cache DISABLED (unwritable dir or disabled "
              "by env); warming in-process registry only",
              file=sys.stderr)

    kinds = tuple(k.strip() for k in args.kinds.split(",") if k.strip())
    counts = tuple(int(t) for t in args.toas.split(",") if t.strip())

    pairs = None
    if args.par is not None:
        from pint_tpu.models.builder import get_model_and_toas

        model, toas = get_model_and_toas(args.par, args.tim)
        pairs = [(model, toas)]
        print(f"warming {args.par} ({len(toas)} TOAs)")

    bucket = (compile_cache.bucketing_default() if args.bucket is None
              else args.bucket)
    if bucket and not compile_cache.bucketing_default():
        print("note: warming BUCKETED shapes — they serve fits made "
              "with bucket=True or PINT_TPU_BUCKET_TOAS=1",
              file=sys.stderr)
    records = compile_cache.warmup(
        toa_counts=counts, kinds=kinds, bucket=bucket,
        progress=print, pairs=pairs)

    total = sum(r["compile_s"] for r in records)
    print(f"warmed {len(records)} shape(s) in {total:.1f}s of compile")
    if cache:
        print(f"persistent cache: {compile_cache.cache_entries()} "
              "entries after warmup")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
