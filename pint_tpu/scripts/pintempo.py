"""Fit a timing model to TOAs — the tempo/tempo2 workalike CLI
(reference: src/pint/scripts/pintempo.py)."""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="pintempo",
        description="Fit a pulsar timing model (par) to TOAs (tim)",
    )
    p.add_argument("parfile")
    p.add_argument("timfile")
    p.add_argument("--outfile", "-o", default=None,
                   help="write post-fit par here")
    p.add_argument("--fit", action="store_true", default=True)
    p.add_argument("--nofit", dest="fit", action="store_false")
    p.add_argument("--gls", action="store_true",
                   help="force the GLS fitter")
    p.add_argument("--plotfile", default=None,
                   help="write a pre/post-fit residual plot (png)")
    p.add_argument("--allow-tcb", action="store_true")
    p.add_argument("--profile", action="store_true",
                   help="print a named-stage wall-time table (reference "
                        "profiling/high_level_benchmark.py stages)")
    args = p.parse_args(argv)

    from pint_tpu.fitter import Fitter, GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.observability import StageTimer
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import get_TOAs

    stages = StageTimer()
    with stages("Construct model"):
        model = get_model(args.parfile, allow_tcb=args.allow_tcb)
    from pint_tpu.models.builder import planets_requested

    planets = planets_requested(model)
    with stages("Load TOAs"):
        toas = get_TOAs(args.timfile,
                        ephem=model.meta.get("EPHEM", "builtin"),
                        planets=planets)
    print(f"Read {len(toas)} TOAs; model "
          f"{model.meta.get('PSR', args.parfile)}")
    with stages("Prefit residuals"):
        r_pre = Residuals(toas, model)
        chi2_pre = float(r_pre.chi2)
    print(f"Prefit  RMS {r_pre.rms_weighted() * 1e6:12.4f} us  "
          f"chi2 {chi2_pre:.2f}")
    if args.fit:
        fitter = (GLSFitter(toas, model) if args.gls
                  else Fitter.auto(toas, model))
        with stages("Fit"):
            fitter.fit_toas()
        print(fitter.get_summary())
        rms_us = fitter.resids.rms_weighted() * 1e6
        print(model.get_derived_params(rms_us=rms_us, ntoas=len(toas)))
    if args.plotfile:
        with stages("Plot"):
            _plot(toas, model, r_pre, args.plotfile)
    if args.outfile:
        with open(args.outfile, "w") as f:
            f.write(model.as_parfile())
        print(f"wrote {args.outfile}")
    if args.profile:
        stages.report()
    return 0


def _plot(toas, model, r_pre, path):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from pint_tpu.residuals import Residuals

    r_post = Residuals(toas, model)
    fig, axes = plt.subplots(2, 1, sharex=True, figsize=(8, 6))
    for ax, r, label in ((axes[0], r_pre, "prefit"),
                         (axes[1], r_post, "postfit")):
        ax.errorbar(toas.mjd_float, r.time_resids * 1e6,
                    yerr=r.scaled_errors * 1e6, fmt=".", ms=3)
        ax.set_ylabel(f"{label} resid [us]")
    axes[1].set_xlabel("MJD")
    fig.savefig(path, dpi=120)


if __name__ == "__main__":
    raise SystemExit(main())
