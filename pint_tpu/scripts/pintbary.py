"""Quick barycentering of times (reference:
src/pint/scripts/pintbary.py): convert topocentric UTC MJDs to
barycentric (SSB TDB) MJDs for given sky coordinates."""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="pintbary", description="Barycenter times quickly"
    )
    p.add_argument("time", nargs="+", help="UTC MJD(s)")
    p.add_argument("--obs", default="GBT")
    p.add_argument("--ra", required=True,
                   help='e.g. "12:13:14.2"')
    p.add_argument("--dec", required=True,
                   help='e.g. "-20:21:22.2"')
    p.add_argument("--ephem", default="builtin")
    p.add_argument("--freq", type=float, default=0.0,
                   help="MHz (0 = infinite frequency)")
    p.add_argument("--dm", type=float, default=0.0)
    args = p.parse_args(argv)

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.time.mjd import ticks_to_mjd_string_tdb
    from pint_tpu.toa import TOA, TOAs
    from pint_tpu.time.mjd import mjd_string_to_day_frac

    par = (
        f"PSR BARY\nRAJ {args.ra}\nDECJ {args.dec}\nF0 1.0\n"
        f"PEPOCH 55000\nDM {args.dm}\nEPHEM {args.ephem}\n"
    )
    model = get_model(par)
    toa_list = []
    for s in args.time:
        d, n, den = mjd_string_to_day_frac(s)
        toa_list.append(
            TOA(d, n, den, 0.0, args.freq or 0.0, args.obs, {}, "bary")
        )
    toas = TOAs(toa_list, ephem=args.ephem)
    prepared = model.prepare(toas)
    delay = np.asarray(prepared.delay())
    for i in range(len(toas)):
        bat_ticks = int(toas.ticks[i]) - int(round(delay[i] * 2**32))
        print(ticks_to_mjd_string_tdb(bat_ticks, 13))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
