"""Generate a LaTeX timing summary (reference:
src/pint/scripts/pintpublish.py)."""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(prog="pintpublish")
    p.add_argument("parfile")
    p.add_argument("timfile", nargs="?", default=None)
    p.add_argument("-o", "--out", default=None)
    p.add_argument("--fit", action="store_true",
                   help="re-fit before publishing")
    args = p.parse_args(argv)

    from pint_tpu.models import get_model
    from pint_tpu.output.publish import publish

    model = get_model(args.parfile)
    toas = None
    if args.timfile:
        from pint_tpu.toa import get_TOAs

        toas = get_TOAs(args.timfile,
                        ephem=model.meta.get("EPHEM", "builtin"))
        if args.fit:
            from pint_tpu.fitter import Fitter

            Fitter.auto(toas, model).fit_toas()
    text = publish(model, toas)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
