"""Photon-domain MCMC optimization of timing parameters against a
light-curve template (reference: src/pint/scripts/event_optimize.py,
1033 LoC driving emcee; here the whole posterior is one jitted device
program driven by the JAX ensemble sampler)."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(prog="event_optimize")
    p.add_argument("eventfile")
    p.add_argument("parfile")
    p.add_argument("--mission", default="nicer")
    p.add_argument("--weightcol", default=None,
                   help="photon-weight column (default WEIGHT for "
                   "fermi, none otherwise)")
    p.add_argument("--template", default=None,
                   help="template file (# gauss / # fourier / # kernel "
                   "header, reference prim_io formats); default: fit a "
                   "--ngauss gaussian seed template to the folded phases")
    p.add_argument("--ngauss", type=int, default=2,
                   help="gaussian components for the seed template")
    p.add_argument("--minWeight", type=float, default=0.0,
                   help="drop photons with -weight below this "
                        "(reference event_optimize minWeight)")
    p.add_argument("--nwalkers", type=int, default=32)
    p.add_argument("--nsteps", type=int, default=500)
    p.add_argument("--burnin", type=int, default=None,
                   help="steps discarded before uncertainty estimation "
                        "(default nsteps/4)")
    p.add_argument("--autocorr", action="store_true",
                   help="sample in chunks until the emcee convergence "
                        "criterion (chain > 50 tau, tau stable), with "
                        "--nsteps as the cap (reference "
                        "run_sampler_autocorr)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--fit-template", action="store_true",
                   help="sample template parameters jointly with the "
                        "timing parameters (reference MCMCFitter "
                        "template fitkeys)")
    p.add_argument("--outtemplate", default=None,
                   help="write the post-fit template here (with "
                        "--fit-template: the jointly-sampled "
                        "max-posterior template)")
    p.add_argument("-o", "--outpar", default=None)
    args = p.parse_args(argv)

    from pint_tpu.event_toas import load_event_TOAs
    from pint_tpu.mcmc_fitter import MCMCFitter
    from pint_tpu.models import get_model
    from pint_tpu.templates import (
        LCFitter,
        LCGaussian,
        LCTemplate,
        read_template,
    )

    model = get_model(args.parfile)
    weightcol = args.weightcol or (
        "WEIGHT" if args.mission.lower() == "fermi" else None
    )
    toas = load_event_TOAs(args.eventfile, args.mission,
                           weights=weightcol,
                           ephem=model.meta.get("EPHEM", "builtin"))
    print(f"Read {len(toas)} events")
    if args.minWeight > 0.0:
        w = np.array(toas.get_flag_values("weight", default=1.0,
                                          astype=float))
        toas = toas[w >= args.minWeight]
        print(f"Kept {len(toas)} events with weight >= {args.minWeight}")
    if args.template:
        template = read_template(args.template)
    else:
        # seed template from the folded profile at the initial parameters
        prepared = model.prepare(toas)
        _, frac = prepared.phase()
        phases = np.asarray(frac) % 1.0
        template = LCTemplate(
            [LCGaussian(sigma=0.05, loc=(i + 0.5) / args.ngauss)
             for i in range(args.ngauss)]
        )
        LCFitter(template, phases).fit()
    fitter = MCMCFitter(toas, model, template,
                        fit_template=args.fit_template)
    if args.nsteps <= 0:
        raise SystemExit("--nsteps must be positive")
    if args.burnin is not None and not 0 <= args.burnin < args.nsteps:
        raise SystemExit(
            f"--burnin must be in [0, nsteps={args.nsteps})")
    lnp = fitter.fit_toas(nwalkers=args.nwalkers, nsteps=args.nsteps,
                          seed=args.seed, burnin=args.burnin,
                          autocorr=args.autocorr)
    if args.autocorr:
        print("converged:", fitter.converged,
              "tau:", np.array2string(np.asarray(fitter.tau),
                                      precision=1))
    print(f"max-posterior lnL = {lnp:.2f}")
    for name in fitter.param_names:
        print(f"  {name} = {model.values[name]!r} "
              f"+/- {model.params[name].uncertainty:.3g}")
    if args.outpar:
        with open(args.outpar, "w") as f:
            f.write(model.as_parfile())
        print(f"wrote {args.outpar}")
    if args.outtemplate:
        from pint_tpu.templates import write_template

        write_template(template, args.outtemplate)
        print(f"wrote {args.outtemplate}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
