"""Summarize, export, and gate on pint_tpu telemetry/bench records.

Eight modes:

- ``pinttrace trace.jsonl`` — aggregate the records written by
  :mod:`pint_tpu.telemetry` (``PINT_TPU_TRACE=trace.jsonl``): spans by
  name (count/total/mean/max), final counter/gauge/histogram values,
  and any benchmark metric records routed through the same sink.
- ``pinttrace --chrome-trace out.json trace.jsonl [more.jsonl ...]``
  — export the span tree as Chrome ``trace_event`` JSON (load in
  Perfetto / ``chrome://tracing``): spans become complete ("X")
  duration events with nesting preserved, metrics become instant
  events.  The serve plane's ``trace_span`` records render as
  per-request tracks keyed by trace id, with each batched device
  call drawn once and fanned into its requests via flow arrows;
  extra trace paths (one sink per replica) land in separate process
  lanes.
- ``pinttrace --programs trace.jsonl`` — the per-program registry
  table (``{"type": "program"}`` records the profiling layer mirrors
  on flush): key, calls, compiles, device-time p50/p99, bytes.
- ``pinttrace --check-regression [BENCH_r*.json ...]`` — the
  perf-regression sentinel: reads a bench-round trajectory, compares
  each metric's latest value against its best non-fallback record
  (``--tolerance``), flags trailing ``cpu-fallback``/failed-round
  streaks (``--streak``) and metrics that vanished from the latest
  round, and exits nonzero on any flag so CI and the bench parent can
  gate on it.
- ``pinttrace --runs trace.jsonl`` — the run ledger: every record
  tagged with a ``run_id`` (spans, iteration traces, guard
  health/rung records, AOT events, bench metric rows) joined per run,
  one row per fit/grid/MCMC/bench entry with its duration, status,
  compile/AOT deltas, programs, serving rung, and record-type census.
- ``pinttrace --convergence RUN_ID trace.jsonl`` — the flight
  recorder's per-iteration chi^2 / step-norm / guard-eps table for
  one run's ``iter_trace`` records (omit RUN_ID for all of them).
- ``pinttrace --sanitizer trace.jsonl`` — the recompile-sanitizer
  story (``{"type": "sanitizer"}`` records, docs/lint.md): which
  programs compiled, classified first / new-shape /
  same-shape-recompile / unattributed, and every violation an armed
  process recorded.
- ``pinttrace --fleet host:port,host:port,...`` — scrape N live
  replicas' ``/metrics`` + ``/slo`` endpoints and print ONE merged
  fleet snapshot: counters summed, SLO histogram windows merged
  bucket-wise with the quantiles recomputed over the merge, verdict
  worst-of across replicas (:mod:`pint_tpu.obs.fleet`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["summarize", "chrome_trace", "programs_table",
           "check_regression", "runs_table", "convergence_table",
           "sanitizer_table", "main"]


def _load(path):
    """Parse a JSONL trace; returns (records, n_bad)."""
    records, n_bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                n_bad += 1
    return records, n_bad


def aggregate(records):
    """Aggregate parsed trace records: returns (spans, counters,
    gauges, metrics, n_other) where spans maps name ->
    [count, total_s, max_s, max_depth].  The ONE aggregation both the
    table and --json outputs are built from."""
    spans: dict = {}
    counters: dict = {}
    gauges: dict = {}
    metrics = []
    other = 0
    for rec in records:
        kind = rec.get("type")
        if kind == "span":
            st = spans.setdefault(rec.get("name", "?"), [0, 0.0, 0.0, 0])
            dur = float(rec.get("dur_s", 0.0))
            st[0] += 1
            st[1] += dur
            st[2] = max(st[2], dur)
            st[3] = max(st[3], int(rec.get("depth", 0)))
        elif kind == "counter":
            # flushes repeat cumulative values; last one wins
            counters[rec.get("name", "?")] = rec.get("value")
        elif kind == "gauge":
            gauges[rec.get("name", "?")] = rec.get("value")
        elif kind == "hist":
            # expose the percentile readout through the gauge table
            name = rec.get("name", "?")
            for k in ("p50", "p95", "p99", "n"):
                gauges[f"hist.{name}.{k}"] = rec.get(k)
        elif kind in ("program", "sink_rotation", "flops_mismatch",
                      "run", "iter_trace", "health", "aot",
                      "guard_trip", "guard_rung", "aot_demotion",
                      "sanitizer", "trace_span"):
            other += 1  # aggregated by their dedicated consumers
        elif kind == "metric" or "metric" in rec:
            metrics.append(rec)
        else:
            other += 1
    return spans, counters, gauges, metrics, other


def summarize(records):
    """Aggregate parsed trace records into report lines."""
    spans, counters, gauges, metrics, other = aggregate(records)

    from pint_tpu.telemetry import render_stats_lines

    lines = [f"{len(records)} records: "
             f"{sum(s[0] for s in spans.values())} spans "
             f"({len(spans)} distinct), {len(counters)} counters, "
             f"{len(gauges)} gauges, {len(metrics)} metrics"
             + (f", {other} other" if other else "")]
    lines.extend(render_stats_lines(spans, counters, gauges))
    for rec in metrics:
        name = rec.get("metric", "?")
        parts = [f"metric {name} = {rec.get('value')!r}"]
        for key in ("backend", "compile_s", "phase_s", "flops",
                    "vs_baseline"):
            if rec.get(key) is not None:
                parts.append(f"{key}={rec[key]!r}")
        lines.append(" ".join(parts))
    return lines


# --------------------------------------------------------------------------
# --chrome-trace: trace_event JSON export
# --------------------------------------------------------------------------

#: pid for the request-scoped tracks (one Perfetto "process" lane per
#: replica file, offset so they never collide with the ordinary span
#: tracks, which use pid = 1 + replica)
_TRACE_PID_BASE = 100


def _flow_id(dev_span, trace_id):
    """Stable numeric flow-event id for one (device span, request)
    edge of the batch fan-out."""
    return (int(str(dev_span)[:12] or "0", 16)
            ^ int(str(trace_id)[:12] or "0", 16)) & 0x7FFFFFFF


def _trace_span_events(rec, tids, metas, replica):
    """Chrome events for one ``trace_span`` record: request spans land
    on a per-trace-id track; the shared device span lands on a
    ``batches`` track with a flow-event edge ("s" -> "f") to every
    request it served, so Perfetto draws the 1-device-span ->
    N-request-spans fan-out as arrows.  Request spans additionally
    expand their phase decomposition (queue/coalesce/build/device/
    writeback) as child slices on their own track."""
    pid = _TRACE_PID_BASE + replica
    if pid not in metas:
        metas[pid] = [{"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": "serve requests"
                                          + (f" (replica {replica})"
                                             if replica else "")}},
                      {"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": 1, "args": {"name": "batches"}}]
    ts = float(rec.get("ts", 0.0)) * 1e6
    dur = float(rec.get("dur_s", 0.0)) * 1e6
    events = []
    if rec.get("name") == "serve.batch.device":
        # the shared span: one slice on the batches track + one flow
        # start per linked request
        events.append({"name": "serve.batch.device", "cat": "trace",
                       "ph": "X", "ts": ts, "dur": dur, "pid": pid,
                       "tid": 1,
                       "args": {k: rec[k] for k in
                                ("span", "op", "run", "bucket",
                                 "occupancy", "size", "programs")
                                if rec.get(k) is not None}})
        for link in rec.get("links") or ():
            events.append({"name": "batch", "cat": "trace",
                           "ph": "s", "ts": ts, "pid": pid, "tid": 1,
                           "id": _flow_id(rec.get("span"),
                                          link.get("trace"))})
        return events
    # request span: own track keyed by trace id
    trace_id = str(rec.get("trace") or rec.get("span") or "?")
    key = (pid, trace_id)
    if key not in tids:
        tids[key] = 16 + len(tids)
        metas[pid].append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tids[key],
                           "args": {"name": f"req {trace_id[:8]}"}})
    tid = tids[key]
    args = {k: rec[k] for k in ("trace", "span", "op", "run",
                                "dataset", "status")
            if rec.get(k) is not None}
    events.append({"name": rec.get("name", "serve.request"),
                   "cat": "trace", "ph": "X", "ts": ts, "dur": dur,
                   "pid": pid, "tid": tid, "args": args})
    # phase decomposition as child slices (time containment on the
    # same track renders them nested under the request slice)
    t = ts
    for phase in ("queue", "coalesce", "build", "device",
                  "writeback"):
        ph_s = (rec.get("phase_s") or {}).get(phase)
        if ph_s is None:
            continue
        events.append({"name": phase, "cat": "trace.phase",
                       "ph": "X", "ts": t, "dur": float(ph_s) * 1e6,
                       "pid": pid, "tid": tid, "args": {}})
        t += float(ph_s) * 1e6
    # flow finish binding this request back to its device span
    for link in rec.get("links") or ():
        if link.get("span"):
            events.append({"name": "batch", "cat": "trace",
                           "ph": "f", "bp": "e",
                           "ts": ts + max(dur, 1.0), "pid": pid,
                           "tid": tid,
                           "id": _flow_id(link["span"], trace_id)})
    return events


def chrome_trace(records) -> dict:
    """Convert span/metric records into Chrome ``trace_event`` format
    (the JSON-object form: {"traceEvents": [...]}).

    Spans map to complete ("X") duration events with ``ts``/``dur`` in
    microseconds; the viewer reconstructs nesting from time
    containment on a track, which the recorded wall-clock enter time
    and duration preserve exactly (depth/parent ride along in
    ``args``).  Metric records become instant ("i") events.  Counter
    flushes become counter ("C") samples so cumulative counters plot
    as time series.  ``trace_span`` records (the serve plane's
    request-scoped tracing, docs/serving.md) render as per-request
    tracks keyed by trace id with the shared batched device call as
    one slice fanning into its requests via flow arrows; records from
    multiple replica files (multi-path load annotates ``_replica``)
    land in separate process lanes."""
    events = []
    tids: dict = {}    # (pid, trace_id) -> tid for request tracks
    metas: dict = {}   # pid -> metadata events (lazily created)
    for rec in records:
        kind = rec.get("type")
        replica = int(rec.get("_replica", 0))
        if kind == "trace_span":
            events.extend(_trace_span_events(rec, tids, metas,
                                             replica))
        elif kind == "span":
            ts = float(rec.get("ts", 0.0))
            dur = float(rec.get("dur_s", 0.0))
            ev = {
                "name": rec.get("name", "?"),
                "cat": "span",
                "ph": "X",
                "ts": ts * 1e6,
                "dur": dur * 1e6,
                "pid": 1 + replica,
                # span nesting is per-thread; one track per thread so
                # concurrent spans can't garble time-containment
                # (records from before the tid field land on track 1)
                "tid": int(rec.get("tid", 1)),
            }
            args = dict(rec.get("attrs") or {})
            args["depth"] = rec.get("depth", 0)
            if rec.get("parent"):
                args["parent"] = rec["parent"]
            if rec.get("error"):
                args["error"] = rec["error"]
            ev["args"] = args
            events.append(ev)
        elif kind == "metric" or "metric" in rec:
            ts = float(rec.get("ts", 0.0))
            events.append({
                "name": f"metric:{rec.get('metric', '?')}",
                "cat": "metric",
                "ph": "i",
                "s": "g",
                "ts": ts * 1e6,
                "pid": 1,
                "tid": 1,
                "args": {"value": rec.get("value"),
                         "backend": rec.get("backend")},
            })
        elif kind == "counter":
            events.append({
                "name": rec.get("name", "?"),
                "cat": "counter",
                "ph": "C",
                "ts": float(rec.get("ts", 0.0)) * 1e6,
                "pid": 1,
                "args": {"value": rec.get("value")},
            })
    events.sort(key=lambda e: e["ts"])
    head = [m for pid in sorted(metas) for m in metas[pid]]
    return {"traceEvents": head + events, "displayTimeUnit": "ms"}


# --------------------------------------------------------------------------
# --programs: per-program registry table from trace records
# --------------------------------------------------------------------------

def programs_table(records):
    """Table lines for the ``{"type": "program"}`` records in a trace.
    Program records are cumulative flush mirrors, so the LAST record
    per (label, key) wins."""
    progs: dict = {}
    for rec in records:
        if rec.get("type") == "program":
            progs[(rec.get("label", "?"), rec.get("key", "?"))] = rec
    from pint_tpu.profiling import table_lines

    return table_lines(list(progs.values()))


# --------------------------------------------------------------------------
# --runs / --convergence: the run ledger
# --------------------------------------------------------------------------

def join_runs(records) -> dict:
    """Group every run-tagged record by ``run_id`` — the ONE join both
    ``--runs`` and the datacheck smoke read.  Returns run_id ->
    {"types": {type: count}, "run": <run record or None>, "rung",
    "n_iter", "programs", "metrics", "spans"} in first-seen order."""
    runs: dict = {}
    for rec in records:
        rid = rec.get("run")
        if rid is None:
            continue
        info = runs.setdefault(rid, {
            "types": {}, "run": None, "rung": None, "n_iter": 0,
            "programs": [], "metrics": [], "spans": 0,
        })
        kind = rec.get("type") or ("metric" if "metric" in rec
                                   else "?")
        info["types"][kind] = info["types"].get(kind, 0) + 1
        if kind == "run":
            info["run"] = rec
            for p in rec.get("programs", ()):
                if p not in info["programs"]:
                    info["programs"].append(p)
        elif kind == "span":
            info["spans"] += 1
        elif kind == "iter_trace":
            info["n_iter"] += int(rec.get("n_iter", 0))
        elif kind == "health":
            info["rung"] = rec.get("rung")
        elif kind == "metric":
            info["metrics"].append(rec.get("metric"))
    return runs


def runs_table(records):
    """Table lines for ``--runs``: one row per run id, joined over
    every record type that carried the tag, plus a detail line naming
    the programs/compile deltas/fingerprint so one fit reconstructs
    end to end."""
    runs = join_runs(records)
    if not runs:
        return ["(no run-tagged records — run with a PINT_TPU_TRACE "
                "sink on a pint_tpu >= PR 10 build)"]
    lines = [
        f"{'RUN':<18s} {'KIND':<12s} {'DUR_S':>8s} {'STATUS':<8s} "
        f"{'RUNG':<10s} {'ITERS':>5s} {'SPANS':>5s} RECORD_TYPES"
    ]
    for rid, info in runs.items():
        run = info["run"] or {}
        types = ",".join(f"{k}:{v}"
                         for k, v in sorted(info["types"].items()))
        dur = run.get("dur_s")
        lines.append(
            f"{rid:<18s} {str(run.get('kind', '?')):<12s} "
            f"{(f'{dur:.3f}' if dur is not None else '-'):>8s} "
            f"{str(run.get('status', '?')):<8s} "
            f"{str(info['rung'] or '-'):<10s} "
            f"{info['n_iter']:>5d} {info['spans']:>5d} {types}")
        details = []
        attrs = run.get("attrs") or {}
        if attrs.get("fingerprint"):
            details.append(f"fingerprint={attrs['fingerprint']}")
        if run.get("compile"):
            details.append("compile=" + ",".join(
                f"{k}:{int(v)}" for k, v in
                sorted(run["compile"].items())))
        if run.get("phase_s"):
            ph = run["phase_s"]
            details.append(
                "phase_s=trace:%.3f,dispatch:%.3f,device:%.3f"
                % (ph.get("trace_s", 0), ph.get("dispatch_s", 0),
                   ph.get("device_s", 0)))
        if info["programs"]:
            details.append("programs=" + ",".join(info["programs"]))
        if info["metrics"]:
            details.append("metrics=" + ",".join(
                str(m) for m in info["metrics"]))
        if details:
            lines.append("  " + " ".join(details))
    return lines


def convergence_table(records, run_id=None):
    """Table lines for ``--convergence``: each ``iter_trace`` record
    (optionally restricted to one run) rendered as a per-iteration
    chi^2 / step-norm / max-|dparam| / guard-eps / ok / rung table —
    batched (grid/PTA) records carry their cross-batch reductions
    (median chi^2, max norms, bad-member count)."""
    recs = [r for r in records if r.get("type") == "iter_trace"
            and (not run_id or r.get("run") == run_id)]
    if not recs:
        where = f" for run {run_id}" if run_id else ""
        return [f"(no iteration-trace records{where} — set "
                "PINT_TPU_ITER_TRACE=1 and a PINT_TPU_TRACE sink)"]
    lines = []
    for rec in recs:
        head = (f"{rec.get('program', '?')} (kind={rec.get('kind')}"
                + (f", run={rec['run']}" if rec.get("run") else ""))
        for k in ("n_points", "n_pulsars", "n_toa"):
            if rec.get(k) is not None:
                head += f", {k}={rec[k]}"
        lines.append(head + ")")
        batched = any("n_bad" in e for e in rec.get("iters", ()))
        hdr = (f"  {'ITER':>4s} {'CHI2':>14s} {'STEP_NORM':>11s} "
               f"{'MAX_DPAR':>11s} {'GUARD_EPS':>9s} {'OK':>3s} "
               f"{'RUNG':<11s}")
        if batched:
            hdr += f" {'N_BAD':>5s} {'CHI2_MIN':>12s} {'CHI2_MAX':>12s}"
        lines.append(hdr)
        for e in rec.get("iters", ()):
            row = (f"  {e.get('i', '?'):>4} {e.get('chi2', 0):>14.6g} "
                   f"{e.get('step_norm', 0):>11.4g} "
                   f"{e.get('max_dpar', 0):>11.4g} "
                   f"{e.get('guard_eps', 0):>9.2g} "
                   f"{('yes' if e.get('ok') else 'NO'):>3s} "
                   f"{str(e.get('rung', '-')):<11s}")
            if batched:
                row += (f" {e.get('n_bad', 0):>5d} "
                        f"{e.get('chi2_min', float('nan')):>12.6g} "
                        f"{e.get('chi2_max', float('nan')):>12.6g}")
            lines.append(row)
        if rec.get("rungs"):
            lines.append("  per-member rungs: " + ", ".join(
                f"{k}->{v}" for k, v in sorted(rec["rungs"].items())))
    return lines


# --------------------------------------------------------------------------
# --check-regression: the perf-regression sentinel
# --------------------------------------------------------------------------

#: metrics where a SMALLER value is better (everything else in the
#: suite is a rate).  cold_replica_warm_s is the serving twin of
#: cold_start_s: fresh pintserve replica, AOT import -> first served
#: fit over HTTP.  slo_p99_ms is the served-stream p99 latency as the
#: SLO engine measures it (bench records it from the same span
#: records /slo reads); trace_overhead_pct is the A/B cost of span
#: emission on the serve path, gated with absolute slack exactly like
#: guard_overhead because it jitters about 0 on a quiet host.
_LOWER_IS_BETTER = {"guard_overhead", "profile_overhead",
                    "cold_start_s", "cold_replica_warm_s",
                    "slo_p99_ms", "trace_overhead_pct",
                    # seconds with zero ready replicas during a
                    # rolling deploy (pint_tpu/fleet): 0 is the
                    # zero-downtime claim
                    "rolling_deploy_downtime_s",
                    # median steady-state streaming append+refit
                    # latency (docs/streaming.md): a regression here
                    # means the rank-k path got slower or fell off
                    # the incremental path entirely
                    "append_latency_ms"}

#: the suite's known rate-metric series (higher is better — the
#: sentinel's default direction).  Purely a registration list: the
#: comparison machinery discovers series from the recorded rounds,
#: but a metric listed here is DECLARED to be a rate, so adding a
#: lower-is-better metric under one of these names (or forgetting to
#: extend _LOWER_IS_BETTER for a new overhead metric) is a reviewable
#: diff, not a silently inverted alarm.  The weak-scaling rows
#: (``*_sharded_w{n}``) are per-device-count variants of their base
#: series and follow the base direction.
RATE_METRICS = frozenset({
    "gls_toas_per_sec", "wls_chisq_grid_points_per_sec",
    "mcmc_evals_per_sec", "pta_batch_fits_per_sec", "os_pairs_per_s",
    "grid_pts_per_sec_sharded", "pta_batch_fits_per_sec_sharded",
    "roofline_f64_matmul_flops",
    # the kron-structured GWB likelihood and the vmapped NUTS sampler
    # (gw/hmc): a kron-path regression trips the sentinel exactly
    # like any other rate series
    "gwb_lnlike_per_sec", "nuts_draws_per_sec",
    # the warm fitting service's mixed-stream throughput (pint_tpu/
    # serve): a coalescing/batching regression trips the sentinel
    "serve_reqs_per_sec",
    # the scenario corpus (pint_tpu/corpus): oracle-parity harness
    # throughput and the serve-plane soak replay — corpus throughput
    # joins the perf trajectory like any other rate
    "corpus_parity_scenarios_per_sec", "corpus_replay_reqs_per_sec",
    # the routed fleet's mixed-stream throughput (pint_tpu/fleet):
    # a placement/re-route regression trips the sentinel
    "fleet_reqs_per_sec",
    # streaming append+refit vs cold prepare+fit (docs/streaming.md):
    # the >=10x ROADMAP acceptance as a standing series
    "append_refit_speedup",
})

#: absolute slack (same units as the metric — percentage points for
#: the overhead metrics, seconds for cold_start_s) under the
#: lower-is-better comparison: a multiplicative tolerance is
#: meaningless around a near-zero or negative best (overhead jitters
#: about 0 on a quiet host)
_LOWER_ABS_SLACK = 2.0

#: absolute slack [s] for the per-metric compile_s.cold series: cold
#: compile on a loaded host jitters by a second or two; a regression
#: alarm should mean "the trace got structurally bigger", not "the
#: host was busy"
_COMPILE_ABS_SLACK = 2.0


def _parse_round(path):
    """One bench round -> (round_no, [metric records]).

    Accepts the driver layout ({"n", "rc", "tail": <log text with one
    JSON line per metric>}), a bare list of metric records, or
    {"metrics": [...]} (synthetic fixtures)."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        return None, [r for r in data if isinstance(r, dict)
                      and "metric" in r]
    if not isinstance(data, dict):
        return None, []
    if isinstance(data.get("metrics"), list):
        return data.get("n"), [r for r in data["metrics"]
                               if isinstance(r, dict) and "metric" in r]
    metrics = []
    for ln in str(data.get("tail", "")).splitlines():
        ln = ln.strip()
        if ln.startswith('{"metric"'):
            try:
                metrics.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
    return data.get("n"), metrics


def _is_fallback(rec) -> bool:
    if "fallback" in str(rec.get("backend") or ""):
        return True
    # rounds recorded before the structured backend field existed
    # (r01-r02 era) carry the label only inside the unit string
    return "backend=cpu-fallback" in str(rec.get("unit") or "")


def _round_is_bad(metrics) -> bool:
    """A round counts against the fallback streak when it produced no
    usable on-target number: every metric null/failed, or every usable
    metric served by a fallback backend.  A round where most metrics
    ran on-chip and one fell back is a metric problem, not a lost
    device — the per-metric REGRESSION/FALLBACK lines cover it."""
    if not metrics:
        return True
    usable = [r for r in metrics if r.get("value") is not None]
    if not usable:
        return True
    return all(_is_fallback(r) for r in usable)


def check_regression(paths, tolerance=0.5, streak=2):
    """The perf-regression sentinel over a BENCH_r*.json trajectory.

    Contract (docs/telemetry.md): for each metric, the best value ever
    recorded on a non-fallback backend is the reference; the latest
    non-fallback value must stay within ``tolerance`` (fraction —
    0.5 means "no worse than half the best rate") or the metric is
    flagged REGRESSION.  A trailing run of >= ``streak`` rounds that
    were fallback-served or produced nothing flags FALLBACK-STREAK
    (the r03-r05 hung-tunnel pathology: the chip was lost and nobody
    alarmed).  A metric that ever produced a real value but is absent
    from the latest round flags MISSING.

    Each metric's ``compile_s.cold`` field is additionally tracked as
    a first-class LOWER-is-better series (``<metric>:compile_s.cold``,
    absolute slack like the overhead metrics) — a compile-time
    regression alarms exactly like a throughput one, because compile
    time is what gates reclaiming the chip (ROADMAP item 5).  The
    compile series never MISSING-flags (not every metric records a
    compile, and fallback rounds compile for a different backend).

    Returns ``(lines, rc)`` with rc nonzero iff anything was
    flagged."""
    rounds = []   # (label, round_no, metrics)
    for i, path in enumerate(paths):
        try:
            n, metrics = _parse_round(path)
        except (OSError, json.JSONDecodeError) as e:
            return [f"ERROR unreadable round {path}: {e}"], 2
        rounds.append((str(path), n if n is not None else i + 1,
                       metrics))
    rounds.sort(key=lambda r: (r[1], r[0]))
    if not rounds:
        return ["ERROR no rounds to check"], 2

    lines = []
    flagged = False

    # trailing fallback/failed streak
    run = 0
    for _, _, metrics in reversed(rounds):
        if _round_is_bad(metrics):
            run += 1
        else:
            break
    if run >= streak:
        flagged = True
        first_bad = rounds[len(rounds) - run][1]
        last_bad = rounds[-1][1]
        lines.append(
            f"FALLBACK-STREAK rounds r{first_bad:02d}-r{last_bad:02d}: "
            f"{run} consecutive round(s) fallback-served or empty "
            "(device lost; see backend_probe retry/backoff)")

    # per-metric best-vs-latest
    best: dict = {}       # metric -> (value, round_no)
    latest: dict = {}     # metric -> (rec, round_no)
    for _, rno, metrics in rounds:
        for rec in metrics:
            name = rec.get("metric")
            val = rec.get("value")
            if name is None:
                continue
            if val is not None:
                latest[name] = (rec, rno)
                if not _is_fallback(rec):
                    lower = name in _LOWER_IS_BETTER
                    cur = best.get(name)
                    if (cur is None
                            or (val < cur[0] if lower else val > cur[0])):
                        best[name] = (val, rno)
    last_round_metrics = {r.get("metric") for r in rounds[-1][2]
                          if r.get("value") is not None}
    # a fully-bad latest round is the streak check's jurisdiction: one
    # transient empty round must not MISSING-flag every metric when it
    # is below the --streak threshold the caller chose to tolerate
    last_round_bad = _round_is_bad(rounds[-1][2])
    for name in sorted(best):
        best_val, best_rno = best[name]
        rec, rno = latest[name]
        val = rec.get("value")
        lower = name in _LOWER_IS_BETTER
        if name not in last_round_metrics:
            if last_round_bad:
                lines.append(
                    f"NOTE {name}: absent from the latest round "
                    "(round empty/fallback-served; streak check "
                    "owns the alarm)")
                continue
            flagged = True
            lines.append(
                f"MISSING {name}: no value in the latest round "
                f"(best {best_val:g} at r{best_rno:02d})")
            continue
        if _is_fallback(rec):
            # the streak check owns fallback alarms; note it per metric
            back = rec.get("backend") or "cpu-fallback"
            lines.append(
                f"FALLBACK {name}: latest value {val:g} is "
                f"{back!r} (best non-fallback "
                f"{best_val:g} at r{best_rno:02d})")
            continue
        if lower:
            floor = best_val + max(abs(best_val) * tolerance,
                                   _LOWER_ABS_SLACK)
            bad = val > floor
        else:
            floor = best_val * (1.0 - tolerance)
            bad = val < floor
        if bad:
            flagged = True
            lines.append(
                f"REGRESSION {name}: latest {val:g} (r{rno:02d}) vs "
                f"best {best_val:g} (r{best_rno:02d}), tolerance "
                f"{tolerance:g}")
        else:
            lines.append(
                f"OK {name}: latest {val:g} (r{rno:02d}), best "
                f"{best_val:g} (r{best_rno:02d})")
    if not best:
        lines.append("NOTE no non-fallback metric values anywhere in "
                     "the trajectory")

    # compile-time trajectory: compile_s.cold per metric, lower is
    # better.  Only non-fallback records enter the series (a CPU
    # fallback compiles a different backend's program), and a metric
    # whose latest round carries no cold number is skipped, never
    # MISSING-flagged.
    cbest: dict = {}    # metric -> (cold_s, round_no)
    clatest: dict = {}  # metric -> (cold_s, round_no, in_last_round)
    last_rno = rounds[-1][1]
    for _, rno, metrics in rounds:
        for rec in metrics:
            name = rec.get("metric")
            cs = rec.get("compile_s")
            cold = cs.get("cold") if isinstance(cs, dict) else None
            if name is None or cold is None or _is_fallback(rec):
                continue
            clatest[name] = (cold, rno, rno == last_rno)
            cur = cbest.get(name)
            if cur is None or cold < cur[0]:
                cbest[name] = (cold, rno)
    for name in sorted(cbest):
        best_cold, best_rno = cbest[name]
        cold, rno, in_last = clatest[name]
        series = f"{name}:compile_s.cold"
        if not in_last:
            continue
        floor = best_cold + max(abs(best_cold) * tolerance,
                                _COMPILE_ABS_SLACK)
        if cold > floor:
            flagged = True
            lines.append(
                f"REGRESSION {series}: latest {cold:g}s (r{rno:02d}) "
                f"vs best {best_cold:g}s (r{best_rno:02d}), slack "
                f"{floor - best_cold:g}s")
        else:
            lines.append(
                f"OK {series}: latest {cold:g}s (r{rno:02d}), best "
                f"{best_cold:g}s (r{best_rno:02d})")
    return lines, 1 if flagged else 0


def regression_verdict(paths=None):
    """The non-fatal sentinel readout shared by ``bench.py`` (suite
    end) and ``datacheck --profile``: globs ``BENCH_r*.json`` in the
    cwd when ``paths`` is None.  Returns ``(header, lines, rc)`` or
    None when no rounds exist.  Gating belongs to the
    ``--check-regression`` CLI exit code, not to these callers."""
    if paths is None:
        import glob

        paths = sorted(glob.glob("BENCH_r*.json"))
    if not paths:
        return None
    lines, rc = check_regression(paths)
    header = (f"perf-regression sentinel over {len(paths)} round(s): "
              + ("OK" if rc == 0 else "FLAGGED"))
    return header, lines, rc


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _print_lines(lines):
    """Print table lines, treating a consumer-closed pipe
    (``| head``) as a clean exit rather than an error."""
    try:
        for line in lines:
            print(line)
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())


def sanitizer_table(records):
    """Render the recompile-sanitizer story of a trace: per-program
    compile census (first / new-shape / same-shape-recompile /
    unattributed, docs/lint.md) plus one line per violation.  The
    records are ``{"type": "sanitizer"}`` events the runtime half
    emits on every attributed compile, arm, and violation."""
    events = [r for r in records if r.get("type") == "sanitizer"]
    compiles = [r for r in events if r.get("event") == "compile"]
    arms = [r for r in events if r.get("event") == "armed"]
    if not events:
        return ["(no sanitizer records — set "
                "PINT_TPU_RECOMPILE_SANITIZER=warn|raise or use "
                "sanitizer.sanitized())"]
    per = {}
    for r in compiles:
        key = f"{r.get('program', '?')}#{r.get('key', '-')}"
        st = per.setdefault(key, {"n": 0, "s": 0.0, "kinds": {},
                                  "violations": 0})
        st["n"] += int(r.get("n_compiles", 1))
        st["s"] += float(r.get("compile_s", 0.0))
        kind = r.get("kind", "?")
        st["kinds"][kind] = st["kinds"].get(kind, 0) + 1
        if r.get("violation"):
            st["violations"] += 1
    n_viol = sum(st["violations"] for st in per.values())
    lines = [f"{len(compiles)} attributed compile event(s) across "
             f"{len(per)} program(s), {n_viol} violation(s), "
             f"{len(arms)} arm event(s)"]
    lines.append(f"{'PROGRAM':<40s} {'COMPILES':>8s} {'SECONDS':>8s} "
                 f"{'VIOL':>5s}  KINDS")
    for key, st in sorted(per.items(),
                          key=lambda kv: -kv[1]["violations"]):
        name = key if len(key) <= 40 else key[:37] + "..."
        kinds = ",".join(f"{k}x{v}" for k, v in
                         sorted(st["kinds"].items()))
        lines.append(f"{name:<40s} {st['n']:>8d} {st['s']:>8.3f} "
                     f"{st['violations']:>5d}  {kinds}")
    for r in compiles:
        if r.get("violation") and r.get("message"):
            lines.append(f"VIOLATION: {r['message']}")
    return lines


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="pinttrace",
        description="Summarize/export a pint_tpu telemetry JSONL "
                    "trace, or gate on a BENCH_r*.json perf "
                    "trajectory")
    p.add_argument("paths", nargs="*",
                   help="the JSONL trace (PINT_TPU_TRACE output); with "
                        "--check-regression, the BENCH_r*.json round "
                        "files (default: BENCH_r*.json in the cwd)")
    p.add_argument("--json", action="store_true",
                   help="emit the aggregate as one JSON object instead "
                        "of a table")
    p.add_argument("--chrome-trace", metavar="OUT",
                   help="write the span tree as Chrome trace_event "
                        "JSON (Perfetto-loadable) to OUT")
    p.add_argument("--programs", action="store_true",
                   help="print the per-program profiling registry "
                        "table from the trace's program records")
    p.add_argument("--runs", action="store_true",
                   help="print the run ledger: every record type "
                        "joined per run_id (fits, grids, MCMC, bench "
                        "metrics)")
    p.add_argument("--convergence", nargs="?", const="",
                   metavar="RUN_ID",
                   help="render the per-iteration convergence table "
                        "from iter_trace records (optionally one "
                        "run's)")
    p.add_argument("--sanitizer", action="store_true",
                   help="print the recompile-sanitizer story: "
                        "per-program compile census + every "
                        "violation record (docs/lint.md)")
    p.add_argument("--check-regression", action="store_true",
                   help="perf-regression sentinel over bench rounds: "
                        "exits 1 on regression/fallback-streak/"
                        "missing metric")
    p.add_argument("--fleet", metavar="HOST:PORT,...",
                   help="scrape N live replicas' /metrics + /slo and "
                        "print one merged fleet snapshot (summed "
                        "counters, bucket-merged SLO windows, "
                        "worst-of verdict); --json emits the raw "
                        "merged document")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-replica scrape timeout for --fleet "
                        "(default 5s)")
    p.add_argument("--tolerance", type=float, default=0.5,
                   help="allowed fractional slack vs the best "
                        "non-fallback value (default 0.5)")
    p.add_argument("--streak", type=int, default=2,
                   help="trailing fallback/failed rounds that flag a "
                        "streak (default 2)")
    args = p.parse_args(argv)

    if args.fleet:
        from pint_tpu.obs import fleet as _fleet

        targets = [t.strip() for t in args.fleet.split(",")
                   if t.strip()]
        if not targets:
            print("pinttrace: --fleet needs at least one host:port",
                  file=sys.stderr)
            return 2
        doc = _fleet.fleet_snapshot(targets, timeout=args.timeout)
        if args.json:
            print(json.dumps(doc))
        else:
            _print_lines(_fleet.format_fleet(doc))
        # all replicas down is an operational alarm, not a render
        return 0 if doc.get("replicas_up") else 2

    # `pinttrace --convergence trace.jsonl` (RUN_ID omitted): argparse
    # hands the trace path to the nargs='?' option and leaves the
    # positional empty — reinterpret an existing-file "RUN_ID" as the
    # path so both documented argument orders work
    if args.convergence and not args.paths \
            and os.path.exists(args.convergence):
        args.paths = [args.convergence]
        args.convergence = ""

    if args.check_regression:
        paths = args.paths
        if not paths:
            import glob

            paths = sorted(glob.glob("BENCH_r*.json"))
        if not paths:
            print("pinttrace: no BENCH_r*.json rounds found",
                  file=sys.stderr)
            return 2
        lines, rc = check_regression(paths, tolerance=args.tolerance,
                                     streak=args.streak)
        for line in lines:
            print(line)
        return rc

    if not args.paths:
        p.error("a trace file is required (or use "
                "--check-regression / --fleet)")
    # multiple traces concatenate (e.g. one sink per replica); each
    # record remembers its file so --chrome-trace can keep replicas
    # in separate process lanes
    records, n_bad = [], 0
    for i, path in enumerate(args.paths):
        try:
            recs, bad = _load(path)
        except OSError as e:
            print(f"pinttrace: {e}", file=sys.stderr)
            return 2
        if len(args.paths) > 1:
            for r in recs:
                if isinstance(r, dict):
                    r["_replica"] = i
        records.extend(recs)
        n_bad += bad

    if args.chrome_trace:
        doc = chrome_trace(records)
        with open(args.chrome_trace, "w") as f:
            json.dump(doc, f, separators=(",", ":"))
        print(f"pinttrace: wrote {len(doc['traceEvents'])} trace "
              f"events to {args.chrome_trace}")
    elif args.programs:
        _print_lines(programs_table(records))
    elif args.runs:
        _print_lines(runs_table(records))
    elif args.sanitizer:
        _print_lines(sanitizer_table(records))
    elif args.convergence is not None:
        _print_lines(convergence_table(records,
                                          args.convergence or None))
    elif args.json:
        spans, counters, gauges, metrics, other = aggregate(records)
        print(json.dumps({
            "n_records": len(records), "n_bad": n_bad,
            "spans": {name: {"count": st[0], "total_s": st[1],
                             "max_s": st[2], "max_depth": st[3]}
                      for name, st in spans.items()},
            "counters": counters, "gauges": gauges,
            "metrics": metrics, "n_other": other,
        }))
    else:
        _print_lines(summarize(records))
    if n_bad:
        print(f"WARNING: {n_bad} unparseable line(s) skipped",
              file=sys.stderr)
    return 1 if n_bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
