"""Summarize a pint_tpu telemetry JSONL trace file.

``pinttrace trace.jsonl`` (or ``python -m pint_tpu.scripts.pinttrace``)
aggregates the records written by :mod:`pint_tpu.telemetry`
(``PINT_TPU_TRACE=trace.jsonl``): spans by name (count/total/mean/max),
final counter and gauge values, and any benchmark metric records that
were routed through the same sink.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["summarize", "main"]


def _load(path):
    """Parse a JSONL trace; returns (records, n_bad)."""
    records, n_bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                n_bad += 1
    return records, n_bad


def aggregate(records):
    """Aggregate parsed trace records: returns (spans, counters,
    gauges, metrics, n_other) where spans maps name ->
    [count, total_s, max_s, max_depth].  The ONE aggregation both the
    table and --json outputs are built from."""
    spans: dict = {}
    counters: dict = {}
    gauges: dict = {}
    metrics = []
    other = 0
    for rec in records:
        kind = rec.get("type")
        if kind == "span":
            st = spans.setdefault(rec.get("name", "?"), [0, 0.0, 0.0, 0])
            dur = float(rec.get("dur_s", 0.0))
            st[0] += 1
            st[1] += dur
            st[2] = max(st[2], dur)
            st[3] = max(st[3], int(rec.get("depth", 0)))
        elif kind == "counter":
            # flushes repeat cumulative values; last one wins
            counters[rec.get("name", "?")] = rec.get("value")
        elif kind == "gauge":
            gauges[rec.get("name", "?")] = rec.get("value")
        elif kind == "metric" or "metric" in rec:
            metrics.append(rec)
        else:
            other += 1
    return spans, counters, gauges, metrics, other


def summarize(records):
    """Aggregate parsed trace records into report lines."""
    spans, counters, gauges, metrics, other = aggregate(records)

    from pint_tpu.telemetry import render_stats_lines

    lines = [f"{len(records)} records: "
             f"{sum(s[0] for s in spans.values())} spans "
             f"({len(spans)} distinct), {len(counters)} counters, "
             f"{len(gauges)} gauges, {len(metrics)} metrics"
             + (f", {other} other" if other else "")]
    lines.extend(render_stats_lines(spans, counters, gauges))
    for rec in metrics:
        name = rec.get("metric", "?")
        parts = [f"metric {name} = {rec.get('value')!r}"]
        for key in ("backend", "compile_s", "flops", "vs_baseline"):
            if rec.get(key) is not None:
                parts.append(f"{key}={rec[key]!r}")
        lines.append(" ".join(parts))
    return lines


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="pinttrace",
        description="Summarize a pint_tpu telemetry JSONL trace file")
    p.add_argument("trace", help="path to the JSONL trace "
                                 "(PINT_TPU_TRACE output)")
    p.add_argument("--json", action="store_true",
                   help="emit the aggregate as one JSON object instead "
                        "of a table")
    args = p.parse_args(argv)
    try:
        records, n_bad = _load(args.trace)
    except OSError as e:
        print(f"pinttrace: {e}", file=sys.stderr)
        return 2
    if args.json:
        spans, counters, gauges, metrics, other = aggregate(records)
        print(json.dumps({
            "n_records": len(records), "n_bad": n_bad,
            "spans": {name: {"count": st[0], "total_s": st[1],
                             "max_s": st[2], "max_depth": st[3]}
                      for name, st in spans.items()},
            "counters": counters, "gauges": gauges,
            "metrics": metrics, "n_other": other,
        }))
    else:
        try:
            for line in summarize(records):
                print(line)
        except BrokenPipeError:  # | head closed the pipe: not an error
            import os

            os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    if n_bad:
        print(f"WARNING: {n_bad} unparseable line(s) skipped",
              file=sys.stderr)
    return 1 if n_bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
