"""Convert a Tempo2 ``BINARY T2`` par file to a concrete binary model
(reference: src/pint/scripts/t2binary2pint.py driving
guess_binary_model / convert_binary_params_dict)."""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="t2binary2pint",
        description="Map a Tempo2 T2 binary par file onto the "
                    "best-covering concrete binary model",
    )
    p.add_argument("input_par")
    p.add_argument("output_par")
    p.add_argument("--list", action="store_true",
                   help="only list the candidate models, best first")
    args = p.parse_args(argv)

    from pint_tpu.models.builder import (
        get_model,
        guess_binary_model,
        model_to_parfile,
        parse_parfile,
    )

    pardict = parse_parfile(open(args.input_par).read())
    binary = (pardict.get("BINARY", [[""]])[0] or [""])[0].upper()
    if binary != "T2":
        raise SystemExit(f"BINARY is {binary or '(absent)'}, not T2 — "
                         "nothing to convert")
    candidates = guess_binary_model(pardict)
    print("candidate models (best first):", ", ".join(candidates))
    if args.list:
        return 0
    model = get_model(args.input_par, allow_T2=True)
    with open(args.output_par, "w") as f:
        f.write(model_to_parfile(model))
    print(f"wrote {args.output_par} (BINARY {candidates[0]})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
