"""Console entry points.

Counterpart of the reference script layer (reference: src/pint/scripts/,
13 entry points registered in setup.cfg:55-68).  Run as modules:

    python -m pint_tpu.scripts.pintempo PAR TIM [--fit]
    python -m pint_tpu.scripts.zima PAR TIM [--ntoa N ...]
    python -m pint_tpu.scripts.pintbary MJD --ra ... --dec ...
    python -m pint_tpu.scripts.tcb2tdb IN.par OUT.par
    python -m pint_tpu.scripts.convert_parfile IN.par [-o OUT]
    python -m pint_tpu.scripts.compare_parfiles A.par B.par
    python -m pint_tpu.scripts.pintpublish PAR TIM
"""
