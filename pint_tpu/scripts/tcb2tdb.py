"""Convert a TCB par file to TDB (reference:
src/pint/scripts/tcb2tdb.py)."""

from __future__ import annotations

import argparse


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="tcb2tdb", description="Approximate TCB->TDB par conversion"
    )
    p.add_argument("input_par")
    p.add_argument("output_par")
    args = p.parse_args(argv)

    from pint_tpu.models.tcb import convert_parfile_tcb_tdb

    with open(args.input_par) as f:
        text = f.read()
    out = convert_parfile_tcb_tdb(text)
    with open(args.output_par, "w") as f:
        f.write(out)
    print(f"wrote {args.output_par} (re-fit recommended; the "
          "conversion is approximate)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
