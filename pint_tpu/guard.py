"""Numerical-health guard layer: on-device health verdicts, the
degradation ladder, structured divergence errors, checkpoint/resume.

The fit hot path runs at ~10^-15 relative precision on hardware whose
f32-pair f64 emulation (~49-bit) makes near-degenerate normal matrices
fail Cholesky outright (linalg.py) — yet before this layer the stack
had no systematic answer to a fit going bad: a NaN chi^2 propagated
into ``model.values``, a truncated pseudo-inverse silently zeroed
degenerate directions, and a killed 10^5-step chain lost everything.

Four surfaces:

- **Health pytrees** — every jitted fit/likelihood program returns a
  small on-device :class:`Health` record alongside its result (isfinite
  verdicts on residuals/sigma/chi2/step/cov, the count of
  pseudo-inverse-truncated eigenvalues, a condition proxy from the
  already-computed spectrum).  The record rides the SAME compiled
  program as the fit step — zero extra XLA compiles — and bucketing
  pad-sentinel rows are masked out so ``PAD_ERROR_US`` rows can never
  raise a false alarm.  Gate: ``$PINT_TPU_GUARD`` (default on; ``0``/
  ``off`` trace the steps without the health outputs — the traced
  program differs, so the flag is part of every step's registry key).
- **Degradation ladder** — :func:`run_ladder` drives bounded retry
  through escalating rungs (prior-jitter escalation -> hard jitter ->
  GLS->WLS downgrade; the eigh pseudo-inverse is the always-on rung-0
  mechanism of ``linalg.gls_normal_solve``).  ``input``-class
  divergence (non-finite residuals or uncertainties — bad data no
  solver rung can fix) aborts the ladder immediately.  The serving
  rung lands in fit meta (``GUARD_RUNG``) and the ``guard.*``
  telemetry counters.
- **Structured errors** — a fit that diverges past every rung raises
  :class:`FitDivergedError` carrying the last-good parameter vector,
  the host-side health record, and the rungs tried — never a silent
  garbage write into ``model.values``.
- **Checkpoint/resume** — :func:`save_checkpoint` atomic-writes
  (tmp + ``os.replace``) a dict of arrays plus a caller fingerprint;
  :func:`load_checkpoint` validates the fingerprint so a stale trace
  (different posterior, different model structure) can never be
  silently resumed — mismatch raises :class:`CheckpointMismatchError`.
  :mod:`pint_tpu.sampler` checkpoints MCMC chain state per chunk and
  :class:`pint_tpu.parallel.PTABatch` checkpoints fit progress.

Importing this module never touches a JAX backend (the traced helpers
import ``jax.numpy`` lazily), matching :mod:`pint_tpu.telemetry`.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import NamedTuple

import numpy as np

from pint_tpu import telemetry

__all__ = [
    "Health", "SolveDiag", "FitDivergedError", "CheckpointMismatchError",
    "StepDiverged", "enabled", "step_health", "verdict", "batch_bad",
    "to_record", "run_ladder", "save_checkpoint", "load_checkpoint",
]

_GUARD_ENV = "PINT_TPU_GUARD"

#: checkpoint payload format version (bumped on incompatible layout
#: changes; load refuses a version it does not understand)
CHECKPOINT_VERSION = 1

#: THE degradation-ladder escalation table (rung name, guard_eps):
#: raised pseudo-inverse cutoff + capacity/prior ridge, as dynamic
#: scalars through the same trace.  Shared by the single-pulsar
#: fitters and the batched PTA path so the two ladders cannot drift.
JITTER_RUNGS = (("jitter", 1e-10), ("jitter_hard", 1e-6))


def enabled() -> bool:
    """Whether fit steps compute health outputs (``$PINT_TPU_GUARD``,
    default on).  Read at trace-build time; the flag is part of every
    step's registry key because it changes the traced program."""
    raw = os.environ.get(_GUARD_ENV, "").strip().lower()
    return raw not in ("0", "off", "false", "no", "disabled")


# --------------------------------------------------------------------------
# on-device health records
# --------------------------------------------------------------------------

class SolveDiag(NamedTuple):
    """Spectrum diagnostics of one normal-equation solve, computed from
    the eigh/SVD spectrum the solver already has in hand (zero extra
    factorizations)."""

    n_truncated: object  #: eigenvalues/singulars zeroed by the cutoff
    cond_log10: object   #: log10(max / smallest KEPT eigenvalue)


class Health(NamedTuple):
    """The per-step health pytree.  All leaves are 0-d device arrays
    (or per-pulsar vectors on the vmapped PTA path); an empty tuple
    ``()`` stands in when the guard is disabled.

    ``ok`` is the AND of every verdict bit, computed ON DEVICE: the
    healthy host path reads exactly one scalar per iteration (next to
    the chi^2 it already pulls) and touches the individual bits only
    after a trip."""

    ok: object            #: all verdicts clean (the one hot-path read)
    input_finite: object  #: dataset float leaves finite (pad masked)
    resid_finite: object  #: residuals finite (pad-sentinel rows masked)
    sigma_finite: object  #: uncertainties finite (pad rows masked)
    chi2_finite: object
    step_finite: object   #: proposed parameter step finite
    cov_finite: object    #: covariance block finite
    n_truncated: object   #: pseudo-inverse-truncated directions
    cond_log10: object    #: condition proxy of the normal matrix


def batch_input_finite(batch, valid=None):
    """Per-TOA finiteness verdict over a TOABatch's float leaves.

    The fixed-point phase pipeline CONVERTS delays to int64 ticks, so
    a NaN observing frequency (corrupted ``.tim`` row) does not
    propagate NaN into the residuals — it silently becomes a
    plausible-looking number.  The only honest detector is a direct
    check on the inputs, masked so bucketing pad rows can't raise
    false alarms."""
    import jax.numpy as jnp

    f = jnp.isfinite(batch.freq_mhz)
    f = f & jnp.isfinite(batch.error_s)
    f = f & jnp.all(jnp.isfinite(batch.ssb_obs_pos), axis=-1)
    f = f & jnp.all(jnp.isfinite(batch.ssb_obs_vel), axis=-1)
    f = f & jnp.all(jnp.isfinite(batch.obs_sun_pos), axis=-1)
    if batch.planet_pos.shape[0]:
        f = f & jnp.all(jnp.isfinite(batch.planet_pos), axis=(0, 2))
    if valid is not None:
        f = jnp.logical_or(f, jnp.logical_not(valid))
    return jnp.all(f)


def step_health(r, sigma, chi2, dpar, cov, diag=None, valid=None,
                inputs_ok=None):
    """Build a :class:`Health` record inside a traced fit step.

    valid: optional boolean mask — bucketing pad-sentinel rows
    (``compile_cache.PAD_ERROR_US``) are excluded from the residual and
    sigma finiteness verdicts so they can never raise a false alarm.
    inputs_ok: optional scalar from :func:`batch_input_finite`.
    """
    import jax.numpy as jnp

    def masked_all_finite(x):
        f = jnp.isfinite(x)
        if valid is not None:
            f = jnp.logical_or(f, jnp.logical_not(valid))
        return jnp.all(f)

    if diag is None:
        diag = SolveDiag(jnp.int32(0), jnp.float64(0.0))
    input_finite = (jnp.bool_(True) if inputs_ok is None
                    else inputs_ok)
    resid_finite = masked_all_finite(r)
    sigma_finite = masked_all_finite(sigma)
    chi2_finite = jnp.isfinite(chi2)
    step_finite = jnp.all(jnp.isfinite(dpar))
    cov_finite = jnp.all(jnp.isfinite(cov))
    return Health(
        ok=(input_finite & resid_finite & sigma_finite & chi2_finite
            & step_finite & cov_finite),
        input_finite=input_finite,
        resid_finite=resid_finite,
        sigma_finite=sigma_finite,
        chi2_finite=chi2_finite,
        step_finite=step_finite,
        cov_finite=cov_finite,
        n_truncated=diag.n_truncated,
        cond_log10=diag.cond_log10,
    )


# --------------------------------------------------------------------------
# host-side verdicts
# --------------------------------------------------------------------------

def verdict(health) -> str:
    """Classify a (scalar) health record host-side.

    ``"ok"`` — all verdicts clean; ``"input"`` — residuals or sigmas
    non-finite (bad data: a NaN TOA, an inf uncertainty — no solver
    rung can fix it, the ladder aborts); ``"solve"`` — inputs clean but
    the solve produced non-finite chi2/step/cov (the degeneracy class
    the jitter rungs exist for)."""
    if not health:
        return "ok"
    # one device read on the hot path; the bit-by-bit classification
    # happens only after a trip
    if bool(health.ok):
        return "ok"
    input_ok = (bool(health.input_finite) and bool(health.resid_finite)
                and bool(health.sigma_finite))
    return "input" if not input_ok else "solve"


def batch_bad(health):
    """Per-pulsar bad mask of a vmapped health record (the PTA path):
    numpy bool array, True where that pulsar's verdict is not ok.
    Returns None when the guard is off (empty health)."""
    if not health:
        return None
    return ~np.asarray(health.ok)


def batch_input_bad(health):
    """Per-pulsar input-class mask (non-finite data): the members no
    solver rung can fix — the batched ladder must not waste full-batch
    retries on them, mirroring :func:`run_ladder`'s immediate
    input-class abort."""
    if not health:
        return None
    return ~(np.asarray(health.input_finite)
             & np.asarray(health.resid_finite)
             & np.asarray(health.sigma_finite))


def to_record(health) -> dict:
    """Host-side dict of plain python values (error payloads, fit_health
    attributes, JSONL telemetry)."""
    if not health:
        return {}
    out = {}
    for k, v in health._asdict().items():
        a = np.asarray(v)
        if a.ndim == 0:
            out[k] = bool(a) if a.dtype == np.bool_ else (
                int(a) if np.issubdtype(a.dtype, np.integer) else float(a))
        else:  # vmapped (PTA) record: keep per-pulsar vectors
            out[k] = a.tolist()
    return out


# --------------------------------------------------------------------------
# structured errors + the degradation ladder
# --------------------------------------------------------------------------

class StepDiverged(Exception):
    """Internal control-flow signal: one fit attempt (one ladder rung)
    saw a bad health verdict.  Carries the last-good parameter state
    and the offending health record; :func:`run_ladder` converts the
    final one into a :class:`FitDivergedError`."""

    def __init__(self, health, last_good=None, n_iter=0, kind=None):
        self.health = health
        self.last_good = last_good
        self.n_iter = n_iter
        self.kind = kind or verdict(health)
        super().__init__(f"fit step diverged ({self.kind}) at "
                         f"iteration {n_iter}")


class FitDivergedError(RuntimeError):
    """A fit/likelihood diverged past every degradation rung.

    Attributes: ``context`` (which program), ``health`` (host-side
    record dict), ``last_good`` (the last parameter state with a finite
    chi^2 — ``{name: value}`` for fitters, an array for samplers),
    ``rungs_tried``, and optionally ``bad_indices``/``results`` on the
    batched PTA path (healthy pulsars' results are written back before
    the raise; the bad ones are listed here)."""

    def __init__(self, context, *, health=None, last_good=None,
                 rungs_tried=(), bad_indices=None, results=None,
                 detail=""):
        self.context = context
        self.health = health or {}
        self.last_good = last_good
        self.rungs_tried = tuple(rungs_tried)
        self.bad_indices = bad_indices
        self.results = results
        msg = f"{context}: fit diverged"
        if rungs_tried:
            msg += f" after rungs {list(self.rungs_tried)}"
        if bad_indices is not None:
            msg += f" for batch members {list(bad_indices)}"
        if detail:
            msg += f" ({detail})"
        if last_good is not None:
            msg += "; .last_good carries the last finite parameter state"
        super().__init__(msg)


class CheckpointMismatchError(RuntimeError):
    """A checkpoint's fingerprint does not match the resuming job — a
    stale chain/fit state must never be silently reused."""


def run_ladder(rungs, *, context):
    """Drive the degradation ladder: try each ``(name, callable)`` in
    order until one returns.  A callable signals divergence by raising
    :class:`StepDiverged`; ``input``-class divergence aborts
    immediately (no rung fixes bad data).  Returns ``(result,
    rung_name)`` or raises :class:`FitDivergedError` carrying the
    best last-good state seen across attempts."""
    tried = []
    last = None
    last_good = None
    for name, fn in rungs:
        try:
            result = fn()
        except StepDiverged as sd:
            tried.append(name)
            last = sd
            if sd.last_good is not None:
                last_good = sd.last_good
            telemetry.counter_add("guard.trips")
            telemetry.counter_add(f"guard.trip.{sd.kind}")
            # ledger record: which rung failed, and how — joined to
            # the active run by the emit-time tag, so `pinttrace
            # --runs` shows the escalation path, not just the final
            # serving rung
            telemetry.emit({"type": "guard_trip", "context": context,
                            "rung": name, "kind": sd.kind,
                            "n_iter": sd.n_iter})
            if sd.kind == "input":
                break
            continue
        if tried:  # a degraded rung is serving — count which
            telemetry.counter_add(f"guard.rung.{name}")
            telemetry.emit({"type": "guard_rung", "context": context,
                            "rung": name, "after": list(tried)})
        return result, name
    raise FitDivergedError(
        context,
        health=to_record(last.health) if last is not None else {},
        last_good=last_good,
        rungs_tried=tried,
        detail=(f"{last.kind}-class divergence" if last is not None
                else "no rungs available"),
    )


# --------------------------------------------------------------------------
# checkpoint/resume
# --------------------------------------------------------------------------

def save_checkpoint(path, arrays: dict, fingerprint, meta=None):
    """Atomic-write a checkpoint: a dict of named arrays plus a caller
    fingerprint (the job's jit/structure identity).  The write goes to
    a same-directory temp file, is fsynced, then ``os.replace``d — a
    process killed mid-save leaves the previous checkpoint intact."""
    head = {"version": CHECKPOINT_VERSION,
            "fingerprint": str(fingerprint),
            "meta": meta or {}}
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".ckpt-", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __meta__=np.array(json.dumps(head)), **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    telemetry.counter_add("guard.checkpoint_saves")
    return path


def load_checkpoint(path, fingerprint=None, missing_ok=True):
    """Load a checkpoint written by :func:`save_checkpoint`.

    Returns ``(arrays, head)`` — the named-array dict and the header
    (version/fingerprint/meta) — or None when the file is missing and
    ``missing_ok``.  A fingerprint mismatch (or an unknown payload
    version) raises :class:`CheckpointMismatchError`: resuming a chain
    against a different posterior, or a fit against a different model
    structure, must fail loudly, never silently."""
    path = os.fspath(path)
    if not os.path.exists(path):
        if missing_ok:
            return None
        raise FileNotFoundError(path)
    with np.load(path, allow_pickle=False) as z:
        head = json.loads(str(z["__meta__"][()]))
        if int(head.get("version", -1)) != CHECKPOINT_VERSION:
            telemetry.counter_add("guard.checkpoint_mismatches")
            raise CheckpointMismatchError(
                f"{path}: checkpoint version {head.get('version')} != "
                f"{CHECKPOINT_VERSION}")
        if fingerprint is not None and \
                head.get("fingerprint") != str(fingerprint):
            telemetry.counter_add("guard.checkpoint_mismatches")
            raise CheckpointMismatchError(
                f"{path}: checkpoint fingerprint "
                f"{head.get('fingerprint')!r} does not match this job's "
                f"{str(fingerprint)!r} — a stale state must not be "
                "silently resumed (delete the file to start fresh)")
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    telemetry.counter_add("guard.checkpoint_resumes")
    return arrays, head
